"""PipeGraph + MultiPipe — the composition layer (reference L4).

Counterpart of ``wf/pipegraph.hpp`` (PipeGraph ``:104-244``, MultiPipe ``:255-571``,
split ``:3030-3062``, select ``:3065-3081``, merge ``:2992-3026``, Application Tree
``AppNode`` ``:64-75``). The reference compiles the logical operator graph into nested
FastFlow farms/pipelines with one thread per node; here each MultiPipe's operator chain
compiles into ONE jitted device program (``CompiledChain``), and the DAG between
MultiPipes (split/merge edges) is executed by a host push-driver:

- ``add(op)`` / ``chain(op)``: both append to the compiled chain. The reference
  distinguishes shuffle (new matrioska + emitter clone, ``:1231-1266``) from chaining
  (``ff_comb`` fusion ``:1272-1318``); on TPU keyed routing happens *inside* the
  program via segment ops, so every add is as cheap as a chain — ``chain`` is kept for
  API parity and asserts the op is chainable (FORWARD routing), mirroring the
  reference's conditions.
- ``split(fn, n)``: installs a splitting function (``Splitting_Emitter``,
  ``wf/splitting_emitter.hpp:41-152``) evaluated per tuple under ``vmap``; branch i
  receives the batch masked to tuples routed to i (multicast when the function
  returns a mask vector).
- ``select(i)``: the i-th split branch as a new MultiPipe (``:3065-3081``).
- ``merge(*others)``: N output streams into one (``:2992-3026``); type compatibility
  is checked on payload specs (the typeid check ``:1573-1578``). In DETERMINISTIC
  mode merged batches are buffered per round and stably sorted by (ts, id) — the
  batch-level Ordering_Node (``wf/ordering_node.hpp``).
- EOS: sources exhaust, then every chain flushes in topological order, cascading
  through downstream chains (reference eosnotify propagation).

Graph introspection: ``listOperators`` and a graphviz ``dump_DOTGraph``
(``wf/pipegraph.hpp:226-237``, GRAPHVIZ_WINDFLOW).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..basic import Mode, DEFAULT_BATCH_SIZE
from ..batch import Batch, concat_batches, tuple_refs
from ..observability import tracing as _tracing
from ..operators.base import Basic_Operator
from ..operators.sink import ReduceSink, Sink
from ..operators.source import SourceBase
from .pipeline import CompiledChain


class AppNode:
    """Node of the Application Tree (``wf/pipegraph.hpp:64-75``).

    A merge removes the absorbed pipes' nodes from the forest (the reference
    deletes them, ``wf/pipegraph.hpp:846-858``): ``absorbed`` is set, ``parent``
    cleared, and split-parent children lists are re-pointed at the merged node —
    so the live forest is exactly the nodes with ``absorbed == False``."""

    def __init__(self, mp: "MultiPipe", parent: Optional["AppNode"] = None):
        self.mp = mp
        self.parent = parent
        self.children: List[AppNode] = []
        self.absorbed = False

    def absorb(self) -> None:
        """Detach this node (and its subtree) from the live forest."""
        self.absorbed = True
        self.parent = None
        for c in self.children:
            c.absorb()


class MultiPipe:
    """A growing chain of operators with optional split/merge structure."""

    def __init__(self, graph: "PipeGraph", source: Optional[SourceBase] = None):
        self.graph = graph
        self.source = source
        # appended during graph BUILD (driver), before any driver runs;
        # pipe threads only iterate
        self.ops: List[Basic_Operator] = []  # wf-lint: single-writer[driver]
        self.sink: Optional[Sink] = None
        self.has_sink = False
        # split structure
        self.split_fn: Optional[Callable] = None
        self.split_branches: List[MultiPipe] = []
        # merge structure: upstream pipes feeding this one
        self.merge_inputs: List[MultiPipe] = []
        self._dataflow_parent: Optional[MultiPipe] = None   # split-branch feeder
        # compiled lazily by whichever thread first pushes through this pipe
        # — the push driver (driver) or the pipe's OWN body thread (stage);
        # a pipe is never driven from two threads at once
        self._chain: Optional[CompiledChain] = None  # wf-lint: single-writer[driver, stage]
        self._outputs_to: List[MultiPipe] = []
        self._ordering = None     # lazily-built Ordering_Node (DETERMINISTIC merges)
        # application-tree position of a PARTIAL merge result: the reference
        # re-parents the merged AppNode under the split parent, replacing the
        # absorbed sibling branches (wf/pipegraph.hpp:944-952) — that is what
        # legalizes graph_8/graph_9-style follow-up merges with the remaining
        # siblings.
        self._merge_parent: Optional[MultiPipe] = None
        self._covers_idx: tuple = ()

    # -- construction (reference add/chain overloads, wf/pipegraph.hpp:1565-2950) -----

    def add(self, op: Basic_Operator) -> "MultiPipe":
        self._check_open()
        if isinstance(op, Sink):
            raise TypeError(
                f"add({op.name}): host Sinks terminate a MultiPipe — use "
                f"add_sink()/chain_sink() (in-graph reductions stay addable via "
                f"ReduceSink)")
        op._mark_used()
        op._chained = False
        self.graph._register(op)
        self.ops.append(op)
        return self

    def chain(self, op: Basic_Operator) -> "MultiPipe":
        """Queue-free fusion when the operator is FORWARD; silent fallback to
        ``add()`` otherwise — exactly the reference's behavior
        (``wf/pipegraph.hpp:1602-1640``: KEYBY or unchainable ops fall through
        to add). The outcome is recorded on the operator (``_chained``) and
        rendered distinctly by ``dump_DOTGraph``, mirroring the reference's
        ``gv_chain_vertex`` vs add-vertex distinction."""
        from ..basic import routing_modes_t
        self.add(op)
        op._chained = op.getRoutingMode() in (routing_modes_t.FORWARD,
                                              routing_modes_t.NONE)
        return self

    def add_sink(self, sink: Sink) -> "MultiPipe":
        self._check_open()
        sink._mark_used()
        self.graph._register(sink)
        self.sink = sink
        self.has_sink = True
        return self

    chain_sink = add_sink

    # -- split / select / merge -------------------------------------------------------

    def split(self, fn: Callable, n_branches: int) -> "MultiPipe":
        """``fn(t) -> int branch`` or ``fn(t) -> bool[n]`` multicast mask."""
        self._check_open()
        if self.has_sink:
            raise RuntimeError("cannot split a MultiPipe with a sink")
        self.split_fn = fn
        self.split_branches = []
        node = self.graph._node_of(self)
        for _ in range(n_branches):
            child = MultiPipe(self.graph)
            child._dataflow_parent = self
            self.split_branches.append(child)
            cn = AppNode(child, node)
            node.children.append(cn)
            self.graph._nodes[id(child)] = cn
        return self

    def select(self, i: int) -> "MultiPipe":
        if self.split_fn is None:
            raise RuntimeError("select() on a non-split MultiPipe (wf/pipegraph.hpp:3065)")
        if not (0 <= i < len(self.split_branches)):
            raise IndexError(f"branch {i} of {len(self.split_branches)}")
        return self.split_branches[i]

    def merge(self, *others: "MultiPipe") -> "MultiPipe":
        """Merge this pipe's output with ``others`` into a new MultiPipe.

        Legality mirrors the reference (``wf/pipegraph.hpp:2992-3026`` entry
        checks; structural cases merge-ind / merge-full / merge-partial with the
        contiguity rule, ``:813-965``): at least two distinct member pipes, none
        already merged or split or sunk, and the set must be independent roots,
        a whole split subtree, or contiguous sibling branches."""
        pipes = [self, *others]
        merge_parent, covers_idx = self.graph._check_merge_legality(pipes)
        specs = [p._out_payload_spec() for p in pipes]
        s0 = jax.tree.structure(specs[0])
        for s in specs[1:]:
            if jax.tree.structure(s) != s0 or any(
                    a.shape != b.shape or a.dtype != b.dtype
                    for a, b in zip(jax.tree.leaves(specs[0]), jax.tree.leaves(s))):
                raise TypeError("merge(): incompatible tuple types "
                                "(wf/pipegraph.hpp:1573-1578 typeid check)")
        merged = MultiPipe(self.graph)
        merged.merge_inputs = pipes
        merged._merge_parent = merge_parent
        merged._covers_idx = covers_idx
        # Application-Tree surgery, as the reference does it: the merged node is
        # a LEAF that replaces the absorbed subtrees — under the split parent
        # for merge-partial / nested merge-full (wf/pipegraph.hpp:846-858,
        # 944-957), as a root for merge-ind / root-level merge-full.
        node = AppNode(merged)
        if merge_parent is not None:
            parent_node = self.graph._node_of(merge_parent)
            node.parent = parent_node
            # a direct child is absorbed iff the branch indexes it covers are
            # within this merge's cover (children are split branches, or the
            # results of earlier partial merges which are NOT in
            # split_branches — identify both by index cover)
            def _child_idxs(c):
                if c.mp._merge_parent is merge_parent:
                    return set(c.mp._covers_idx)
                return {i for i, b in enumerate(merge_parent.split_branches)
                        if b is c.mp}
            target = set(covers_idx)
            new_children, replaced = [], False
            for c in parent_node.children:
                ci = _child_idxs(c)
                if ci and ci <= target:
                    c.absorb()
                    if not replaced:
                        new_children.append(node)
                        replaced = True
                else:
                    new_children.append(c)
            parent_node.children = new_children
        else:
            # root-level merge (merge-ind / merge-full of whole roots): the
            # absorbed roots leave the forest, like the partial case above
            for p in pipes:
                self.graph._node_of(p).absorb()
        for p in pipes:
            p._outputs_to.append(merged)
        self.graph._nodes[id(merged)] = node
        self.graph._merged_roots = [r for r in self.graph._merged_roots
                                    if r not in pipes]
        self.graph._merged_roots.append(merged)
        return merged

    def join_with(self, other: "MultiPipe", join_op) -> "MultiPipe":
        """Two-input join wiring over merge semantics: merge this pipe with
        ``other`` (the ``wf/pipegraph.hpp:1573-1578`` typeid check applies —
        both sides must already carry the unified/tagged payload schema) and
        add ``join_op`` (a :class:`~windflow_tpu.operators.join.
        StreamTableJoin` / :class:`~windflow_tpu.operators.join.
        IntervalJoin`, whose ``side_fn`` separates the sides again). Under
        ``Mode.DETERMINISTIC`` the merge's Ordering_Node fixes the
        interleave, making the join byte-identical across drivers."""
        from ..operators.join import IntervalJoin, StreamTableJoin
        if not isinstance(join_op, (StreamTableJoin, IntervalJoin)):
            raise TypeError(
                f"join_with expects a StreamTableJoin/IntervalJoin operator, "
                f"got {type(join_op).__name__}")
        merged = self.merge(other)
        merged.add(join_op)
        return merged

    # -- internals --------------------------------------------------------------------

    def _check_open(self):
        if self.split_fn is not None:
            raise RuntimeError("MultiPipe already split; use select()")
        if self.has_sink:
            raise RuntimeError("MultiPipe already has a sink")

    def _in_payload_spec(self):
        if self.source is not None:
            return self.source.payload_spec()
        if self.merge_inputs:
            return self.merge_inputs[0]._out_payload_spec()
        # split branch: the splitting pipe's output spec
        return self._dataflow_parent._out_payload_spec()

    def _out_payload_spec(self):
        spec = self._in_payload_spec()
        for op in self.ops:
            spec = op.out_spec(spec)
        return spec

    def _compile(self, batch_capacity: int):
        if self._chain is None:
            # event-time sub-toggle: geometry-binding (lateness histograms
            # live in operator state), resolved from the graph's monitoring=
            from ..observability import event_time_enabled
            self._chain = CompiledChain(
                self.ops, self._in_payload_spec(),
                batch_capacity=batch_capacity,
                event_time=event_time_enabled(self.graph._monitoring_arg))
            # health-ledger stage label = the flight-recorder pipe label, so
            # the dispatch-bound classifier names the same edges wf_trace
            # renders (the fusion candidates of ROADMAP item 2)
            self._chain.label = self.graph._trace_label(self)
        return self._chain


class PipeGraph:
    """The streaming environment (``wf/pipegraph.hpp:104-244``)."""

    def __init__(self, name: str = "pipegraph", mode: Mode = Mode.DEFAULT,
                 batch_size: int = None, monitoring=None, control=None,
                 queue_capacity=8, trace=None, dispatch=None):
        self.name = name
        self.mode = mode
        #: None = resolve at start(): min withBatch hint over registered
        #: operators (capacity ceilings, wf/builders_gpu.hpp:115-122), else
        #: DEFAULT_BATCH_SIZE; an explicit value always wins.  Written by
        #: start() on the driver BEFORE the threaded bodies spawn.
        self.batch_size = batch_size      # wf-lint: single-writer[driver]
        #: telemetry opt-in (the reference's MONITORING mode): None = consult
        #: WF_MONITORING; True / out-dir string / observability.MonitoringConfig
        #: enable the metrics registry + periodic reporter + event journal +
        #: topology dump for this graph's run. Off by default (zero hot-path
        #: cost beyond a None check).
        self._monitoring_arg = monitoring
        self._monitor = None
        #: per-batch causal tracing opt-in (mirrors monitoring=): None =
        #: consult WF_TRACE; resolved at start(). Trace ids are minted per
        #: (root stream, offered position) — deterministic, so the supervised
        #: driver replays identical ids after a restore.
        self._trace_arg = trace
        self._tracer = None
        # id(pipe) -> "pipe<i>", built lazily by whichever thread first
        # needs a label; concurrent rebuilds produce the IDENTICAL dict
        # (pure function of the pipe list), so last-writer-wins is benign
        self._trace_labels = None     # wf-lint: single-writer[driver, stage]
        #: control-plane opt-in (mirrors monitoring=/faults=): None = consult
        #: WF_CONTROL; resolved at start(). Admission control gates every
        #: source loop; the backpressure governor throttles the threaded
        #: driver's sources on SPSC ring watermarks.
        self._control_arg = control
        self._control = None
        #: SPSC ring capacity for the threaded driver's dataflow edges: one
        #: int for all, a dict keyed by edge label ("src->2", "0->1", by
        #: consumer pipe index), or a callable (label, index) -> int.
        self.queue_capacity = queue_capacity
        #: scan-dispatch opt-in (mirrors monitoring=/control=): None =
        #: consult WF_DISPATCH; resolved at start(). The push driver buffers
        #: root batches in arrival order, fuses each root's run as one
        #: compiled scan (up to K), and delivers outputs in the original
        #: interleave — downstream split/merge hops stay per-batch, in the
        #: per-batch order.
        self._dispatch_arg = dispatch
        # resolved by start() on the driver before any body thread spawns
        self._dispatch = None         # wf-lint: single-writer[driver]
        self._e2e_t0 = None           # in-flight e2e latency sample start
        # graph build is driver-only; bodies and the reporter only iterate
        self._roots: List[MultiPipe] = []  # wf-lint: single-writer[driver]
        self._merged_roots: List[MultiPipe] = []
        self._nodes = {}
        self._operators: List[Basic_Operator] = []
        self._started = False
        self._ended = False
        self._exhausted = set()       # pipe ids whose inputs are known complete

    # -- reference surface ------------------------------------------------------------

    def add_source(self, source: SourceBase) -> MultiPipe:
        if self._started:
            raise RuntimeError("graph already running")
        source._mark_used()
        self._register(source)
        mp = MultiPipe(self, source)
        self._roots.append(mp)
        node = AppNode(mp)
        self._nodes[id(mp)] = node
        return mp

    def run(self, threaded: bool = False):
        """Drive the graph to completion. ``threaded=True`` gives each MultiPipe its
        own host thread connected by native SPSC rings — true pipeline parallelism
        across segments (the reference's thread-per-node model at segment
        granularity, ``wf/pipegraph.hpp:1522-1533``)."""
        self.start()
        if threaded:
            return self._run_threaded()
        return self.wait_end()

    def start(self):
        if self.batch_size is None:
            from .pipeline import resolve_batch_hint
            self.batch_size = (resolve_batch_hint(self._operators)
                               or DEFAULT_BATCH_SIZE)
        self._started = True
        if self._monitor is None:
            from ..observability import Monitor, MonitoringConfig
            cfg = MonitoringConfig.resolve(self._monitoring_arg)
            if cfg is not None:
                self._monitor = Monitor(cfg, self.name)
                self._monitor.registry.register_graph(self)
                self._monitor.start()
        if self._control is None:
            from ..control import ControlConfig
            self._control = ControlConfig.resolve(self._control_arg)
        if self._dispatch is None:
            from .dispatch import DispatchConfig
            self._dispatch = DispatchConfig.resolve(self._dispatch_arg)
        if self._tracer is None:
            from ..observability import TraceConfig, Tracer
            tcfg = TraceConfig.resolve(self._trace_arg)
            if tcfg is not None:
                self._tracer = Tracer(tcfg, self.name).start()

    def _trace_label(self, mp) -> str:
        """Flight-recorder stage label of one pipe (stable pipe index)."""
        if self._trace_labels is None or id(mp) not in self._trace_labels:
            self._trace_labels = {id(p): f"pipe{i}"
                                  for i, p in enumerate(self._all_pipes())}
        return self._trace_labels.get(id(mp), "pipe?")

    def _make_admissions(self, driver: str):
        """Per-source admission controllers over ONE shared token bucket
        (total-ingest rate limit, per-source holding cells), keyed by root
        pipe id. Every value is None when admission is off."""
        from ..control import admission_group
        group = admission_group(self._control, self.batch_size,
                                len(self._roots), driver=driver)
        return {id(mp): adm for mp, adm in zip(self._roots, group)}

    def run_supervised(self, *, checkpoint_every: int = 8,
                       max_restarts: int = 3, **hardening):
        """Supervised execution of the whole DAG: aligned checkpoints, replay
        from the committed positions on failure, exactly-once delivery on every
        sink (``runtime/supervisor.py::run_graph_supervised``; the reference's
        failure model is exit(EXIT_FAILURE), SURVEY §5). ``hardening`` forwards
        the recovery knobs: ``backoff_base``/``backoff_cap`` (decorrelated-
        jitter restart backoff), ``dead_letter``/``poison_threshold``
        (poison-batch quarantine), ``step_timeout`` (hung-step watchdog),
        ``faults`` (a FaultPlan/FaultInjector for chaos testing)."""
        from .supervisor import run_graph_supervised
        return run_graph_supervised(self, checkpoint_every=checkpoint_every,
                                    max_restarts=max_restarts, **hardening)

    # -- threaded driver --------------------------------------------------------------

    def _iter_edges(self):
        """Dataflow edges of the threaded driver, in ring-creation order:
        yields ``(producer, consumer, label, index)`` — ``producer`` None for
        source-ingest edges. THE single enumeration, consumed by
        ``_run_threaded`` (ring creation) and ``analysis.validate`` (pre-run
        capacity/watermark checks) — edge labels are minted nowhere else, so
        the validator can never check rings the driver does not build."""
        pipes = self._all_pipes()
        pipe_idx = {id(p): i for i, p in enumerate(pipes)}
        n = 0
        for p in pipes:
            if p.source is not None:
                yield None, p, f"src->{pipe_idx[id(p)]}", n
                n += 1
            for b in p.split_branches:
                yield p, b, f"{pipe_idx[id(p)]}->{pipe_idx[id(b)]}", n
                n += 1
            for m in p._outputs_to:
                yield p, m, f"{pipe_idx[id(p)]}->{pipe_idx[id(m)]}", n
                n += 1

    def _run_threaded(self):
        import threading
        from ..native import SPSCQueue

        pipes = self._all_pipes()
        pipe_idx = {id(p): i for i, p in enumerate(pipes)}
        EOS = object()
        # one SPSC ring per dataflow EDGE (single producer, single consumer); a
        # consumer with several inputs (merge) polls its rings round-robin
        in_queues = {id(p): [] for p in pipes}
        out_edges = {}                           # (producer id, consumer id) -> queue
        channel_of = {}                          # queue id -> merge channel index
        edge_label = {}                          # queue id -> edge label (tracing)
        from .threaded import _resolve_edge_capacity
        from ..control import governor_from_config
        governor = governor_from_config(self._control)
        admissions = self._make_admissions("graph-threaded")

        for prod, dst, label, index in self._iter_edges():
            cap = _resolve_edge_capacity(self.queue_capacity, label, index)
            q = SPSCQueue(cap)
            in_queues[id(dst)].append(q)
            out_edges[("src" if prod is None else id(prod), id(dst))] = q
            edge_label[id(q)] = label
            if self._monitor is not None:
                # live ring-depth gauge per dataflow edge: depth near capacity
                # = backpressure, the consumer pipe is the bottleneck
                self._monitor.registry.attach_queue_gauge(label, q.size,
                                                          capacity=cap)
            if governor is not None:
                governor.watch(label, q.size, cap)
            if prod is not None and dst.merge_inputs:
                channel_of[id(q)] = dst.merge_inputs.index(prod)
        errors = []

        def deliver(mp, out):
            if mp.sink is not None:
                mp.sink.consume(out)
            if mp.split_fn is not None:
                sel = jax.vmap(mp.split_fn)(tuple_refs(out))
                for i, branch in enumerate(mp.split_branches):
                    if getattr(sel, "ndim", 1) == 2:
                        keep = sel[:, i].astype(jnp.bool_)
                    else:
                        keep = jnp.asarray(sel, jnp.int32) == i
                    q = out_edges[(id(mp), id(branch))]
                    masked = out.mask(keep)
                    _tracing.carry(out, masked)
                    _tracing.event(masked, edge_label[id(q)], "enq")
                    q.push(masked)
            for merged in mp._outputs_to:
                q = out_edges[(id(mp), id(merged))]
                _tracing.event(out, edge_label[id(q)], "enq")
                q.push(out)

        def propagate_eos(mp):
            from ..observability import journal as _journal
            _journal.record("eos_propagate", graph=self.name,
                            pipe=pipe_idx[id(mp)])
            for branch in mp.split_branches:
                out_edges[(id(mp), id(branch))].push(EOS)
            for merged in mp._outputs_to:
                out_edges[(id(mp), id(merged))].push(EOS)

        def pipe_body(mp):
            # DETERMINISTIC merges go through the SAME Ordering_Node as the push
            # driver — cross-channel low-watermark holdback, not per-batch sorting
            onode = (self._ordering_of(mp)
                     if self.mode == Mode.DETERMINISTIC and mp.merge_inputs
                     else None)
            # scan dispatch: each pipe thread gathers up to K same-capacity
            # batches (ThreadedPipeline's segment shape — bounded linger when
            # its rings run dry) and runs them as ONE compiled scan; ordering
            # releases flow through the same accumulator, a capacity switch
            # between chunk shapes flushing the buffered run short
            acc = None
            if self._dispatch is not None and self._dispatch.k > 1:
                from .dispatch import MicrobatchAccumulator
                # per-pipe-thread accumulator: no global linger gauge (the
                # threaded.py convention — N threads would stomp it)
                acc = MicrobatchAccumulator(self._dispatch.k,
                                            self._dispatch.linger_s,
                                            publish_gauge=False)
            from .dispatch import fused_push

            def run_group(group):
                chain = mp._compile(group[0].capacity)
                for out in fused_push(chain, group, self._trace_label(mp)):
                    deliver(mp, out)

            def run_batch(item):
                if acc is None:
                    run_group([item])
                else:
                    for g in acc.feed(item):
                        run_group(g)

            live = list(in_queues[id(mp)])
            try:
                while live:
                    for q in list(live):
                        ok, item = q.pop(spin=64, max_yields=0)
                        if not ok:
                            # ring dry: a lingering partial group goes out
                            # short rather than hold latency hostage
                            if acc is not None and acc.expired():
                                run_group(acc.take())
                            continue
                        if item is EOS:
                            live.remove(q)
                            if onode is not None and id(q) in channel_of:
                                rel = onode.close_channel(channel_of[id(q)])
                                for piece in self._chunks(
                                        rel, onode.last_release_count):
                                    run_batch(piece)
                            continue
                        if onode is not None and id(q) in channel_of:
                            _tracing.event(item, edge_label[id(q)], "deq")
                            rel = onode.push(channel_of[id(q)], item)
                            for piece in self._chunks(
                                    rel, onode.last_release_count):
                                run_batch(piece)
                        else:
                            _tracing.event(item, edge_label[id(q)], "deq")
                            run_batch(item)
                if onode is not None:
                    for piece in self._chunks(onode.flush(),
                                              onode.last_release_count):
                        run_batch(piece)
                if acc is not None:
                    tail = acc.drain()          # partial tail < K at EOS
                    if tail:
                        run_group(tail)
                if mp._chain is not None:
                    for out in mp._chain.flush():
                        deliver(mp, out)
                if mp.sink is not None:
                    mp.sink.consume(None)
            except BaseException as e:          # noqa: BLE001 — re-raised at join
                errors.append(e)
                if governor is not None:
                    governor.stop()     # a throttled source must not wait on
                                        # a ring this dead pipe will drain
                # drain the remaining input rings to EOS so upstream producers
                # blocked on a full ring behind this dead pipe can finish and
                # send their own EOS (otherwise the join above deadlocks)
                from . import faults as _faults
                for q in list(live):
                    if _faults.drain_queue_to_sentinel(q, EOS):
                        live.remove(q)
            finally:
                propagate_eos(mp)

        def source_body(mp):
            from .pipeline import record_source_launch
            q = out_edges[("src", id(mp))]
            adm = admissions.get(id(mp))
            stream = self._roots.index(mp)
            try:
                n = 0
                for batch in mp.source.batches(self.batch_size):
                    record_source_launch(mp.source, batch)
                    _tracing.ingest(batch, n, stream=stream)
                    admitted = (batch,) if adm is None else adm.offer(
                        batch, pos=n, stream=stream)
                    for ab in admitted:
                        if governor is not None:
                            governor.throttle()
                        _tracing.event(ab, edge_label[id(q)], "enq")
                        q.push(ab)
                    n += 1
                if adm is not None:
                    for ab in adm.drain():
                        if governor is not None:
                            governor.throttle()
                        q.push(ab)
            except BaseException as e:          # noqa: BLE001
                errors.append(e)
            finally:
                q.push(EOS)

        try:
            threads = []
            for p in pipes:
                threads.append(threading.Thread(  # wf-lint: thread-role[stage]
                    target=pipe_body, args=(p,),
                    name=f"wf-pipe-{id(p) % 1000}"))
            for p in self._roots:
                threads.append(threading.Thread(  # wf-lint: thread-role[stage]
                    target=source_body, args=(p,), name="wf-src"))
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errors:
                raise errors[0]
            for p in pipes:
                if p._chain is not None:
                    p._chain.sync_stats()
            for op in self._operators:
                op.close()            # closing_func per replica (svc_end parity)
            self._ended = True
            return self._results()
        finally:
            if governor is not None:
                governor.stop()
            if self._tracer is not None:
                self._tracer.finish()
            if self._monitor is not None:
                self._monitor.finish(self)

    def wait_end(self):
        """Drive the whole DAG to completion (the reference joins threads here,
        ``wf/pipegraph.hpp:1058-1105``; our driver is a host push loop)."""
        if self._ended:
            return self._results()
        if not self._started:
            self.start()              # resolves batch_size from withBatch hints
        import time as _time
        from .pipeline import record_source_launch
        from ..observability import journal as _journal
        try:
            admissions = self._make_admissions("graph")
            sources = [(mp, mp.source.batches(self.batch_size))
                       for mp in self._roots]
            live = list(sources)
            round_robin_pos = 0
            n_pushed = 0
            # trace ids are minted per (root stream, per-root offered
            # position) — the same coordinates the supervised driver replays
            root_idx = {id(mp): i for i, mp in enumerate(self._roots)}
            offered = {id(mp): 0 for mp in self._roots}
            # scan dispatch: batches buffer in ARRIVAL order across ALL
            # roots and flush together the moment any root holds K — each
            # root's run dispatches as ONE fused scan, but outputs deliver
            # in the original round-robin interleave, so every downstream
            # merge sees byte-identically the per-batch arrival order. (A
            # per-root flush would reorder the merged stream: K batches of
            # root a would land before the interleaved batches of root b.)
            # The pull loop is synchronous — no linger; a partial run only
            # exists at a flush triggered by a sibling root or at EOS.
            dk = (self._dispatch.k
                  if self._dispatch is not None and self._dispatch.k > 1
                  else 0)
            from ..control import _state as _cstate
            buf = []          # (mp, batch, e2e t0 | None) in arrival order
            buf_n = {}        # root id -> batches buffered

            def flush_buf():
                if not buf:
                    return
                outs = {}
                for mp2 in self._roots:
                    run = [b for m, b, _ in buf if m is mp2]
                    if run:
                        outs[id(mp2)] = iter(self._compute_many(mp2, run, dk))
                for m, b, t0 in buf:
                    self._e2e_t0 = t0
                    self._deliver(m, next(outs[id(m)]))
                    self._e2e_t0 = None
                buf.clear()
                buf_n.clear()
                _cstate.set_gauge("dispatch_linger_depth", 0)

            def ingest(mp, ab, sampled):
                if not dk:
                    if sampled:
                        # e2e latency sample: source framing -> first sink's
                        # host receipt (recorded in _deliver after consume)
                        self._e2e_t0 = _time.perf_counter()
                    self._push(mp, ab)
                    self._e2e_t0 = None
                    return
                buf.append((mp, ab,
                            _time.perf_counter() if sampled else None))
                buf_n[id(mp)] = buf_n.get(id(mp), 0) + 1
                _cstate.set_gauge("dispatch_linger_depth", len(buf))
                if buf_n[id(mp)] >= dk:
                    flush_buf()

            while live:
                mp, it = live[round_robin_pos % len(live)]
                try:
                    batch = next(it)
                except StopIteration:
                    live.remove((mp, it))
                    adm = admissions.get(id(mp))
                    if adm is not None:
                        for ab in adm.drain():  # bounded held tail
                            ingest(mp, ab, False)
                    # buffered batches (every root's) must land before this
                    # root's chain flushes downstream
                    flush_buf()
                    self._exhaust(mp)
                    continue
                record_source_launch(mp.source, batch)
                opos = offered[id(mp)]
                _tracing.ingest(batch, opos, stream=root_idx[id(mp)])
                offered[id(mp)] += 1
                adm = admissions.get(id(mp))
                # shed journal coordinates = (stream, per-root offered pos),
                # the same coordinates trace ids are minted from — wf_trace's
                # report joins shed events to traced batches on them
                admitted = (batch,) if adm is None else adm.offer(
                    batch, pos=opos, stream=root_idx[id(mp)])
                round_robin_pos += 1
                for ab in admitted:
                    sampled = (self._monitor is not None
                               and self._monitor.config.should_sample_e2e(
                                   n_pushed))
                    ingest(mp, ab, sampled)
                    n_pushed += 1
            # EOS: flush every pipe in topological order; a merged pipe first
            # drains its Ordering_Node (tuples held back by the low-watermark)
            pipe_idx = {id(p): i for i, p in enumerate(self._all_pipes())}
            for mp in self._topo_order():
                _journal.record("eos_flush", graph=self.name,
                                pipe=pipe_idx.get(id(mp)))
                if mp._ordering is not None:
                    for piece in self._chunks(mp._ordering.flush(),
                                              mp._ordering.last_release_count):
                        self._push(mp, piece)
                self._flush_pipe(mp)
            for mp in self._all_pipes():
                if mp.sink is not None:
                    mp.sink.consume(None)
            for mp in self._all_pipes():
                if mp._chain is not None:
                    mp._chain.sync_stats()
            for op in self._operators:
                op.close()            # closing_func per replica (svc_end parity)
            self._ended = True
            return self._results()
        finally:
            if self._tracer is not None:
                self._tracer.finish()
            if self._monitor is not None:
                self._monitor.finish(self)

    def getNumThreads(self) -> int:
        """API parity: total replicas across operators (the reference counts OS
        threads; ours are logical shards, wf/pipegraph.hpp:1025-1053 banner)."""
        return sum(op.getParallelism() for op in self._operators)

    def listOperators(self) -> List[Basic_Operator]:
        return list(self._operators)

    def dump_stats(self, log_dir: str = "log"):
        """Dump every operator's Stats_Record to ``log/`` (TRACE_WINDFLOW analogue,
        ``wf/stats_record.hpp:109-155``). Returns the written paths."""
        paths = []
        for op in self._operators:
            for rec in op.get_StatsRecords():
                paths.append(rec.dump_to_file(log_dir))
        return paths

    def dump_DOTGraph(self, path: str = None) -> str:
        """Graphviz dump (GRAPHVIZ_WINDFLOW, wf/pipegraph.hpp:226-237,1450-1518)."""
        lines = ["digraph PipeGraph {", "  rankdir=LR;"]
        def op_label(o):
            # chained (queue-free fused) ops render bare; routed adds carry
            # their routing mode — the reference's gv_chain_vertex vs
            # add-vertex distinction (wf/pipegraph.hpp:1450-1518)
            if o._chained:
                return f"{o.getName()} (chained)"
            mode = o.getRoutingMode().name.lower()
            return (o.getName() if mode in ("forward", "none")
                    else f"{o.getName()} ({mode})")
        def label(mp, idx):
            ops = " | ".join(op_label(o) for o in mp.ops) or "(empty)"
            src = f"{mp.source.getName()} -> " if mp.source else ""
            snk = f" -> {mp.sink.getName()}" if mp.sink else ""
            return f'  mp{idx} [shape=record, label="{src}{ops}{snk}"];'
        pipes = self._all_pipes()
        index = {id(p): i for i, p in enumerate(pipes)}
        for i, p in enumerate(pipes):
            lines.append(label(p, i))
        for p in pipes:
            for b in p.split_branches:
                lines.append(f"  mp{index[id(p)]} -> mp{index[id(b)]} [label=split];")
            for m in p._outputs_to:
                lines.append(f"  mp{index[id(p)]} -> mp{index[id(m)]} [label=merge];")
        lines.append("}")
        dot = "\n".join(lines)
        if path:
            with open(path, "w") as f:
                f.write(dot)
        return dot

    # -- driver internals -------------------------------------------------------------

    def _register(self, op):
        self._operators.append(op)

    def _node_of(self, mp) -> AppNode:
        return self._nodes[id(mp)]

    def _all_pipes(self) -> List[MultiPipe]:
        out, seen = [], set()
        def visit(mp):
            if id(mp) in seen:
                return
            seen.add(id(mp))
            out.append(mp)
            for b in mp.split_branches:
                visit(b)
            for m in mp._outputs_to:
                visit(m)
        for r in self._roots:
            visit(r)
        return out

    def _topo_order(self) -> List[MultiPipe]:
        """Upstream-before-downstream order for EOS flushing."""
        order, seen = [], set()
        def visit(mp):
            if id(mp) in seen:
                return
            seen.add(id(mp))
            for up in mp.merge_inputs:
                visit(up)
            if mp._dataflow_parent is not None:
                visit(mp._dataflow_parent)
            order.append(mp)
        for p in self._all_pipes():
            visit(p)
        return order

    def _push(self, mp: MultiPipe, batch: Batch):
        """Push one batch through mp's chain and onward through split/merge edges."""
        chain = mp._compile(batch.capacity)
        tr = _tracing.get_active()
        span = tr.service(batch, self._trace_label(mp)) if tr is not None \
            else None
        out = chain.push(batch)
        if span is not None:
            span.done()
            _tracing.carry(batch, out)
        self._deliver(mp, out)

    def _compute_many(self, mp: MultiPipe, batches, k: int):
        """Outputs for a buffered run of mp's batches WITHOUT delivering:
        same-capacity runs of up to ``k`` dispatch as ONE compiled scan
        (``CompiledChain.push_many``), singletons as today's per-batch push
        — byte-identical to len(batches) sequential :meth:`_push` computes,
        per-batch trace spans synthesized from each fused launch in batch
        order. The caller interleaves delivery with its sibling roots'
        outputs so downstream merge order is untouched."""
        from .dispatch import MicrobatchAccumulator, fused_push
        acc = MicrobatchAccumulator(max(int(k), 1), publish_gauge=False)
        groups = []
        for b in batches:
            groups += acc.feed(b)
        if len(acc):
            groups.append(acc.drain())
        outs = []
        for g in groups:
            outs += fused_push(mp._compile(g[0].capacity), g,
                               self._trace_label(mp))
        return outs

    def _ordering_of(self, merged: MultiPipe):
        """Per-merge Ordering_Node (DETERMINISTIC mode): holds tuples back to the
        low-watermark over the merge's input channels — the reference inserts the
        node before each replica the same way (wf/pipegraph.hpp:1197-1248).
        Count-based windows downstream of the merge get TS_RENUMBERING (the
        reference's broadcast+renumbering case, wf/pipegraph.hpp:1954-1957,
        wf/ordering_node.hpp:218,257) so released tuples carry progressive ids."""
        if merged._ordering is None:
            from ..basic import ordering_mode_t
            from ..parallel.ordering import Ordering_Node
            cb_downstream = any(
                getattr(getattr(op, "spec", None), "is_cb", False)
                for op in merged.ops)
            mode = (ordering_mode_t.TS_RENUMBERING if cb_downstream
                    else ordering_mode_t.TS)
            merged._ordering = Ordering_Node(len(merged.merge_inputs), mode)
        return merged._ordering

    def _chunks(self, batch: Optional[Batch], n: Optional[int] = None,
                compact: bool = False):
        """Re-slice a released (variable-capacity) batch into batch_size-capacity
        pieces so downstream chains keep ONE compiled shape. ``n`` (the
        valid-lane count) can be passed by callers that already fetched it —
        Ordering_Node releases carry ``last_release_count`` — to avoid a second
        device sync. Ordering_Node releases are prefix-compacted by
        construction (the sorted-pool release is a physical prefix), so the
        default skips the compaction sort; pass ``compact=True`` for batches
        whose live lanes may be scattered."""
        import numpy as np
        if batch is None:
            return
        b = batch.compact() if compact else batch
        if n is None:
            n = int(np.asarray(jnp.sum(b.valid)))
        cap = self.batch_size
        for s in range(0, n, cap):
            def cut(a):
                seg = a[s:s + cap]
                pad = cap - seg.shape[0]
                if pad:
                    seg = jnp.pad(seg, [(0, pad)] + [(0, 0)] * (seg.ndim - 1))
                return seg
            yield Batch(key=cut(b.key), id=cut(b.id), ts=cut(b.ts),
                        payload=jax.tree.map(cut, b.payload), valid=cut(b.valid))

    def _deliver(self, mp: MultiPipe, out: Batch):
        if mp.sink is not None:
            mp.sink.consume(out)
            if self._e2e_t0 is not None and self._monitor is not None:
                import time as _time
                self._monitor.registry.record_e2e(
                    _time.perf_counter() - self._e2e_t0,
                    exemplar=_tracing.tid_of(out))
                self._e2e_t0 = None    # one sample per sampled source batch
        if mp.split_fn is not None:
            self._push_split(mp, out)
        for merged in mp._outputs_to:
            if self.mode == Mode.DETERMINISTIC:
                onode = self._ordering_of(merged)
                rel = onode.push(merged.merge_inputs.index(mp), out)
                for piece in self._chunks(rel, onode.last_release_count):
                    self._push(merged, piece)
            else:
                self._push(merged, out)

    def _push_split(self, mp: MultiPipe, out: Batch):
        n = len(mp.split_branches)
        fn = mp.split_fn
        sel = jax.vmap(fn)(tuple_refs(out))
        for i, branch in enumerate(mp.split_branches):
            if getattr(sel, "ndim", 1) == 2:           # multicast mask [C, n]
                keep = sel[:, i].astype(jnp.bool_)
            else:
                keep = jnp.asarray(sel, jnp.int32) == i
            masked = out.mask(keep)
            _tracing.carry(out, masked)     # mask() builds a new Batch — the
            #                                 trace sidecar must follow it
            self._push(branch, masked)

    def _check_merge_legality(self, pipes):
        """The reference's merge rules (``wf/pipegraph.hpp:813-965,2992-3026``).

        Entry checks: >=2 distinct pipes, all members of this graph, none already
        merged into another pipe, split, or terminated by a sink. Structural
        cases: merge-ind (independent roots), merge-full (a whole split subtree,
        collapsed bottom-up like ``get_MergedNodes1``), merge-partial (siblings
        under one split parent, CONTIGUOUS branch indexes —
        ``get_MergedNodes2`` + the adjacency check at ``:903-910``)."""
        if len(pipes) < 2:
            raise RuntimeError(
                "merge must be applied to at least two MultiPipe instances "
                "(wf/pipegraph.hpp:2996-2999)")
        if len({id(p) for p in pipes}) != len(pipes):
            raise RuntimeError("a MultiPipe cannot be merged with itself "
                               "(wf/pipegraph.hpp:3003-3008)")
        for p in pipes:
            if id(p) not in self._nodes:
                raise RuntimeError("MultiPipe to be merged does not belong to "
                                   "this PipeGraph (wf/pipegraph.hpp:673-676)")
            if p._outputs_to:
                raise RuntimeError("MultiPipe has already been merged "
                                   "(application-tree leaf check, "
                                   "wf/pipegraph.hpp:678)")
            if p.split_fn is not None:
                raise RuntimeError("a split MultiPipe cannot be merged — merge "
                                   "its branches (wf/pipegraph.hpp:678)")
            if p.has_sink:
                raise RuntimeError("a MultiPipe with a sink has no output to "
                                   "merge")
        # Structural classification over the APPLICATION tree (not the dataflow
        # graph): each work item covers a set of branch indexes under its
        # app-tree parent — a split branch covers its own index; a partial-merge
        # result covers the indexes of the branches it absorbed (the reference
        # re-parents the merged AppNode under the split parent,
        # wf/pipegraph.hpp:944-952). Collapse bottom-up: whenever items under
        # one parent cover ALL its branches, they become that parent
        # (get_MergedNodes1's subtree-covering walk).
        def cover_of(p):
            """(app-tree parent, covered branch-index set) — (None, None) = root."""
            if p._merge_parent is not None:
                return p._merge_parent, set(p._covers_idx)
            par = p._dataflow_parent
            if par is None:
                return None, None
            return par, {next(i for i, b in enumerate(par.split_branches)
                              if b is p)}

        work = list(pipes)
        changed = True
        while changed:
            changed = False
            by_parent: dict = {}
            for p in work:
                par, idxs = cover_of(p)
                if par is not None:
                    key = id(par)
                    by_parent.setdefault(key, (par, []))[1].append((p, idxs))
            for par, items in by_parent.values():
                covered = set().union(*(i for _, i in items))
                if covered == set(range(len(par.split_branches))):
                    drop = {id(p) for p, _ in items}
                    work = [w for w in work if id(w) not in drop] + [par]
                    changed = True
                    break
        covers = [cover_of(w) for w in work]
        if all(par is None for par, _ in covers):
            # merge-ind (len>1) or merge-full (collapsed to one root)
            return None, ()
        if any(par is None for par, _ in covers):
            raise RuntimeError("the requested merge operation is not supported: "
                               "mixed roots and split branches "
                               "(wf/pipegraph.hpp:963-965)")
        if len({id(par) for par, _ in covers}) != 1:
            raise RuntimeError("the requested merge operation is not supported: "
                               "branches of different split parents "
                               "(wf/pipegraph.hpp:963-965)")
        par = covers[0][0]
        idxs = sorted(set().union(*(i for _, i in covers)))
        if idxs != list(range(idxs[0], idxs[0] + len(idxs))):
            raise RuntimeError("sibling MultiPipes to be merged must be "
                               "contiguous branches of the same MultiPipe "
                               "(wf/pipegraph.hpp:903-910)")
        # merge-partial: the result pipe takes this position in the app tree
        return par, tuple(idxs)

    def _exhaust(self, mp: MultiPipe):
        """A pipe's inputs are complete: flush its chain now, close its channels
        into DETERMINISTIC merge Ordering_Nodes (a frozen watermark must not gate —
        or hoard — the surviving channels, cf. close_channel), and cascade to
        consumers whose every input is now exhausted. Keeps Ordering_Node memory
        bounded when merge inputs are unbalanced."""
        if id(mp) in self._exhausted:
            return
        self._exhausted.add(id(mp))
        self._flush_pipe(mp)
        for branch in mp.split_branches:
            self._exhaust(branch)
        for merged in mp._outputs_to:
            if self.mode == Mode.DETERMINISTIC:
                onode = self._ordering_of(merged)
                rel = onode.close_channel(merged.merge_inputs.index(mp))
                for piece in self._chunks(rel, onode.last_release_count):
                    self._push(merged, piece)
            if all(id(p) in self._exhausted for p in merged.merge_inputs):
                self._exhaust(merged)

    def _flush_pipe(self, mp: MultiPipe):
        if mp._chain is None:
            return
        for out in mp._chain.flush():
            self._deliver(mp, out)

    def _results(self):
        res = {}
        for mp in self._all_pipes():
            if mp._chain is not None:
                res.update(mp._chain.result())
        return res
