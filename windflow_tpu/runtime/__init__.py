from .pipeline import CompiledChain, Pipeline
from ..stats import Stats_Record

__all__ = ["CompiledChain", "Pipeline", "Stats_Record"]
