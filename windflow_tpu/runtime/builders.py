"""Fluent builders — construction with build-time signature checking.

Counterpart of ``wf/builders.hpp`` (13 CPU builders, ``:42-2195``) and
``wf/builders_gpu.hpp`` (8 GPU builders with ``withBatch``/``withGPU``, ``:44-1433``).
Common methods mirror the reference: ``withName``, ``withParallelism``,
``enable_KeyBy``, ``withCBWindows``, ``withTBWindows``, ``withLateness``, ``withOpt``,
``withBatch``; terminal ``build()`` returns the operator (``build_ptr``/``build_unique``
aliases for API parity, ``wf/builders.hpp:583-643``). Signature validation happens at
``build()`` via ``meta.classify_*`` — ill-formed user callables fail at graph-build
time with the accepted-signature list, like the reference's static_asserts
(``wf/builders.hpp:56-58``).

Device parameters: the reference GPU builders take ``withBatch(batch_len)`` and
``withGPU(gpu_id, n_thread_block)`` (``wf/builders_gpu.hpp:67-130``); the TPU
equivalents are ``withBatch`` (micro-batch capacity hint) and ``withDevice(device)``
(a ``jax.Device``), plus ``withMaxWins``/``withArchive`` for window-engine sizing
(the scratchpad_size analogue).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax.numpy as jnp

from ..basic import opt_level_t, win_type_t, DEFAULT_MAX_KEYS
from ..operators.accumulator import Accumulator
from ..operators.filter import Filter, FilterMap
from ..operators.flatmap import FlatMap
from ..operators.map import Map, KeyedMap
from ..operators.sink import ReduceSink, Sink
from ..operators.source import DeviceSource, GeneratorSource
from ..operators.window import WindowSpec
from ..operators.win_patterns import (Key_Farm, Key_FFAT, Pane_Farm, Win_Farm,
                                      Win_MapReduce)
from ..operators.win_seq import Win_Seq
from ..operators.win_seqffat import Win_SeqFFAT


class _Builder:
    _cls: type = None

    def __init__(self, *fns):
        self._fns = fns
        self._kw: dict = {}
        self._batch_hint: Optional[int] = None
        self._device = None
        self._opt: Optional[opt_level_t] = None
        self._closing: Optional[Callable] = None

    def withName(self, name: str):
        self._kw["name"] = name
        return self

    def withParallelism(self, n: int):
        self._kw["parallelism"] = n
        return self

    def withOpt(self, level: opt_level_t):
        """Optimization level (wf/basic.hpp:92). XLA fuses chained stages
        unconditionally, so every level executes as LEVEL2; recorded on the
        operator for introspection parity."""
        self._opt = opt_level_t(level)
        return self

    def withBatch(self, batch_len: int):
        """Micro-batch capacity for this operator (reference GPU builders'
        ``withBatch(batch_len)``, wf/builders_gpu.hpp:115-122). Honored as a
        capacity CEILING by Pipeline/PipeGraph batch-size resolution: a fused
        chain runs at min over its operators' hints when no explicit
        batch_size is given."""
        if int(batch_len) < 1:
            raise ValueError(f"withBatch: batch_len must be >= 1, got {batch_len}")
        self._batch_hint = int(batch_len)
        return self

    def withDevice(self, device):
        """Place this operator's state on ``device`` (a ``jax.Device``) — the
        reference's ``withGPU(gpu_id, ...)`` device-selection half
        (wf/builders_gpu.hpp:123-130). The fused chain containing the operator
        executes on that device; conflicting hints inside one chain are a
        build-time error."""
        self._device = device
        return self

    def withClosingFunction(self, fn: Callable):
        """Host callback ``fn(RuntimeContext)`` run once per replica at teardown
        (reference closing_func at svc_end; wf/builders.hpp common methods)."""
        self._closing = fn
        return self

    def _construct(self):
        return self._cls(*self._fns, **self._kw)

    def build(self):
        op = self._construct()
        if self._closing is not None:
            op.closing_func = self._closing
        if self._batch_hint is not None:
            op._batch_hint = self._batch_hint
        if self._device is not None:
            op._device = self._device
        if self._opt is not None:
            op._opt_level = self._opt
        return op

    # C++ API parity aliases (wf/builders.hpp:583-643)
    build_ptr = build
    build_unique = build


class Source_Builder(_Builder):
    """``Source_Builder(f)`` with ``f(i) -> payload`` (+rich) — wf/builders.hpp:49."""
    _cls = DeviceSource

    def withTotal(self, total: int):
        self._kw["total"] = total
        return self

    def withKeys(self, num_keys: int, key_fn: Callable = None):
        self._kw["num_keys"] = num_keys
        if key_fn is not None:
            self._kw["key_fn"] = key_fn
        return self

    def withTimestamps(self, ts_fn: Callable):
        self._kw["ts_fn"] = ts_fn
        return self

    def _construct(self):
        if "total" not in self._kw:
            raise ValueError("Source_Builder: withTotal(n) is required")
        return DeviceSource(*self._fns, **self._kw)


class Filter_Builder(_Builder):
    """wf/builders.hpp:168; predicate ``f(t) -> bool`` (+rich)."""
    _cls = Filter

    def enable_KeyBy(self):
        self._kw["keyed"] = True
        return self


class Map_Builder(_Builder):
    """wf/builders.hpp:332; ``f(t) -> payload`` (+rich)."""
    _cls = Map

    def enable_KeyBy(self):
        self._kw["keyed"] = True
        return self


class FlatMap_Builder(_Builder):
    """wf/builders.hpp:494; ``f(t, shipper)`` (+rich)."""
    _cls = FlatMap

    def withMaxFanout(self, f: int):
        self._kw["max_fanout"] = f
        return self

    def _construct(self):
        if "max_fanout" not in self._kw:
            raise ValueError("FlatMap_Builder: withMaxFanout(F) is required (static "
                             "fan-out capacity makes 1:N XLA-static)")
        return FlatMap(*self._fns, **self._kw)


class Accumulator_Builder(_Builder):
    """wf/builders.hpp:653; ``value_fn(t)`` + associative combine."""
    _cls = Accumulator

    def withInitialValue(self, v):
        self._kw["init_value"] = v
        return self

    def withCombine(self, fn, identity=0):
        self._kw["combine"] = fn
        self._kw["identity"] = identity
        return self

    def withKeys(self, num_keys: int):
        self._kw["num_keys"] = num_keys
        return self


class _WinBuilder(_Builder):
    def __init__(self, *fns):
        super().__init__(*fns)
        self._win = None

    def withCBWindows(self, win_len: int, slide: int):
        self._win = WindowSpec(win_len, slide, win_type_t.CB)
        return self

    def withTBWindows(self, win_len: int, slide: int):
        self._win = WindowSpec(win_len, slide, win_type_t.TB,
                               self._win.delay if self._win else 0)
        return self

    def withLateness(self, delay: int):
        if self._win is None or self._win.is_cb:
            raise ValueError("withLateness applies to TB windows "
                             "(triggering_delay, wf/window.hpp:83-121)")
        self._win = WindowSpec(self._win.win_len, self._win.slide,
                               self._win.wtype, delay)
        return self

    def withKeys(self, num_keys: int):
        self._kw["num_keys"] = num_keys
        return self

    def withMaxWins(self, w: int):
        self._kw["max_wins"] = w
        return self

    def withArchive(self, capacity: int):
        self._kw["archive_capacity"] = capacity
        return self

    def prepare4Nesting(self):
        return self

    def _spec(self):
        if self._win is None:
            raise ValueError("window builder: call withCBWindows/withTBWindows first")
        return self._win


class WinSeq_Builder(_WinBuilder):
    """wf/builders.hpp:789; ``f(wid, iterable) -> result`` or incremental via
    ``withIncremental(init_acc)``."""
    def withIncremental(self, init_acc):
        self._kw["incremental"] = True
        self._kw["init_acc"] = init_acc
        return self

    def _construct(self):
        return Win_Seq(self._fns[0], self._spec(), **self._kw)


class WinSeqFFAT_Builder(_WinBuilder):
    """wf/builders.hpp:950; lift + combine (winLift/winComb)."""
    def withIdentity(self, identity):
        self._kw["identity"] = identity
        return self

    def _construct(self):
        lift, comb = self._fns
        return Win_SeqFFAT(lift, comb, spec=self._spec(), **self._kw)


def _nesting_kw(builder: str, win, kw) -> dict:
    """Nested builds take only withParallelism/withName — window geometry belongs
    to the inner pattern's builder (extra kwargs are rejected downstream by the
    ctor's nesting check, win_patterns._check_nesting_args)."""
    if win is not None:
        raise TypeError(
            f"{builder}(inner_pattern): nesting accepts only withParallelism/"
            f"withName — configure windows on the inner builder, not "
            f"withCB/TBWindows here")
    return kw


class WinFarm_Builder(_WinBuilder):
    """wf/builders.hpp:1120. Accepts a window function, or a built Pane_Farm /
    Win_MapReduce for the nesting ctors (``wf/win_farm.hpp:266-355``) — in that case
    the window spec comes from the inner pattern."""
    def _construct(self):
        inner = self._fns[0]
        if isinstance(inner, (Pane_Farm, Win_MapReduce)):
            return Win_Farm(inner, **_nesting_kw("WinFarm_Builder", self._win,
                                                 self._kw))
        return Win_Farm(inner, self._spec(), **self._kw)


class KeyFarm_Builder(_WinBuilder):
    """wf/builders.hpp:1343. Accepts a window function, or a built Pane_Farm /
    Win_MapReduce for the nesting ctors (``wf/key_farm.hpp:155-167``)."""
    def _construct(self):
        inner = self._fns[0]
        if isinstance(inner, (Pane_Farm, Win_MapReduce)):
            return Key_Farm(inner, **_nesting_kw("KeyFarm_Builder", self._win,
                                                 self._kw))
        return Key_Farm(inner, self._spec(), **self._kw)


class KeyFFAT_Builder(_WinBuilder):
    """wf/builders.hpp:1569."""
    def withIdentity(self, identity):
        self._kw["identity"] = identity
        return self

    def _construct(self):
        lift, comb = self._fns
        return Key_FFAT(lift, comb, spec=self._spec(), **self._kw)


class PaneFarm_Builder(_WinBuilder):
    """wf/builders.hpp:1755; plq_fn + wlq_fn."""
    def withPLQParallelism(self, n: int):
        self._kw["plq_parallelism"] = n
        return self

    def withWLQParallelism(self, n: int):
        self._kw["wlq_parallelism"] = n
        return self

    def _construct(self):
        self._kw.pop("parallelism", None)
        plq, wlq = self._fns
        return Pane_Farm(plq, wlq, self._spec(), **self._kw)


class WinMapReduce_Builder(_WinBuilder):
    """wf/builders.hpp:1975; map_fn + reduce_fn."""
    def withMapParallelism(self, n: int):
        self._kw["map_parallelism"] = n
        return self

    def _construct(self):
        self._kw.pop("parallelism", None)
        m, r = self._fns
        return Win_MapReduce(m, r, self._spec(), **self._kw)


class Sink_Builder(_Builder):
    """wf/builders.hpp:2195; host callback ``f(batch_view)`` (+rich)."""
    _cls = Sink

    def enable_KeyBy(self):
        self._kw["keyed"] = True
        return self


class ReduceSink_Builder(_Builder):
    _cls = ReduceSink

    def withCombine(self, fn, identity=0):
        self._kw["combine"] = fn
        self._kw["identity"] = identity
        return self


# TPU builder aliases: the reference ships parallel *_GPU builders
# (wf/builders_gpu.hpp:44-1433); here every operator IS the device operator, so the
# _TPU names alias the same builders (MapGPU_Builder:1433 analogue included).
MapTPU_Builder = Map_Builder
FilterTPU_Builder = Filter_Builder
WinSeqTPU_Builder = WinSeq_Builder
WinSeqFFATTPU_Builder = WinSeqFFAT_Builder
WinFarmTPU_Builder = WinFarm_Builder
KeyFarmTPU_Builder = KeyFarm_Builder
KeyFFATTPU_Builder = KeyFFAT_Builder
PaneFarmTPU_Builder = PaneFarm_Builder
WinMapReduceTPU_Builder = WinMapReduce_Builder
