"""Linear operator chain compiled to one XLA program + host run loop.

This is the execution core under MultiPipe: a chain of operators between shuffle-free
boundaries compiles into ONE jitted ``step(states, batch) -> (states, out_batch)``.
That is the TPU answer to the reference's two composition mechanisms at once:

- ``chain()`` / ``ff_comb`` fusion (``wf/pipegraph.hpp:1272-1318``): adjacent operators
  run with no queue hop — here they are *literally one program*, with XLA fusing the
  elementwise bodies (the optimization the reference can only approximate with
  ``ff_comb``).
- the GPU micro-batch overlap (``was_batch_started`` double buffering,
  ``wf/map_gpu_node.hpp:224-340``): JAX dispatch is async — the host loop builds/feeds
  batch N+1 while the device executes batch N; no explicit stream management needed.

EOS protocol: the source exhausts; then each stateful operator's ``flush`` drains
residual state (partial windows etc. — reference ``eosnotify``, ``wf/win_seq.hpp:468-529``)
and the flushed batches cascade through the *remaining* suffix of the chain. All flush
paths reuse the same compiled shapes (mask padding, never shape change).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Sequence

import jax

from ..basic import DEFAULT_BATCH_SIZE
from ..batch import Batch, stack_batches, unstack_batches
from ..observability import device_health as _dh
from ..observability import journal as _journal
from ..observability import tracing as _tracing
from . import dispatch as _dispatch
from ..operators.base import Basic_Operator
from ..operators.sink import ReduceSink, Sink
from ..operators.source import SourceBase


def resolve_batch_hint(ops) -> Optional[int]:
    """Smallest withBatch hint among ``ops`` (each hint is a per-operator
    capacity ceiling — reference GPU ``batch_len``, wf/builders_gpu.hpp:115-122 —
    and a fused chain cannot exceed any member's ceiling); None if no op
    carries a hint."""
    hints = [op._batch_hint for op in ops
             if getattr(op, "_batch_hint", None) is not None]
    return min(hints) if hints else None


def record_source_launch(source, batch: Batch) -> None:
    """Per-batch source-side stats: one launch + the H2D bytes the framed batch
    cost (a DeviceSource generates inside the compiled program — zero
    transfer). The SINGLE place H2D bytes are counted (wf/stats_record.hpp:
    76-80); every driver loop calls this as it pulls a batch from a source.
    Byte size is static per capacity — cached on the source after the first
    batch of each shape (the tree walk is driver-loop overhead otherwise)."""
    from ..operators.source import DeviceSource
    if isinstance(source, DeviceSource):
        hd = 0
    else:
        cache = getattr(source, "_nbytes_by_cap", None)
        if cache is None:
            cache = source._nbytes_by_cap = {}
        cap = batch.capacity
        hd = cache.get(cap)
        if hd is None:
            hd = cache[cap] = _batch_nbytes(batch)
    source.get_StatsRecords()[0].record_launch(hd_bytes=hd)


def _batch_nbytes(batch: Batch) -> int:
    """Static byte size of a batch from shapes/dtypes (no device access)."""
    total = 0
    for leaf in jax.tree.leaves(batch):
        size = 1
        for d in getattr(leaf, "shape", ()):
            size *= d
        total += size * jax.numpy.dtype(getattr(leaf, "dtype", "float32")).itemsize
    return total


def _health_sig(tree) -> str:
    """Shape/dtype/weak-type signature of a (possibly abstract) pytree —
    the compile-ledger cache key component.  Safe at trace time: tracers
    expose shape/dtype/weak_type without concretization."""
    parts = []
    for leaf in jax.tree.leaves(tree):
        parts.append(f"{getattr(leaf, 'shape', ())}/"
                     f"{getattr(leaf, 'dtype', '?')}"
                     + ("w" if getattr(leaf, "weak_type", False) else ""))
    return ";".join(parts)


# a chain instance is driven by exactly ONE thread — the pipeline driver,
# a segment thread (ThreadedPipeline), or a pipe body (threaded PipeGraph);
# states/_steps/counters are plain unlocked fields on that basis.  The
# reporter thread only READS (snapshot-time state readbacks tolerate
# observing the previous push's list reference — each element is an
# immutable pytree).  Recorded for the WF260 concurrency lint.
class CompiledChain:  # wf-lint: single-writer[driver, stage]
    """Compile ``ops`` (no source/sink) into suffix-runnable jitted programs.

    ``step_from(i)`` runs ops[i:] — used both for the main path (i=0) and for EOS
    flush cascades starting after operator i."""

    #: every Nth push is timed dispatch->completion (block_until_ready) and the
    #: sample recorded as the entry op's service time (wf/stats_record.hpp:76-80
    #: tracks per-svc service time; sampling keeps the async overlap intact on
    #: the other N-1 pushes)
    SERVICE_SAMPLE_EVERY = 16

    def __init__(self, ops: Sequence[Basic_Operator], in_spec: Any,
                 batch_capacity: int = None, event_time: bool = None):
        self.ops = list(ops)
        # event-time observability toggle (MonitoringConfig.event_time) —
        # GEOMETRY-BINDING: stateful operators add lateness histograms to
        # their state pytrees, so it must be known before init_state below.
        # None consults WF_MONITORING/WF_MONITORING_EVENT_TIME; the drivers
        # pass their own monitoring= resolution.  Off (the default) leaves
        # state and compiled programs byte-for-byte unchanged.
        if event_time is None:
            from ..observability import event_time_enabled
            event_time = event_time_enabled(None)
        self.event_time = bool(event_time)
        for op in self.ops:
            # set unconditionally: operator instances reused across chains
            # must not keep a previous chain's toggle (sticky True would
            # compile histograms into an off chain's state)
            op._event_time = self.event_time
        self._drop_synced = {}      # id(op) -> {kind: last journaled value}
        self.specs = [in_spec]          # specs[i] = input payload spec of ops[i]
        if batch_capacity is None:
            batch_capacity = resolve_batch_hint(self.ops)
        # withDevice placement (reference withGPU device selection,
        # wf/builders_gpu.hpp:123-130): the chain is ONE fused program, so one
        # device per chain — conflicting per-op hints are a build error.
        devs = {id(op._device): op._device for op in self.ops
                if getattr(op, "_device", None) is not None}
        if len(devs) > 1:
            names = ", ".join(f"{op.getName()}->{op._device}" for op in self.ops
                              if getattr(op, "_device", None) is not None)
            raise ValueError(
                f"conflicting withDevice hints inside one fused chain ({names}); "
                f"a CompiledChain executes as one XLA program on one device — "
                f"split the graph at the device boundary")
        self.device = next(iter(devs.values())) if devs else None
        cap = batch_capacity
        for op in self.ops:
            if cap is not None:
                op.bind_geometry(cap)
                cap = op.out_capacity(cap)
            self.specs.append(op.out_spec(self.specs[-1]))
        self.states = [op.init_state(self.specs[i]) for i, op in enumerate(self.ops)]
        if self.device is not None:
            self.states = [jax.device_put(s, self.device) for s in self.states]
        #: operators with tiered keyed state (state/tiered.py): their
        #: controllers' maintain runs after every push — the async
        #: HBM->host spill settle point. Empty (one falsy check per push)
        #: unless some operator was built with tiered= on.
        self._tier_ops = [j for j, op in enumerate(self.ops)
                          if op.tier_controllers()]
        self._steps = {}
        self._push_count = 0
        self._fused_count = 0       # push_many launches (scan dispatch)
        self._nbytes_cache = {}     # (from_op, in capacity) -> (in, out bytes)
        #: stage label for the health ledger's compile + device-time
        #: attribution (the flight-recorder stage convention): drivers
        #: overwrite it with their real stage name — ThreadedPipeline
        #: ``seg<i>``, PipeGraph ``pipe<i>``, Pipeline/supervised ``chain``
        self.label = "chain"

    def warm(self, capacity: int) -> None:
        """Trace + compile the full-chain step for ``capacity`` WITHOUT
        touching operator state: a functional dry-run on an all-invalid batch
        whose outputs are discarded (``step`` is pure, so the real states are
        untouched). jax.jit caches one executable per input shape, so after
        warming every rung of a capacity ladder the autotuner's switches pick
        cached executables — the hot path never pays a trace/compile."""
        b = Batch.empty(capacity, self.specs[0])
        if self.device is not None:
            b = jax.device_put(b, self.device)
        hl, t0c = self._health_begin("warm")
        self._step_fn(0)(tuple(self.states), b)
        self._health_end(hl, t0c, 0, "step", b)

    def reset_states(self) -> None:
        """Re-initialize every operator's state (supervised replay of a chain
        that did not exist at the last checkpoint)."""
        self.states = [op.init_state(self.specs[i])
                       for i, op in enumerate(self.ops)]
        if self.device is not None:
            self.states = [jax.device_put(s, self.device) for s in self.states]

    @property
    def out_spec(self):
        return self.specs[-1]

    def _step_fn(self, i: int):
        if i not in self._steps:
            def step(states, batch):
                # compile-ledger hook: this line runs at TRACE time only
                # (host side effect, zero equations in the program — the
                # compiled executable and the perf-gate pins are byte-for-
                # byte identical with the ledger on or off); one module-
                # attribute load + None check per trace when health is off
                hl = _dh.get_active()
                if hl is not None:
                    hl.note_trace(self.label, i, "step", _health_sig(batch),
                                  capacity=jax.tree.leaves(batch)[0].shape[0]
                                  if jax.tree.leaves(batch) else None)
                states = list(states)
                for j in range(i, len(self.ops)):
                    states[j], batch = self.ops[j].apply(states[j], batch)
                return tuple(states), batch
            self._steps[i] = jax.jit(step)
        return self._steps[i]

    def _scan_fn(self, i: int):
        """The scan-dispatch core: ONE jitted program running K consecutive
        batch steps via ``lax.scan`` over the per-op ``apply`` with operator
        states as carry. The body is the SAME per-batch step ``_step_fn``
        traces, so a fused launch is byte-identical to K sequential pushes;
        jax.jit caches one executable per stacked input shape — one trace,
        one executable per (K, capacity), one host dispatch per K batches."""
        key = ("scan", i)
        if key not in self._steps:
            def scan_step(states, stacked):
                # compile-ledger hook — trace-time only, in the OUTER fn
                # (lax.scan may trace `body` more than once; that is one
                # executable, so it must count as one compile)
                hl = _dh.get_active()
                if hl is not None:
                    leaves = jax.tree.leaves(stacked)
                    hl.note_trace(
                        self.label, i, "scan", _health_sig(stacked),
                        capacity=leaves[0].shape[1] if leaves else None,
                        k=leaves[0].shape[0] if leaves else None)

                def body(carry, batch):
                    carry = list(carry)
                    for j in range(i, len(self.ops)):
                        carry[j], batch = self.ops[j].apply(carry[j], batch)
                    return tuple(carry), batch
                return jax.lax.scan(body, tuple(states), stacked)
            self._steps[key] = jax.jit(scan_step)
        return self._steps[key]

    def warm_scan(self, k: int, capacity: int) -> None:
        """Trace + compile the K-fused scan executable for ``(k, capacity)``
        WITHOUT touching operator state (the :meth:`warm` discipline): the
        dispatch autotuner pre-warms every K rung so a rung switch on the hot
        path selects a cached executable, never a trace."""
        if k <= 1:
            return self.warm(capacity)
        b = Batch.empty(capacity, self.specs[0])
        if self.device is not None:
            b = jax.device_put(b, self.device)
        stacked = stack_batches([b] * int(k))
        hl, t0c = self._health_begin("warm_scan")
        self._scan_fn(0)(tuple(self.states), stacked)
        self._health_end(hl, t0c, 0, "scan", stacked)

    # -- runtime-health ledger (MonitoringConfig.health) --------------------

    def _health_begin(self, cause: str):
        """(ledger, t0) when the health ledger is active: arm the cause and
        the trace-count mark so :meth:`_health_end` can journal any compile
        this invocation triggers with its measured duration.  (None, 0.0)
        when health is off — the only off-path cost is this None check."""
        hl = _dh.get_active()
        if hl is None:
            return None, 0.0
        hl.set_cause(cause)
        return hl, time.perf_counter()

    def _health_end(self, hl, t0c: float, from_op: int, kind: str,
                    example) -> None:
        """Commit any trace notes the invocation parked: duration = the
        whole first call (trace + XLA compile + first execution — the
        honest number a user waits for), cost = AOT cost/memory analysis of
        the just-compiled program (suppressed re-lowering, so it cannot
        count as another compile)."""
        if hl is None:
            return
        pending = hl.take_pending()
        if not pending:
            return
        cost = self._health_cost(hl, from_op, kind, example)
        hl.commit_pending(time.perf_counter() - t0c, cost,
                          op=self.ops[from_op].getName() if self.ops else "",
                          notes=pending)

    def _health_cost(self, hl, from_op: int, kind: str, example) -> dict:
        """AOT cost-analysis flops/bytes + executable memory footprint of
        the program just compiled for (from_op, kind, example's shapes).
        One extra lowering on the health path only (``hl.cost_analysis``
        gates it); every failure degrades to an empty dict — the compile
        event then simply carries no cost columns."""
        if not hl.cost_analysis:
            return {}
        hl._suppress(True)
        try:
            fn = self._steps[from_op if kind == "step" else ("scan", from_op)]
            compiled = fn.lower(tuple(self.states), example).compile()
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            out = {}
            if ca.get("flops") is not None:
                out["flops"] = int(ca["flops"])
            if ca.get("bytes accessed") is not None:
                out["bytes_accessed"] = int(ca["bytes accessed"])
            ma = compiled.memory_analysis()
            if ma is not None:
                out["argument_bytes"] = int(ma.argument_size_in_bytes)
                out["output_bytes"] = int(ma.output_size_in_bytes)
                out["temp_bytes"] = int(ma.temp_size_in_bytes)
                out["code_bytes"] = int(ma.generated_code_size_in_bytes)
            return out
        except Exception:   # noqa: BLE001 — cost columns are best-effort,
            return {}       # backend-dependent telemetry; the compile event
            #                 itself (cause/key/duration) always lands
        finally:
            hl._suppress(False)

    # -- tiered keyed state (state/tiered.py) -------------------------------

    def _tier_maintain(self) -> None:
        """Per-push maintenance of every tiered operator: advance the async
        spill pipeline (start/consume ``copy_to_host_async`` copies, apply
        settled prefixes to the host stores, one cached clear executable
        when a prefix settled) + the compaction cadence. Called by
        ``push``/``push_many`` right after the state update — the cadence
        is therefore a pure function of stream position, so supervised
        replay re-walks it exactly."""
        for j in self._tier_ops:
            st = self.states[j]
            for t in self.ops[j].tier_controllers():
                st = t.maintain(st)
            self.states[j] = st

    def tier_settle(self) -> None:
        """Synchronously drain every tiered operator's spill outbox into
        its host store and drop in-flight copies — the pre-snapshot
        barrier (supervised snapshots settle first, so a checkpoint always
        captures a consistent (state, store) pair)."""
        for j in self._tier_ops:
            st = self.states[j]
            for t in self.ops[j].tier_controllers():
                st = t.settle(st)
            self.states[j] = st

    def tier_snapshot(self):
        """Host-memory copies of every tiered operator's cold tier (after
        :meth:`tier_settle` — callers settle first); None when no operator
        is tiered."""
        if not self._tier_ops:
            return None
        return {j: [t.manifest() for t in self.ops[j].tier_controllers()]
                for j in self._tier_ops}

    def tier_restore(self, snap) -> None:
        """Restore the cold tiers from a :meth:`tier_snapshot`; in-flight
        spill copies of the failed attempt are discarded (the restored
        device states still hold those rows in their outboxes — replay
        re-derives the spill)."""
        for j in self._tier_ops:
            ctls = self.ops[j].tier_controllers()
            mans = (snap or {}).get(j)
            for i, t in enumerate(ctls):
                if mans is not None and i < len(mans):
                    t.restore(mans[i])
                else:
                    t.discard_inflight()

    def tier_manifests(self) -> dict:
        """Flat ``{"tier<op>_<ctl>_<name>": np.ndarray}`` map of every cold
        tier — the checkpoint-file representation (``runtime/checkpoint.py``
        stores these beside the ``op<i>_leaf<j>`` state arrays, covered by
        the same per-array sha256)."""
        out = {}
        for j in self._tier_ops:
            for i, t in enumerate(self.ops[j].tier_controllers()):
                for k, v in t.manifest().items():
                    out[f"tier{j}_{i}_{k}"] = v
        return out

    def tier_restore_manifests(self, arrays: dict) -> None:
        """Restore cold tiers from checkpoint arrays (the
        :meth:`tier_manifests` layout). A checkpoint written before an
        operator was tiered simply has no ``tier*`` keys — the fresh empty
        store stands (the legacy grown-field stance of ``load_chain``)."""
        for j in self._tier_ops:
            for i, t in enumerate(self.ops[j].tier_controllers()):
                prefix = f"tier{j}_{i}_"
                man = {k[len(prefix):]: v for k, v in arrays.items()
                       if k.startswith(prefix)}
                if man:
                    t.restore(man)
                else:
                    t.discard_inflight()

    def state_footprints(self) -> dict:
        """Per-operator state-pytree footprint in bytes, from static
        shape/dtype metadata (the specs bound at construction — no device
        access, no sync).  THE memory-ledger row tiered state (ROADMAP 3)
        sizes its promotion/eviction against."""
        out: dict = {}
        for op, st in zip(self.ops, self.states):
            n = 0
            for leaf in jax.tree.leaves(st):
                size = 1
                for d in getattr(leaf, "shape", ()):
                    size *= d
                n += size * jax.numpy.dtype(
                    getattr(leaf, "dtype", "float32")).itemsize
            name = op.getName()
            out[name] = out.get(name, 0) + n
        return out

    def push_many(self, batches: Sequence[Batch],
                  from_op: int = 0) -> List[Batch]:
        """Run K same-capacity batches through ops[from_op:] as ONE compiled
        scan dispatch; updates states; returns the K out batches in order —
        byte-identical to K sequential :meth:`push` calls. Stats attribute
        the launch the way one fused program deserves: K batches counted per
        op, ONE kernel launch on the entry op. K = 1 degenerates to
        :meth:`push` (same executable, same sampling path)."""
        batches = list(batches)
        if len(batches) == 1:
            return [self.push(batches[0], from_op=from_op)]
        k = len(batches)
        stacked = stack_batches(batches)
        if self.device is not None:
            stacked = jax.device_put(stacked, self.device)
        # per-LAUNCH sampling (the push-path predicate over launch count):
        # every Nth fused dispatch is timed to completion, the other N-1 keep
        # the async queue full
        self._fused_count += 1
        c = self._fused_count
        sampled = ((c % self.SERVICE_SAMPLE_EVERY) == 0
                   or (1 < c < self.SERVICE_SAMPLE_EVERY
                       and (c & (c - 1)) == 0))
        hl, t0c = self._health_begin("push_many")
        t0 = time.perf_counter() if sampled else 0.0
        states, outs_stacked = self._scan_fn(from_op)(tuple(self.states),
                                                      stacked)
        if sampled:
            # device-time attribution (health): the dispatch call above
            # already returned asynchronously, so the split between "host
            # dispatch" and "device completion" is one extra perf_counter
            # on a path that pays a block_until_ready anyway
            t_disp = time.perf_counter()
            jax.block_until_ready(outs_stacked)
            t_done = time.perf_counter()
            service_s = t_done - t0
            # never attribute a launch that COMPILED (pending trace notes):
            # its "dispatch" span is trace+XLA time, and the sums never
            # decay — one such sample would mis-flag the stage forever
            if (hl is not None and not hl.has_pending()
                    and hl.service_sample()):
                hl.note_service(self.label, dispatch_s=t_disp - t0,
                                device_s=t_done - t_disp)
            if _journal.get_active() is not None:
                _journal.record(
                    "dispatch_fused",
                    op=self.ops[from_op].getName() if self.ops else "",
                    from_op=from_op, k=k, launch=c,
                    service_s=round(service_s, 6))
        else:
            service_s = None
        if hl is not None:
            # after the timed window, so the cost-analysis lowering of a
            # compile event can never inflate the service sample
            self._health_end(hl, t0c, from_op, "scan", stacked)
        self.states = list(states)
        if self._tier_ops:
            self._tier_maintain()
        if sampled:
            # the fused launch is already synced: fold the event-time drop
            # readback into it (coordinates = the group's first traced batch)
            self._journal_drops(next(
                (b for b in batches if _tracing.tid_of(b) is not None), None))
        self._push_count += k
        outs = unstack_batches(outs_stacked, k)
        # batch/byte counters mirror push: K batches per op, static shapes
        ck = (from_op, batches[0].capacity)
        if ck in self._nbytes_cache:
            in_bytes, out_bytes = self._nbytes_cache[ck]
        else:
            in_bytes, out_bytes = (_batch_nbytes(batches[0]),
                                   _batch_nbytes(outs[0]))
            self._nbytes_cache[ck] = (in_bytes, out_bytes)
        for j in range(from_op, len(self.ops)):
            rec = self.ops[j].get_StatsRecords()[0]
            rec.batches_received += k
            rec.batches_sent += k
            rec.bytes_received += k * in_bytes
            rec.bytes_sent += k * out_bytes
        if self.ops:
            # ONE launch for K batches — the dispatch-amortization claim the
            # perf gate asserts (num_kernels vs batches_received)
            tid = next((t for t in map(_tracing.tid_of, batches)
                        if t is not None), None)
            self.ops[from_op].get_StatsRecords()[0].record_launch(
                service_s,
                exemplar=None if service_s is None else tid)
        return outs

    def push(self, batch: Batch, from_op: int = 0) -> Batch:
        """Run one batch through ops[from_op:]; updates states; returns the out batch."""
        if self.device is not None:
            batch = jax.device_put(batch, self.device)
        self._push_count += 1
        # never sample push #1 — it would time JIT trace + XLA compile, not
        # service. Early pushes sample at powers of two (2, 4, 8) so SHORT
        # runs still carry service-time percentiles (the monitoring snapshot's
        # p50/p95/p99 needs samples); steady state samples every
        # SERVICE_SAMPLE_EVERY to keep the async pipeline overlapped.
        c = self._push_count
        sampled = ((c % self.SERVICE_SAMPLE_EVERY) == 0
                   or (1 < c < self.SERVICE_SAMPLE_EVERY
                       and (c & (c - 1)) == 0))
        hl, t0c = self._health_begin("push")
        t0 = time.perf_counter() if sampled else 0.0
        states, out = self._step_fn(from_op)(tuple(self.states), batch)
        if sampled:
            # device-time attribution (health): dispatch returned async, so
            # t_disp - t0 is host-dispatch overhead and t_done - t_disp the
            # device completion wait — riding the block_until_ready this
            # sampled push already pays
            t_disp = time.perf_counter()
            jax.block_until_ready(out)
            t_done = time.perf_counter()
            service_s = t_done - t0
            # never attribute a launch that COMPILED (pending trace notes):
            # its "dispatch" span is trace+XLA time, and the sums never
            # decay — one such sample would mis-flag the stage forever
            if (hl is not None and not hl.has_pending()
                    and hl.service_sample()):
                hl.note_service(self.label, dispatch_s=t_disp - t0,
                                device_s=t_done - t_disp)
            # sampled compiled-program launch -> the event journal (no-op —
            # one None check — unless monitoring activated a journal)
            if _journal.get_active() is not None:
                _journal.record(
                    "launch", op=self.ops[from_op].getName() if self.ops else "",
                    from_op=from_op, push=self._push_count,
                    service_s=round(service_s, 6))
        else:
            service_s = None
        if hl is not None:
            # after the timed window, so the cost-analysis lowering of a
            # compile event can never inflate the service sample
            self._health_end(hl, t0c, from_op, "step", batch)
        self.states = list(states)
        if self._tier_ops:
            self._tier_maintain()
        if sampled:
            # the sampled push already paid the block_until_ready: fold the
            # event-time drop readback (lateness_drop journal events carrying
            # this batch's trace coordinates) into the same sync
            self._journal_drops(batch)
        # batch counters are per-op; ops[from_op:] execute as ONE fused compiled
        # program, so num_kernels counts ONE launch, attributed to the entry op
        # (reference GPU Stats_Record fields, wf/stats_record.hpp:76-80).
        # Byte counts come from static shapes (capacity x itemsize — the
        # reference counts sizeof(tuple_t) per tuple), no device sync; static
        # per capacity, so cached after the first push of each shape.
        ck = (from_op, batch.capacity)
        if ck in self._nbytes_cache:
            in_bytes, out_bytes = self._nbytes_cache[ck]
        else:
            in_bytes, out_bytes = _batch_nbytes(batch), _batch_nbytes(out)
            self._nbytes_cache[ck] = (in_bytes, out_bytes)
        for j in range(from_op, len(self.ops)):
            rec = self.ops[j].get_StatsRecords()[0]
            rec.batches_received += 1
            rec.batches_sent += 1
            rec.bytes_received += in_bytes
            rec.bytes_sent += out_bytes
        if self.ops:
            # H2D bytes are counted ONCE, at the source that framed the batch
            # (Pipeline.run / pipegraph source loops) — counting the possible
            # device_put above too would double-count the same transfer.
            # Sampled launches carry the batch's trace id (if any) as the
            # service-histogram exemplar — the p99 service bucket then names
            # a concrete batch in the flight recorder.
            self.ops[from_op].get_StatsRecords()[0].record_launch(
                service_s,
                exemplar=(None if service_s is None
                          else _tracing.tid_of(batch)))
        return out

    def flush(self) -> List[Batch]:
        """EOS: drain every operator in order, cascading flushed batches through the
        remaining suffix. Returns the list of final out-batches produced."""
        outs: List[Batch] = []
        for i, op in enumerate(self.ops):
            while True:
                self.states[i], fb = op.flush(self.states[i])
                if fb is None:
                    break
                if i + 1 < len(self.ops):
                    outs.append(self.push(fb, from_op=i + 1))
                else:
                    outs.append(fb)
        return outs

    def sync_stats(self) -> None:
        """Pull device-resident stats counters (e.g. window OLD-drop counts)
        into every operator's host Stats_Record — called at EOS and by the
        metrics registry at snapshot time."""
        if self._tier_ops:
            # EOS barrier: in-flight spills settle so the final counters /
            # tier sections (and any following checkpoint) are consistent
            self.tier_settle()
        for op, st in zip(self.ops, self.states):
            op.collect_stats(st)
        self._journal_drops(None)

    def _journal_drops(self, batch) -> None:
        """Event-time drop forensics: journal ``lateness_drop`` events for
        every operator drop counter that advanced since the last readback,
        carrying the PR 5 trace coordinates of ``batch`` (the sampled batch
        whose existing block_until_ready this read rides — zero extra
        syncs; EOS passes None).  ``wf_trace.py``/``wf_state.py`` join the
        events to traced batches on (tid, pos).  No-op unless event_time
        monitoring is on AND a journal is active."""
        if not self.event_time or _journal.get_active() is None:
            return
        tid = _tracing.tid_of(batch) if batch is not None else None
        for op, st in zip(self.ops, self.states):
            try:
                counters = op.drop_counters(st)
            except Exception:   # noqa: BLE001 — telemetry must not kill a run
                continue
            if not counters:
                continue
            prev = self._drop_synced.setdefault(id(op), {})
            for kind, val in counters.items():
                delta = int(val) - prev.get(kind, 0)
                if delta <= 0:
                    continue
                prev[kind] = int(val)
                fields = {"op": op.getName(), "kind": kind, "n": delta,
                          "total": int(val)}
                if tid is not None:
                    fields["tid"] = int(tid)
                    fields["pos"] = _tracing.trace_pos(tid)
                _journal.record("lateness_drop", **fields)

    def result(self):
        """Results of any ReduceSink-style terminal ops (device accumulators)."""
        res = {}
        for i, op in enumerate(self.ops):
            if isinstance(op, ReduceSink):
                res[op.name] = op.result(self.states[i])
        return res


class Pipeline:
    """Source -> ops... -> sink, run batch-at-a-time. The minimum end-to-end slice
    (SURVEY §7 step 3); MultiPipe builds on this per-segment."""

    def __init__(self, source: SourceBase, ops: Sequence[Basic_Operator],
                 sink: Optional[Sink] = None, *,
                 batch_size: Optional[int] = None, prefetch: int = 0,
                 monitoring=None, control=None, trace=None, dispatch=None):
        self.source = source
        self.sink = sink
        if batch_size is None:
            # withBatch hints are capacity ceilings; explicit batch_size wins
            batch_size = resolve_batch_hint(ops) or DEFAULT_BATCH_SIZE
        self.batch_size = batch_size
        self.prefetch = int(prefetch)   # >0: overlapped host framing + H2D transfers
        #: prefetch pause hook: the backpressure governor (or any external
        #: controller) sets this Event to suspend the prefetch worker
        import threading as _threading
        self.prefetch_pause = _threading.Event()
        chain_ops = list(ops)
        cap = getattr(source, "out_capacity", lambda b: b)(batch_size)
        #: adaptive control plane (None = off, the default — today's exact
        #: code path, no controller state). Resolved HERE (not lazily like
        #: monitoring) because the capacity ladder governs chain geometry:
        #: autotuning binds the operators at the ladder's top rung so every
        #: smaller rung runs inside the same (oversized-is-safe) rings.
        from ..control import ControlConfig
        self._control = ControlConfig.resolve(control)
        self._ladder = None
        chain_cap = cap
        if self._control is not None and self._control.autotune:
            from ..control import build_ladder
            self._ladder = build_ladder(cap, up=self._control.ladder_up,
                                        down=self._control.ladder_down)
            chain_cap = self._ladder[-1]
        # event-time sub-toggle resolved at CONSTRUCTION (geometry-binding,
        # the control= convention): the histograms live in operator state
        from ..observability import event_time_enabled
        self.chain = CompiledChain(chain_ops, source.payload_spec(),
                                   batch_capacity=chain_cap,
                                   event_time=event_time_enabled(monitoring))
        #: None = consult WF_MONITORING; True/str/MonitoringConfig = enable
        #: (see observability.MonitoringConfig.resolve); resolved lazily so an
        #: env change between construction and run() is honored
        self._monitoring_arg = monitoring
        self._monitor = None
        #: per-batch causal tracing (None = consult WF_TRACE; see
        #: observability.tracing.TraceConfig.resolve) — same lazy resolution
        self._trace_arg = trace
        self._tracer = None
        #: scan dispatch (None = consult WF_DISPATCH; see
        #: runtime.dispatch.DispatchConfig.resolve) — off by default: with
        #: dispatch off the drive loop runs today's exact per-batch path
        self._dispatch_arg = dispatch

    def _make_controller(self):
        """Assemble the run-scoped control pieces from the resolved config:
        (autotuner, rebatcher, admission) — any of them None when that
        sub-system is off."""
        cfg = self._control
        if cfg is None:
            return None, None, None
        from ..control import (CapacityAutotuner, Rebatcher, TuningCache,
                               admission_from_config, chain_signature,
                               device_kind, payload_signature, tuning_key)
        base = getattr(self.source, "out_capacity",
                       lambda b: b)(self.batch_size)
        tuner = rebatcher = None
        if cfg.autotune and self._ladder and len(self._ladder) > 1:
            cache = key = None
            if cfg.cache_path:
                cache = TuningCache(cfg.cache_path)
                key = tuning_key(chain_signature(self.chain.ops),
                                 payload_signature(self.chain.specs[0]),
                                 device_kind())
            tuner = CapacityAutotuner(
                self._ladder, start_capacity=base,
                decide_every=cfg.decide_every,
                settle_batches=cfg.settle_batches,
                improve_threshold=cfg.improve_threshold,
                cache=cache, cache_key=key,
                name=self.source.getName() + "-pipeline")
            rebatcher = Rebatcher(base)
            if tuner.capacity != base:        # cache warm start: actuate now
                rebatcher.set_target(tuner.capacity)
            if cfg.prewarm:
                # a converged warm start only ever runs the cached rung plus
                # the base shape (rebatcher drain/passthrough) — compiling
                # the rest of the ladder would spend seconds on executables
                # that cannot execute
                warm_caps = ({tuner.capacity, base} if tuner.converged
                             else self._ladder)
                with _dh.cause("autotune_prewarm"):
                    for c in sorted(warm_caps):
                        self.chain.warm(c)
        admission = admission_from_config(cfg, base, driver="pipeline")
        return tuner, rebatcher, admission

    def _make_dispatcher(self):
        """Resolve ``dispatch=``/``WF_DISPATCH`` into (accumulator, K-tuner)
        — both None when scan dispatch is off. The K tuner is the SAME
        hill-climber class the capacity ladder uses, pointed at a power-of-two
        K ladder (1 included — the degenerate rung IS per-batch push), its
        winner persisted in the shared TuningCache under a dispatch key."""
        from .dispatch import DispatchConfig, MicrobatchAccumulator, \
            build_k_ladder
        dcfg = DispatchConfig.resolve(self._dispatch_arg)
        if dcfg is None:
            return None, None
        acc = MicrobatchAccumulator(dcfg.k, dcfg.linger_s)
        ktuner = None
        cfg = self._control
        base = getattr(self.source, "out_capacity",
                       lambda b: b)(self.batch_size)
        if (dcfg.autotune_k and cfg is not None and cfg.autotune
                and dcfg.k > 1):
            from ..control import (CapacityAutotuner, TuningCache,
                                   chain_signature, device_kind,
                                   dispatch_tuning_key, payload_signature)
            ladder = build_k_ladder(dcfg.k)
            cache = key = None
            if cfg.cache_path:
                cache = TuningCache(cfg.cache_path)
                key = dispatch_tuning_key(
                    chain_signature(self.chain.ops),
                    payload_signature(self.chain.specs[0]), device_kind())
            ktuner = CapacityAutotuner(
                ladder, start_capacity=dcfg.k,
                decide_every=cfg.decide_every,
                settle_batches=cfg.settle_batches,
                improve_threshold=cfg.improve_threshold,
                cache=cache, cache_key=key,
                name=self.source.getName() + "-dispatch-k",
                gauge="dispatch_k")
            acc.set_k(ktuner.capacity)
            if dcfg.prewarm:
                warm_ks = ({ktuner.capacity, 1} if ktuner.converged
                           else ladder)
                with _dh.cause("autotune_prewarm"):
                    for kr in sorted(warm_ks):
                        self.chain.warm_scan(kr, base)
        elif dcfg.prewarm and dcfg.k > 1:
            with _dh.cause("autotune_prewarm"):
                self.chain.warm_scan(dcfg.k, base)
        return acc, ktuner

    def run(self):
        import time as _time
        from ..observability import Monitor, MonitoringConfig, TraceConfig, \
            Tracer
        cfg = MonitoringConfig.resolve(self._monitoring_arg)
        if cfg is not None and self._monitor is None:
            self._monitor = Monitor(cfg, self.source.getName() + "-pipeline")
            self._monitor.registry.register_pipeline(self)
            self._monitor.start()
        mon = self._monitor
        tcfg = TraceConfig.resolve(self._trace_arg)
        if tcfg is not None and self._tracer is None:
            self._tracer = Tracer(tcfg,
                                  self.source.getName() + "-pipeline").start()
        tuner, rebatcher, admission = self._make_controller()
        acc, ktuner = self._make_dispatcher()
        if mon is not None and tuner is not None:
            mon.registry.attach_gauge("control_chosen_capacity",
                                      lambda: tuner.capacity)
        if mon is not None and acc is not None:
            mon.registry.attach_gauge("dispatch_k", lambda: acc.k)
        if mon is not None and mon.remediation is not None:
            # bind the actuators THIS run owns (control/remediation.py):
            # unbound actuators skip loudly.  scale_rate is lock-guarded;
            # the re-climb request is an Event the drive loop consumes at
            # its next on_batch boundary — both safe from the Reporter tick
            if admission is not None:
                mon.remediation.bind(
                    "admission_rate",
                    lambda a, _adm=admission: _adm.scale_rate(a.factor,
                                                              a.floor))
            if tuner is not None or ktuner is not None:
                def _reclimb(_a, _t=tuner, _k=ktuner):
                    names = []
                    for t in (_t, _k):
                        if t is not None:
                            t.request_reclimb()
                            names.append(t.name)
                    return {"tuners": names}
                mon.remediation.bind("autotune_reclimb", _reclimb)
        try:
            batches = (self.source.batches_prefetched(
                           self.batch_size, self.prefetch,
                           pause_event=self.prefetch_pause)
                       if self.prefetch else self.source.batches(self.batch_size))
            n = 0

            def drive(b):
                # push one chain-capacity batch + sink delivery + sampling;
                # with control off this runs exactly once per source batch —
                # today's code path
                nonlocal n
                # e2e sampling needs a host sink (its consume blocks on the
                # materialized result — the "receipt"); in-graph ReduceSinks
                # have no host receipt to time
                sampled = (mon is not None and self.sink is not None
                           and mon.config.should_sample_e2e(n))
                t0 = _time.perf_counter() if sampled else 0.0
                span = _tracing.service(b, "chain")
                out = self.chain.push(b)
                if span is not None:
                    span.done()
                    _tracing.carry(b, out)
                if self.sink is not None:
                    sspan = _tracing.service(out, "sink")
                    self.sink.consume(out)
                    if sspan is not None:
                        sspan.done()
                if sampled:
                    # Sink.consume materialized the batch on the host (or the
                    # sink is in-graph) — this is a true source-framing ->
                    # host-receipt sample through device compute + transfer
                    mon.registry.record_e2e(_time.perf_counter() - t0,
                                            exemplar=_tracing.tid_of(b))
                n += 1
                if tuner is not None:
                    newcap = tuner.on_batch(b.capacity)
                    if newcap is not None:
                        rebatcher.set_target(newcap)
                if ktuner is not None:
                    newk = ktuner.on_batch(b.capacity)
                    if newk is not None:
                        acc.set_k(newk)

            def drive_many(group):
                # K batches, ONE compiled scan dispatch: per-batch sink
                # delivery, trace spans, e2e samples, and tuner accounting
                # are synthesized from the one launch, in batch order
                nonlocal n
                if len(group) == 1:
                    drive(group[0])
                    return
                sampled_any = (mon is not None and self.sink is not None
                               and any(mon.config.should_sample_e2e(n + i)
                                       for i in range(len(group))))
                t0 = _time.perf_counter() if sampled_any else 0.0
                outs = _dispatch.fused_push(self.chain, group, "chain")
                for b, out in zip(group, outs):
                    if self.sink is not None:
                        sspan = _tracing.service(out, "sink")
                        self.sink.consume(out)
                        if sspan is not None:
                            sspan.done()
                    if (mon is not None and self.sink is not None
                            and mon.config.should_sample_e2e(n)):
                        mon.registry.record_e2e(_time.perf_counter() - t0,
                                                exemplar=_tracing.tid_of(b))
                    n += 1
                    if tuner is not None:
                        newcap = tuner.on_batch(b.capacity)
                        if newcap is not None:
                            rebatcher.set_target(newcap)
                    if ktuner is not None:
                        newk = ktuner.on_batch(b.capacity)
                        if newk is not None:
                            acc.set_k(newk)

            def feed(rb):
                # with dispatch off this IS drive(rb) — today's exact path
                if acc is None:
                    drive(rb)
                else:
                    for g in acc.feed(rb):
                        drive_many(g)

            n_offered = 0
            for batch in batches:
                record_source_launch(self.source, batch)
                _tracing.ingest(batch, n_offered)
                # shed journal coordinate = the offered position trace ids
                # are minted from (n counts DRIVEN batches, which drifts past
                # a shed — the report joins on offered positions)
                admitted = (batch,) if admission is None \
                    else admission.offer(batch, pos=n_offered)
                n_offered += 1
                for ab in admitted:
                    for rb in (rebatcher.feed(ab) if rebatcher is not None
                               else (ab,)):
                        feed(rb)
            _journal.record("eos", pipeline=self.source.getName())
            if admission is not None:
                for ab in admission.drain():      # bounded held tail
                    for rb in (rebatcher.feed(ab) if rebatcher is not None
                               else (ab,)):
                        feed(rb)
            if rebatcher is not None:
                for rb in rebatcher.drain():      # partial up-rung buffer
                    feed(rb)
            if acc is not None:
                tail = acc.drain()                # partial tail < K at EOS
                if tail:
                    drive_many(tail)
            for out in self.chain.flush():
                if self.sink is not None:
                    self.sink.consume(out)
            if self.sink is not None:
                self.sink.consume(None)  # empty-optional EOS signal (wf/sink.hpp)
            self.chain.sync_stats()
            for op in [self.source, *self.chain.ops,
                       *([self.sink] if self.sink is not None else [])]:
                op.close()            # closing_func per replica (svc_end parity)
            return self.chain.result()
        finally:
            if self._tracer is not None:
                self._tracer.finish()
            if mon is not None:
                mon.finish(self)
