"""Whole-repo static concurrency analyzer — Pillar 3 of the static-analysis
layer (the WF26x family).

The runtime is one-thread-per-stage over lock-free queues (the reference
WindFlow shape), plus a reporter thread, step-timeout watchdog workers, a
sharded-checkpoint thread pool, prefetch workers, and JAX ``io_callback``
threads mutating host state.  The load-bearing cross-thread contracts
("``settle()`` is driver-thread-only", "readmission callbacks run on JAX
callback threads") used to live in docstrings; this pass makes them CHECKED.
Stdlib ``ast`` only, loadable by file path without JAX (the ``lint.py``
convention) — ``analysis/lint.py`` runs it as part of ``run_lint`` and its
findings ride the same ``baseline.json`` ratchet.

Four pillars:

====== ========= =====================================================
code   severity  invariant
====== ========= =====================================================
WF260  error     inferred shared-state discipline: a ``self.<attr>``
                 written under one thread role and read/written under
                 another must be accessed inside ``with self.<lock>:``
                 everywhere (one consistent lock), or carry an explicit
                 ``guarded-by[<lock>]`` / ``single-writer[<roles>]``
                 annotation stating why the race is benign
WF261  error     a function annotated ``thread-role[<roles>]`` (a
                 role-constrained API, e.g. the driver-thread-only
                 ``Ordering_Node.settle``) is reachable — through the
                 spawn-site/call-graph role inference — from a role
                 outside its declared set
WF262  error     an ``io_callback`` in a deterministic-replay module
                 must pass a LITERAL ``ordered=True`` (an unordered
                 callback reorders host effects under scan fusion and
                 silently breaks byte-identical replay) and its callback
                 must resolve to a known function (which then carries
                 the ``jax-callback`` role, so WF260 checks its shared
                 state)
WF263  warning   lock-order cycle: the lock-acquisition graph (nested
                 ``with`` blocks + locks acquired by callees while a
                 lock is held) contains a cycle — a potential deadlock
WF264  warning   a non-daemon ``threading.Thread`` is started with no
                 reachable ``join()`` (enclosing function, its direct
                 callees, or a method of the same class) — a leaked
                 thread on the shutdown path
WF265  error     wf-lint concurrency annotation grammar error (unknown
                 role, empty role list)
====== ========= =====================================================

Thread roles
------------

Every function is classified by the set of ROLES it can run on:

- ``driver``          — the user/main thread driving a pipeline run
- ``stage``           — a per-stage/per-pipe worker of the threaded drivers
- ``reporter``        — the metrics reporter tick thread
- ``watchdog``        — a heartbeat/monitor thread (detection only)
- ``checkpoint-pool`` — a sharded-checkpoint ``ThreadPoolExecutor`` worker
- ``jax-callback``    — a JAX ``io_callback`` host-callback thread
- ``prefetch``        — the double-buffered H2D ingest worker
- ``telemetry``       — fleet telemetry plane threads (agent sender,
  aggregator accept/reader/ticker)
- ``ingest``          — serving front-door network threads (SocketSource
  accept loop + per-client frame decoders)
- ``native``          — short-lived native record-framing workers
- ``thread``          — an UNANNOTATED spawned thread (unknown worker)

Inference: spawn sites seed roles (``threading.Thread(target=f)`` seeds
``f`` with the spawn line's ``thread-role[...]`` annotation, else the
``thread`` default; ``ThreadPoolExecutor.submit``/``.map`` seeds
``checkpoint-pool``; a callable passed to ``io_callback`` seeds
``jax-callback``), ``thread-role`` annotations on ``def`` lines seed their
declared roles, and roles propagate through a module-level call graph.
Functions never reached by any spawned role default to ``driver`` (code
only the main thread can reach) and propagate ``driver`` onward.  Call
resolution is deliberately conservative: ``self.m()`` resolves within the
class (+ in-repo bases), locals/attributes constructed from a repo class
resolve precisely, and a bare-name method fallback applies only when the
name is unambiguous (one class) or every definition carries a
``thread-role`` annotation — an unresolved call adds NO edge, so the
analysis under-approximates reachability rather than drowning real
findings in phantom ones.

Annotation grammar (one per physical line; a declaration may also sit on a
pure-comment line directly above):

- ``# wf-lint: thread-role[<role>{,<role>}]``
  * on a ``def`` line: the COMPLETE set of roles this function may run on
    — it both seeds inference and is enforced (WF261 fires when inference
    finds an extra role);
  * on a spawn line (``threading.Thread(...)`` / ``.submit(...)``): the
    role the spawned target runs as (overrides the defaults above).
- ``# wf-lint: single-writer[<role>{,<role>}]`` — on an attribute
  assignment inside a class body (or on the ``class`` line, covering every
  attribute): mutation of the attribute is confined to one owning thread
  (whose role is one of those listed); cross-role readers tolerate
  GIL-atomic staleness.  Suppresses WF260 for the attribute — the roles
  name the writers for the reader of the code, and unknown role names are
  rejected (WF265).
- ``# wf-lint: guarded-by[<lock>]`` — unchanged from WF220 (lint.py
  enforces every access under the lock); WF260 skips declared attributes.
- ``# wf-lint: allow[unguarded]`` — per-line WF260/WF220 escape.
- ``# wf-lint: allow[unordered]`` — per-line WF262 escape.
- ``# wf-lint: allow[lock-order]`` — on a ``with`` line: WF263 escape.
- ``# wf-lint: allow[unjoined]`` — on a spawn line: WF264 escape.

Known limitations (documented, deliberate): attribute PROPERTY loads do
not create call edges (``o.last_release_count`` invoking ``settle`` is
invisible); callables stashed in containers/registries (metrics gauge
closures) are not traced; module-level globals are out of WF260's scope
(they have their own module locks and the WF210/WF241 rules).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

# ------------------------------------------------------------------ grammar

ROLES = ("driver", "stage", "reporter", "watchdog", "checkpoint-pool",
         "jax-callback", "prefetch", "telemetry", "ingest", "native",
         "thread")

#: default role a spawn seeds when the spawn line carries no annotation
DEFAULT_THREAD_ROLE = "thread"
DEFAULT_POOL_ROLE = "checkpoint-pool"
CALLBACK_ROLE = "jax-callback"

_ROLE_RE = re.compile(r"#\s*wf-lint:\s*thread-role\[([a-z0-9_,\- ]*)\]")
_SINGLE_WRITER_RE = re.compile(r"#\s*wf-lint:\s*single-writer"
                               r"\[([a-z0-9_,\- ]*)\]")
_GUARDED_RE = re.compile(r"#\s*wf-lint:\s*guarded-by\[([A-Za-z_]\w*)\]")
_ALLOW_RE = re.compile(r"#\s*wf-lint:\s*allow\[([a-z0-9_,\- ]+)\]")

#: constructors whose product is intrinsically thread-safe (or IS the lock):
#: an attribute initialized from one of these is exempt from WF260
_THREADSAFE_CTORS = frozenset({
    "Lock", "RLock", "Condition", "Event", "Semaphore", "BoundedSemaphore",
    "Barrier", "local", "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
    "SPSCQueue",
})

#: method names treated as MUTATING their receiver (``self.x.append(...)``
#: counts as a write to ``x`` — heuristic, the common stdlib mutators)
_MUTATOR_METHODS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popleft", "appendleft",
    "clear", "update", "setdefault", "add", "discard", "put", "put_nowait",
    "sort", "reverse", "write",
})

#: names the global ``obj.m()`` fallback must NEVER resolve by name alone:
#: ubiquitous container/stdlib method names would otherwise alias onto the
#: one repo class that happens to define them (``entries.pop(0)`` is a list
#: pop, not ``SPSCQueue.pop``) and spray phantom roles/lock edges
_FALLBACK_BLOCKLIST = _MUTATOR_METHODS | frozenset({
    "get", "keys", "values", "items", "copy", "index", "count", "join",
    "start", "close", "run", "read", "readline", "open", "next", "send",
    "wait", "set", "is_set", "acquire", "release", "notify", "notify_all",
    "tolist", "item", "sum", "max", "min", "mean", "reshape", "astype",
    "push",
})

#: replay-sensitive modules for the WF262 ordered-effect rule (relative,
#: posix) — the lint.py deterministic set plus the two operator modules
#: whose compiled programs embed host callbacks
DEFAULT_REPLAY_MODULES = (
    "windflow_tpu/runtime/supervisor.py",
    "windflow_tpu/runtime/checkpoint.py",
    "windflow_tpu/control/admission.py",
    "windflow_tpu/state/tiered.py",
    "windflow_tpu/state/host_store.py",
    "windflow_tpu/ops/lookup.py",
    "windflow_tpu/operators/join.py",
    # the serving plane (PR 18) and fleet aggregation (PR 16) postdate
    # this list: their callbacks/admission decisions ride the same
    # deterministic-replay path as the supervised drivers they feed
    "windflow_tpu/serving/framing.py",
    "windflow_tpu/serving/sources.py",
    "windflow_tpu/serving/tenants.py",
    "windflow_tpu/serving/runtime.py",
    "windflow_tpu/observability/fleet.py",
)


def _parse_roles(text: str, regex) -> Optional[List[str]]:
    m = regex.search(text)
    if m is None:
        return None
    return [r.strip() for r in m.group(1).split(",")]


def _allows(line: str, tag: str) -> bool:
    m = _ALLOW_RE.search(line)
    return bool(m) and tag in [t.strip() for t in m.group(1).split(",")]


# --------------------------------------------------------------- file model


class _File:
    """One parsed python file (the lint.py shape, self-contained here so the
    module loads by path without importing lint)."""

    def __init__(self, abspath: str, relpath: str):
        self.rel = relpath.replace(os.sep, "/")
        self.tree: Optional[ast.AST] = None
        try:
            with open(abspath, encoding="utf-8") as f:
                self.source = f.read()
        except UnicodeDecodeError:
            self.source = ""              # WF200 is lint.py's job
        self.lines = self.source.splitlines()
        try:
            self.tree = ast.parse(self.source)
        except SyntaxError:
            self.tree = None              # ditto

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def ann(self, lineno: int, regex) -> Optional[List[str]]:
        """Annotation on ``lineno`` or on a pure-comment line directly
        above (the guarded-by convention)."""
        got = _parse_roles(self.line(lineno), regex)
        if got is None:
            above = self.line(lineno - 1).strip()
            if above.startswith("#"):
                got = _parse_roles(above, regex)
        return got

    def allows(self, lineno: int, tag: str) -> bool:
        return _allows(self.line(lineno), tag)


def _walk_py(root: str, rel_dirs: Sequence[str]) -> List[str]:
    out = []
    for d in rel_dirs:
        top = os.path.join(root, d)
        for dirpath, dirnames, names in os.walk(top):
            dirnames[:] = [x for x in dirnames if x != "__pycache__"]
            out += [os.path.join(dirpath, n) for n in sorted(names)
                    if n.endswith(".py")]
    return out


# ------------------------------------------------------------ function index


class _Func:
    """One function/method/lambda definition."""

    __slots__ = ("qual", "node", "file", "cls", "name", "lineno",
                 "decl_roles", "roles", "provenance",
                 "calls", "call_sites", "entry_held", "local_types",
                 "spawns", "accesses", "acquires",
                 "has_join", "resolved_sites", "edges")

    def __init__(self, qual: str, node, file: _File, cls: Optional[str],
                 name: str):
        self.qual = qual
        self.node = node
        self.file = file
        self.cls = cls                      # innermost enclosing class name
        self.name = name
        self.lineno = getattr(node, "lineno", 1)
        #: declared allowed-role set (thread-role[...] on the def line)
        self.decl_roles: Optional[List[str]] = None
        #: inferred roles + how each was first reached (for the message)
        self.roles: Set[str] = set()
        self.provenance: Dict[str, str] = {}
        #: raw call specs: ("name", id, node) / ("attr", base, attr, node)
        #: / ("selfattr", attr_of_self, method, node)
        self.calls: List[tuple] = []
        #: every call with the locks held at the call site: (held, spec)
        self.call_sites: List[tuple] = []
        #: locks PROVABLY held at entry (every resolved call site holds
        #: them — the must-analysis that lets ``_append_row`` inherit the
        #: ``upsert`` lock); filled by _effective_held
        self.entry_held: frozenset = frozenset()
        #: local var -> repo class name, from in-body constructor bindings
        #: (``acc = MicrobatchAccumulator(...)``) and with-as bindings —
        #: consulted by _resolve_call for ``obj.m()`` receivers
        self.local_types: Dict[str, str] = {}
        #: call sites RESOLVED once per index build (``_indexed``):
        #: ``[(held, spec, [callee quals])]`` — _infer_roles,
        #: _effective_held, _rule_lock_order, and _join_reachable all
        #: consume this instead of re-resolving the whole-repo graph
        self.resolved_sites: List[tuple] = []
        #: flattened unique callee quals of resolved_sites
        self.edges: List[str] = []
        #: spawn records: (kind, target_expr, role, node) with kind in
        #: {"thread", "pool", "iocb"}; role already annotation-resolved
        self.spawns: List[tuple] = []
        #: self-attribute accesses: (attr, is_write, lineno, frozenset(held))
        self.accesses: List[tuple] = []
        #: lock acquisitions: (lock_key, frozenset(held_before), lineno)
        self.acquires: List[tuple] = []
        self.has_join = False


class _Class:
    __slots__ = ("name", "file", "node", "bases", "methods", "attr_types",
                 "threadsafe_attrs", "guarded", "single_writer",
                 "class_single_writer", "lock_attrs", "lock_kinds")

    def __init__(self, name: str, file: _File, node: ast.ClassDef):
        self.name = name
        self.file = file
        self.node = node
        self.bases: List[str] = []
        self.methods: Dict[str, _Func] = {}
        #: self.<attr> -> repo class name (from ``self.x = ClassName(...)``)
        self.attr_types: Dict[str, str] = {}
        self.threadsafe_attrs: Set[str] = set()
        self.guarded: Dict[str, str] = {}          # guarded-by decls
        self.single_writer: Dict[str, List[str]] = {}
        self.class_single_writer: Optional[List[str]] = None
        self.lock_attrs: Set[str] = set()
        self.lock_kinds: Dict[str, str] = {}       # attr -> Lock/RLock/...


class _Index:
    """Whole-tree index: functions, classes, per-file import aliases."""

    def __init__(self):
        self.funcs: List[_Func] = []
        self.by_qual: Dict[str, _Func] = {}
        self.classes: Dict[str, _Class] = {}       # class name -> _Class
        self.module_funcs: Dict[Tuple[str, str], _Func] = {}  # (rel, name)
        self.methods_by_name: Dict[str, List[_Func]] = {}
        self.funcs_by_name: Dict[str, List[_Func]] = {}
        #: per file: local alias -> module basename ("_faults" -> "faults")
        self.mod_alias: Dict[str, Dict[str, str]] = {}
        #: per file: imported name -> (module basename, original name)
        self.from_imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        #: module basename -> rel path (ambiguous basenames dropped)
        self.module_files: Dict[str, str] = {}
        #: per file: names bound to threading.Thread / ThreadPoolExecutor /
        #: io_callback via from-imports
        self.thread_names: Dict[str, Set[str]] = {}
        self.pool_names: Dict[str, Set[str]] = {}
        self.iocb_names: Dict[str, Set[str]] = {}
        #: module-level locks: (rel, var) present in ``with var:`` handling
        self.module_locks: Dict[Tuple[str, str], str] = {}
        self.findings: List[dict] = []
        #: snapshot of the indexing-time (WF265 grammar) findings, so cached
        #: re-runs re-emit them exactly once (filled by _indexed)
        self.grammar_findings: List[dict] = []

    def finding(self, code: str, severity: str, file: _File, lineno: int,
                message: str) -> None:
        self.findings.append({
            "code": code, "severity": severity, "path": file.rel,
            "line": lineno, "message": message,
            "text": file.line(lineno).strip()})


# ---------------------------------------------------------------- indexing


def _ctor_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _index_imports(idx: _Index, f: _File) -> None:
    mods: Dict[str, str] = {}
    froms: Dict[str, Tuple[str, str]] = {}
    threads, pools, iocbs = set(), set(), set()
    for node in ast.walk(f.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                base = a.name.split(".")[-1]
                mods[a.asname or a.name.split(".")[0]] = base
        elif isinstance(node, ast.ImportFrom):
            mod = (node.module or "").split(".")[-1]
            for a in node.names:
                local = a.asname or a.name
                if node.module == "threading" and a.name == "Thread":
                    threads.add(local)
                elif a.name == "ThreadPoolExecutor":
                    pools.add(local)
                elif a.name == "io_callback":
                    iocbs.add(local)
                else:
                    # `from . import faults as _faults` imports a MODULE
                    froms[local] = (mod, a.name)
    idx.mod_alias[f.rel] = mods
    idx.from_imports[f.rel] = froms
    idx.thread_names[f.rel] = threads
    idx.pool_names[f.rel] = pools
    idx.iocb_names[f.rel] = iocbs


def _is_thread_ctor(idx: _Index, f: _File, call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id in idx.thread_names[f.rel]
    return (isinstance(fn, ast.Attribute) and fn.attr == "Thread"
            and isinstance(fn.value, ast.Name)
            and idx.mod_alias[f.rel].get(fn.value.id) == "threading")


def _is_pool_ctor(idx: _Index, f: _File, call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id in idx.pool_names[f.rel]
    return isinstance(fn, ast.Attribute) and fn.attr == "ThreadPoolExecutor"


def _is_iocb(idx: _Index, f: _File, call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id in idx.iocb_names[f.rel]
    return isinstance(fn, ast.Attribute) and fn.attr == "io_callback"


class _FuncVisitor:
    """Extract calls/spawns/accesses/locks from ONE function body (does not
    descend into nested function definitions — they are their own _Funcs)."""

    def __init__(self, idx: _Index, fn: _Func, local_types: Dict[str, str]):
        self.idx = idx
        self.fn = fn
        self.f = fn.file
        self.types = local_types        # local var -> repo class name

    # -- lock identity ----------------------------------------------------

    def _lock_key(self, expr) -> Optional[str]:
        """Identity of a ``with`` context that looks like a lock:
        ``self.<attr>`` (class-scoped) or a bare module-level name."""
        if (isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and self.fn.cls):
            cls = self.idx.classes.get(self.fn.cls)
            attr = expr.attr
            if cls is not None and (attr in cls.lock_attrs
                                    or "lock" in attr.lower()):
                return f"{self.fn.cls}.{attr}"
            return None
        if isinstance(expr, ast.Name) and "lock" in expr.id.lower():
            self.idx.module_locks[(self.f.rel, expr.id)] = expr.id
            return f"{self.f.rel}::{expr.id}"
        return None

    # -- traversal --------------------------------------------------------

    def run(self):
        body = self.fn.node.body if not isinstance(self.fn.node, ast.Lambda) \
            else [self.fn.node.body]
        for stmt in body:
            self._visit(stmt, frozenset())

    def _visit(self, node, held: frozenset):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return                       # separate _Func; held does not carry
        if isinstance(node, ast.With):
            taken = []
            for item in node.items:
                k = self._lock_key(item.context_expr)
                if k is not None:
                    # allow[lock-order] only hides the acquisition from the
                    # WF263 graph — the lock still counts as HELD for WF260.
                    # Earlier items of the SAME statement are already held
                    # when a later one acquires (`with self.a, self.b:` is
                    # an a->b edge like nested withs).
                    if not self.f.allows(node.lineno, "lock-order"):
                        self.fn.acquires.append(
                            (k, held | frozenset(taken), node.lineno))
                    taken.append(k)
                # a with-as over a repo class (ThreadPoolExecutor as ex)
                if (isinstance(item.context_expr, ast.Call)
                        and item.optional_vars is not None
                        and isinstance(item.optional_vars, ast.Name)):
                    if _is_pool_ctor(self.idx, self.f, item.context_expr):
                        self.types[item.optional_vars.id] = \
                            "ThreadPoolExecutor"
                    else:
                        cn = _ctor_name(item.context_expr)
                        if cn in self.idx.classes:
                            self.types[item.optional_vars.id] = cn
                self._visit(item.context_expr, held)
            inner = held | frozenset(taken)
            for child in node.body:
                self._visit(child, inner)
            return
        if isinstance(node, ast.Assign):
            # local type binding: x = ClassName(...) / x = ThreadPoolExecutor(...)
            if (isinstance(node.value, ast.Call)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                if _is_pool_ctor(self.idx, self.f, node.value):
                    self.types[node.targets[0].id] = "ThreadPoolExecutor"
                elif _is_thread_ctor(self.idx, self.f, node.value):
                    self.types[node.targets[0].id] = "threading.Thread"
                else:
                    cn = _ctor_name(node.value)
                    if cn in self.idx.classes:
                        self.types[node.targets[0].id] = cn
        if isinstance(node, ast.Call):
            self._record_call(node, held)
        if isinstance(node, ast.Attribute):
            self._record_access(node, held)
        if (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, (ast.Store, ast.Del))
                and isinstance(node.value, ast.Attribute)
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "self"):
            # self.x[k] = v: a WRITE to x (the attr itself loads, the
            # container mutates)
            self.fn.accesses.append((node.value.attr, True, node.lineno,
                                     held))
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    def _record_access(self, node: ast.Attribute, held: frozenset):
        if not (isinstance(node.value, ast.Name) and node.value.id == "self"):
            return
        is_write = isinstance(node.ctx, (ast.Store, ast.Del))
        self.fn.accesses.append((node.attr, is_write, node.lineno, held))

    def _record_call(self, node: ast.Call, held: frozenset):
        fn = node.func
        # spawn sites ------------------------------------------------------
        if _is_thread_ctor(self.idx, self.f, node):
            target = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
            daemon = any(kw.arg == "daemon"
                         and isinstance(kw.value, ast.Constant)
                         and kw.value.value is True
                         for kw in node.keywords)
            for role in self._spawn_roles(node, DEFAULT_THREAD_ROLE):
                if target is not None:
                    self.fn.spawns.append(("thread", target, role, node,
                                           daemon))
            return
        if isinstance(fn, ast.Attribute) and fn.attr in ("submit", "map"):
            base = fn.value
            is_pool = (isinstance(base, ast.Name)
                       and self.types.get(base.id) == "ThreadPoolExecutor")
            if (not is_pool and isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self" and self.fn.cls):
                # executor stored on self (`self._pool.submit(...)`) —
                # typed by _index_class_attrs from the __init__ assignment
                cls = self.idx.classes.get(self.fn.cls)
                is_pool = (cls is not None and cls.attr_types.get(base.attr)
                           == "ThreadPoolExecutor")
            if is_pool and node.args:
                for role in self._spawn_roles(node, DEFAULT_POOL_ROLE):
                    self.fn.spawns.append(("pool", node.args[0], role, node,
                                           True))
                return
        if _is_iocb(self.idx, self.f, node) and node.args:
            for role in self._spawn_roles(node, CALLBACK_ROLE):
                self.fn.spawns.append(("iocb", node.args[0], role, node,
                                       True))
            # fall through: also a call (WF262 inspects it via spawns)
        # mutator-method writes -------------------------------------------
        if (isinstance(fn, ast.Attribute) and fn.attr in _MUTATOR_METHODS
                and isinstance(fn.value, ast.Attribute)
                and isinstance(fn.value.value, ast.Name)
                and fn.value.value.id == "self"):
            cls = self.idx.classes.get(self.fn.cls) if self.fn.cls else None
            # an attr holding a REPO object is a method call (edge), not a
            # container mutation (`self._seg.add(...)` is _Segment.add)
            if cls is None or fn.value.attr not in cls.attr_types:
                self.fn.accesses.append((fn.value.attr, True, node.lineno,
                                         held))
        if isinstance(fn, ast.Attribute) and fn.attr == "join":
            # only thread-shaped receivers count for WF264: a bare local
            # (`t.join()`, incl. loop vars over a thread list) that is not
            # a module alias, or a self attribute (`self._thread.join()`)
            # — NOT os.path.join / ", ".join / some_module.join
            recv = fn.value
            if isinstance(recv, ast.Name):
                if (recv.id not in self.idx.mod_alias[self.f.rel]
                        and recv.id not in self.idx.from_imports[self.f.rel]
                        and self.types.get(recv.id) != "ThreadPoolExecutor"):
                    self.fn.has_join = True
            elif (isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self"):
                self.fn.has_join = True
        # call edges -------------------------------------------------------
        spec = None
        if isinstance(fn, ast.Name):
            spec = ("name", fn.id, node)
        elif isinstance(fn, ast.Attribute):
            base = fn.value
            if isinstance(base, ast.Name):
                spec = ("attr", base.id, fn.attr, node)
            elif (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"):
                spec = ("selfattr", base.attr, fn.attr, node)
            else:
                spec = ("attr", None, fn.attr, node)
        if spec is not None:
            self.fn.calls.append(spec)
            self.fn.call_sites.append((held, spec))

    def _spawn_roles(self, node: ast.Call, default: str) -> List[str]:
        """Role(s) a spawn line declares — EVERY listed role seeds the
        target (a multi-role spawn annotation must not silently drop its
        tail); unannotated spawns get the kind's default."""
        roles = self.f.ann(node.lineno, _ROLE_RE)
        if roles is None:
            return [default]
        bad = [r for r in roles if r not in ROLES]
        if bad or not roles or roles == [""]:
            self.idx.finding(
                "WF265", "error", self.f, node.lineno,
                f"thread-role annotation names unknown role(s) "
                f"{bad or roles} — roles: {', '.join(ROLES)}")
            return [default]
        return roles


def _index_tree(root: str, package_dirs: Sequence[str]) -> _Index:
    idx = _Index()
    files = [_File(p, os.path.relpath(p, root))
             for p in _walk_py(root, package_dirs)]
    files = [f for f in files if f.tree is not None]
    # module basename -> rel path (drop ambiguous, e.g. two __init__.py)
    seen: Dict[str, List[str]] = {}
    for f in files:
        seen.setdefault(os.path.basename(f.rel)[:-3], []).append(f.rel)
    idx.module_files = {b: p[0] for b, p in seen.items() if len(p) == 1}

    for f in files:
        _index_imports(idx, f)
        _collect_defs(idx, f)
    # class attr types + lock/threadsafe attrs need the class table complete
    for cls in idx.classes.values():
        _index_class_attrs(idx, cls)
    # extract bodies; each visitor fills the function's local-type map
    # (constructor + with-as bindings), consulted later by _resolve_call
    for fn in idx.funcs:
        v = _FuncVisitor(idx, fn, {})
        v.run()
        fn.local_types = v.types
    return idx


def _collect_defs(idx: _Index, f: _File) -> None:
    def walk(node, scope: List[str], cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                c = _Class(child.name, f, child)
                c.bases = [b.id if isinstance(b, ast.Name)
                           else (b.attr if isinstance(b, ast.Attribute)
                                 else "") for b in child.bases]
                c.class_single_writer = f.ann(child.lineno,
                                              _SINGLE_WRITER_RE)
                if c.class_single_writer is not None:
                    _check_roles(idx, f, child.lineno,
                                 c.class_single_writer, "single-writer")
                # first definition wins; duplicate class names across the
                # tree are rare and only blunt resolution
                idx.classes.setdefault(child.name, c)
                walk(child, scope + [child.name], child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{f.rel}::{'.'.join(scope + [child.name])}"
                fn = _Func(qual, child, f, cls, child.name)
                fn.decl_roles = f.ann(child.lineno, _ROLE_RE)
                if fn.decl_roles is not None:
                    _check_roles(idx, f, child.lineno, fn.decl_roles,
                                 "thread-role")
                idx.funcs.append(fn)
                idx.by_qual[qual] = fn
                if cls is not None and len(scope) and scope[-1] == cls:
                    idx.classes[cls].methods.setdefault(child.name, fn)
                    idx.methods_by_name.setdefault(child.name,
                                                   []).append(fn)
                elif not scope:
                    idx.module_funcs[(f.rel, child.name)] = fn
                idx.funcs_by_name.setdefault(child.name, []).append(fn)
                walk(child, scope + [child.name], cls)
            elif isinstance(child, ast.Lambda):
                qual = f"{f.rel}::{'.'.join(scope)}.<lambda>@{child.lineno}"
                fn = _Func(qual, child, f, cls, "<lambda>")
                idx.funcs.append(fn)
                idx.by_qual[qual] = fn
                walk(child, scope, cls)
            else:
                walk(child, scope, cls)

    walk(f.tree, [], None)


def _check_roles(idx: _Index, f: _File, lineno: int, roles: List[str],
                 kind: str) -> None:
    bad = [r for r in roles if r not in ROLES]
    if bad or not roles or roles == [""]:
        idx.finding("WF265", "error", f, lineno,
                    f"{kind} annotation names unknown role(s) "
                    f"{bad or roles} — roles: {', '.join(ROLES)}")


def _param_ann_types(cls: _Class) -> Dict[str, str]:
    """``{param name: annotated class name}`` of the class's ``__init__``
    (string annotations like ``"Tracer"`` included)."""
    out: Dict[str, str] = {}
    for node in cls.node.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "__init__":
            for arg in node.args.args + node.args.kwonlyargs:
                ann = arg.annotation
                if isinstance(ann, ast.Name):
                    out[arg.arg] = ann.id
                elif isinstance(ann, ast.Constant) \
                        and isinstance(ann.value, str):
                    out[arg.arg] = ann.value
    return out


def _index_class_attrs(idx: _Index, cls: _Class) -> None:
    f = cls.file
    for node in ast.walk(cls.node):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        for t in targets:
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            attr = t.attr
            g = f.ann(node.lineno, _GUARDED_RE)
            if g:
                cls.guarded[attr] = g[0]
            sw = f.ann(node.lineno, _SINGLE_WRITER_RE)
            if sw is not None:
                _check_roles(idx, f, node.lineno, sw, "single-writer")
                cls.single_writer[attr] = sw
            val = getattr(node, "value", None)
            if isinstance(val, ast.Call):
                cn = _ctor_name(val)
                if cn == "ThreadPoolExecutor":
                    cls.attr_types[attr] = "ThreadPoolExecutor"
                elif cn in _THREADSAFE_CTORS:
                    cls.threadsafe_attrs.add(attr)
                    if cn in ("Lock", "RLock", "Condition"):
                        cls.lock_attrs.add(attr)
                        cls.lock_kinds[attr] = cn
                elif cn in idx.classes:
                    cls.attr_types[attr] = cn
            elif isinstance(val, ast.Name):
                # `self.x = seg` where __init__ declares `seg: _Segment` —
                # the parameter annotation types the attribute
                t = _param_ann_types(cls).get(val.id)
                if t is not None and t in idx.classes:
                    cls.attr_types[attr] = t


# ------------------------------------------------------------ call resolution


def _class_method(idx: _Index, cls_name: str, meth: str,
                  _seen=None) -> Optional["_Func"]:
    """Method lookup through the in-repo base chain (by class name)."""
    if _seen is None:
        _seen = set()
    if cls_name in _seen:
        return None
    _seen.add(cls_name)
    cls = idx.classes.get(cls_name)
    if cls is None:
        return None
    if meth in cls.methods:
        return cls.methods[meth]
    for b in cls.bases:
        got = _class_method(idx, b, meth, _seen)
        if got is not None:
            return got
    return None


def _name_fallback(idx: _Index, meth: str) -> List["_Func"]:
    """Conservative global fallback for an unresolved ``obj.m()``: edges
    only when the name is defined in exactly ONE class, or when EVERY
    definition carries the SAME thread-role declaration (the analyst opted
    those APIs into being chased through untyped receivers; identical sets
    mean the edges cannot smear one class's allowed roles into a stricter
    class — either every candidate violates or none does).  Ubiquitous
    stdlib method names never resolve by name alone."""
    if meth in _FALLBACK_BLOCKLIST or meth.startswith("__"):
        return []
    cands = idx.methods_by_name.get(meth, [])
    classes = {c.cls for c in cands}
    if len(classes) == 1:
        return cands
    if cands and all(c.decl_roles is not None for c in cands):
        sets = {frozenset(c.decl_roles) for c in cands}
        if len(sets) == 1:
            return cands
    return []


def _resolve_call(idx: _Index, caller: _Func, spec) -> List["_Func"]:
    kind = spec[0]
    if kind == "name":
        name = spec[1]
        # nested def in an enclosing scope of this file: qual prefix search
        prefix = caller.qual.rsplit("::", 1)
        scope_path = prefix[1] if len(prefix) == 2 else ""
        parts = scope_path.split(".")
        for i in range(len(parts), -1, -1):
            qual = f"{caller.file.rel}::{'.'.join(parts[:i] + [name])}"
            got = idx.by_qual.get(qual)
            if got is not None:
                return [got]
        got = idx.module_funcs.get((caller.file.rel, name))
        if got is not None:
            return [got]
        fi = idx.from_imports[caller.file.rel].get(name)
        if fi is not None:
            mod_rel = idx.module_files.get(fi[0])
            if mod_rel:
                got = idx.module_funcs.get((mod_rel, fi[1]))
                if got is not None:
                    return [got]
        return []
    if kind == "attr":
        _k, base, meth, _node = spec
        if base == "self" and caller.cls:
            got = _class_method(idx, caller.cls, meth)
            return [got] if got is not None else []
        if base is not None:
            # a constructor-typed local resolves precisely (`acc =
            # MicrobatchAccumulator(...); acc.drain()`)
            t = caller.local_types.get(base)
            if t is not None and t in idx.classes:
                got = _class_method(idx, t, meth)
                return [got] if got is not None else []
            mod = idx.mod_alias[caller.file.rel].get(base)
            if mod is None:
                fi = idx.from_imports[caller.file.rel].get(base)
                mod = fi[0] if fi is not None and fi[1] == fi[0] else \
                    (fi[1] if fi is not None else None)
            if mod is not None:
                mod_rel = idx.module_files.get(mod)
                if mod_rel:
                    got = idx.module_funcs.get((mod_rel, meth))
                    return [got] if got is not None else []
                return []
        return _name_fallback(idx, meth)
    if kind == "selfattr":
        _k, attr, meth, _node = spec
        cls = idx.classes.get(caller.cls) if caller.cls else None
        if cls is not None and attr in cls.attr_types:
            got = _class_method(idx, cls.attr_types[attr], meth)
            return [got] if got is not None else []
        return _name_fallback(idx, meth)
    return []


def _resolve_target(idx: _Index, caller: _Func, expr) -> List["_Func"]:
    """Spawn/callback target resolution — broader than call edges (a missed
    target means a whole thread's code runs unclassified)."""
    if isinstance(expr, ast.Lambda):
        qual_prefix = caller.qual.rsplit("::", 1)
        scope = qual_prefix[1] if len(qual_prefix) == 2 else ""
        parts = scope.split(".") if scope else []
        for i in range(len(parts), -1, -1):
            qual = (f"{caller.file.rel}::"
                    f"{'.'.join(parts[:i] + [f'<lambda>@{expr.lineno}'])}")
            got = idx.by_qual.get(qual)
            if got is not None:
                return [got]
        # lambda quals are scope-exact; fall back to a scan
        return [fn for fn in idx.funcs
                if fn.node is expr]
    if isinstance(expr, ast.Name):
        got = _resolve_call(idx, caller, ("name", expr.id, None))
        if got:
            return got
        return idx.funcs_by_name.get(expr.id, [])
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if isinstance(base, ast.Name):
            got = _resolve_call(idx, caller,
                                ("attr", base.id, expr.attr, None))
            if got:
                return got
        elif (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"):
            got = _resolve_call(idx, caller,
                                ("selfattr", base.attr, expr.attr, None))
            if got:
                return got
        return idx.methods_by_name.get(expr.attr, []) or \
            idx.funcs_by_name.get(expr.attr, [])
    return []


# ------------------------------------------------------------ role inference


def _infer_roles(idx: _Index) -> None:
    edges: Dict[str, List[str]] = {fn.qual: fn.edges for fn in idx.funcs}

    def propagate(seeds: List[Tuple[_Func, str, str]]):
        work = []
        for fn, role, why in seeds:
            if role not in fn.roles:
                fn.roles.add(role)
                fn.provenance[role] = why
                work.append((fn, role))
        while work:
            fn, role = work.pop()
            for q in edges.get(fn.qual, ()):
                callee = idx.by_qual[q]
                if role not in callee.roles:
                    callee.roles.add(role)
                    callee.provenance[role] = \
                        f"{fn.provenance.get(role, fn.qual)} -> {callee.name}"
                    work.append((callee, role))

    seeds: List[Tuple[_Func, str, str]] = []
    for fn in idx.funcs:
        if fn.decl_roles:
            for r in fn.decl_roles:
                if r in ROLES:
                    seeds.append((fn, r, f"declared at {fn.qual}"))
        for kind, target, role, node, _daemon in fn.spawns:
            for tgt in _resolve_target(idx, fn, target):
                seeds.append((
                    tgt, role,
                    f"spawned as {role} at "
                    f"{fn.file.rel}:{node.lineno} ({kind})"))
    propagate(seeds)
    # driver default: anything no spawned role reaches is main-thread code
    driver_seeds = [(fn, "driver", f"main-thread default at {fn.qual}")
                    for fn in idx.funcs if not fn.roles]
    propagate(driver_seeds)


def _effective_held(idx: _Index) -> None:
    """Must-analysis: a function whose EVERY resolved call site holds lock L
    effectively runs under L (``HostStore._append_row`` inherits the
    ``upsert`` lock).  Standard intersection fixpoint: entry_held(f) =
    ∩ over call sites (site_held ∪ entry_held(caller)); functions with no
    known call sites (entry points, spawn targets) start — and stay — at ∅.
    Self-recursive edges are ignored (a recursive call cannot prove its own
    entry lock)."""
    sites: Dict[str, List[Tuple[str, frozenset]]] = {}
    for fn in idx.funcs:
        for held, _spec, quals in fn.resolved_sites:
            for q in quals:
                if q != fn.qual:
                    sites.setdefault(q, []).append((fn.qual, held))
    universe = frozenset(k for f in idx.funcs for k, _h, _l in f.acquires)
    eff = {fn.qual: (universe if fn.qual in sites and not fn.spawns
                     and fn.decl_roles is None else frozenset())
           for fn in idx.funcs}
    # spawn TARGETS must also start at ∅ — being called somewhere under a
    # lock proves nothing about the spawned invocation
    spawn_targets = set()
    for fn in idx.funcs:
        for _k, target, _r, _n, _d in fn.spawns:
            for tgt in _resolve_target(idx, fn, target):
                spawn_targets.add(tgt.qual)
    for q in spawn_targets:
        eff[q] = frozenset()
    changed = True
    while changed:
        changed = False
        for q, callers in sites.items():
            if q in spawn_targets:
                continue
            new = None
            for caller_q, held in callers:
                s = held | eff.get(caller_q, frozenset())
                new = s if new is None else (new & s)
            new = new or frozenset()
            if new != eff.get(q):
                eff[q] = new
                changed = True
    for fn in idx.funcs:
        fn.entry_held = eff.get(fn.qual, frozenset())


# ------------------------------------------------------------------- rules


def _rule_role_constraints(idx: _Index) -> None:
    """WF261: inferred roles must stay inside the declared set."""
    for fn in idx.funcs:
        if not fn.decl_roles:
            continue
        declared = {r for r in fn.decl_roles if r in ROLES}
        extra = sorted(fn.roles - declared)
        for role in extra:
            where = fn.provenance.get(role, "?")
            label = f"{fn.cls}.{fn.name}" if fn.cls else fn.name
            idx.finding(
                "WF261", "error", fn.file, fn.lineno,
                f"{label} is declared thread-role"
                f"[{', '.join(fn.decl_roles)}] but is reachable on role "
                f"'{role}' (via {where}) — call it from an allowed role "
                f"only, widen the annotation with a rationale, or break "
                f"the call path")


def _rule_shared_state(idx: _Index) -> None:
    """WF260: cross-role mutable attributes must be consistently locked or
    explicitly annotated."""
    for cls in idx.classes.values():
        # collect accesses per attr from every method (incl. nested funcs
        # whose enclosing class is this one)
        per_attr: Dict[str, List[tuple]] = {}
        for fn in idx.funcs:
            if fn.cls != cls.name or fn.file.rel != cls.file.rel:
                continue
            if fn.name in ("__init__", "__post_init__") \
                    or ".__init__." in fn.qual \
                    or ".__post_init__." in fn.qual:
                continue                  # construction happens-before spawn
            roles = frozenset(fn.roles) or frozenset({"driver"})
            for attr, is_write, lineno, held in fn.accesses:
                per_attr.setdefault(attr, []).append(
                    (roles, is_write, lineno, held | fn.entry_held, fn))
        for attr, accs in sorted(per_attr.items()):
            if attr in cls.guarded or attr in cls.threadsafe_attrs:
                continue
            if attr in cls.single_writer or \
                    cls.class_single_writer is not None:
                continue
            roles_all: Set[str] = set()
            for roles, _w, _l, _h, _fn in accs:
                roles_all |= roles
            writes = [a for a in accs if a[1]]
            if not writes or len(roles_all) < 2:
                continue
            live = [a for a in accs
                    if not a[4].file.allows(a[2], "unguarded")]
            if not live:
                continue
            held_sets = [a[3] for a in live]
            common = set(held_sets[0])
            for h in held_sets[1:]:
                common &= h
            if common:
                continue                  # one lock covers every access
            unlocked = next((a for a in live if not a[3]), live[0])
            writer_roles = set()
            for roles, w, _l, _h, _fn in accs:
                if w:
                    writer_roles |= roles
            idx.finding(
                "WF260", "error", cls.file, unlocked[2],
                f"{cls.name}.{attr} is written under role(s) "
                f"{sorted(writer_roles)} and accessed under "
                f"{sorted(roles_all)} without one consistent "
                f"`with self.<lock>:` around every access — guard it, or "
                f"annotate the declaration with "
                f"`# wf-lint: guarded-by[<lock>]` / "
                f"`# wf-lint: single-writer[<role>]` and say why the "
                f"race is benign")


def _rule_ordered_effects(idx: _Index, replay: Set[str]) -> None:
    """WF262: io_callback in replay modules — literal ordered=True + a
    resolvable callback."""
    seen: Set[int] = set()
    for fn in idx.funcs:
        if fn.file.rel not in replay:
            continue
        for kind, target, _role, node, _d in fn.spawns:
            if kind != "iocb" or id(node) in seen:
                continue                 # one check per call site (a multi-
            seen.add(id(node))           # role spawn has N records)
            if fn.file.allows(node.lineno, "unordered"):
                continue
            ordered = None
            for kw in node.keywords:
                if kw.arg == "ordered":
                    ordered = kw.value
            if not (isinstance(ordered, ast.Constant)
                    and ordered.value is True):
                idx.finding(
                    "WF262", "error", fn.file, node.lineno,
                    "io_callback in a deterministic-replay module must "
                    "pass a LITERAL ordered=True — an unordered host "
                    "callback reorders side effects under scan-fused "
                    "dispatch and breaks byte-identical replay")
            if not _resolve_target(idx, fn, target):
                idx.finding(
                    "WF262", "error", fn.file, node.lineno,
                    "io_callback target does not resolve to a known "
                    "function/method — the analyzer cannot assign it the "
                    "jax-callback role, so its shared-state discipline "
                    "is unchecked; pass a named function or method")


def _rule_lock_order(idx: _Index) -> None:
    """WF263: cycles in the lock-acquisition graph."""
    # eventual locks per function (direct + callees, fixpoint)
    direct: Dict[str, Set[str]] = {
        fn.qual: {k for k, _h, _l in fn.acquires} for fn in idx.funcs}
    callees: Dict[str, List[str]] = {fn.qual: fn.edges for fn in idx.funcs}
    eventual = {q: set(s) for q, s in direct.items()}
    changed = True
    while changed:
        changed = False
        for q, outs in callees.items():
            for o in outs:
                new = eventual.get(o, set()) - eventual[q]
                if new:
                    eventual[q] |= new
                    changed = True
    # edges held -> acquired
    graph: Dict[str, Set[str]] = {}
    site: Dict[Tuple[str, str], Tuple[_File, int]] = {}

    def edge(a: str, b: str, f: _File, lineno: int):
        if a == b:
            return                        # reentrancy handled separately
        graph.setdefault(a, set()).add(b)
        site.setdefault((a, b), (f, lineno))

    def _is_plain_lock(k: str) -> bool:
        cls_attr = k.split(".", 1)
        if len(cls_attr) == 2 and cls_attr[0] in idx.classes:
            return idx.classes[cls_attr[0]].lock_kinds.get(
                cls_attr[1]) == "Lock"
        return False

    for fn in idx.funcs:
        for k, held, lineno in fn.acquires:
            for h in held:
                edge(h, k, fn.file, lineno)
        for held, spec, quals in fn.resolved_sites:
            if not held:
                continue
            for q in quals:
                for k in eventual.get(q, ()):
                    if k in held:
                        # calling into code that re-takes a lock we hold:
                        # a plain Lock deadlocks right here (the a==b edge
                        # the cycle graph deliberately drops)
                        if _is_plain_lock(k):
                            idx.finding(
                                "WF263", "warning", fn.file,
                                spec[-1].lineno,
                                f"call while holding {k} reaches code "
                                f"that re-acquires it ({q.split('::')[-1]}"
                                f") — a non-reentrant Lock deadlocks; "
                                f"hoist the call out of the lock or use "
                                f"an RLock")
                        continue
                    for h in held:
                        edge(h, k, fn.file, spec[-1].lineno)
        # direct self-reacquire of a non-reentrant Lock (nested withs)
        for k, held, lineno in fn.acquires:
            if k in held and _is_plain_lock(k):
                idx.finding(
                    "WF263", "warning", fn.file, lineno,
                    f"re-acquiring non-reentrant lock {k} while "
                    f"already holding it — guaranteed deadlock")
    # cycle detection (DFS)
    color: Dict[str, int] = {}
    stack: List[str] = []
    reported: Set[frozenset] = set()

    def dfs(u: str):
        color[u] = 1
        stack.append(u)
        for v in graph.get(u, ()):
            if color.get(v, 0) == 0:
                dfs(v)
            elif color.get(v) == 1:
                cyc = stack[stack.index(v):] + [v]
                key = frozenset(cyc)
                if key not in reported:
                    reported.add(key)
                    f, lineno = site[(u, v)]
                    idx.finding(
                        "WF263", "warning", f, lineno,
                        f"lock-order cycle {' -> '.join(cyc)} — two "
                        f"threads taking these locks in opposite orders "
                        f"deadlock; impose one global order or collapse "
                        f"to one lock")
        stack.pop()
        color[u] = 2

    for u in list(graph):
        if color.get(u, 0) == 0:
            dfs(u)


def _rule_unjoined_threads(idx: _Index) -> None:
    """WF264: a non-daemon thread with no reachable join() leaks past
    shutdown."""
    seen: Set[int] = set()
    for fn in idx.funcs:
        for kind, _t, _r, node, daemon in fn.spawns:
            if kind != "thread" or daemon or id(node) in seen:
                continue
            seen.add(id(node))
            if fn.file.allows(node.lineno, "unjoined"):
                continue
            if _join_reachable(idx, fn):
                continue
            idx.finding(
                "WF264", "warning", fn.file, node.lineno,
                "non-daemon thread is started but no join() is reachable "
                "from the spawning function, its callees, or its class — "
                "join it on the shutdown path, mark it daemon=True, or "
                "annotate `# wf-lint: allow[unjoined]` with a rationale")


def _join_reachable(idx: _Index, fn: _Func) -> bool:
    if fn.has_join:
        return True
    for q in fn.edges:                          # direct callees, one hop
        if idx.by_qual[q].has_join:
            return True
    if fn.cls:
        cls = idx.classes.get(fn.cls)
        if cls is not None and any(m.has_join for m in cls.methods.values()):
            return True
    return False


# -------------------------------------------------------------- entry point

#: (root, dirs, file-signature) -> indexed+inferred tree.  The index (parse
#: + call graph + role inference + must-held fixpoint) dominates the pass's
#: cost and is a pure function of the scanned sources, so repeat runs in one
#: process (the tier-1 gates call run_lint several times) reuse it; the
#: signature carries every file's (path, mtime_ns, size), so an edited tree
#: re-indexes.  The per-rule passes re-run every time (they are cheap and
#: depend on replay_modules).
_INDEX_CACHE: Dict[tuple, "_Index"] = {}


def _indexed(root: str, package_dirs: Sequence[str]) -> "_Index":
    sig = []
    for p in _walk_py(root, package_dirs):
        try:
            st = os.stat(p)
            sig.append((p, st.st_mtime_ns, st.st_size))
        except OSError:
            sig.append((p, 0, 0))
    key = (os.path.abspath(root), tuple(package_dirs), tuple(sig))
    idx = _INDEX_CACHE.get(key)
    if idx is None:
        idx = _index_tree(root, package_dirs)
        # resolve the call graph ONCE; every later pass reads
        # fn.resolved_sites/fn.edges instead of re-resolving
        for fn in idx.funcs:
            resolved = []
            outs = set()
            for held, spec in fn.call_sites:
                quals = [c.qual for c in _resolve_call(idx, fn, spec)]
                resolved.append((held, spec, quals))
                outs.update(quals)
            fn.resolved_sites = resolved
            fn.edges = sorted(outs)
        #: grammar (WF265) findings discovered during indexing — snapshot
        #: so repeat runs re-emit them without double-appending
        _infer_roles(idx)
        _effective_held(idx)
        idx.grammar_findings = list(idx.findings)
        if len(_INDEX_CACHE) >= 8:    # bound the memory across fixture trees
            _INDEX_CACHE.clear()
        _INDEX_CACHE[key] = idx
    idx.findings = list(idx.grammar_findings)
    return idx


def run_rules(root: str, package_dirs: Sequence[str] = ("windflow_tpu",),
              replay_modules: Optional[Sequence[str]] = None) -> List[dict]:
    """Run the whole-repo concurrency pass; returns plain finding dicts
    (``code``/``severity``/``path``/``line``/``message``/``text``) —
    ``analysis/lint.py`` wraps them into its ``Finding`` type so they ride
    the shared baseline ratchet."""
    idx = _indexed(root, package_dirs)
    _rule_role_constraints(idx)
    _rule_shared_state(idx)
    replay = {p.replace(os.sep, "/")
              for p in (replay_modules if replay_modules is not None
                        else DEFAULT_REPLAY_MODULES)}
    _rule_ordered_effects(idx, replay)
    _rule_lock_order(idx)
    _rule_unjoined_threads(idx)
    out = sorted(idx.findings,
                 key=lambda d: (d["path"], d["line"], d["code"]))
    return out


def inferred_roles(root: str, package_dirs: Sequence[str] = ("windflow_tpu",),
                   ) -> Dict[str, List[str]]:
    """Debug/report surface: ``{function qualname: sorted roles}`` (used by
    tests and by humans answering 'why did WF261 fire?')."""
    idx = _indexed(root, package_dirs)
    return {fn.qual: sorted(fn.roles) for fn in idx.funcs}
