"""Framework invariant linter — Pillar 2 of the static-analysis layer.

Walks ``windflow_tpu/`` (plus ``scripts/`` and ``bench.py`` for the env rule)
with stdlib ``ast``/``re`` ONLY — no JAX import, so the CLI
(``scripts/wf_lint.py``) runs in any environment, pre-commit included — and
enforces the codebase invariants that PRs 1-3 established by convention:

====== ========= =====================================================
code   severity  invariant
====== ========= =====================================================
WF116  error     SLO config the run cannot honor (a validate()-time
                 code, registered in RULES for --explain/--select):
                 ``WF_SLO`` set while monitoring itself resolves off
                 (the engine could never evaluate), a spec set that
                 does not resolve (malformed JSON / unreadable file /
                 unknown field), an unknown signal name (see
                 ``observability/slo.py::SIGNALS``), or burn-window
                 geometry the math rejects (``fast_window >=
                 slow_window``, objective outside (0, 1),
                 ``warn_burn > page_burn``) — fix hints name the
                 registered signals and the window contract
WF117  error     telemetry config the run cannot honor (a
                 validate()-time code, registered in RULES for
                 --explain/--select): ``WF_TELEMETRY`` set while
                 monitoring itself resolves off (the agent rides the
                 Reporter tick — no frames could ever stream), an
                 endpoint that does not parse (``tcp://HOST:PORT`` /
                 ``unix:///path.sock``), or an outbox capacity < 1
WF118  error     remediation config the run cannot honor (a
                 validate()-time code, registered in RULES for
                 --explain/--select): ``WF_REMEDIATION`` set while
                 monitoring/SLO resolve off, an unresolvable policy,
                 an action naming an actuator the run config does not
                 own, a sub-tick cooldown, or a non-barrier actuator
                 under the supervised drivers
WF119  error     serving config the run cannot honor (a
                 validate()-time code, registered in RULES for
                 --explain/--select): serving on while monitoring
                 resolves off, an unparseable endpoint, duplicate
                 tenant ids, wall-clock tenant buckets under
                 supervision, replay < 1, ``swap_warm=False``, or an
                 SLO ``tenant=`` label naming an undeclared tenant
WF200  error     scanned file fails to parse (the linter cannot see it)
WF201  error     ``WF_*`` env read missing from ``docs/ENV_FLAGS.md``
WF202  error     ENV_FLAGS.md row does not state WHEN the flag is read
                 (trace time / run time / process start — the cached-
                 executable footgun the inventory exists to prevent)
WF210  error     wall-clock / ``random`` use inside a deterministic-
                 replay module without ``# wf-lint: allow[wall-clock]``
WF220  error     attribute declared ``# wf-lint: guarded-by[_lock]``
                 accessed outside ``with self._lock:``
WF230  warning   bare ``except:`` / ``except Exception`` without a
                 ``noqa`` rationale (handlers that re-raise are exempt)
WF240  error     journal event/span name not in the central registry
                 (``observability/names.py::JOURNAL_EVENTS``)
WF241  error     counter/gauge name not in the central registries
                 (``RECOVERY_COUNTERS`` / ``CONTROL_COUNTERS`` /
                 ``CONTROL_GAUGES``)
WF250  error     kernel/impl name at a ``register_kernel``/
                 ``resolve_impl`` call site not in the central
                 registries (``observability/names.py::KERNELS`` /
                 ``KERNEL_IMPLS``) — a typo'd kernel name silently
                 forks the env-override/tuning-cache/WF109 namespaces
WF26x  —         the whole-repo static CONCURRENCY pass (thread-role
                 inference, inferred lock discipline WF260, role
                 constraints WF261, ordered effects WF262, lock-order
                 cycles WF263, unjoined threads WF264, grammar WF265)
                 — implemented in the sibling ``concurrency.py``
                 (loaded by path, still no JAX), run by ``run_lint``
                 by default, findings ride this module's baseline
====== ========= =====================================================

Annotation grammar (one per physical line; for a multi-line statement the
annotation goes on the line of the flagged name; declarations may also sit on
the line directly above the assignment):

- ``# wf-lint: allow[<tag>{,<tag>}]`` — suppress a rule at this line.
  Tags: ``wall-clock`` (WF210), ``unguarded`` (WF220),
  ``broad-except`` (WF230 — but prefer the repo's ``noqa: BLE001`` idiom).
- ``# wf-lint: guarded-by[<lock_attr>]`` — trailing an attribute assignment
  inside a class body: declares ``self.<attr>`` as guarded by
  ``self.<lock_attr>``; every access outside a ``with self.<lock_attr>:``
  block (``__init__`` excepted) is a WF220.

Baseline: ``analysis/baseline.json`` suppresses pre-existing findings so the
tier-1 gate (``tests/test_lint_clean.py``) fails only on REGRESSIONS.
Baseline entries match on ``(code, path, stripped source line)`` — stable
across unrelated line-number drift. ``WF_LINT_BASELINE`` overrides the path;
``scripts/wf_lint.py --update-baseline`` rewrites it.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

# --------------------------------------------------------------- findings

SEVERITIES = ("error", "warning")

#: THE lint rule table — one row per diagnostic code, shared by this module,
#: the concurrency pass (``analysis/concurrency.py``, the WF26x family), and
#: the CLI's ``--select``/``--ignore``/``--explain`` surface, so the help
#: text can never drift from the registered codes.  Values:
#: ``(severity, one-line summary)``.
RULES: Dict[str, Tuple[str, str]] = {
    # WF116 is a validate()-time code (analysis/validate.py::_check_slo),
    # registered here so --explain/--select know it — the linter itself
    # never emits it (pre-run config legality needs the live env/config)
    "WF116": ("error", "SLO config the run cannot honor (WF_SLO while "
                       "monitoring off, malformed spec set, unknown "
                       "signal name, fast_window >= slow_window)"),
    # WF117 is likewise validate()-time (validate.py::_check_telemetry)
    "WF117": ("error", "telemetry config the run cannot honor "
                       "(WF_TELEMETRY while monitoring off, "
                       "missing/unparseable endpoint, outbox < 1)"),
    # WF118 is likewise validate()-time (validate.py::_check_remediation /
    # _check_remediation_supervised)
    "WF118": ("error", "remediation config the run cannot honor "
                       "(WF_REMEDIATION while monitoring/SLO off, "
                       "unresolvable policy, unowned actuator, "
                       "sub-tick cooldown, non-barrier actuator under "
                       "supervision)"),
    # WF119 is likewise validate()-time (validate.py::_check_serving,
    # sharing serving/config.py::serving_problems with the ServingRuntime
    # constructor)
    "WF119": ("error", "serving config the run cannot honor "
                       "(WF_SERVE while monitoring off, unparseable "
                       "endpoint, duplicate tenant ids, wall-clock "
                       "tenant buckets under supervision, replay < 1, "
                       "swap_warm=false, SLO tenant= label naming an "
                       "undeclared tenant)"),
    "WF200": ("error", "scanned file fails to parse (the linter cannot "
                       "see it)"),
    "WF201": ("error", "WF_* env read missing from docs/ENV_FLAGS.md"),
    "WF202": ("error", "ENV_FLAGS.md row does not state WHEN the flag is "
                       "read (trace time / run time / process start)"),
    "WF210": ("error", "wall-clock / random use inside a deterministic-"
                       "replay module without allow[wall-clock]"),
    "WF220": ("error", "attribute declared guarded-by[<lock>] accessed "
                       "outside `with self.<lock>:`"),
    "WF230": ("warning", "bare except / except Exception without a "
                         "noqa: BLE001 rationale"),
    "WF240": ("error", "journal event/span name not in "
                       "names.py::JOURNAL_EVENTS"),
    "WF241": ("error", "counter/gauge name not in the central names.py "
                       "registries"),
    "WF250": ("error", "kernel/impl name at register_kernel/resolve_impl "
                       "not in names.py::KERNELS / KERNEL_IMPLS"),
    # -- the WF26x concurrency family (analysis/concurrency.py) -----------
    "WF260": ("error", "cross-thread-role mutable attribute without one "
                       "consistent lock or a guarded-by/single-writer "
                       "annotation"),
    "WF261": ("error", "function reachable from a thread role outside its "
                       "declared thread-role[...] set (e.g. a driver-"
                       "thread-only API called from a spawned thread)"),
    "WF262": ("error", "io_callback in a deterministic-replay module "
                       "without a literal ordered=True, or with an "
                       "unresolvable callback"),
    "WF263": ("warning", "lock-order cycle (potential deadlock) in the "
                         "lock-acquisition graph"),
    "WF264": ("warning", "non-daemon thread started with no reachable "
                         "join() on the shutdown path"),
    "WF265": ("error", "wf-lint concurrency annotation grammar error "
                       "(unknown role / empty role list)"),
    # -- the WF30x device-program family (analysis/progcheck.py) ----------
    # progcheck-time codes: emitted by the jaxpr analyzer (which needs
    # JAX), registered here so --explain/--select know them — this linter
    # never emits them (the WF116-119 precedent).  --explain reads the
    # analyzer's docstring via progcheck_doc() WITHOUT importing it.
    "WF300": ("error", "order-dependent float accumulation (scatter-add "
                       "with possibly-duplicate indices on a float dtype) "
                       "in a deterministic-replay program"),
    "WF301": ("error", "unordered host effect (io_callback/debug_callback "
                       "without ordered=True) reachable from a compiled "
                       "step/scan body — the jaxpr-level complement of "
                       "WF262"),
    "WF302": ("warning", "host-sync in the per-push hot path: a callback "
                         "primitive forcing a blocking D2H round trip "
                         "outside the maintain/settle surfaces (a fusion "
                         "candidate next to wf_health's dispatch_ratio)"),
    "WF303": ("warning", "retrace-signature hazard from actual avals: "
                         "weak-typed program inputs/consts or Python-"
                         "scalar promotions that retrace per call value "
                         "(subsumes the WF102 heuristic)"),
    "WF304": ("error", "donated-buffer aliasing: a donated input read "
                       "after the equation XLA aliases it into, or "
                       "aliased into two outputs"),
    "WF305": ("warning", "shard/K-variant float reduction: accumulation "
                         "grouping that can change with shard count or "
                         "dispatch K (the static evidence for retiring "
                         "WF115 pairings)"),
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint violation, pinned to ``path:line`` with a stable code."""

    code: str
    severity: str
    path: str                    # repo-relative, posix separators
    line: int
    message: str
    text: str = ""               # stripped source line (baseline match key)

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line numbers drift, (code, path, text) do not."""
        return (self.code, self.path, self.text)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.code} [{self.severity}] "
                f"{self.message}")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LintConfig:
    """Scan roots + per-rule scope. Tests override fields to point the rules
    at fixture trees; the defaults describe THIS repository."""

    root: str = "."
    #: directories scanned by every rule (package invariants)
    package_dirs: Sequence[str] = ("windflow_tpu",)
    #: extra scan surface for the env-flag rule only (scripts read WF_* too)
    env_extra_dirs: Sequence[str] = ("scripts",)
    env_extra_files: Sequence[str] = ("bench.py",)
    env_doc: str = os.path.join("docs", "ENV_FLAGS.md")
    #: modules on the deterministic-replay path: checkpoint replay must
    #: reproduce their decisions exactly, so wall-clock/random reads need an
    #: explicit allow[wall-clock] annotation arguing why they are safe
    deterministic_modules: Sequence[str] = (
        os.path.join("windflow_tpu", "runtime", "supervisor.py"),
        os.path.join("windflow_tpu", "runtime", "checkpoint.py"),
        os.path.join("windflow_tpu", "control", "admission.py"),
        # tiered keyed state: tier assignments and host-store content must
        # replay exactly (the spill/readmit protocol is position-driven)
        os.path.join("windflow_tpu", "state", "tiered.py"),
        os.path.join("windflow_tpu", "state", "host_store.py"),
        # the serving plane: admission, framing and replay decisions feed
        # the supervised drivers, so they must replay position-driven
        os.path.join("windflow_tpu", "serving", "framing.py"),
        os.path.join("windflow_tpu", "serving", "sources.py"),
        os.path.join("windflow_tpu", "serving", "tenants.py"),
        os.path.join("windflow_tpu", "serving", "runtime.py"),
        # fleet aggregation windows feed SLO verdicts that remediation
        # acts on — wall-clock reads need an argued allow[wall-clock]
        os.path.join("windflow_tpu", "observability", "fleet.py"),
    )
    #: the central name registries (parsed with ast, never imported)
    names_file: str = os.path.join("windflow_tpu", "observability", "names.py")
    baseline: str = os.path.join("windflow_tpu", "analysis", "baseline.json")
    #: replay-sensitive modules for the WF262 ordered-effect rule — None =
    #: the concurrency pass's default (the deterministic set above plus the
    #: operator modules whose compiled programs embed host callbacks);
    #: fixture tests point it at their module under test
    replay_modules: Optional[Sequence[str]] = None
    #: run the whole-repo concurrency pass (analysis/concurrency.py,
    #: WF26x) as part of run_lint — on by default; fixture tests for the
    #: WF2xx rules may disable it to stay single-concern
    concurrency: bool = True


_ALLOW_RE = re.compile(r"#\s*wf-lint:\s*allow\[([a-z0-9_,\- ]+)\]")
_GUARDED_RE = re.compile(r"#\s*wf-lint:\s*guarded-by\[([A-Za-z_]\w*)\]")
#: the WF230 opt-out requires the BLE001 code (the repo idiom is
#: ``# noqa: BLE001 — <why>``) — a bare ``# noqa`` or an unrelated code
#: (``# noqa: E501``) does not silence the broad-except rule
_NOQA_RE = re.compile(r"#\s*noqa:\s*BLE001\b")

# same patterns as the original tests/test_env_flags.py scanner (now the
# single source of truth; the test delegates here)
_READ_LINE = re.compile(r"environ|getenv|var\s*:\s*str\s*=\s*\"WF_")
_FLAG = re.compile(r"WF_[A-Z][A-Z0-9_]*")
_DOC_ROW = re.compile(r"\|\s*`(WF_[A-Z0-9_]+)`\s*\|([^|]*)\|")
_READ_TIME = re.compile(r"trace|run time|process start|start", re.I)

#: wall-clock attribute reads flagged by WF210 (``random.<anything>`` too)
_WALL_CLOCK_TIME_ATTRS = ("time", "monotonic", "monotonic_ns", "time_ns",
                          "perf_counter", "perf_counter_ns")


def _allows(line: str, tag: str) -> bool:
    m = _ALLOW_RE.search(line)
    if not m:
        return False
    tags = [t.strip() for t in m.group(1).split(",")]
    return tag in tags


# --------------------------------------------------------------- file model


class _File:
    """One parsed python file: source lines + AST (or a parse failure)."""

    def __init__(self, abspath: str, relpath: str):
        self.rel = relpath.replace(os.sep, "/")
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            with open(abspath, encoding="utf-8") as f:
                self.source = f.read()
        except UnicodeDecodeError as e:
            # a non-UTF-8 file is a WF200 like any other unparseable file —
            # it must never crash the gate into 'internal error'
            self.source = ""
            self.parse_error = f"not UTF-8: {e.reason} at byte {e.start}"
        self.lines = self.source.splitlines()
        if self.parse_error is None:
            try:
                self.tree = ast.parse(self.source)
            except SyntaxError as e:
                self.parse_error = (f"{type(e).__name__}: {e.msg} "
                                    f"(line {e.lineno})")

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def allows(self, lineno: int, tag: str) -> bool:
        return _allows(self.line(lineno), tag)

    def finding(self, code: str, severity: str, lineno: int,
                message: str) -> Finding:
        return Finding(code=code, severity=severity, path=self.rel,
                       line=lineno, message=message,
                       text=self.line(lineno).strip())


def _walk_py(root: str, rel_dirs: Sequence[str],
             rel_files: Sequence[str] = ()) -> List[str]:
    out = []
    for d in rel_dirs:
        top = os.path.join(root, d)
        for dirpath, dirnames, names in os.walk(top):
            dirnames[:] = [x for x in dirnames if x != "__pycache__"]
            out += [os.path.join(dirpath, n) for n in sorted(names)
                    if n.endswith(".py")]
    for f in rel_files:
        p = os.path.join(root, f)
        if os.path.exists(p):
            out.append(p)
    return out


def _load_files(root: str, rel_dirs: Sequence[str],
                rel_files: Sequence[str] = ()) -> List[_File]:
    return [_File(p, os.path.relpath(p, root))
            for p in _walk_py(root, rel_dirs, rel_files)]


# ------------------------------------------------------------ rule: WF20x env


def parse_env_doc(doc_path: str) -> Dict[str, Tuple[int, str]]:
    """ENV_FLAGS.md table rows: ``{flag: (line_no, read-at cell)}``."""
    rows: Dict[str, Tuple[int, str]] = {}
    with open(doc_path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            m = _DOC_ROW.match(line)
            if m:
                rows[m.group(1)] = (lineno, m.group(2).strip())
    return rows


def env_flags_read(root: str, cfg: LintConfig) -> Dict[str, Tuple[str, int]]:
    """Every ``WF_*`` flag the tree reads: ``{flag: (relpath, line)}`` (first
    site). A line is a read when it touches the environment (``os.environ`` /
    ``getenv``) or declares the default env-var name a reader resolves later
    (``var: str = "WF_..."`` — the FaultPlan.from_env idiom)."""
    found: Dict[str, Tuple[str, int]] = {}
    scan = list(cfg.package_dirs) + list(cfg.env_extra_dirs)
    for path in _walk_py(root, scan, cfg.env_extra_files):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        # scan-only pass: a stray non-UTF-8 byte must not kill the rule
        with open(path, encoding="utf-8", errors="replace") as f:
            for lineno, line in enumerate(f, 1):
                if not _READ_LINE.search(line):
                    continue
                for flag in _FLAG.findall(line):
                    found.setdefault(flag, (rel, lineno))
    return found


def rule_env_flags(cfg: LintConfig) -> List[Finding]:
    out: List[Finding] = []
    doc_path = os.path.join(cfg.root, cfg.env_doc)
    doc_rel = cfg.env_doc.replace(os.sep, "/")
    if not os.path.exists(doc_path):
        return [Finding("WF201", "error", doc_rel, 1,
                        "docs/ENV_FLAGS.md is missing — every WF_* env read "
                        "must be documented there", "")]
    docs = parse_env_doc(doc_path)
    read = env_flags_read(cfg.root, cfg)
    for flag, (rel, lineno) in sorted(read.items()):
        if flag not in docs:
            out.append(Finding(
                "WF201", "error", rel, lineno,
                f"env flag {flag} is read here but has no row in "
                f"{doc_rel} (add the row — including the read-at column — "
                f"in the same commit)", text=flag))
    for flag, (lineno, cell) in sorted(docs.items()):
        if not _READ_TIME.search(cell):
            out.append(Finding(
                "WF202", "error", doc_rel, lineno,
                f"{doc_rel} row for {flag} does not state WHEN the flag is "
                f"read (trace time / run time / process start) — trace-time "
                f"reads are baked into cached executables", text=flag))
    return out


# ----------------------------------------------------- rule: WF210 wall clock


def _wall_clock_names(tree) -> Tuple[set, set, set]:
    """Per-file alias resolution for the WF210 rule: ``import time as _t`` /
    ``from time import monotonic`` must not escape the gate.  Returns
    (aliases of the time module, aliases of the random module, bare names
    from-imported from either that are wall-clock reads)."""
    time_mods, random_mods, bare = set(), set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    time_mods.add(a.asname or "time")
                elif a.name == "random":
                    random_mods.add(a.asname or "random")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "time":
                for a in node.names:
                    if a.name in _WALL_CLOCK_TIME_ATTRS:
                        bare.add(a.asname or a.name)
            elif node.module == "random":
                for a in node.names:
                    bare.add(a.asname or a.name)
    return time_mods, random_mods, bare


def rule_wall_clock(cfg: LintConfig, files: List[_File]) -> List[Finding]:
    """No ``time.time``/``time.monotonic``/``random.*`` (under any import
    alias) in deterministic-replay modules except at
    ``# wf-lint: allow[wall-clock]`` lines: replay re-drives these modules'
    decisions from checkpoints, and a wall-clock or RNG dependency silently
    forks the replayed stream from the original."""
    det = {p.replace(os.sep, "/") for p in cfg.deterministic_modules}
    out: List[Finding] = []
    for f in files:
        if f.rel not in det or f.tree is None:
            continue
        time_mods, random_mods, bare = _wall_clock_names(f.tree)

        def flag(node, what):
            if f.allows(node.lineno, "wall-clock"):
                return
            out.append(f.finding(
                "WF210", "error", node.lineno,
                f"{what} inside deterministic-replay module {f.rel} — "
                f"replay must reproduce this module's decisions exactly; "
                f"if this use is timing-only (never data), annotate the "
                f"line with `# wf-lint: allow[wall-clock]` and say why"))

        for node in ast.walk(f.tree):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name):
                base = node.value.id
                if (base in time_mods
                        and node.attr in _WALL_CLOCK_TIME_ATTRS) \
                        or base in random_mods:
                    flag(node, f"{base}.{node.attr}")
            elif isinstance(node, ast.Name) and node.id in bare \
                    and isinstance(node.ctx, ast.Load):
                flag(node, node.id)
    return out


# ------------------------------------------------------ rule: WF220 lock use


def _guarded_decls(f: _File, cls: ast.ClassDef) -> Dict[str, str]:
    """``{attr: lock_attr}`` for declarations annotated guarded-by inside
    ``cls`` (annotation on the assignment line or the line directly above)."""
    decls: Dict[str, str] = {}
    for node in ast.walk(cls):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        for t in targets:
            if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                m = _GUARDED_RE.search(f.line(node.lineno))
                if m is None:
                    # line-above form, ONLY on a pure comment line — a
                    # trailing annotation on the previous assignment must
                    # not leak onto this one
                    above = f.line(node.lineno - 1).strip()
                    if above.startswith("#"):
                        m = _GUARDED_RE.search(above)
                if m:
                    decls[t.attr] = m.group(1)
    return decls


def _with_locks(node: ast.With) -> List[str]:
    """Lock attribute names taken by ``with self.<lock>:`` items."""
    out = []
    for item in node.items:
        e = item.context_expr
        if (isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name)
                and e.value.id == "self"):
            out.append(e.attr)
    return out


def rule_lock_guard(cfg: LintConfig, files: List[_File]) -> List[Finding]:
    """Attributes declared ``# wf-lint: guarded-by[<lock>]`` may only be
    touched inside ``with self.<lock>:`` (``__init__`` excepted — the lock is
    being built there). Catches the classic drift: a new method reads a
    shared dict without the lock the rest of the class holds."""
    out: List[Finding] = []
    for f in files:
        if f.tree is None:
            continue
        for cls in [n for n in ast.walk(f.tree)
                    if isinstance(n, ast.ClassDef)]:
            decls = _guarded_decls(f, cls)
            if not decls:
                continue
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if method.name == "__init__":
                    continue

                def visit(node, held):
                    if isinstance(node, ast.With):
                        held = held | set(_with_locks(node))
                    elif isinstance(node, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.Lambda)) and node is not method:
                        # a nested function/lambda DEFINED under the lock
                        # does not RUN under it — a deferred callback
                        # touching the attribute races exactly like any
                        # other unlocked access
                        held = frozenset()
                    if (isinstance(node, ast.Attribute)
                            and isinstance(node.value, ast.Name)
                            and node.value.id == "self"
                            and node.attr in decls
                            and decls[node.attr] not in held
                            and not f.allows(node.lineno, "unguarded")):
                        out.append(f.finding(
                            "WF220", "error", node.lineno,
                            f"{cls.name}.{method.name} touches "
                            f"self.{node.attr} outside `with "
                            f"self.{decls[node.attr]}:` — the attribute is "
                            f"declared guarded-by[{decls[node.attr]}]"))
                    for child in ast.iter_child_nodes(node):
                        visit(child, held)

                visit(method, frozenset())
    return out


# -------------------------------------------------- rule: WF230 broad except


def _handler_reraises(handler: ast.ExceptHandler) -> bool:
    """A handler that re-raises (bare ``raise`` or ``raise <bound name>``) is
    a cleanup handler, not a swallow — exempt."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            if node.exc is None:
                return True
            if (handler.name and isinstance(node.exc, ast.Name)
                    and node.exc.id == handler.name):
                return True
    return False


def _broad_names(type_node) -> List[str]:
    names = []
    nodes = (type_node.elts if isinstance(type_node, ast.Tuple)
             else [type_node])
    for n in nodes:
        if isinstance(n, ast.Name) and n.id in ("Exception", "BaseException"):
            names.append(n.id)
    return names


def rule_broad_except(cfg: LintConfig, files: List[_File]) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if f.tree is None:
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                what = "bare `except:`"
            else:
                broad = _broad_names(node.type)
                if not broad:
                    continue
                what = f"`except {'/'.join(broad)}`"
            line = f.line(node.lineno)
            if _NOQA_RE.search(line) or _allows(line, "broad-except"):
                continue
            if _handler_reraises(node):
                continue
            out.append(f.finding(
                "WF230", "warning", node.lineno,
                f"{what} without a `# noqa: BLE001 — <why>` rationale "
                f"swallows unexpected failures (KeyboardInterrupt, injected "
                f"chaos faults, real bugs); catch the concrete errors or "
                f"state why broad is correct here"))
    return out


# -------------------------------------------- rules: WF240/241 emitted names


def load_name_registries(cfg: LintConfig) -> Dict[str, frozenset]:
    """Parse ``observability/names.py`` with ``ast.literal_eval`` — the
    linter never imports the package (no JAX dependency)."""
    path = os.path.join(cfg.root, cfg.names_file)
    wanted = {"JOURNAL_EVENTS", "RECOVERY_COUNTERS", "CONTROL_COUNTERS",
              "CONTROL_GAUGES"}
    # optional registries (WF250): absent in minimal fixture trees — the
    # rule then simply has nothing to check against
    optional = {"KERNELS", "KERNEL_IMPLS"}
    regs: Dict[str, frozenset] = {}
    tree = ast.parse(open(path, encoding="utf-8").read())
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id in (wanted | optional)):
            regs[node.targets[0].id] = frozenset(
                ast.literal_eval(node.value))
    missing = wanted - set(regs)
    if missing:
        raise ValueError(f"{cfg.names_file} is missing registries: "
                         f"{sorted(missing)}")
    return regs


#: in-module ``bump("...")`` calls resolve by the defining file
_BUMP_FILES = {"windflow_tpu/runtime/faults.py": "RECOVERY_COUNTERS",
               "windflow_tpu/control/_state.py": "CONTROL_COUNTERS"}

#: counter-emitting module basenames -> registry
_COUNTER_MODULES = {"faults": "RECOVERY_COUNTERS",
                    "_state": "CONTROL_COUNTERS"}


def _counter_aliases(tree) -> Tuple[Dict[str, str], Dict[str, str]]:
    """Per-file alias resolution for the WF241 rule: which registry a
    ``bump``/``set_gauge`` call charges, under ANY import spelling
    (``from . import faults as flt``, ``import windflow_tpu.control._state
    as cs``, ``from ..runtime.faults import bump``).  Returns
    (module alias -> registry, directly-imported function name -> registry).
    """
    mod_alias: Dict[str, str] = {}
    func_alias: Dict[str, str] = {}

    def reg_of(dotted: Optional[str]) -> Optional[str]:
        if not dotted:
            return None
        return _COUNTER_MODULES.get(dotted.rsplit(".", 1)[-1])

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                r = reg_of(a.name)
                if r:
                    # `import pkg.faults` binds `pkg`; only an asname gives
                    # a usable single-name base for the call-site check
                    mod_alias[a.asname or a.name.split(".")[0]] = r
        elif isinstance(node, ast.ImportFrom):
            from_reg = reg_of(node.module)
            for a in node.names:
                r = reg_of(a.name)
                if r:                       # from ..runtime import faults as X
                    mod_alias[a.asname or a.name] = r
                elif from_reg and a.name == "bump":
                    func_alias[a.asname or a.name] = from_reg
                elif from_reg and a.name == "set_gauge":
                    func_alias[a.asname or a.name] = "CONTROL_GAUGES"
    return mod_alias, func_alias


def _const_str_arg(node: ast.Call) -> Optional[str]:
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def rule_emitted_names(cfg: LintConfig, files: List[_File]) -> List[Finding]:
    regs = load_name_registries(cfg)
    events = regs["JOURNAL_EVENTS"]
    names_rel = cfg.names_file.replace(os.sep, "/")
    out: List[Finding] = []
    for f in files:
        if f.tree is None or f.rel == names_rel:
            continue
        mod_alias, func_alias = _counter_aliases(f.tree)
        for node in ast.walk(f.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            base = node.func.value
            base_name = base.id if isinstance(base, ast.Name) else \
                (base.attr if isinstance(base, ast.Attribute) else None)
            name = _const_str_arg(node)
            if name is None:
                continue
            is_journal_call = (
                (attr in ("record", "span", "event")
                 and base_name in ("journal", "_journal"))
                # known wrapper that forwards a constant event name to
                # journal.record (parallel/ordering.py::_journal_release) —
                # the direct call site passes a variable, so check the
                # wrapper's callers instead
                or attr == "_journal_release")
            if is_journal_call:
                if name not in events:
                    out.append(f.finding(
                        "WF240", "error", node.lineno,
                        f"journal {attr} name {name!r} is not in "
                        f"{names_rel}::JOURNAL_EVENTS — register it there "
                        f"(one source of truth for dashboards/tests) or fix "
                        f"the typo"))
            elif attr == "bump":
                reg = (mod_alias.get(base_name)
                       if base_name else None) or _BUMP_FILES.get(f.rel)
                if reg and name not in regs[reg]:
                    out.append(f.finding(
                        "WF241", "error", node.lineno,
                        f"counter {name!r} is not in {names_rel}::{reg} — "
                        f"an undeclared counter never appears in snapshots "
                        f"initialized from the registry"))
            elif attr == "set_gauge" and (base_name in mod_alias
                                          or f.rel in _BUMP_FILES):
                if name not in regs["CONTROL_GAUGES"]:
                    out.append(f.finding(
                        "WF241", "error", node.lineno,
                        f"gauge {name!r} is not in "
                        f"{names_rel}::CONTROL_GAUGES"))
        # bare bump("...")/set_gauge("...") calls: directly-imported
        # functions (any alias) and in-module calls in faults.py/_state.py
        for node in ast.walk(f.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                continue
            fname = node.func.id
            name = _const_str_arg(node)
            if name is None:
                continue
            target = func_alias.get(fname)
            if target is None and f.rel in _BUMP_FILES:
                if fname == "bump":
                    target = _BUMP_FILES[f.rel]
                elif fname == "set_gauge":
                    target = "CONTROL_GAUGES"
            if target is None or name in regs[target]:
                continue
            out.append(f.finding(
                "WF241", "error", node.lineno,
                f"{'gauge' if target == 'CONTROL_GAUGES' else 'counter'} "
                f"{name!r} is not in {names_rel}::{target}"))
    return out


# -------------------------------------------- rule: WF250 kernel registry


#: call names the WF250 rule inspects (module functions of ``ops/registry.py``
#: and the ``KernelRegistry`` methods — both spellings appear at call sites)
_KERNEL_CALLS = ("register_kernel", "resolve_impl")


def rule_kernel_names(cfg: LintConfig, files: List[_File]) -> List[Finding]:
    """Every LITERAL kernel name passed to ``register_kernel``/
    ``resolve_impl`` must be in ``names.py::KERNELS`` (and a literal impl
    name at a ``register_kernel`` site in ``KERNEL_IMPLS``) — the same
    one-source-of-truth discipline as WF240/241, for the per-backend kernel
    registry's selection/autotune/WF109 namespaces."""
    regs = load_name_registries(cfg)
    kernels = regs.get("KERNELS")
    impls = regs.get("KERNEL_IMPLS", frozenset())
    names_rel = cfg.names_file.replace(os.sep, "/")
    if kernels is None:
        return []                  # minimal tree without a kernel registry
    out: List[Finding] = []
    for f in files:
        if f.tree is None or f.rel == names_rel:
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            called = (fn.id if isinstance(fn, ast.Name)
                      else (fn.attr if isinstance(fn, ast.Attribute)
                            else None))
            if called not in _KERNEL_CALLS:
                continue
            name = _const_str_arg(node)
            if name is not None and name not in kernels:
                out.append(f.finding(
                    "WF250", "error", node.lineno,
                    f"kernel {name!r} is not in {names_rel}::KERNELS — "
                    f"register it there (env overrides, tuning-cache "
                    f"entries, and WF109 records key on this name) or fix "
                    f"the typo"))
            if (called == "register_kernel" and len(node.args) > 1
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)
                    and node.args[1].value not in impls):
                out.append(f.finding(
                    "WF250", "error", node.lineno,
                    f"kernel impl {node.args[1].value!r} is not in "
                    f"{names_rel}::KERNEL_IMPLS"))
    return out


# --------------------------------------------------------------- the driver


def rule_parse_errors(cfg: LintConfig, files: List[_File]) -> List[Finding]:
    return [f.finding("WF200", "error", 1,
                      f"cannot parse {f.rel}: {f.parse_error}")
            for f in files if f.parse_error is not None]


_CONCURRENCY_MOD = None


def concurrency_module():
    """Load the sibling ``concurrency.py`` by file path (NOT via the
    package — this module itself is path-loaded by ``scripts/wf_lint.py``
    in environments without JAX, where ``windflow_tpu.__init__`` cannot
    import)."""
    global _CONCURRENCY_MOD
    if _CONCURRENCY_MOD is None:
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "concurrency.py")
        spec = importlib.util.spec_from_file_location(
            "wf_analysis_concurrency", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["wf_analysis_concurrency"] = mod
        spec.loader.exec_module(mod)
        _CONCURRENCY_MOD = mod
    return _CONCURRENCY_MOD


def progcheck_doc() -> str:
    """The docstring of the sibling ``progcheck.py`` — parsed with ast,
    NEVER imported (progcheck genuinely needs JAX; this linter and the
    ``wf_lint --explain WF30x`` path must keep working on a box without
    it)."""
    import ast
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "progcheck.py")
    with open(path, encoding="utf-8") as f:
        return ast.get_docstring(ast.parse(f.read())) or ""


def rule_concurrency(cfg: LintConfig) -> List[Finding]:
    """The WF26x whole-repo concurrency pass (thread-role inference,
    inferred lock discipline, ordered effects, lock order, unjoined
    threads) — implemented in ``analysis/concurrency.py``, surfaced here so
    its findings ride the same baseline ratchet and CLI as WF2xx."""
    conc = concurrency_module()
    return [Finding(**d) for d in conc.run_rules(
        cfg.root, cfg.package_dirs, replay_modules=cfg.replay_modules)]


def run_lint(root: str = None, cfg: LintConfig = None) -> List[Finding]:
    """Run every rule over the tree; findings sorted by (path, line, code)."""
    if cfg is None:
        cfg = LintConfig(root=root or ".")
    elif root is not None:
        cfg.root = root
    files = _load_files(cfg.root, cfg.package_dirs)
    findings: List[Finding] = []
    findings += rule_parse_errors(cfg, files)
    findings += rule_env_flags(cfg)
    findings += rule_wall_clock(cfg, files)
    findings += rule_lock_guard(cfg, files)
    findings += rule_broad_except(cfg, files)
    findings += rule_emitted_names(cfg, files)
    findings += rule_kernel_names(cfg, files)
    if cfg.concurrency:
        findings += rule_concurrency(cfg)
    return sorted(findings, key=lambda x: (x.path, x.line, x.code))


# --------------------------------------------------------------- baseline


def baseline_path(cfg: LintConfig) -> str:
    """``WF_LINT_BASELINE`` (run time, CLI/test invocation) overrides the
    checked-in ``analysis/baseline.json`` — point a branch gate at an
    alternate suppression set without editing the tree."""
    override = os.environ.get("WF_LINT_BASELINE", "")
    if override:
        return override if os.path.isabs(override) \
            else os.path.join(cfg.root, override)
    return os.path.join(cfg.root, cfg.baseline)


def load_baseline(path: str) -> Dict[tuple, int]:
    """Suppression keys -> occurrence count from a baseline file; empty when
    absent. Counts matter: two identical ``except Exception:`` lines in one
    file share a key, and a baseline holding ONE must not also suppress a
    newly added second."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    counts: Dict[tuple, int] = {}
    for e in data.get("findings", ()):
        k = (e["code"], e["path"], e.get("text", ""))
        counts[k] = counts.get(k, 0) + 1
    return counts


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    payload = {
        "comment": "pre-existing wf-lint findings suppressed from the tier-1 "
                   "gate; regenerate with scripts/wf_lint.py "
                   "--update-baseline (entries match on code+path+source "
                   "text, so unrelated line drift does not invalidate them)",
        "findings": [{"code": x.code, "path": x.path, "text": x.text,
                      "message": x.message} for x in findings],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=False)
        f.write("\n")


def apply_baseline(findings: Sequence[Finding],
                   baseline: Dict[tuple, int]) -> List[Finding]:
    """Findings NOT suppressed by the baseline (the gate fails on these).
    Each baseline entry suppresses ONE occurrence of its key, in order — a
    new duplicate of a baselined line is a fresh finding."""
    remaining = dict(baseline)
    fresh = []
    for x in findings:
        k = x.key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
        else:
            fresh.append(x)
    return fresh


def split_baseline(cfg: LintConfig, findings: Sequence[Finding],
                   ) -> Tuple[List[Finding], List[Finding]]:
    """(fresh, suppressed) split of ``findings`` against the resolved
    baseline — THE gate semantics, shared by :func:`lint_repo` and the CLI
    so the two can never disagree on what is suppressed."""
    path = baseline_path(cfg)
    if os.environ.get("WF_LINT_BASELINE", "") and not os.path.exists(path):
        # an EXPLICIT override pointing nowhere must fail loudly (CLI exit
        # 2), not resurface the whole baseline as a misleading gate failure
        raise FileNotFoundError(
            f"WF_LINT_BASELINE points at a missing baseline file: {path}")
    base = load_baseline(path)
    fresh = apply_baseline(findings, base)
    fresh_ids = {id(x) for x in fresh}
    return fresh, [x for x in findings if id(x) not in fresh_ids]


def lint_repo(root: str = None, cfg: LintConfig = None,
              ) -> Tuple[List[Finding], List[Finding]]:
    """(fresh, suppressed) findings for the gate: run + baseline filter."""
    if cfg is None:
        cfg = LintConfig(root=root or ".")
    elif root is not None:
        cfg.root = root
    return split_baseline(cfg, run_lint(cfg=cfg))
