"""Graph validator — Pillar 1 of the static-analysis layer.

Flows abstract ``jax.ShapeDtypeStruct`` specs through every operator of a
built (but not yet run) driver — ``PipeGraph`` / ``Pipeline`` /
``ThreadedPipeline`` / ``SupervisedPipeline`` / a raw ``CompiledChain`` — via
the operators' existing ``out_spec``/``eval_shape`` paths (``operators/
filter.py``, ``win_seq.py``, ``sink.py``), and checks the run configuration
(fault plans, governor watermarks, admission control, prefetch) against the
invariants the runtime otherwise only enforces mid-stream.  Zero FLOPs, zero
device access: everything happens at the abstract-spec level, so validation
is safe on a CPU-only box for a graph destined for a TPU pod.

Diagnostics carry stable codes (negative tests pin each one):

====== ========= =====================================================
code   severity  condition
====== ========= =====================================================
WF100  error     nothing to validate (graph without sources / empty)
WF101  error     operator rejects its input payload spec (chained spec
                 mismatch, bad split function, source spec failure)
WF102  warning   operator introduces a weak-typed payload leaf (Python
                 scalar promotion — a silent retrace hazard: the same
                 chain retraces when a later caller passes a strongly-
                 typed value)
WF103  warn/err  fault-plan site unknown (error) or never threaded
                 through the chosen driver (warning — the fault would
                 silently never fire)
WF104  warning   backpressure watermarks degenerate against an edge's
                 ring capacity (resolved high >= capacity: throttle
                 can only trigger on a completely full ring; resolved
                 low >= high: the clamp forces low = high - 1)
WF105  error     admission control illegal under supervision (wall-
                 clock TokenBucket or a drop_oldest_ts holding cell —
                 shed decisions would not replay deterministically)
WF106  warning   prefetch depth exceeds the first ring's capacity
                 (prefetched batches pile up behind a full ring; the
                 governor's pause hook cannot help at that granularity)
WF107  warning   dangling branch: a pipe with no sink, no in-graph
                 ReduceSink, and no downstream edge — its output is
                 silently discarded
WF108  error     trace config illegal / non-deterministic under the
                 chosen driver (unparseable WF_TRACE/WF_TRACE_SAMPLE;
                 ``ids="sequence"`` under supervision — a replay after
                 restore would mint fresh ids and orphan every
                 exemplar and ring-edge flow)
WF109  warning   kernel impl recorded at trace time disagrees with the
                 current registry/env selection (``ops/registry.py``):
                 a cached jitted executable keeps the impl it was
                 traced with, so the toggle the operator thinks is
                 active is NOT what the program runs — the bench would
                 silently measure the same implementation twice
WF111  error     join operator configuration the watermark machinery
                 cannot honor: an interval join with an empty match
                 window (lower > upper), bounds incompatible with the
                 configured watermark delay (upper + delay < 0 — the
                 eviction rule removes every in-window right tuple
                 before any left probe can arrive), or a two-input join
                 whose per-side event-time extractors resolve different
                 dtypes over the upstream pipes' specs (a silent
                 promotion inside every watermark compare)
WF112  error     session-window gap under a CB-only source: every
                 source feeding the session operator assigns no event
                 time (ts defaults to the arrival index), so the gap —
                 defined in event-time units — fires on arrival
                 positions instead
WF113  error     runtime-health config the run cannot honor: the
                 ``WF_MONITORING_HEALTH`` sub-toggle set while
                 monitoring itself resolves off (the ledger could
                 never activate — the run would silently produce no
                 health artifacts), or an illegal
                 ``WF_HEALTH_SAMPLE`` (non-integer / < 1)
WF116  error     SLO config the run cannot honor
                 (``observability/slo.py``): the ``WF_SLO`` sub-toggle
                 set while monitoring itself resolves off (the engine
                 could never evaluate — no burn-rate alerting, no
                 incident capture), a spec set that does not resolve
                 (malformed JSON / unreadable file / bad field), an
                 unknown signal name, or per-spec geometry the burn
                 math rejects (``fast_window >= slow_window``,
                 objective outside (0, 1), ``warn_burn > page_burn``)
WF117  error     telemetry config the run cannot honor
                 (``observability/fleet.py``): the ``WF_TELEMETRY``
                 sub-toggle set while monitoring itself resolves off
                 (the agent rides the Reporter tick — no frames could
                 ever stream), a telemetry endpoint that does not
                 parse (``tcp://HOST:PORT`` / ``unix:///path.sock``),
                 or an outbox capacity < 1 (cannot hold one frame)
WF118  error     remediation config the run cannot honor
                 (``control/remediation.py``): ``WF_REMEDIATION`` set
                 while monitoring itself resolves off (live mode rides
                 the SLO engine's Reporter-tick verdicts — no action
                 could ever fire), remediation on while the SLO engine
                 is off, a policy that does not resolve (unknown
                 actuator / unknown SLO name / unparseable gate), a
                 cooldown below the reporter tick, an action naming an
                 actuator the run config does not own (admission rate
                 without an admission bucket, autotune re-climb with
                 the tuner off, reshard under a live driver), or — on
                 the supervised drivers — an action whose actuator has
                 no deterministic barrier signal (replay could not
                 re-derive it)
WF119  error     serving config the run cannot honor
                 (``serving/config.py``): serving on (``serving=``/
                 ``WF_SERVE``) while monitoring itself resolves off
                 (tenant counters, per-tenant SLOs, and ``graph_swap``
                 spans all live in the monitoring snapshot/journal),
                 an endpoint that does not parse, a tenant set that
                 does not resolve / duplicate tenant ids, wall-clock
                 tenant buckets (``rate_tps``) under supervision (the
                 WF105 mirror — shed decisions would not replay),
                 ``replay`` < 1, ``swap_warm=False`` (the incoming
                 chain would compile inside the swap quiesce, stalling
                 live traffic), or an SLO spec whose ``tenant=`` label
                 names an undeclared tenant (the SLO idles at OK
                 forever)
WF120  error     profile-on-page config the run cannot honor
                 (``observability/profiling.py``): profiling on
                 (``profile=``/``WF_PROFILE``) while the SLO engine
                 resolves off (captures fire from PAGE entry only),
                 a capture window that reaches the reporter interval
                 (the capture runs ON the Reporter tick thread, so
                 such a window stacks ticks), or profiling on under a
                 box with no importable ``jax`` (every capture would
                 be recorded as ``profile_skipped``)
WF114  warn/err  tiered keyed state (``windflow_tpu/state``) combined
                 with a configuration its determinism/sizing contract
                 cannot honor: sequence-id tracing or wall-clock
                 admission under supervision (error — the ordered
                 re-admission callbacks must replay against an
                 identical admitted stream, the WF105/WF108 mirror); a
                 hot table that does not clear its per-batch admission
                 reserve (error — the zero-overflow-drop guarantee is
                 structurally broken); a miss-resolution width outside
                 the probe kernel's blockable geometry (warning — the
                 ``_pallas_block`` gate routes the fused probe to the
                 XLA reference inside the call)
WF110  warn/err  scan dispatch (K > 1) combined with a configuration
                 the fused launch cannot honor: an unresolvable
                 ``dispatch=``/``WF_DISPATCH`` (error);
                 ``ids="sequence"`` tracing or a wall-clock admission
                 bucket under supervision (error — the re-formed
                 groups of a replay would fuse different batches /
                 mint fresh ids, mirroring WF105/WF108); K exceeding
                 a ring's capacity (warning, the WF106 shape — a full
                 fused group can never be ring-resident, so the
                 consumer always flushes short on the linger)
WF115  warn/err  shard-local supervision (``shards=``/``WF_SHARDS``)
                 combined with a configuration its per-shard recovery
                 contract cannot honor: an unresolvable shard count or
                 re-sharding plan (error); scan dispatch K > 1 (error
                 — a fused group failure has no single shard's replay
                 extent); tiered keyed state (error — one process-wide
                 HostStore per operator, a shard restore could roll
                 back peers); wall-clock admission or sequence-id
                 tracing under sharded supervision (error, the
                 WF105/WF108 mirror); a re-sharding plan whose move
                 targets a nonexistent shard (error); more shards than
                 a keyed operator's key space (error — empty shards) /
                 an indivisible key space (warning — uneven ranges);
                 shard fault sites in a plan while shards resolve to 1
                 (warning — the specs could never fire)
====== ========= =====================================================

Usage::

    from windflow_tpu.analysis import validate
    report = validate(graph, faults=plan, control=cfg)
    report.raise_if_errors()          # or: assert not report.errors
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, List, Optional

import jax

from ..batch import CTRL_DTYPE, TupleRef

# ---------------------------------------------------------------- reporting


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One validator finding: stable code, severity, operator path, message,
    and a fix hint (the shift-left counterpart of the runtime's mid-stream
    stack trace)."""

    code: str
    severity: str            # "error" | "warning"
    where: str               # operator path, e.g. "pipe[1].ops[2]:join"
    message: str
    hint: str = ""

    def render(self) -> str:
        s = f"{self.code} [{self.severity}] {self.where}: {self.message}"
        if self.hint:
            s += f"\n    hint: {self.hint}"
        return s


class ValidationError(RuntimeError):
    """Raised by :meth:`ValidationReport.raise_if_errors`; carries the
    report as ``.report``."""

    def __init__(self, report: "ValidationReport"):
        super().__init__("graph validation failed:\n" + str(report))
        self.report = report


class ValidationReport:
    """All diagnostics of one :func:`validate` run."""

    def __init__(self, target: str):
        self.target = target
        self.diagnostics: List[Diagnostic] = []

    def add(self, code: str, severity: str, where: str, message: str,
            hint: str = "") -> None:
        self.diagnostics.append(Diagnostic(code, severity, where, message,
                                           hint))

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "warning"]

    @property
    def ok(self) -> bool:
        return not self.errors

    def codes(self) -> List[str]:
        return [d.code for d in self.diagnostics]

    def raise_if_errors(self) -> "ValidationReport":
        if self.errors:
            raise ValidationError(self)
        return self

    def to_json(self) -> dict:
        return {"target": self.target,
                "diagnostics": [dataclasses.asdict(d)
                                for d in self.diagnostics]}

    def __str__(self) -> str:
        if not self.diagnostics:
            return f"{self.target}: clean"
        return "\n".join(d.render() for d in self.diagnostics)

    __repr__ = __str__


# ------------------------------------------------------------- spec flowing


def _payload_fields(spec) -> str:
    """Human rendering of a payload spec for WF101 hints."""
    try:
        leaves, treedef = jax.tree.flatten(spec)
        shapes = ", ".join(f"{getattr(s, 'shape', '?')}:"
                           f"{getattr(s, 'dtype', '?')}" for s in leaves)
        return f"{treedef.unflatten(leaves)!r} ({shapes})"
    except Exception:  # noqa: BLE001 — hint rendering must never mask WF101
        return repr(spec)


def _weak_leaves(spec) -> List[str]:
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(spec)[0]:
        if getattr(leaf, "weak_type", False):
            out.append(jax.tree_util.keystr(path) or "<leaf>")
    return out


def _check_weak(report, out_spec, in_spec, where: str) -> None:
    """WF102 on NEWLY introduced weak leaves (upstream weakness was already
    reported where it appeared)."""
    new = _weak_leaves(out_spec)
    if new and not _weak_leaves(in_spec):
        report.add(
            "WF102", "warning", where,
            f"output payload leaf {', '.join(new)} is weakly typed (a "
            f"Python-scalar result promoted by eval_shape)",
            hint="return explicitly-dtyped arrays (jnp.asarray(x, "
                 "jnp.float32) / .astype) — weak types make the compiled "
                 "chain's signature depend on Python promotion rules, a "
                 "silent retrace hazard")


def _flow_ops(report, ops, in_spec, where_prefix: str,
              in_capacity: Optional[int]):
    """Flow ``in_spec`` through ``ops`` (binding geometry exactly as
    ``CompiledChain.__init__`` would, so budget-dependent ``out_spec``s — TB
    window archives — resolve). Returns ``(out_spec, out_capacity)``, both
    None after a WF101 (downstream of a broken operator nothing is
    knowable); capacity is None whenever ``in_capacity`` was."""
    spec, cap = in_spec, in_capacity
    for i, op in enumerate(ops):
        where = f"{where_prefix}.ops[{i}]:{op.getName()}"
        try:
            if cap is not None:
                op.bind_geometry(cap)
                cap = op.out_capacity(cap)
            out = op.out_spec(spec)
        except Exception as e:  # noqa: BLE001 — diagnosis IS the product here
            report.add(
                "WF101", "error", where,
                f"operator rejects its input payload spec: "
                f"{type(e).__name__}: {e}",
                hint=f"input payload spec here is {_payload_fields(spec)}; "
                     f"the upstream operator's output must match what "
                     f"{op.getName()!r}'s function destructures")
            return None, None
        _check_weak(report, out, spec, where)
        spec = out
    return spec, cap


def _check_split(report, mp, out_spec, where: str) -> None:
    t = TupleRef(key=jax.ShapeDtypeStruct((), CTRL_DTYPE),
                 id=jax.ShapeDtypeStruct((), CTRL_DTYPE),
                 ts=jax.ShapeDtypeStruct((), CTRL_DTYPE), data=out_spec)
    n = len(mp.split_branches)
    try:
        sel = jax.eval_shape(mp.split_fn, t)
    except Exception as e:  # noqa: BLE001 — diagnosis IS the product here
        report.add("WF101", "error", f"{where}.split",
                   f"split function rejects the pipe's output tuples: "
                   f"{type(e).__name__}: {e}",
                   hint=f"split fn receives TupleRef over payload "
                        f"{_payload_fields(out_spec)}")
        return
    shape = getattr(sel, "shape", None)
    if shape not in ((), (n,)):
        report.add(
            "WF101", "error", f"{where}.split",
            f"split function returns shape {shape}, expected a scalar "
            f"branch index or a multicast mask of shape ({n},) for "
            f"{n} branches")


def _has_reduce_sink(ops) -> bool:
    from ..operators.sink import ReduceSink
    return any(isinstance(op, ReduceSink) for op in ops)


# --------------------------------------------------------- config checking


#: fault-injection sites each driver actually threads (``runtime/threaded.py``
#: fires per stage; the supervisors fire around steps + checkpoint I/O; the
#: plain push drivers fire nothing)
DRIVER_SITES = {
    "pipeline": frozenset(),
    "graph": frozenset(),
    "graph-threaded": frozenset(),
    "threaded": frozenset({"source.next", "queue.stall", "chain.step",
                           "sink.consume"}),
    "supervised": frozenset({"source.next", "chain.step", "sink.consume",
                             "checkpoint.save", "checkpoint.load",
                             "shard.kill", "reshard.handoff"}),
}


def _check_faults(report, faults, driver: str) -> None:
    from ..runtime import faults as _faults
    if faults is None:
        try:
            plan = _faults.FaultPlan.from_env()
        except (ValueError, OSError) as e:
            report.add("WF103", "error", "faults",
                       f"WF_FAULT_PLAN does not parse: {e}")
            return
    elif isinstance(faults, _faults.FaultInjector):
        plan = faults.plan
    elif isinstance(faults, _faults.FaultPlan):
        plan = faults
    elif isinstance(faults, str):
        try:
            plan = _faults.FaultPlan.from_json(faults)
        except (ValueError, KeyError, TypeError) as e:
            report.add("WF103", "error", "faults",
                       f"fault plan does not parse: {type(e).__name__}: {e}",
                       hint="FaultPlan JSON is {\"seed\": n, \"faults\": "
                            "[{\"site\": ..., ...}]}; sites: "
                            + ", ".join(_faults.SITES))
            return
    else:
        plan = None
    if plan is None:
        return
    threaded = DRIVER_SITES.get(driver, frozenset())
    for i, spec in enumerate(plan.faults):
        if spec.site not in _faults.SITES:
            report.add("WF103", "error", f"faults[{i}]",
                       f"unknown fault site {spec.site!r} "
                       f"(sites: {', '.join(_faults.SITES)})")
        elif spec.site not in threaded:
            fired = (", ".join(sorted(threaded)) or
                     "(none — use the threaded or supervised drivers for "
                     "injection)")
            report.add(
                "WF103", "warning", f"faults[{i}]",
                f"fault site {spec.site!r} is never threaded through the "
                f"{driver!r} driver — the spec can never fire",
                hint=f"sites this driver fires: {fired}")


#: shard-only fault sites (warned as WF115 when a plan schedules them while
#: shards resolve to 1 — they could never fire, the WF103 shape)
_SHARD_SITES = frozenset({"shard.kill", "reshard.handoff"})


def _check_shards(report, shards_arg, reshard_arg, ops, cfg, trace,
                  stored_trace, dispatch, stored_dispatch, faults,
                  where: str, shard_key=None) -> None:
    """WF115: shard-local supervision (``runtime/supervisor.py``
    ``ShardedSupervisor``) against configurations its per-shard recovery /
    deterministic re-sharding contracts cannot honor."""
    from ..parallel.sharding import ReshardPlan, resolve_shards
    from ..runtime import faults as _faults
    if reshard_arg is None:
        # mirror the drivers: reshard=None consults WF_RESHARD — an
        # env-driven plan must get the same legality checks as an explicit
        # one (the resolve_shards parity rule)
        try:
            reshard_arg = ReshardPlan.resolve(None)
        except (ValueError, TypeError, KeyError) as e:
            report.add("WF115", "error", f"{where}:reshard",
                       f"WF_RESHARD does not parse: {e}",
                       hint="WF_RESHARD is an int shard count, JSON "
                            "{'at_pos', 'new_shards', 'moves'}, or 'auto'")
            reshard_arg = None
    try:
        n = resolve_shards(shards_arg)
    except (ValueError, TypeError) as e:
        report.add("WF115", "error", f"{where}:shards",
                   f"shard count does not resolve: {e}",
                   hint="shards= (or WF_SHARDS) must be an integer >= 1; "
                        "1/unset = single supervision domain")
        return
    # shard sites scheduled but sharding off: the WF103 can-never-fire shape
    plan = None
    if isinstance(faults, _faults.FaultInjector):
        plan = faults.plan
    elif isinstance(faults, _faults.FaultPlan):
        plan = faults
    elif faults is None:
        try:
            plan = _faults.FaultPlan.from_env()
        except (ValueError, OSError):
            plan = None                    # already a WF103 error
    if n <= 1:
        if plan is not None:
            for i, spec in enumerate(plan.faults):
                if spec.site in _SHARD_SITES:
                    report.add(
                        "WF115", "warning", f"faults[{i}]",
                        f"fault site {spec.site!r} is scheduled but shards "
                        f"resolve to 1 — the spec can never fire",
                        hint="pass shards=N (or WF_SHARDS=N) to run the "
                             "sharded supervisor, or drop the spec")
        if reshard_arg is not None and reshard_arg is not False:
            report.add("WF115", "warning", f"{where}:reshard",
                       "a reshard plan is configured but shards resolve to "
                       "1 — it can never apply",
                       hint="pass shards=N (or WF_SHARDS=N); re-sharding "
                            "runs only under sharded supervision")
        return
    # -- sharded: composition checks --------------------------------------
    from ..runtime.dispatch import DispatchConfig
    try:
        dcfg = (DispatchConfig.resolve(dispatch) if dispatch is not None
                else DispatchConfig.resolve(stored_dispatch))
    except (ValueError, TypeError):
        dcfg = None                        # already a WF110 error
    if dcfg is not None and dcfg.k > 1:
        report.add(
            "WF115", "error", f"{where}:shards",
            f"shards={n} does not compose with scan dispatch (K={dcfg.k}): "
            f"a fused group failure cannot be attributed to one shard's "
            f"replay extent",
            hint="drop dispatch=/WF_DISPATCH (per-shard pushes amortize "
                 "dispatch across shards already), or run shards=1")
    tiered = [op.getName() for op in ops
              if getattr(op, "_tier_cfg", None) is not None]
    if tiered:
        report.add(
            "WF115", "error", f"{where}:shards",
            f"shards={n} does not compose with tiered keyed state "
            f"({', '.join(tiered)}): the per-operator HostStore is one "
            f"process-wide cold tier, so a shard-local restore could roll "
            f"back a peer shard's spilled rows",
            hint="run tiered tables with shards=1, or size the hot tables "
                 "for the full key space and keep tiered= off")
    if cfg is not None and cfg.admission and cfg.refill_per_batch is None:
        report.add(
            "WF115", "error", f"{where}:shards",
            "wall-clock admission under SHARDED supervision: a shard-local "
            "replay must re-shed exactly what the failed attempt shed "
            "(the WF105 contract, per key range)",
            hint="use ControlConfig(refill_per_batch=...) — the "
                 "deterministic positional bucket")
    tcfg = _resolve_trace(trace, stored_trace)
    if tcfg is not None and getattr(tcfg, "ids", "position") != "position":
        report.add(
            "WF115", "error", f"{where}:shards",
            "sequence-id tracing under SHARDED supervision: a shard replay "
            "would mint fresh ids for its key range (the WF108 contract)",
            hint="use TraceConfig(ids='position') — the default")
    # a KeyBy re-keys the stream: ownership is computed at INGEST, so
    # without a shard_key= the re-keyed group scatters across shards and
    # every shard holds a partial (wrong) per-key state
    if shard_key is None:
        from ..operators.map import KeyBy
        rekeys = [op.getName() for op in ops if isinstance(op, KeyBy)]
        if rekeys:
            report.add(
                "WF115", "error", f"{where}:shards",
                f"shards={n} with a KeyBy re-key ({', '.join(rekeys)}) and "
                f"no shard_key=: ownership follows the ingest key, so a "
                f"re-keyed group's tuples scatter across shards (partial "
                f"per-key state, wrong results)",
                hint="pass shard_key=<the KeyBy's fn> (TupleRef -> key) so "
                     "ownership follows the key the state tables use")
    # per-key-range geometry: shards vs every keyed operator's key space
    for op in ops:
        nk = getattr(op, "num_keys", None)
        if not isinstance(nk, int) or nk <= 1:
            continue
        opw = f"{where}:{op.getName()}"
        if n > nk:
            report.add(
                "WF115", "error", opw,
                f"shards={n} exceeds the operator's key space "
                f"(num_keys={nk}): at least {n - nk} shard(s) own no keys "
                f"and can never make progress against their restart budget",
                hint=f"use shards <= {nk} (key ownership is key % shards)")
        elif nk % n:
            report.add(
                "WF115", "warning", opw,
                f"num_keys={nk} is not divisible by shards={n}: key ranges "
                f"are uneven (largest shard owns "
                f"{-(-nk // n)} keys, smallest {nk // n})",
                hint="a shard count dividing the key space balances load "
                     "(and matches any key-axis mesh sharding downstream)")
    # re-sharding plan legality (the nonexistent-shard check)
    if reshard_arg is not None and reshard_arg is not False:
        try:
            rplan = ReshardPlan.resolve(reshard_arg)
        except (ValueError, TypeError, KeyError) as e:
            report.add("WF115", "error", f"{where}:reshard",
                       f"reshard plan does not resolve: {e}",
                       hint="pass a ReshardPlan, dict {'at_pos', "
                            "'new_shards', 'moves'}, an int shard count, "
                            "or 'auto'")
            return
        if rplan == "auto" or rplan is None:
            return
        target_n = rplan.new_shards if rplan.new_shards is not None else n
        if target_n < 1:
            report.add("WF115", "error", f"{where}:reshard",
                       f"reshard plan requests new_shards={target_n} (< 1)",
                       hint="the target shard count must be >= 1")
            return
        for k, s in rplan.moves:
            if not (0 <= s < target_n):
                report.add(
                    "WF115", "error", f"{where}:reshard",
                    f"reshard plan moves key {k} to shard {s}, which does "
                    f"not exist in the target layout ({target_n} shards)",
                    hint=f"move targets must be in [0, {target_n})")


def _resolve_trace(trace, stored_trace):
    """Resolved TraceConfig honoring explicit-over-stored (the WF108
    resolution, shared with the WF115 sequence-id mirror)."""
    from ..observability import TraceConfig
    try:
        return (TraceConfig.resolve(trace) if trace is not None
                else TraceConfig.resolve(stored_trace))
    except (ValueError, TypeError):
        return None                        # already diagnosed as WF108


def _check_watermarks(report, cfg, edges) -> None:
    """``edges``: list of (label, capacity). Mirrors the resolution in
    ``control/governor.py::watch`` — warn where the resolved thresholds
    degenerate."""
    if cfg is None or not cfg.backpressure:
        return
    for label, cap in edges:
        hi = max(1, int(cap * cfg.high_watermark))
        lo_raw = int(cap * cfg.low_watermark)
        if hi >= cap:
            report.add(
                "WF104", "warning", f"edge[{label}]",
                f"resolved high watermark {hi} >= ring capacity {cap} "
                f"(high_watermark={cfg.high_watermark}): the governor can "
                f"only throttle once the ring is completely full, i.e. "
                f"after the producer already blocked inside push",
                hint="raise queue_capacity for this edge (capacity >= 2 "
                     "gives the watermark headroom) or lower high_watermark")
        elif lo_raw >= hi:
            report.add(
                "WF104", "warning", f"edge[{label}]",
                f"resolved low watermark {lo_raw} >= high watermark {hi} "
                f"on capacity {cap}; the runtime clamps low to {hi - 1}, "
                f"so the throttle releases after a single pop",
                hint="widen the high/low fraction gap or raise the edge's "
                     "queue_capacity so the fractions resolve distinctly")


def _check_admission(report, cfg, supervised: bool, where: str) -> None:
    if cfg is None or not cfg.admission:
        return
    if not supervised:
        return
    if cfg.refill_per_batch is None:
        report.add(
            "WF105", "error", where,
            "admission control under supervision uses the wall-clock "
            "TokenBucket (rate_tps) — a restore changes the refill "
            "timeline, so replayed shed decisions diverge from the "
            "original run and exactly-once delivery breaks",
            hint="use ControlConfig(refill_per_batch=...) — the positional "
                 "bucket makes shedding a pure function of stream position, "
                 "which the supervisor snapshots and restores")
    if cfg.shed_policy != "drop_newest":
        report.add(
            "WF105", "error", where,
            f"admission shed_policy={cfg.shed_policy!r} under supervision: "
            f"a drop_oldest_ts holding cell would have to be serialized "
            f"into every checkpoint",
            hint="supervised drivers support shed_policy='drop_newest' only")


def _check_trace(report, trace, stored_arg, supervised: bool) -> None:
    """WF108: the tracing mirror of :func:`_check_admission` — resolve the
    trace config exactly as the driver will (explicit ``trace=`` wins, else
    the object's stored ``trace=`` argument / ``WF_TRACE``) and reject
    configurations the supervised drivers would refuse mid-run."""
    from ..observability.tracing import TraceConfig
    try:
        cfg = TraceConfig.resolve(trace if trace is not None else stored_arg)
    except (ValueError, TypeError) as e:
        report.add("WF108", "error", "trace",
                   f"trace config does not resolve: {type(e).__name__}: {e}",
                   hint="trace= accepts None/bool/out-dir string/TraceConfig;"
                        " WF_TRACE_SAMPLE must be a positive integer")
        return
    if cfg is None:
        return
    if supervised and cfg.ids != "position":
        report.add(
            "WF108", "error", "trace",
            f"trace ids={cfg.ids!r} under supervision: sequence ids come "
            f"from a process-global counter, so a replay after a restore "
            f"mints fresh ids — every exemplar and ring-edge flow recorded "
            f"before the failure dangles",
            hint="use TraceConfig(ids='position') (the default) — ids become "
                 "a pure function of (run_id, stream, position), the same "
                 "replay-determinism contract as the admission "
                 "PositionBucket")


def _check_health(report, stored_monitoring) -> None:
    """WF113: the runtime-health mirror of WF108 — resolve the monitoring
    config exactly as the driver will (the object's stored ``monitoring=``
    argument / ``WF_MONITORING``) and reject health configurations the run
    cannot honor before it starts."""
    import os
    from ..observability import MonitoringConfig
    try:
        cfg = MonitoringConfig.resolve(stored_monitoring)
    except (ValueError, TypeError) as e:
        report.add(
            "WF113", "error", "monitoring.health",
            f"monitoring/health config does not resolve: "
            f"{type(e).__name__}: {e}",
            hint="WF_HEALTH_SAMPLE must be a positive integer "
                 "(MonitoringConfig.health_sample >= 1)")
        return
    if cfg is None:
        env = os.environ.get("WF_MONITORING_HEALTH", "")
        if env not in ("", "0"):
            report.add(
                "WF113", "error", "monitoring.health",
                "WF_MONITORING_HEALTH is set but monitoring itself resolves "
                "off — the health ledger can never activate, so the run "
                "would silently produce no HBM/compile/device-time "
                "artifacts",
                hint="enable monitoring alongside the sub-toggle: "
                     "WF_MONITORING=1 (or monitoring=/MonitoringConfig("
                     "health=True) on the driver)")


def _check_slo(report, stored_monitoring) -> None:
    """WF116: the SLO mirror of WF113 — resolve the monitoring config
    exactly as the Monitor will and reject SLO configurations the engine
    cannot honor before the run starts (the engine itself raises the same
    problems at Monitor construction; this surfaces them pre-run with the
    operator-path/hint shape)."""
    import os
    from ..observability import MonitoringConfig
    from ..observability import slo as _slo
    try:
        cfg = MonitoringConfig.resolve(stored_monitoring)
    except (ValueError, TypeError):
        return                          # already diagnosed as WF113
    if cfg is None:
        env = os.environ.get("WF_SLO", "")
        if env not in ("", "0"):
            report.add(
                "WF116", "error", "monitoring.slo",
                "WF_SLO is set but monitoring itself resolves off — the "
                "SLO engine can never evaluate, so burn-rate alerting and "
                "incident capture are silently disabled",
                hint="enable monitoring alongside the sub-toggle: "
                     "WF_MONITORING=1 (or monitoring=/MonitoringConfig("
                     "slo=...) on the driver)")
        return
    try:
        specs = _slo.resolve_specs(cfg.slo)
    except (ValueError, TypeError, OSError) as e:
        report.add(
            "WF116", "error", "monitoring.slo",
            f"SLO spec set does not resolve: {type(e).__name__}: {e}",
            hint="slo=/WF_SLO accept True/'1' (default specs), a list of "
                 "slo.SLOSpec/dicts, or a JSON file path / inline JSON "
                 "(a list of {name,signal,target,...} objects)")
        return
    if not specs:
        return
    seen = set()
    for spec in specs:
        where = f"slo[{spec.name}]"
        for prob in _slo.spec_problems(spec):
            report.add(
                "WF116", "error", where, prob,
                hint=f"registered signals: {', '.join(sorted(_slo.SIGNALS))}"
                     f"; the burn windows are Reporter ticks — the fast "
                     f"window detects the spike, the slow one confirms the "
                     f"sustained burn (fast < slow)")
        if spec.name in seen:
            report.add("WF116", "error", where,
                       "duplicate SLO name — the snapshot/Prometheus "
                       "surface keys per-SLO rows by name",
                       hint="give every SLOSpec a unique name")
        seen.add(spec.name)


def _check_telemetry(report, stored_monitoring) -> None:
    """WF117: the telemetry mirror of WF116 — resolve the monitoring config
    exactly as the Monitor will and reject telemetry configurations the
    agent cannot honor before the run starts (the TelemetryAgent raises the
    same problems at Monitor construction; this surfaces them pre-run with
    the operator-path/hint shape)."""
    import os
    from ..observability import MonitoringConfig
    try:
        cfg = MonitoringConfig.resolve(stored_monitoring)
    except (ValueError, TypeError):
        return                          # already diagnosed as WF113
    if cfg is None:
        env = os.environ.get("WF_TELEMETRY", "")
        if env not in ("", "0"):
            report.add(
                "WF117", "error", "monitoring.telemetry",
                "WF_TELEMETRY is set but monitoring itself resolves off — "
                "the telemetry agent rides the Reporter tick, so no frames "
                "can ever stream to the fleet aggregator",
                hint="enable monitoring alongside the sub-toggle: "
                     "WF_MONITORING=1 (or monitoring=/MonitoringConfig("
                     "telemetry=...) on the driver)")
        return
    if cfg.telemetry in (False, None):
        return
    # the plane is on: the endpoint must parse and the outbox must hold
    # at least one frame (fleet.py raises the identical ValueErrors at
    # Monitor construction — WF117 is the pre-run surface of those)
    from ..observability import fleet as _fleet
    endpoint = (cfg.telemetry if isinstance(cfg.telemetry, str)
                else os.environ.get("WF_TELEMETRY_ENDPOINT", ""))
    try:
        _fleet.parse_endpoint(endpoint)
    except ValueError as e:
        report.add(
            "WF117", "error", "monitoring.telemetry",
            f"telemetry endpoint does not parse: {e}",
            hint="telemetry='tcp://HOST:PORT' / 'unix:///path.sock' (or "
                 "telemetry=True + WF_TELEMETRY_ENDPOINT); the aggregator "
                 "side is scripts/wf_fleet.py serve --listen <endpoint>")
    if int(cfg.telemetry_outbox) < 1:
        report.add(
            "WF117", "error", "monitoring.telemetry",
            f"telemetry_outbox={cfg.telemetry_outbox} cannot hold a single "
            "frame — the agent's drop-oldest outbox needs capacity >= 1",
            hint="telemetry_outbox/WF_TELEMETRY_OUTBOX must be a positive "
                 "integer (default 64 ticks of backlog)")


def _check_remediation(report, stored_monitoring, control_cfg) -> None:
    """WF118: the remediation mirror of WF116 — resolve the monitoring
    config exactly as the Monitor will and reject remediation policies the
    run cannot honor before it starts (the MonitoringConfig/Monitor raise
    the same problems loudly at construction; this surfaces them pre-run
    with the operator-path/hint shape).  Live-driver surface: ownership is
    checked against the CONTROL config — an action naming an actuator whose
    subsystem is off could only ever skip, never act."""
    import os
    from ..control import remediation as _remediation
    from ..observability import MonitoringConfig
    from ..observability import slo as _slo
    try:
        cfg = MonitoringConfig.resolve(stored_monitoring)
    except (ValueError, TypeError) as e:
        if "remediation" in str(e).lower():
            report.add(
                "WF118", "error", "monitoring.remediation",
                f"monitoring/remediation config does not resolve: "
                f"{type(e).__name__}: {e}",
                hint="remediation requires the SLO engine (slo=/WF_SLO), a "
                     "cooldown >= the reporter interval, and "
                     "max_actions >= 1")
        return                          # otherwise WF113's diagnosis
    if cfg is None:
        env = os.environ.get("WF_REMEDIATION", "")
        if env not in ("", "0"):
            report.add(
                "WF118", "error", "monitoring.remediation",
                "WF_REMEDIATION is set but monitoring itself resolves off — "
                "the remediation engine rides the SLO engine's Reporter-tick "
                "verdicts, so no action could ever fire",
                hint="enable monitoring alongside the sub-toggle: "
                     "WF_MONITORING=1 (or monitoring=/MonitoringConfig("
                     "remediation=...) on the driver); note the supervised "
                     "drivers consume WF_REMEDIATION directly (barrier "
                     "mode) and need no monitoring")
        return
    try:
        policy = _remediation.resolve_policy(cfg.remediation)
    except (ValueError, TypeError) as e:
        report.add(
            "WF118", "error", "monitoring.remediation",
            f"remediation policy does not resolve: {type(e).__name__}: {e}",
            hint="remediation=/WF_REMEDIATION accept True/'1' (the default "
                 "policy), a RemediationPolicy, a list of actions/dicts, a "
                 "JSON file path, or inline JSON (actions = {name, slo, "
                 "actuator, ...})")
        return
    if policy is None:
        return
    try:
        spec_names = [s.name for s in (_slo.resolve_specs(cfg.slo) or [])]
    except (ValueError, TypeError, OSError):
        spec_names = None               # already diagnosed as WF116
    for prob in _remediation.policy_problems(policy, spec_names or None):
        report.add(
            "WF118", "error", "monitoring.remediation", prob,
            hint=f"actuators: {', '.join(sorted(_remediation.ACTUATORS))}; "
                 f"every action's slo must name a configured SLOSpec")
    # ownership: an actuator whose owning subsystem the control config has
    # off can only ever skip (reason 'unbound') — reject it pre-run
    for a in policy.actions:
        where = f"remediation[{a.name}]"
        if a.actuator == "admission_rate" and (
                control_cfg is None or not control_cfg.admission):
            report.add(
                "WF118", "error", where,
                "actuator 'admission_rate' but the run has no admission "
                "controller — the action could only ever skip as 'unbound'",
                hint="enable ControlConfig(admission=True, ...) (control=/"
                     "WF_CONTROL) alongside the policy, or drop the action")
        elif a.actuator == "autotune_reclimb" and (
                control_cfg is None or not control_cfg.autotune):
            report.add(
                "WF118", "error", where,
                "actuator 'autotune_reclimb' but the autotuner is off — "
                "the action could only ever skip as 'unbound'",
                hint="enable ControlConfig(autotune=True) (the Pipeline "
                     "driver's capacity ladder), or drop the action")
        elif a.actuator == "reshard":
            report.add(
                "WF118", "error", where,
                "actuator 'reshard' under a live driver — re-sharding is "
                "the sharded supervisor's barrier actuator, never bound by "
                "the live drivers",
                hint="run SupervisedPipeline(shards=N, remediation=...) for "
                     "remediation-driven resharding, or drop the action")


def _check_remediation_supervised(report, sp) -> None:
    """WF118 (barrier surface): re-resolve the supervised driver's
    ``remediation=``/``WF_REMEDIATION`` argument exactly as its constructor
    does — every action must be barrier-actionable AND owned by the run
    config (deterministic admission bucket / shards > 1)."""
    import os
    from ..control import remediation as _remediation
    arg = getattr(sp, "_remediation_arg", None)
    if arg is None:
        arg = os.environ.get("WF_REMEDIATION")
    try:
        policy = _remediation.resolve_barrier_policy(
            arg, admission=getattr(sp, "_admission", None) is not None,
            shards=getattr(sp, "_shards", 1))
    except (ValueError, TypeError) as e:
        report.add(
            "WF118", "error", "supervised.remediation",
            f"supervised remediation config cannot work: "
            f"{type(e).__name__}: {e}",
            hint="barrier mode fires only actuators with deterministic "
                 "committed signals: 'admission_rate' (needs ControlConfig("
                 "admission=True, refill_per_batch=...)) and 'reshard' "
                 "(needs shards > 1); use the live drivers' monitoring= "
                 "remediation for the rest")
        return
    if policy is None:
        return
    cool = os.environ.get("WF_REMEDIATION_COOLDOWN_S", "")
    if cool:
        try:
            ok = float(cool) >= 0
        except ValueError:
            ok = False
        if not ok:
            report.add(
                "WF118", "error", "supervised.remediation",
                f"WF_REMEDIATION_COOLDOWN_S={cool!r} does not parse as a "
                f"non-negative number",
                hint="barrier mode rounds the cooldown to whole barriers "
                     "(>= 1)")
    maxa = os.environ.get("WF_REMEDIATION_MAX_ACTIONS", "")
    if maxa:
        try:
            ok = int(maxa) >= 1
        except ValueError:
            ok = False
        if not ok:
            report.add(
                "WF118", "error", "supervised.remediation",
                f"WF_REMEDIATION_MAX_ACTIONS={maxa!r} must be an integer "
                f">= 1",
                hint="the per-run action budget bounds remediation blast "
                     "radius, like slo_max_incidents bounds bundles")


def _check_serving(report, stored_serving, stored_monitoring,
                   supervised) -> None:
    """WF119: the serving mirror of WF116/117 — resolve the serving config
    exactly as ``ServingRuntime`` will (``serving=`` argument, else
    ``WF_SERVE``/``WF_SERVE_ENDPOINT``/``WF_TENANTS``) and reject
    configurations the serving plane cannot honor before the run starts
    (the runtime raises the same problems at construction; this surfaces
    them pre-run with the operator-path/hint shape)."""
    from ..serving.config import ServingConfig, serving_problems
    try:
        cfg = ServingConfig.resolve(stored_serving)
    except (ValueError, TypeError, OSError) as e:
        report.add(
            "WF119", "error", "serving",
            f"serving config does not resolve: {type(e).__name__}: {e}",
            hint="serving=/WF_SERVE accept True/'1' (defaults), an endpoint "
                 "string ('tcp://HOST:PORT' / 'unix:///path.sock'), a "
                 "ServingConfig/dict, a JSON file path, or inline JSON "
                 "({endpoint, tenants, swap_warm, replay})")
        return
    if cfg is None:
        return
    slo_specs = None
    try:
        from ..observability import MonitoringConfig
        from ..observability import slo as _slo
        mcfg = MonitoringConfig.resolve(stored_monitoring)
        if mcfg is not None:
            slo_specs = _slo.resolve_specs(mcfg.slo)
    except (ValueError, TypeError, OSError):
        slo_specs = None                # already diagnosed as WF113/WF116
    for prob in serving_problems(cfg, monitoring=stored_monitoring,
                                 supervised=supervised,
                                 slo_specs=slo_specs):
        report.add(
            "WF119", "error", "serving", prob,
            hint="the serving plane rides monitoring for per-tenant SLOs "
                 "and remediation: tenant ids must be unique, supervised "
                 "buckets deterministic (refill_per_batch, not rate_tps), "
                 "swaps warmed (swap_warm=True), and every slo tenant= "
                 "label a declared tenant id")


def _check_profile(report, stored_monitoring) -> None:
    """WF120: the profile-on-page mirror of WF118 — resolve the monitoring
    config exactly as the Monitor will and reject profile configurations
    the capture path cannot honor before the run starts (the
    MonitoringConfig/Monitor raise the structural problems at
    construction; WF120 is the pre-run surface of those PLUS the
    jax-availability probe only a validator run can usefully report)."""
    import os
    from ..observability import MonitoringConfig
    from ..observability import profiling as _profiling
    try:
        cfg = MonitoringConfig.resolve(stored_monitoring)
    except (ValueError, TypeError) as e:
        if "profile" in str(e).lower():
            report.add(
                "WF120", "error", "monitoring.profile",
                f"monitoring/profile config does not resolve: "
                f"{type(e).__name__}: {e}",
                hint="profile-on-page requires the SLO engine (slo=/WF_SLO) "
                     "and a capture window below the reporter interval "
                     "(WF_PROFILE_WINDOW_MS < WF_MONITORING_INTERVAL)")
        return                          # otherwise WF113's diagnosis
    if cfg is None:
        env = os.environ.get("WF_PROFILE", "")
        if env not in ("", "0"):
            report.add(
                "WF120", "error", "monitoring.profile",
                "WF_PROFILE is set but monitoring itself resolves off — "
                "profile-on-page rides the SLO engine's incident capture, "
                "so no profiler window could ever open",
                hint="enable monitoring alongside the sub-toggle: "
                     "WF_MONITORING=1 WF_SLO=1 (or monitoring=/"
                     "MonitoringConfig(slo=..., profile=...) on the driver)")
        return
    try:
        prof = _profiling.resolve_profile(
            cfg.profile if cfg.profile is not False else None)
    except (ValueError, TypeError) as e:
        report.add(
            "WF120", "error", "monitoring.profile",
            f"profile config does not resolve: {type(e).__name__}: {e}",
            hint="profile=/WF_PROFILE accept True/'1' (defaults) or a "
                 "profiling.ProfileConfig; WF_PROFILE_WINDOW_MS must be a "
                 "positive number, WF_PROFILE_MAX_CAPTURES an integer >= 1")
        return
    for prob in _profiling.profile_problems(
            prof, slo_on=cfg.slo not in (False, None, "", "0"),
            interval_s=cfg.interval_s):
        report.add(
            "WF120", "error", "monitoring.profile", prob,
            hint="captures fire from PAGE entry on the Reporter tick "
                 "thread through the ONE stats.xprof_trace session guard; "
                 "see observability/profiling.py + scripts/wf_profile.py")


def _check_kernel_records(report) -> None:
    """WF109: compare every kernel-impl choice the registry recorded at
    trace time against what it would resolve to NOW (env/tuning-cache as of
    this call). A disagreement means some cached executable in this process
    is running an impl the current configuration no longer selects — the
    A/B-measured-the-same-impl-twice footgun documented at the
    ``WF_*_IMPL`` definition sites, now detectable instead of folklore."""
    from ..ops import registry as _registry
    for rec in _registry.stale_selections():
        report.add(
            "WF109", "warning",
            f"kernel[{rec['kernel']}]",
            f"impl {rec['recorded']!r} was resolved at trace time (spec "
            f"{rec['spec_key']!r}, {rec['device']}) but the registry now "
            f"selects {rec['current']!r} — executables compiled before the "
            f"change keep {rec['recorded']!r} for the life of the process "
            f"(XLA caches the traced program, not the env)",
            hint="force a retrace (fresh process / new shapes), pass impl= "
                 "explicitly, or revert the WF_KERNEL_IMPL/alias/tuning-"
                 "cache change; docs/ENV_FLAGS.md lists the trace-time "
                 "flags")


def _check_prefetch(report, prefetch: int, first_edge) -> None:
    if not prefetch or first_edge is None:
        return
    label, cap = first_edge
    if prefetch > cap:
        report.add(
            "WF106", "warning", f"edge[{label}]",
            f"prefetch depth {prefetch} exceeds the first ring's capacity "
            f"{cap}: up to {prefetch - cap} prefetched (H2D-transferred) "
            f"batches pile up behind a full ring where the governor's "
            f"pause hook cannot reach them",
            hint="size prefetch <= the src edge's queue_capacity")


def _check_dispatch(report, dispatch, stored_arg, cfg, trace, stored_trace,
                    supervised: bool, edges=None) -> None:
    """WF110: scan dispatch (``runtime/dispatch.py``) against configurations
    the K-fused launch cannot honor — resolved exactly as the driver will
    (explicit ``dispatch=`` wins, else the object's stored argument /
    ``WF_DISPATCH``), the WF105/WF108 convention."""
    from ..runtime.dispatch import DispatchConfig
    try:
        dcfg = DispatchConfig.resolve(dispatch if dispatch is not None
                                      else stored_arg)
    except (ValueError, TypeError, OSError) as e:
        report.add("WF110", "error", "dispatch",
                   f"dispatch config does not resolve: "
                   f"{type(e).__name__}: {e}",
                   hint="dispatch= accepts None/bool/int K/dict/"
                        "DispatchConfig; WF_DISPATCH_K must be a positive "
                        "integer")
        return
    if dcfg is None or dcfg.k <= 1:
        return
    if supervised:
        from ..observability.tracing import TraceConfig
        try:
            tcfg = TraceConfig.resolve(trace if trace is not None
                                       else stored_trace)
        except (ValueError, TypeError):
            tcfg = None                # already diagnosed as WF108
        if tcfg is not None and tcfg.ids != "position":
            report.add(
                "WF110", "error", "dispatch",
                f"dispatch k={dcfg.k} with trace ids={tcfg.ids!r} under "
                f"supervision: per-batch spans are synthesized from each "
                f"fused launch, and sequence ids come from a process-global "
                f"counter — a replay after restore re-forms the groups but "
                f"mints fresh ids for them, orphaning every exemplar "
                f"recorded before the failure",
                hint="use TraceConfig(ids='position') (the default) so span "
                     "ids are a pure function of stream position, the same "
                     "contract the accumulator's count-based flush follows")
        if (cfg is not None and cfg.admission
                and cfg.refill_per_batch is None):
            report.add(
                "WF110", "error", "dispatch",
                f"dispatch k={dcfg.k} with wall-clock admission (rate_tps) "
                f"under supervision: group boundaries are count-based so "
                f"replay re-forms them, but the wall-clock refill timeline "
                f"shifts on restore — the re-formed groups would fuse "
                f"DIFFERENT batches than the original run",
                hint="use ControlConfig(refill_per_batch=...) — positional "
                     "admission keeps the admitted stream (and therefore "
                     "every fused group) a pure function of position")
    for label, cap in (edges or []):
        if dcfg.k > cap:
            report.add(
                "WF110", "warning", f"edge[{label}]",
                f"dispatch k={dcfg.k} exceeds ring capacity {cap}: a full "
                f"fused group can never be resident in the ring at once, so "
                f"the consumer flushes short on the linger nearly every "
                f"group — the (K, capacity) executable is traced and warmed "
                f"but rarely runs at full K",
                hint="size queue_capacity >= dispatch k (room for one full "
                     "group) or lower k for this topology")


def _check_tiered(report, ops, cfg, trace, stored_trace,
                  supervised: bool, where_prefix: str) -> None:
    """WF114: tiered keyed state (``windflow_tpu/state``) against
    configurations its determinism/sizing contract cannot honor.

    - **error** — tiered state under supervision with sequence-id tracing
      or a wall-clock admission bucket (the WF105/WF108 mirror): the
      ordered re-admission callbacks replay in stream order, but a shifted
      shed pattern / fresh trace ids would desynchronize the replayed
      miss sequence from the failed attempt's host-store mutations.
    - **error** — a tiered table whose hot capacity does not clear its
      per-batch admission reserve (batch keys + parked pending keys): the
      zero-overflow-drop guarantee is structurally broken, every batch
      thrashes the whole table through the spill path.
    - **warning** — the miss-resolution probe width does not satisfy the
      probe kernel's blockable-geometry constraint (``ops/lookup.py::
      _pallas_block``): with ``WF_KERNEL_IMPL=pallas`` the fused probe
      falls back to the XLA reference inside the call (correct, slower).
    """
    from ..ops.lookup import _pallas_block
    tiered = [(i, op, op._tier_cfg) for i, op in enumerate(ops)
              if getattr(op, "_tier_cfg", None) is not None]
    if not tiered:
        return
    if supervised:
        from ..observability.tracing import TraceConfig
        try:
            tcfg = TraceConfig.resolve(trace if trace is not None
                                       else stored_trace)
        except (ValueError, TypeError):
            tcfg = None                # already diagnosed as WF108
        if tcfg is not None and tcfg.ids != "position":
            report.add(
                "WF114", "error", f"{where_prefix}:tiered",
                f"tiered state with trace ids={tcfg.ids!r} under "
                f"supervision: the spill/readmit protocol replays the "
                f"ordered host callbacks by stream position, but sequence "
                f"ids are minted from a process counter — a replay after "
                f"restore would walk a different id timeline than the "
                f"host-store mutations it re-derives",
                hint="use TraceConfig(ids='position') (the default), the "
                     "same contract supervised tracing itself requires")
        if (cfg is not None and cfg.admission
                and cfg.refill_per_batch is None):
            report.add(
                "WF114", "error", f"{where_prefix}:tiered",
                "tiered state with wall-clock admission (rate_tps) under "
                "supervision: eviction/re-admission decisions are a pure "
                "function of the admitted stream, and a wall-clock refill "
                "timeline shifts on restore — replay would re-derive "
                "DIFFERENT tier assignments than the failed attempt spilled",
                hint="use ControlConfig(refill_per_batch=...) so the "
                     "admitted stream — and every tier decision — is a "
                     "pure function of position")
    for i, op, tc in tiered:
        where = f"{where_prefix}.ops[{i}]:{op.getName()}"
        cap = getattr(op, "_cap_resolved", None) \
            or getattr(op, "_cap", None) or getattr(op, "_pending", None)
        pending = getattr(op, "_pending_resolved", None)
        if cap is None:
            continue                    # not geometry-bound yet
        hot = int(tc.hot_capacity
                  or getattr(op, "_slots", None)
                  or getattr(op, "num_slots", 0) or 0)
        reserve = int(cap) + int(pending or 0)
        if hot and pending is not None and hot <= reserve:
            report.add(
                "WF114", "error", where,
                f"tiered hot capacity {hot} <= per-batch admission reserve "
                f"{reserve} (batch capacity {cap} + pending ring "
                f"{pending}): the miss-resolution pass can need a fresh "
                f"slot for every resolved key, so the zero-overflow-drop "
                f"guarantee is structurally broken and every batch "
                f"thrashes the whole table through the spill path",
                hint="raise num_slots/TierConfig.hot_capacity above "
                     "batch + pending (the resolve width), or shrink the "
                     "batch")
        elif hot and pending is None and hot <= int(cap):
            report.add(
                "WF114", "error", where,
                f"tiered hot capacity {hot} <= batch capacity {cap}: one "
                f"batch of distinct keys can oversubscribe the hot "
                f"directory — those lanes drop (counted overflow_drops)",
                hint="raise num_keys/TierConfig.hot_capacity above the "
                     "batch capacity")
        width = int(cap) + int(pending or 0)
        if width and not _pallas_block(width):
            report.add(
                "WF114", "warning", where,
                f"tiered miss-resolution width {width} (batch + pending) "
                f"does not satisfy the probe kernel's blockable-geometry "
                f"constraint (ops/lookup.py::_pallas_block): under "
                f"WF_KERNEL_IMPL=pallas the fused probe falls back to the "
                f"XLA reference inside the call — correct, but the Pallas "
                f"win silently disappears",
                hint="keep batch + pending a multiple of 128 (or of 8192 "
                     "beyond 8192 lanes) so the Pallas envelope holds")


def _feeding_sources(mp) -> list:
    """Every source transitively feeding a graph pipe (through merges and
    split parents) — the WF112 session/event-time check needs to know
    whether ANY upstream assigns event time."""
    out, seen = [], set()

    def visit(p):
        if id(p) in seen:
            return
        seen.add(id(p))
        if p.source is not None:
            out.append(p.source)
        for up in p.merge_inputs:
            visit(up)
        if p._dataflow_parent is not None:
            visit(p._dataflow_parent)
    visit(mp)
    return out


def _check_stream_ops(report, ops, in_spec, where_prefix: str,
                      sources=()) -> None:
    """WF111/WF112: join/session operator configuration against the
    watermark machinery — spec-level only, zero device work."""
    from ..operators.join import IntervalJoin
    from ..operators.session import SessionWindow
    from ..operators.source import DeviceSource
    spec = in_spec
    for i, op in enumerate(ops):
        where = f"{where_prefix}.ops[{i}]:{op.getName()}"
        if isinstance(op, IntervalJoin):
            if op.lower > op.upper:
                report.add(
                    "WF111", "error", where,
                    f"interval-join match window is empty: lower "
                    f"{op.lower} > upper {op.upper} — no pair can ever "
                    f"satisfy r.ts - l.ts in [lower, upper]",
                    hint="swap the bounds (lower <= upper); [0, W] matches "
                         "rights up to W ticks after their left")
            elif op.upper + op.delay < 0:
                report.add(
                    "WF111", "error", where,
                    f"interval-join bounds are incompatible with the "
                    f"configured watermark delay: upper {op.upper} + delay "
                    f"{op.delay} < 0, so the eviction rule (keep r.ts >= "
                    f"wm - delay + lower) removes every in-window right "
                    f"tuple before any left probe can arrive",
                    hint="raise delay to at least -upper (the lateness the "
                         "backward-looking window implies), or widen upper")
            if ((op.ts_l is not None or op.ts_r is not None)
                    and spec is not None):
                ref = TupleRef(key=jax.ShapeDtypeStruct((), CTRL_DTYPE),
                               id=jax.ShapeDtypeStruct((), CTRL_DTYPE),
                               ts=jax.ShapeDtypeStruct((), CTRL_DTYPE),
                               data=spec)
                try:
                    dl = (jax.eval_shape(op.ts_l, ref).dtype
                          if op.ts_l is not None else CTRL_DTYPE)
                    dr = (jax.eval_shape(op.ts_r, ref).dtype
                          if op.ts_r is not None else CTRL_DTYPE)
                except Exception as e:  # noqa: BLE001 — surfaced as WF111
                    report.add("WF111", "error", where,
                               f"event-time extractor rejects the upstream "
                               f"payload spec: {type(e).__name__}: {e}")
                else:
                    if dl != dr:
                        report.add(
                            "WF111", "error", where,
                            f"the two join inputs disagree on timestamp "
                            f"dtype: left extractor resolves {dl}, right "
                            f"resolves {dr} — every watermark compare "
                            f"would silently promote one side",
                            hint="cast both extractors to one dtype "
                                 "(int32 event time is the control-field "
                                 "contract)")
        spec_attr = getattr(op, "spec", None)
        if (isinstance(op, SessionWindow)
                or getattr(spec_attr, "is_session", False)):
            from ..operators.source import RecordSource

            def _no_event_time(s):
                # ts defaults to the arrival index: DeviceSource without a
                # ts_fn, RecordSource without a ts_field. GeneratorSource
                # items MAY carry (payload, key, ts) triples — unknowable
                # statically, so it never triggers the diagnostic.
                if isinstance(s, RecordSource):
                    return s.ts_field is None
                if isinstance(s, DeviceSource):
                    return s.ts_fn is None
                return False
            if sources and all(_no_event_time(s) for s in sources):
                report.add(
                    "WF112", "error", where,
                    f"session gap ({spec_attr.gap if spec_attr else '?'}) "
                    f"under a CB-only source: every source feeding this "
                    f"operator assigns no event time (ts defaults to the "
                    f"tuple index), so the gap — an event-time quantity — "
                    f"would fire on arrival positions",
                    hint="give the source a ts_fn (DeviceSource) / ts "
                         "column (GeneratorSource ts triple, RecordSource "
                         "ts_field) carrying real event time")
        try:
            spec = op.out_spec(spec) if spec is not None else None
        except Exception:  # noqa: BLE001 — already diagnosed as WF101
            spec = None


def _resolve_control(explicit, stored):
    from ..control import ControlConfig
    if explicit is not None:
        return ControlConfig.resolve(explicit)
    return stored


# -------------------------------------------------------------- validators


def _source_spec(report, source, where: str) -> Optional[Any]:
    """Source ``payload_spec()`` with the WF101/WF102 checks — the one
    implementation every driver validator goes through. None on failure."""
    try:
        spec = source.payload_spec()
    except Exception as e:  # noqa: BLE001 — diagnosis IS the product here
        report.add("WF101", "error", where,
                   f"source payload_spec() fails: {type(e).__name__}: {e}")
        return None
    weak = _weak_leaves(spec)
    if weak:
        report.add("WF102", "warning", where,
                   f"source payload leaf {', '.join(weak)} is weakly typed",
                   hint="emit explicitly-dtyped payloads from the source")
    return spec


def _validate_chain_ops(report, ops, in_spec, in_cap, where: str,
                        sink=None) -> Optional[Any]:
    out, _cap = _flow_ops(report, ops, in_spec, where, in_cap)
    if sink is None and not _has_reduce_sink(ops):
        report.add(
            "WF107", "warning", where,
            "no sink and no in-graph ReduceSink: every output batch is "
            "computed, transferred, and discarded",
            hint="add a Sink/ReduceSink, or drop the dead tail of the chain")
    return out


def _validate_pipeline(report, p, faults, control, supervised,
                       trace=None, dispatch=None) -> None:
    cfg = _resolve_control(control, getattr(p, "_control", None))
    in_spec = _source_spec(report, p.source, f"source:{p.source.getName()}")
    if in_spec is None:
        return
    # the chain's operators were geometry-bound at construction — flow the
    # specs only (binding again with a validator-chosen capacity could skew
    # budget-derived archive sizes)
    _validate_chain_ops(report, p.chain.ops, in_spec, None, "pipeline",
                        sink=p.sink)
    _check_stream_ops(report, p.chain.ops, in_spec, "pipeline", [p.source])
    _check_tiered(report, p.chain.ops, cfg, trace,
                  getattr(p, "_trace_arg", None), supervised, "pipeline")
    _check_faults(report, faults, "supervised" if supervised else "pipeline")
    _check_admission(report, cfg, supervised, "control.admission")
    _check_trace(report, trace, getattr(p, "_trace_arg", None), supervised)
    _check_health(report, getattr(p, "_monitoring_arg", None))
    _check_slo(report, getattr(p, "_monitoring_arg", None))
    _check_telemetry(report, getattr(p, "_monitoring_arg", None))
    _check_profile(report, getattr(p, "_monitoring_arg", None))
    _check_remediation(report, getattr(p, "_monitoring_arg", None), cfg)
    _check_serving(report, getattr(p, "_serving_arg", None),
                   getattr(p, "_monitoring_arg", None), supervised)
    _check_dispatch(report, dispatch, getattr(p, "_dispatch_arg", None), cfg,
                    trace, getattr(p, "_trace_arg", None), supervised)


def _validate_supervised(report, sp, faults, control, trace=None,
                         dispatch=None, shards=None, reshard=None,
                         shard_key=None) -> None:
    cfg = _resolve_control(control, getattr(sp, "_control", None))
    in_spec = _source_spec(report, sp.source,
                           f"source:{sp.source.getName()}")
    if in_spec is None:
        return
    _validate_chain_ops(report, sp.chain.ops, in_spec, None, "supervised",
                        sink=sp.sink)
    _check_stream_ops(report, sp.chain.ops, in_spec, "supervised",
                      [sp.source])
    _check_tiered(report, sp.chain.ops, cfg, trace,
                  getattr(sp, "_trace_arg", None), True, "supervised")
    _check_faults(report, faults if faults is not None
                  else getattr(sp, "_faults_arg", None), "supervised")
    _check_admission(report, cfg, True, "control.admission")
    _check_trace(report, trace, getattr(sp, "_trace_arg", None), True)
    _check_health(report, getattr(sp, "_monitoring_arg", None))
    _check_slo(report, getattr(sp, "_monitoring_arg", None))
    _check_telemetry(report, getattr(sp, "_monitoring_arg", None))
    _check_profile(report, getattr(sp, "_monitoring_arg", None))
    _check_remediation_supervised(report, sp)
    _check_serving(report, getattr(sp, "_serving_arg", None),
                   getattr(sp, "_monitoring_arg", None), True)
    _check_dispatch(report, dispatch, getattr(sp, "_dispatch_arg", None),
                    cfg, trace, getattr(sp, "_trace_arg", None), True)
    _check_shards(report,
                  shards if shards is not None
                  else getattr(sp, "_shards", None),
                  reshard if reshard is not None
                  else getattr(sp, "_reshard_arg", None),
                  sp.chain.ops, cfg, trace, getattr(sp, "_trace_arg", None),
                  dispatch, getattr(sp, "_dispatch_arg", None),
                  faults if faults is not None
                  else getattr(sp, "_faults_arg", None), "supervised",
                  shard_key=(shard_key if shard_key is not None
                             else getattr(sp, "_shard_key", None)))


def _validate_threaded(report, tp, faults, control, supervised,
                       trace=None, dispatch=None) -> None:
    cfg = _resolve_control(control, getattr(tp, "_control", None))
    spec = _source_spec(report, tp.source,
                        f"source:{tp.source.getName()}")
    if spec is None:
        return
    wf114_sup_done = False
    for i, chain in enumerate(tp.chains):
        # capacity None: segment chains were geometry-bound at construction
        _check_stream_ops(report, chain.ops, spec, f"seg{i}", [tp.source])
        # supervised-combination findings emit once, from the FIRST segment
        # that actually has tiered ops (the graph-driver convention)
        has_tiered = any(getattr(op, "_tier_cfg", None) is not None
                         for op in chain.ops)
        _check_tiered(report, chain.ops, cfg, trace,
                      getattr(tp, "_trace_arg", None),
                      supervised and has_tiered and not wf114_sup_done,
                      f"seg{i}")
        wf114_sup_done = wf114_sup_done or has_tiered
        spec, _cap = _flow_ops(report, chain.ops, spec, f"seg{i}", None)
        if spec is None:
            break
    if tp.sink is None and not any(_has_reduce_sink(c.ops)
                                   for c in tp.chains):
        report.add("WF107", "warning", "threaded",
                   "no sink and no in-graph ReduceSink: the final ring's "
                   "batches are popped and discarded",
                   hint="add a Sink/ReduceSink, or drop the dead tail")
    edges = [(name, tp.edge_capacities[name]) for name in tp.edge_names]
    _check_watermarks(report, cfg, edges)
    _check_prefetch(report, getattr(tp, "prefetch", 0),
                    edges[0] if edges else None)
    _check_faults(report, faults if faults is not None
                  else getattr(tp, "_faults_arg", None), "threaded")
    _check_admission(report, cfg, supervised, "control.admission")
    _check_trace(report, trace, getattr(tp, "_trace_arg", None), supervised)
    _check_health(report, getattr(tp, "_monitoring_arg", None))
    _check_slo(report, getattr(tp, "_monitoring_arg", None))
    _check_telemetry(report, getattr(tp, "_monitoring_arg", None))
    _check_profile(report, getattr(tp, "_monitoring_arg", None))
    _check_remediation(report, getattr(tp, "_monitoring_arg", None), cfg)
    _check_serving(report, getattr(tp, "_serving_arg", None),
                   getattr(tp, "_monitoring_arg", None), supervised)
    _check_dispatch(report, dispatch, getattr(tp, "_dispatch_arg", None),
                    cfg, trace, getattr(tp, "_trace_arg", None), supervised,
                    edges=edges)


def _graph_edges(g):
    """(label, capacity) per dataflow edge — resolved over the SAME
    enumeration the threaded driver builds rings from
    (``PipeGraph._iter_edges``), so the checks can never drift onto edges
    the driver does not create."""
    from ..runtime.threaded import _resolve_edge_capacity
    return [(label, _resolve_edge_capacity(g.queue_capacity, label, index))
            for _prod, _dst, label, index in g._iter_edges()]


def _check_graph_edges(report, g, cfg) -> None:
    """Resolve every threaded-driver edge capacity the way the driver will —
    an illegal per-edge capacity (<1, bad dict/callable) is a WF104 error
    *now* instead of a ValueError mid-``run(threaded=True)``."""
    try:
        edges = _graph_edges(g)
    except Exception as e:  # noqa: BLE001 — diagnosis IS the product here
        report.add("WF104", "error", "queue_capacity",
                   f"edge capacity resolution fails: "
                   f"{type(e).__name__}: {e}",
                   hint="queue_capacity must resolve every edge to an int "
                        ">= 1 (one int, a dict keyed by edge label/index, "
                        "or a callable (label, index) -> int)")
        return
    _check_watermarks(report, cfg, edges)


def _validate_graph(report, g, faults, control, supervised,
                    threaded, trace=None, dispatch=None, shards=None,
                    reshard=None, shard_key=None) -> None:
    from ..basic import DEFAULT_BATCH_SIZE
    from ..control import ControlConfig
    from ..runtime.pipeline import resolve_batch_hint
    if not g._roots:
        report.add("WF100", "error", "graph",
                   "PipeGraph has no sources — nothing will run",
                   hint="add_source(...) before validating/running")
        return
    stored = g._control
    if stored is None:
        stored = ControlConfig.resolve(g._control_arg)
    cfg = _resolve_control(control, stored)
    batch = (g.batch_size if g.batch_size is not None
             else (resolve_batch_hint(g._operators) or DEFAULT_BATCH_SIZE))
    pipes = g._all_pipes()
    pipe_idx = {id(p): i for i, p in enumerate(pipes)}
    out_specs, out_caps = {}, {}
    wf114_sup_done = False
    for mp in g._topo_order():
        where = f"pipe[{pipe_idx[id(mp)]}]"
        if mp.source is not None:
            in_spec = _source_spec(
                report, mp.source,
                f"{where}.source:{mp.source.getName()}")
            if in_spec is None:
                continue
            in_cap = getattr(mp.source, "out_capacity",
                             lambda b: b)(batch)
        elif mp.merge_inputs:
            specs = [out_specs.get(id(p)) for p in mp.merge_inputs]
            if any(s is None for s in specs):
                continue               # upstream already diagnosed
            in_spec = specs[0]         # merge() checked compatibility
            in_cap = batch             # merged releases re-chunk to batch
        else:
            parent = mp._dataflow_parent
            in_spec = out_specs.get(id(parent))
            in_cap = out_caps.get(id(parent))
            if in_spec is None:
                continue               # upstream already diagnosed
        _check_stream_ops(report, mp.ops, in_spec, where,
                          _feeding_sources(mp))
        # supervised-combination findings emit once (first tiered pipe);
        # the per-op geometry findings emit per pipe
        has_tiered = any(getattr(op, "_tier_cfg", None) is not None
                         for op in mp.ops)
        _check_tiered(report, mp.ops, cfg, trace,
                      getattr(g, "_trace_arg", None),
                      supervised and has_tiered and not wf114_sup_done,
                      where)
        wf114_sup_done = wf114_sup_done or has_tiered
        out, out_cap = _flow_ops(report, mp.ops, in_spec, where, in_cap)
        out_specs[id(mp)] = out
        if out_cap is not None:
            out_caps[id(mp)] = out_cap
        if mp.split_fn is not None and out is not None:
            _check_split(report, mp, out, where)
        if (mp.sink is None and not mp.split_branches
                and not mp._outputs_to and mp.split_fn is None
                and not _has_reduce_sink(mp.ops)):
            report.add(
                "WF107", "warning", where,
                "leaf pipe has no sink, no in-graph ReduceSink, and no "
                "downstream edge — its output batches are discarded",
                hint="add a sink to this branch (or merge it into a pipe "
                     "that has one)")
    if threaded:
        # ring edges exist only under run(threaded=True) — the push driver
        # never resolves queue_capacity, so these checks would be spurious
        _check_graph_edges(report, g, cfg)
    driver = ("supervised" if supervised
              else ("graph-threaded" if threaded else "graph"))
    _check_faults(report, faults, driver)
    _check_admission(report, cfg, supervised, "control.admission")
    _check_trace(report, trace, getattr(g, "_trace_arg", None), supervised)
    _check_health(report, getattr(g, "_monitoring_arg", None))
    _check_slo(report, getattr(g, "_monitoring_arg", None))
    _check_telemetry(report, getattr(g, "_monitoring_arg", None))
    _check_profile(report, getattr(g, "_monitoring_arg", None))
    _check_remediation(report, getattr(g, "_monitoring_arg", None), cfg)
    _check_serving(report, getattr(g, "_serving_arg", None),
                   getattr(g, "_monitoring_arg", None), supervised)
    dedges = None
    if threaded:
        try:
            dedges = _graph_edges(g)
        except Exception:  # noqa: BLE001 — already a WF104 error above
            dedges = None
    _check_dispatch(report, dispatch, getattr(g, "_dispatch_arg", None),
                    cfg, trace, getattr(g, "_trace_arg", None), supervised,
                    edges=dedges)
    if supervised:
        # run unconditionally: shards=None consults WF_SHARDS inside
        # _check_shards (the run_graph_supervised resolution) — an
        # env-driven sharded run must get the same WF115 coverage as an
        # explicit one
        _check_shards(report, shards, reshard, g._operators, cfg, trace,
                      getattr(g, "_trace_arg", None), dispatch,
                      getattr(g, "_dispatch_arg", None), faults, "graph",
                      shard_key=shard_key)


def _validate_compiled_chain(report, chain, faults, control,
                             supervised, trace=None) -> None:
    _flow_ops(report, chain.ops, chain.specs[0], "chain", None)
    _check_faults(report, faults, "supervised" if supervised else "pipeline")
    from ..control import ControlConfig
    _check_admission(report, ControlConfig.resolve(control)
                     if control is not None else None,
                     supervised, "control.admission")
    if trace is not None:
        _check_trace(report, trace, None, supervised)


def _check_progcheck(report, obj, progcheck, dispatch, supervised,
                     shards) -> None:
    """WF300-WF305: trace the driver's built-but-not-run step/scan
    programs (``analysis/progcheck.py`` — zero FLOPs, zero device) and
    append the device-program findings, baseline-suppressed like the CLI.

    Gated by the ``progcheck=`` kwarg, else ``WF_PROGCHECK`` (default on,
    ``'0'`` disables).  Skipped when the report already carries errors
    (tracing a graph whose specs do not even flow would only bury the real
    diagnosis under a TypeError), and NEVER fatal: a trace failure means
    the dynamic path will surface it with full context."""
    if progcheck is None:
        progcheck = os.environ.get("WF_PROGCHECK", "1") not in ("", "0")
    if not progcheck or not report.ok:
        return
    try:
        from . import progcheck as pc
        from ..runtime.dispatch import DispatchConfig
        chains = []
        if getattr(obj, "chain", None) is not None:
            chains.append(("chain", obj.chain))
        elif getattr(obj, "chains", None):
            chains += [(f"seg{i}", c) for i, c in enumerate(obj.chains)]
        elif getattr(obj, "ops", None) is not None \
                and getattr(obj, "specs", None) is not None:
            chains.append(("chain", obj))        # a raw CompiledChain
        if not chains:
            return
        dcfg = DispatchConfig.resolve(
            dispatch if dispatch is not None
            else getattr(obj, "_dispatch_arg", None))
        k = dcfg.k if dcfg is not None else 1
        from ..parallel.sharding import resolve_shards
        n_shards = resolve_shards(shards if shards is not None
                                  else getattr(obj, "_shards", None)) or 1
        programs = []
        for label, chain in chains:
            programs += pc.chain_programs(
                chain, k=k, shards=n_shards,
                replay=bool(supervised), target=label)
        findings = pc.analyze_programs(programs)
        counts, _problems = pc.load_baseline(pc.baseline_path())
        for f in pc.apply_baseline(findings, counts):
            report.add(f.code, f.severity, f.path, f.message)
    except Exception:  # noqa: BLE001 — analysis must never block validation
        return


def _validate_serving_runtime(report, rt, faults, control, trace=None,
                              dispatch=None) -> None:
    """A ServingRuntime is a Pipeline to the spec-flow checks, plus the
    WF119 serving checks over its ALREADY-resolved config (construction
    raised on fatal problems; the report re-derives them for tooling) and
    a spec-flow pass over every registered swap-candidate graph — a swap
    target that cannot type-check against the source would otherwise fail
    mid-run, inside the cutover quiesce."""
    cfg = _resolve_control(control, None)
    in_spec = _source_spec(report, rt.source,
                           f"source:{rt.source.getName()}")
    if in_spec is None:
        return
    _validate_chain_ops(report, rt.chain.ops, in_spec, None, "serving",
                        sink=rt.sink)
    _check_stream_ops(report, rt.chain.ops, in_spec, "serving", [rt.source])
    for label, g_ops in getattr(rt, "_graphs", {}).items():
        _flow_ops(report, g_ops, in_spec, f"serving.graph[{label}]", None)
    _check_faults(report, faults,
                  "supervised" if rt._supervised else "pipeline")
    _check_trace(report, trace, None, rt._supervised)
    _check_health(report, rt._monitoring_arg)
    _check_slo(report, rt._monitoring_arg)
    _check_telemetry(report, rt._monitoring_arg)
    _check_profile(report, rt._monitoring_arg)
    _check_remediation(report, rt._monitoring_arg, cfg)
    _check_serving(report, rt.config, rt._monitoring_arg, rt._supervised)


# ------------------------------------------------------------------ public


def validate(obj, *, faults=None, control=None, supervised: bool = None,
             threaded: bool = False, trace=None, dispatch=None,
             shards=None, reshard=None, shard_key=None,
             progcheck: bool = None) -> ValidationReport:
    """Validate a built-but-not-run driver object; returns a
    :class:`ValidationReport` (never raises on findings — call
    ``.raise_if_errors()`` to gate).

    ``obj``: a ``PipeGraph``, ``Pipeline``, ``ThreadedPipeline``,
    ``SupervisedPipeline``, ``ServingRuntime``, or raw ``CompiledChain``.

    ``faults``: a ``FaultPlan``/``FaultInjector``/JSON string to check
    against the sites the chosen driver actually threads; ``None`` consults
    ``WF_FAULT_PLAN`` (mirroring the drivers).

    ``control``: a ``ControlConfig``/dict/bool overriding the object's own
    stored control config for the configuration checks.

    ``supervised``: declare that the object will run under supervision
    (``run_supervised`` / ``run_graph_supervised``); inferred True for a
    ``SupervisedPipeline``. ``threaded``: a ``PipeGraph`` destined for
    ``run(threaded=True)`` (enables the ring-edge checks).

    ``trace``: a ``TraceConfig``/bool/out-dir overriding the object's own
    stored ``trace=`` argument for the WF108 determinism checks; ``None``
    consults the stored argument and ``WF_TRACE`` (mirroring the drivers).

    ``dispatch``: a ``DispatchConfig``/bool/int K/dict overriding the
    object's own stored ``dispatch=`` argument for the WF110 scan-dispatch
    checks; ``None`` consults the stored argument and ``WF_DISPATCH``
    (mirroring the drivers).

    ``shards``/``reshard``/``shard_key``: the shard count, re-sharding
    plan, and ownership-key override destined for the sharded supervisors,
    for the WF115 checks — a ``SupervisedPipeline`` consults its own
    stored arguments when these are None; for a ``PipeGraph`` pass the
    values you will pass to ``run_supervised`` (with ``supervised=True``;
    ``shards=None`` consults ``WF_SHARDS``, mirroring the driver).

    ``progcheck``: run the device-program analyzer (WF300-WF305,
    ``analysis/progcheck.py``) over the object's built-but-not-run
    step/scan programs under the resolved dispatch K / shard / supervision
    config; ``None`` consults ``WF_PROGCHECK`` (default on, ``'0'``
    disables). Skipped when the report already has errors."""
    from ..runtime.pipegraph import PipeGraph
    from ..runtime.pipeline import CompiledChain, Pipeline
    from ..runtime.supervisor import SupervisedPipeline
    from ..runtime.threaded import ThreadedPipeline
    from ..serving.runtime import ServingRuntime

    if isinstance(obj, ServingRuntime):
        report = ValidationReport("ServingRuntime")
        _validate_serving_runtime(report, obj, faults, control,
                                  trace, dispatch)
    elif isinstance(obj, PipeGraph):
        report = ValidationReport(f"PipeGraph({obj.name!r})")
        _validate_graph(report, obj, faults, control, bool(supervised),
                        threaded, trace, dispatch, shards, reshard,
                        shard_key)
    elif isinstance(obj, SupervisedPipeline):
        report = ValidationReport("SupervisedPipeline")
        _validate_supervised(report, obj, faults, control, trace, dispatch,
                             shards, reshard, shard_key)
    elif isinstance(obj, ThreadedPipeline):
        report = ValidationReport("ThreadedPipeline")
        _validate_threaded(report, obj, faults, control, bool(supervised),
                           trace, dispatch)
    elif isinstance(obj, Pipeline):
        report = ValidationReport("Pipeline")
        _validate_pipeline(report, obj, faults, control, bool(supervised),
                           trace, dispatch)
    elif isinstance(obj, CompiledChain):
        report = ValidationReport("CompiledChain")
        _validate_compiled_chain(report, obj, faults, control,
                                 bool(supervised), trace)
    else:
        report = ValidationReport(type(obj).__name__)
        report.add("WF100", "error", "target",
                   f"cannot validate a {type(obj).__name__}; expected "
                   f"PipeGraph, Pipeline, ThreadedPipeline, "
                   f"SupervisedPipeline, ServingRuntime, or CompiledChain")
        return report
    _check_kernel_records(report)
    _check_progcheck(report, obj, progcheck, dispatch,
                     supervised if supervised is not None
                     else isinstance(obj, SupervisedPipeline),
                     shards)
    return report
