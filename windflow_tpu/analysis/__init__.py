"""Static analysis — shift-left validation of graphs and framework invariants.

Two pillars, both wired into tier-1 (``tests/test_analysis_validate.py``,
``tests/test_lint_clean.py``) and usable standalone:

- :func:`validate` (``analysis/validate.py``): flow abstract
  ``jax.ShapeDtypeStruct`` specs through a built-but-not-run driver
  (``PipeGraph``/``Pipeline``/``ThreadedPipeline``/``SupervisedPipeline``/
  ``CompiledChain``) and check the run configuration (fault plans, governor
  watermarks, admission control, prefetch) — typed ``WF1xx`` diagnostics with
  operator paths and fix hints, before anything compiles or runs.
- the invariant linter (``analysis/lint.py``): stdlib-``ast`` rules over
  ``windflow_tpu/`` enforcing the codebase's cross-cutting contracts
  (documented env reads, clock-free deterministic-replay modules,
  lock-guarded attributes, no silent broad excepts, journal/metric names
  registered centrally) — ``WF2xx`` findings gated against
  ``analysis/baseline.json``. CLI: ``scripts/wf_lint.py``.

The motivating stance is the GPU-portability literature's (arxiv 2306.11686,
2601.17526): classify and validate programs against the execution model *up
front* instead of discovering incompatibilities on the device — here, before
a chain traces, a ring deadlocks, or a replay diverges.
"""

from .validate import (Diagnostic, ValidationError, ValidationReport,
                       validate)
from .lint import (Finding, LintConfig, apply_baseline, lint_repo,
                   load_baseline, run_lint, save_baseline)

__all__ = [
    "validate", "ValidationReport", "ValidationError", "Diagnostic",
    "run_lint", "lint_repo", "Finding", "LintConfig",
    "load_baseline", "save_baseline", "apply_baseline",
]
