"""Hermetic perf gate — Pillar 3 of the static-analysis layer.

Three of five bench rounds were lost to the dead dev-chip tunnel: the
headline could not move because measuring it required hardware. This module
makes the perf *trajectory* device-free. Two instruments, no accelerator:

1. **XLA cost-analysis pins.** The compiled YSB and mp-matrix chains are
   AOT-lowered on the CPU backend and XLA's own cost model
   (``compiled.cost_analysis()``: FLOPs / bytes accessed per step) is
   compared against a checked-in baseline
   (``analysis/perfgate_baseline.json``). The numbers are *logical* program
   costs — deterministic for a given source tree + jax version, identical
   on a laptop and in CI — so a change that bloats the compiled chain
   (a fusion break, an accidental f64 promotion, a gather that became a
   scalar loop) fails tier-1 the day it lands, tunnel or no tunnel.

   Ratchet-down semantics (the ``analysis/baseline.json`` discipline):
   cost ABOVE the pin (beyond ``rtol``) is a **regression** finding; cost
   BELOW the pin is a **stale-pin** finding — the improvement must be
   banked with ``--update-baseline`` so the gate guards the new, better
   number. Workloads missing a pin, and pins whose workload no longer
   exists, also fail: silence is never evidence.

2. **CPU-proxy microbenchmarks.** Every kernel family in
   ``observability/names.py::KERNELS`` is timed on the CPU backend (small
   shapes, min-of-reps). Wall-clock on shared CI boxes is noisy, so these
   are ADVISORY by default: recorded in the gate report (and in
   ``bench_trend.py``'s cost columns) for trend reading, compared against
   the baseline only under ``--strict-proxy`` with a generous factor.

CLI: ``scripts/wf_perfgate.py`` (exit 0 clean / 1 findings / 2 internal
error — the ``wf_lint.py`` contract). Baseline override:
``WF_PERFGATE_BASELINE`` env or ``--baseline``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

#: default location of the pinned baseline (checked in, ratchet-down)
BASELINE_REL = os.path.join("windflow_tpu", "analysis",
                            "perfgate_baseline.json")
#: relative tolerance around a cost pin: above = regression, below = stale
DEFAULT_RTOL = 0.02
#: advisory proxy-microbench regression factor (strict mode only)
PROXY_FACTOR = 3.0

#: compile capacities per workload — small enough that the CPU-backend AOT
#: compile stays test-budget friendly, pinned in the baseline for honesty
WORKLOAD_CAPACITY = {"ysb": 2048, "mp_matrix": 1024,
                     "nexmark_join": 512, "nexmark_session": 512,
                     "nexmark_topn": 512,
                     # the tiered-state miss->readmit->reprobe round: the
                     # Nexmark join chain with tiered= on (resolve + probe
                     # fallback + eviction compiled into the step; the
                     # io_callback lowers to a host custom-call)
                     "tiered_probe_miss": 512}

#: scan-dispatch workloads: (base workload, K) — the K-fused
#: ``CompiledChain._scan_fn`` program AOT-lowered and pinned beside the
#: per-batch step, so a change that breaks the scan body's fusion (or makes
#: the fused program cost more than K x the single step) fails tier-1
SCAN_WORKLOADS = {"ysb_scan_k8": ("ysb", 8)}


# ------------------------------------------------------------- workloads


def _build_ysb():
    """The YSB chain exactly as ``bench.py::bench_ysb`` builds it, at the
    gate capacity."""
    from ..benchmarks import ysb, device_cursor_step
    from ..runtime.pipeline import CompiledChain
    cap = WORKLOAD_CAPACITY["ysb"]
    panes_per_batch = cap // (ysb.EVENTS_PER_TICK * ysb.WIN_LEN) + 1
    src = ysb.make_source(total=16 * cap)
    ops = ysb.make_ops(pane_capacity=2 * panes_per_batch + 2,
                       max_wins=panes_per_batch + 64)
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=cap,
                          event_time=False)
    step = device_cursor_step(chain, src, cap)
    return chain, step, cap


def _build_mp_matrix():
    """A representative mp-matrix chain (the ``kf_ffat`` + chaining shape of
    ``tests/test_mp_matrix.py``): stateless map/filter fused ahead of a
    keyed TB FFAT window — the fold path the segment/histogram kernels
    serve."""
    import jax.numpy as jnp
    from ..basic import win_type_t
    from ..benchmarks import device_cursor_step
    from ..operators.filter import Filter
    from ..operators.map import Map
    from ..operators.win_patterns import Key_FFAT
    from ..operators.window import WindowSpec
    from ..operators.source import DeviceSource
    from ..runtime.pipeline import CompiledChain
    cap = WORKLOAD_CAPACITY["mp_matrix"]
    src = DeviceSource(lambda i: {"v": ((i * 13) % 23).astype(jnp.float32)},
                       total=16 * cap, num_keys=8)
    ops = [Map(lambda t: {"v": t.v + 1.0}),
           Filter(lambda t: t.v > 2.0),
           Key_FFAT(lambda t: t.v, jnp.add,
                    spec=WindowSpec(40, 20, win_type_t.TB), num_keys=8)]
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=cap,
                          event_time=False)
    step = device_cursor_step(chain, src, cap)
    return chain, step, cap


def _build_nexmark(query: str, cap: int):
    """One Nexmark query chain at the gate capacity (the ``bench.py::
    bench_nexmark`` construction): the join pin covers the versioned
    JoinTable upsert + registry probe, the session pin the data-dependent
    triggerer path, the top-N pin the bitonic rank merge."""
    from ..nexmark import make_query
    from ..runtime.pipeline import CompiledChain
    from ..benchmarks import device_cursor_step
    src, ops = make_query(query, total=16 * cap)
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=cap,
                          event_time=False)
    step = device_cursor_step(chain, src, cap)
    return chain, step, cap


def _build_nexmark_join():
    return _build_nexmark("q3_enrich_join", WORKLOAD_CAPACITY["nexmark_join"])


def _build_nexmark_session():
    return _build_nexmark("q5_session", WORKLOAD_CAPACITY["nexmark_session"])


def _build_nexmark_topn():
    return _build_nexmark("q6_topn", WORKLOAD_CAPACITY["nexmark_topn"])


def _build_tiered_probe_miss():
    """The q3 join chain with tiered state ON (``windflow_tpu/state``):
    the pin covers the in-graph tier machinery — miss-resolution probes
    (hot + outbox), the deterministic fresh-slot re-admission, the probe
    fallback chain, and the pressure-eviction pack — compiled into the
    SAME step as the join. Hot capacity clears the admission reserve
    (WF114's sizing rule) at a 100x key space, so the compiled shape is
    the acceptance workload's."""
    from ..nexmark import make_query
    from ..runtime.pipeline import CompiledChain
    from ..benchmarks import device_cursor_step
    cap = WORKLOAD_CAPACITY["tiered_probe_miss"]
    src, ops = make_query("q3_enrich_join", 16 * cap,
                          n_auctions=100 * 16, num_slots=2048,
                          tiered=dict())
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=cap,
                          event_time=False)
    step = device_cursor_step(chain, src, cap)
    return chain, step, cap


WORKLOADS: Dict[str, Callable] = {
    "ysb": _build_ysb,
    "mp_matrix": _build_mp_matrix,
    "nexmark_join": _build_nexmark_join,
    "nexmark_session": _build_nexmark_session,
    "nexmark_topn": _build_nexmark_topn,
    "tiered_probe_miss": _build_tiered_probe_miss,
}


# ------------------------------------------------------------ cost model


def _cost_of(compiled) -> Dict[str, float]:
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return {"flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0))}


def _arg_specs(args):
    import jax
    return jax.tree.map(
        lambda a: (jax.ShapeDtypeStruct(a.shape, a.dtype)
                   if hasattr(a, "shape") else a), args)


def workload_cost(name: str) -> Dict[str, float]:
    """Compile one gate workload AOT (zero execution) and read XLA's logical
    cost model for the full chain step."""
    import jax
    import jax.numpy as jnp
    chain, step, cap = WORKLOADS[name]()
    specs = _arg_specs((tuple(chain.states),
                        jax.ShapeDtypeStruct((), jnp.int32)))
    compiled = step.lower(*specs).compile()
    out = _cost_of(compiled)
    out["capacity"] = cap
    return out


def chain_step_cost(name: str) -> Dict[str, float]:
    """Cost of ONE chain-only batch step (``CompiledChain._step_fn``, no
    source framing) — the denominator of the scan amortization check."""
    import jax
    from ..batch import Batch
    chain, _, cap = WORKLOADS[name]()
    bspec = jax.eval_shape(lambda: Batch.empty(cap, chain.specs[0]))
    sspec = _arg_specs(tuple(chain.states))
    compiled = chain._step_fn(0).lower(sspec, bspec).compile()
    out = _cost_of(compiled)
    out["capacity"] = cap
    return out


#: reshard_pack pin geometry: one batch split into N masked per-shard
#: sub-batches (``parallel/sharding.py::ShardAssignment.split_fn`` — the
#: only per-batch program the sharded supervisors add, and the pack step of
#: the re-sharding handoff)
RESHARD_PACK_CAPACITY = 2048
RESHARD_PACK_SHARDS = 4


def reshard_pack_cost() -> Dict[str, float]:
    """AOT cost of the shard splitter at the pinned geometry — zero
    execution, CPU backend. The pin guards the claim that sharding's
    per-batch overhead is ONE masked split (a change that sneaks a gather,
    sort, or device round trip into the splitter moves this number)."""
    import jax
    import jax.numpy as jnp
    from ..batch import Batch
    from ..parallel.sharding import ShardAssignment
    cap = RESHARD_PACK_CAPACITY
    assign = ShardAssignment(RESHARD_PACK_SHARDS)
    bspec = jax.eval_shape(
        lambda: Batch.empty(cap, {"v": jnp.zeros((), jnp.float32)}))
    compiled = assign.split_fn().lower(bspec).compile()
    out = _cost_of(compiled)
    out["capacity"] = cap
    out["shards"] = RESHARD_PACK_SHARDS
    return out


def workload_scan_cost(name: str) -> Dict[str, float]:
    """AOT cost of the K-fused scan-dispatch program for one
    ``SCAN_WORKLOADS`` entry: ``CompiledChain._scan_fn`` (the ``lax.scan``
    over the per-batch step with states as carry) lowered for a
    ``[K, C, ...]`` stacked batch — zero execution, CPU backend. The pin
    guards the scanned step the same way the per-batch pins guard ``push``:
    a fusion break INSIDE the scan body moves this number."""
    import jax
    from ..batch import Batch
    base, k = SCAN_WORKLOADS[name]
    chain, _, cap = WORKLOADS[base]()
    bspec = jax.eval_shape(lambda: Batch.empty(cap, chain.specs[0]))
    stacked = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct((k,) + s.shape, s.dtype), bspec)
    sspec = _arg_specs(tuple(chain.states))
    compiled = chain._scan_fn(0).lower(sspec, stacked).compile()
    out = _cost_of(compiled)
    out["capacity"] = cap
    out["k"] = k
    return out


def stage_costs(chain, capacity: int) -> List[dict]:
    """Per-operator cost-analysis of a built chain: each op's ``apply``
    lowered in isolation with the chain's own specs — the per-stage
    flops/bytes column ``bench.py`` attaches next to its metrics snapshots
    (so BENCH_r*.json carry *which stage* grew, not just that the chain
    did). Isolated lowering loses cross-op fusion, so the rows are an upper
    bound that localizes changes; the whole-chain number is the pin."""
    import jax
    from ..batch import Batch
    out = []
    cap = capacity
    for i, op in enumerate(chain.ops):
        row = {"op": op.getName(), "capacity": int(cap) if cap else None}
        try:
            bspec = jax.eval_shape(
                lambda c=cap, s=chain.specs[i]: Batch.empty(c, s))
            sspec = _arg_specs(chain.states[i])
            compiled = jax.jit(op.apply).lower(sspec, bspec).compile()
            row.update(_cost_of(compiled))
        except Exception as e:  # noqa: BLE001 — a stage that refuses abstract
            #               lowering (host callbacks etc.) reports, not raises
            row["error"] = f"{type(e).__name__}: {e}"
        if cap is not None:
            try:
                cap = op.out_capacity(cap)
            except Exception:  # noqa: BLE001 — capacity flow is best-effort
                cap = None
        out.append(row)
    return out


# --------------------------------------------------------- proxy benches


def _bench_one(fn, *args, reps: int = 3) -> float:
    """Min-of-reps wall time of a jitted call on the current backend."""
    import jax
    jax.block_until_ready(fn(*args))          # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def proxy_microbench(reps: int = 3) -> Dict[str, dict]:
    """CPU-proxy timings for every registry kernel family (reference impls —
    the trend instrument, not a TPU prediction). Keyed by
    ``names.py::KERNELS`` so a newly registered kernel without a proxy row
    fails the gate's coverage check."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..ops.bitonic import merge_network
    from ..ops.histogram import keyed_pane_histogram
    from ..ops.lookup import join_probe, table_lookup
    from ..ops.segment import segment_fold

    rng = np.random.default_rng(0)
    out: Dict[str, dict] = {}

    C, K, P = 8192, 100, 256
    key = jnp.asarray(rng.integers(0, K, C).astype(np.int32))
    pane = jnp.asarray((np.arange(C) // 200).astype(np.int32))
    ok = jnp.asarray(rng.random(C) < 0.9)
    f = jax.jit(lambda a, b, c: keyed_pane_histogram(a, b, c, K, P))
    out["histogram"] = {"elems": C, "seconds": _bench_one(f, key, pane, ok,
                                                          reps=reps)}

    KT, CT = 1000, 8192
    table = jnp.asarray(rng.integers(0, 1 << 12, KT).astype(np.int32))
    idx = jnp.asarray(rng.integers(0, KT, CT).astype(np.int32))
    f = jax.jit(table_lookup)
    out["lookup"] = {"elems": CT, "seconds": _bench_one(f, table, idx,
                                                        reps=reps)}

    n = 8192
    prim = jnp.asarray(rng.integers(0, 1 << 20, n).astype(np.int32))
    zero = jnp.zeros((n,), jnp.int32)
    iota = jnp.arange(n, dtype=jnp.int32)
    f = jax.jit(merge_network)
    out["ordering_merge"] = {"elems": n,
                             "seconds": _bench_one(f, prim, zero, zero, iota,
                                                   reps=reps)}

    S = 512
    vals = jnp.asarray(rng.integers(-100, 100, C).astype(np.int32))
    seg = jnp.asarray(rng.integers(0, S, C).astype(np.int32))
    f = jax.jit(lambda v, s, o: segment_fold(v, s, o, S))
    out["segment_fold"] = {"elems": C, "seconds": _bench_one(f, vals, seg, ok,
                                                             reps=reps)}

    KJ = 512
    tk = jnp.asarray(rng.permutation(1 << 16)[:KJ].astype(np.int32))
    tv = jnp.asarray(rng.integers(0, 1 << 12, KJ).astype(np.int32))
    probe = jnp.asarray(rng.integers(0, 1 << 16, C).astype(np.int32))
    f = jax.jit(join_probe)
    out["join_probe"] = {"elems": C, "seconds": _bench_one(f, tk, tv, probe,
                                                           ok, reps=reps)}

    # join: one full versioned-JoinTable step — upsert (pending ring +
    # LWW dominance + slot allocation) then probe through the registry's
    # join_probe kernel. The probe kernels keep their microbench through
    # this family (PERF_PROXY_FAMILIES coverage) even if the raw
    # "join_probe" row ever moves.
    CJ, KJ2, PJ = 1024, 256, 2048
    from ..ops.lookup import join_table_init, join_table_probe, \
        join_table_upsert
    jt = join_table_init(KJ2, PJ, {"v": jnp.zeros((), jnp.int32)})
    jk = jnp.asarray(rng.integers(0, KJ2, CJ).astype(np.int32))
    jv = {"v": jnp.asarray(rng.integers(0, 1 << 20, CJ).astype(np.int32))}
    jts = jnp.asarray(np.arange(CJ, dtype=np.int32))
    jid = jnp.asarray(np.arange(CJ, dtype=np.int32))
    jok = jnp.asarray(rng.random(CJ) < 0.5)

    def join_step(st):
        st = join_table_upsert(st, jk, jv, jts, jid, jok, delay=0)
        vals, hit = join_table_probe(st, jk, ~jok)
        return st, vals["v"], hit
    f = jax.jit(join_step)
    out["join"] = {"elems": CJ, "seconds": _bench_one(f, jt, reps=reps)}

    # spill: the tiered-state eviction/pack path (ops/lookup.py
    # join_table_tier_evict: the deterministic coldness sort + outbox pack
    # + slot clear) over a fully-loaded hot table — the device-side cost of
    # moving one batch's worth of cold keys toward the host tier
    from ..ops.lookup import (JOIN_KEY_SENTINEL, join_table_init,
                              join_table_tier_evict, join_table_tier_init)
    KT2, ST2 = 2048, 1024
    vspec = {"v": jnp.zeros((), jnp.int32)}
    ts0 = join_table_init(KT2, 8, vspec)
    ts0 = join_table_tier_init(ts0, ST2, vspec)
    ts0["key"] = jnp.asarray(rng.permutation(1 << 20)[:KT2].astype(np.int32))
    ts0["used"] = jnp.ones((KT2,), jnp.bool_)
    ts0["lap"] = jnp.asarray(rng.integers(0, 1 << 16, KT2).astype(np.int32))
    ts0["tick"] = jnp.asarray(1 << 16, jnp.int32)
    f = jax.jit(lambda s: join_table_tier_evict(s, KT2 // 2))
    out["spill"] = {"elems": KT2, "seconds": _bench_one(f, ts0, reps=reps)}

    # dispatch: K batches through ONE fused push_many scan launch (the
    # runtime/dispatch.py hot path) — time per tuple of the fused call, with
    # the jit-boundary launch counts riding along as evidence
    KD, CD = 8, 1024
    chain_d, group = _dispatch_chain(KD, CD)
    chain_d.warm_scan(KD, CD)                 # compile outside the timing
    row = {"elems": KD * CD,
           "seconds": _bench_one(lambda g: chain_d.push_many(g), group,
                                 reps=reps)}
    row.update(dispatch_launch_counts(k=KD, capacity=CD))
    out["dispatch"] = row

    # shard: the sharded supervisors' key-ownership splitter (one batch ->
    # N masked sub-batches, parallel/sharding.py) — the only per-batch cost
    # shard-local supervision adds; also the pack step of a reshard handoff
    from ..batch import Batch
    from ..parallel.sharding import ShardAssignment
    CS, NS = 8192, 4
    assign = ShardAssignment(NS)
    sb = Batch.of({"v": jnp.asarray(rng.random(CS).astype(np.float32))},
                  key=jnp.asarray(rng.integers(0, 64, CS).astype(np.int32)))
    out["shard"] = {"elems": CS,
                    "seconds": _bench_one(assign.split_fn(), sb, reps=reps)}

    for row in out.values():
        row["ns_per_elem"] = round(row.pop("seconds") / row["elems"] * 1e9, 3)
    return out


def _dispatch_chain(k: int, capacity: int):
    """A tiny stateless map+filter chain + exactly ``k`` capacity-C batches
    for the scan-dispatch proxy/count instruments."""
    import jax.numpy as jnp
    from ..operators.filter import Filter
    from ..operators.map import Map
    from ..operators.source import Source
    from ..runtime.pipeline import CompiledChain
    src = Source(lambda i: {"v": (i % 97).astype(jnp.float32)},
                 total=k * capacity, num_keys=8)
    ops = [Map(lambda t: {"v": t.v * 2.0 + 1.0}),
           Filter(lambda t: t.v > 3.0)]
    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=capacity,
                          event_time=False)
    return chain, list(src.batches(capacity))


def dispatch_launch_counts(k: int = 8, capacity: int = 1024,
                           n_batches: Optional[int] = None) -> Dict[str, int]:
    """Count jit-boundary executable dispatches for ``n_batches`` batches
    driven through a ``MicrobatchAccumulator(k)`` + ``push_many`` (tail
    drained short, the driver shape) by wrapping the chain's cached
    executables — the dispatch-amortization claim measured, not assumed:
    one launch per full K group, one per partial tail, so
    ``launches == ceil(batches / k)`` and the per-batch path would have paid
    ``batches``. Device-free (CPU backend)."""
    from ..runtime.dispatch import MicrobatchAccumulator
    n = int(n_batches) if n_batches else 2 * k + max(1, k // 2)
    chain, batches = _dispatch_chain(n, capacity)
    calls = {"n": 0}
    for name in ("_scan_fn", "_step_fn"):
        orig = getattr(chain, name)

        def counting(i, _orig=orig):
            f = _orig(i)

            def call(*a, **kw):
                calls["n"] += 1
                return f(*a, **kw)
            return call
        setattr(chain, name, counting)
    acc = MicrobatchAccumulator(k)
    fed = 0
    for b in batches:
        fed += 1
        for group in acc.feed(b):
            chain.push_many(group)
    tail = acc.drain()
    if tail:
        chain.push_many(tail)
    return {"k": int(k), "batches": fed, "launches": calls["n"]}


# --------------------------------------------------------------- baseline


def baseline_path(root: str = ".") -> str:
    override = os.environ.get("WF_PERFGATE_BASELINE", "")
    if override:
        return override if os.path.isabs(override) \
            else os.path.join(root, override)
    return os.path.join(root, BASELINE_REL)


def load_baseline(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def save_baseline(path: str, report: dict) -> None:
    payload = {
        "comment": "hermetic perf-gate pins (XLA logical cost model per "
                   "compiled workload step, CPU backend; proxy rows are "
                   "advisory). Regenerate with scripts/wf_perfgate.py "
                   "--update-baseline after an INTENTIONAL cost change — "
                   "the gate ratchets down: improvements must be banked "
                   "here or they fail as stale pins.",
        "workloads": report["workloads"],
        "proxy": report.get("proxy", {}),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")


def measure(skip_proxy: bool = False, reps: int = 3) -> dict:
    """The gate's current measurement: cost pins for every workload (+
    advisory proxy timings)."""
    report = {"workloads": {name: workload_cost(name) for name in WORKLOADS}}
    for name in SCAN_WORKLOADS:
        report["workloads"][name] = workload_scan_cost(name)
    report["workloads"]["reshard_pack"] = reshard_pack_cost()
    if not skip_proxy:
        report["proxy"] = proxy_microbench(reps=reps)
    return report


def compare(current: dict, baseline: Optional[dict],
            rtol: float = DEFAULT_RTOL, strict_proxy: bool = False,
            proxy_factor: float = PROXY_FACTOR) -> List[dict]:
    """Findings between a measurement and the pinned baseline (empty =
    clean). Kinds: ``regression`` / ``stale-pin`` / ``unpinned`` /
    ``stale-workload`` / ``capacity-drift`` / ``proxy-regression`` /
    ``proxy-coverage``."""
    out: List[dict] = []
    if baseline is None:
        for name in current["workloads"]:
            out.append({"kind": "unpinned", "workload": name,
                        "message": f"workload {name!r} has no baseline — "
                                   f"run --update-baseline to pin it"})
        return out
    pinned = baseline.get("workloads", {})
    for name, cur in current["workloads"].items():
        pin = pinned.get(name)
        if pin is None:
            out.append({"kind": "unpinned", "workload": name,
                        "message": f"workload {name!r} has no baseline pin "
                                   f"— run --update-baseline"})
            continue
        if int(pin.get("capacity", -1)) != int(cur.get("capacity", -2)):
            out.append({"kind": "capacity-drift", "workload": name,
                        "message": f"{name}: gate capacity changed "
                                   f"({pin.get('capacity')} -> "
                                   f"{cur.get('capacity')}); costs are not "
                                   f"comparable — re-pin with "
                                   f"--update-baseline"})
            continue
        if int(pin.get("k", 1)) != int(cur.get("k", 1)):
            # scan workloads carry the fused K beside the capacity — a K
            # change re-scales every cost, same incomparability as capacity
            out.append({"kind": "capacity-drift", "workload": name,
                        "message": f"{name}: scan dispatch K changed "
                                   f"({pin.get('k', 1)} -> "
                                   f"{cur.get('k', 1)}); costs are not "
                                   f"comparable — re-pin with "
                                   f"--update-baseline"})
            continue
        for metric in ("flops", "bytes_accessed"):
            c, p = float(cur.get(metric, 0.0)), float(pin.get(metric, 0.0))
            if p <= 0.0:
                continue
            if c > p * (1.0 + rtol):
                out.append({
                    "kind": "regression", "workload": name, "metric": metric,
                    "current": c, "pinned": p,
                    "message": f"{name}.{metric} regressed: {c:.4g} vs "
                               f"pinned {p:.4g} (+{(c / p - 1) * 100:.1f}%, "
                               f"rtol {rtol:g}) — the compiled chain got "
                               f"more expensive"})
            elif c < p * (1.0 - rtol):
                out.append({
                    "kind": "stale-pin", "workload": name, "metric": metric,
                    "current": c, "pinned": p,
                    "message": f"{name}.{metric} improved: {c:.4g} vs "
                               f"pinned {p:.4g} "
                               f"({(1 - c / p) * 100:.1f}% below) — bank it "
                               f"with --update-baseline (ratchet-down: the "
                               f"gate must guard the better number)"})
    for name in pinned:
        if name not in current["workloads"]:
            out.append({"kind": "stale-workload", "workload": name,
                        "message": f"baseline pins workload {name!r} which "
                                   f"the gate no longer measures — remove "
                                   f"via --update-baseline"})
    # proxy coverage: every registry kernel family + every extra gate family
    # (names.py::PERF_PROXY_FAMILIES — the scan "dispatch" row) must have a
    # proxy microbenchmark
    if "proxy" in current:
        from ..observability.names import KERNELS, PERF_PROXY_FAMILIES
        for k in KERNELS + PERF_PROXY_FAMILIES:
            if k not in current["proxy"]:
                out.append({"kind": "proxy-coverage", "workload": k,
                            "message": f"family {k!r} (names.py::KERNELS / "
                                       f"PERF_PROXY_FAMILIES) has no proxy "
                                       f"microbenchmark"})
        if strict_proxy:
            for k, cur in current["proxy"].items():
                pin = baseline.get("proxy", {}).get(k)
                if not pin:
                    continue
                c, p = float(cur["ns_per_elem"]), float(pin["ns_per_elem"])
                if p > 0 and c > p * proxy_factor:
                    out.append({
                        "kind": "proxy-regression", "workload": k,
                        "current": c, "pinned": p,
                        "message": f"proxy {k}: {c:g} ns/elem vs pinned "
                                   f"{p:g} (>{proxy_factor:g}x)"})
    return out


def run_gate(root: str = ".", rtol: float = DEFAULT_RTOL,
             skip_proxy: bool = False, strict_proxy: bool = False,
             reps: int = 3) -> Tuple[dict, List[dict]]:
    """Measure + compare against the resolved baseline. Returns
    ``(measurement report, findings)`` — empty findings = gate clean."""
    path = baseline_path(root)
    if os.environ.get("WF_PERFGATE_BASELINE", "") \
            and not os.path.exists(path):
        # an EXPLICIT override pointing nowhere must fail loudly (exit 2),
        # never read as "no baseline yet" (the wf_lint.py contract)
        raise FileNotFoundError(
            f"WF_PERFGATE_BASELINE points at a missing baseline: {path}")
    current = measure(skip_proxy=skip_proxy, reps=reps)
    findings = compare(current, load_baseline(path), rtol=rtol,
                       strict_proxy=strict_proxy)
    return current, findings
