"""Device-program analyzer — Pillar 4 of the static-analysis layer (WF3xx).

Every other static gate reasons about Python *source* (WF1xx config/spec
validation, WF2xx invariant lint, WF26x concurrency).  This one walks the
closed jaxprs of the programs that actually run on the chip — obtained via
``jax.make_jaxpr`` over the same step/scan bodies ``CompiledChain.warm`` /
``warm_scan`` trace (zero FLOPs, zero device: inputs are
``jax.ShapeDtypeStruct``), recursing through ``scan``/``cond``/``while``/
``pjit`` sub-jaxprs — and checks the invariants the whole system rests on
(byte-identical replay, ordered effects inside scan bodies, "OFF path is
byte-for-byte") where they actually live: in the traced equations.

====== ========= =====================================================
code   severity  invariant
====== ========= =====================================================
WF300  error     order-dependent float accumulation in a deterministic-
                 replay program: a ``scatter-add`` (``.at[].add`` /
                 ``segment_sum``) whose indices are not statically
                 unique accumulates float values in index-collision
                 order — XLA may reorder colliding adds per backend/
                 geometry, so supervised replay is only
                 bitwise-reproducible by luck.  Fix: integer
                 accumulation, ``unique_indices=True`` where provable,
                 or a sort-then-segment formulation
WF301  error     unordered host effect in a compiled body: an
                 ``io_callback`` without a literal ``ordered=True`` (or
                 a ``debug_callback`` without ``ordered=True``)
                 reachable from a step/scan program — under scan-fused
                 dispatch the K bodies' effects interleave freely; the
                 jaxpr-level complement of the AST-only WF262, catching
                 aliased imports and wrapped call sites
WF302  warning   host-sync in the per-push hot path: a callback
                 primitive forces the device to round-trip to the host
                 (blocking D2H) on EVERY push, outside the
                 maintain/settle surfaces designed for it — rank the
                 site against wf_health's per-stage ``dispatch_ratio``
                 as a whole-graph fusion candidate (ROADMAP item 2)
WF303  warning   retrace-signature hazard from actual avals: a
                 weak-typed program input/const (a Python scalar the
                 caller may later pass strongly typed) or a weak-typed
                 promotion inside the program (Python-scalar closure
                 constant) — the same chain silently retraces when the
                 weak leaf strengthens; subsumes the WF102 heuristic
                 with evidence from the traced program itself
WF304  error     donated-buffer aliasing: a donated input is read by a
                 later equation (or returned) after the equation XLA
                 will alias it into, or is aliased into two outputs —
                 the classic donate_argnums use-after-free
WF305  warning   shard/K-variant float reduction: a float-dtype
                 ``reduce_sum``/``reduce_prod``/``cumsum``/
                 ``dot_general`` in a program analyzed under dispatch
                 K>1 or shards>1 — float addition is non-associative,
                 so the reduction's grouping (and therefore the bytes)
                 can change with the composition geometry; the precise
                 static evidence needed to retire WF115 pairings one by
                 one (integer reductions are exact and never flagged)
====== ========= =====================================================

``program_fingerprint`` is the other half: a canonical structural hash of a
closed jaxpr — primitives, params, avals, topology under first-use variable
numbering, sub-jaxprs included, const values digested, callables reduced to
qualnames — a pure function of the program (no ids, no addresses), stable
across processes.  The prose claim "toggle OFF is byte-for-byte" becomes a
pinned program-identity test (``tests/test_program_fingerprint.py``).

Baseline: ``analysis/progcheck_baseline.json`` (override:
``WF_PROGCHECK_BASELINE``) suppresses audited findings, but EVERY entry must
carry a non-empty ``rationale`` — an entry without one fails the gate (the
WF26x discipline: suppression is an argued decision, not a shrug).
``scripts/wf_progcheck.py --update-baseline`` rewrites entries while
preserving rationales already written.

This module needs JAX (program analysis genuinely does); the CLI exits 2
cleanly on a box without it.  Registration of the WF3xx codes for
``wf_lint --explain``/``--select`` lives in ``lint.RULES`` (parsed without
importing this module).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax

from .lint import Finding

# --------------------------------------------------------------- programs


@dataclasses.dataclass
class Program:
    """One traced device program plus the execution context it was traced
    for — the unit every WF3xx rule runs over."""

    target: str              # audit-target label, e.g. "nexmark:q3"
    kind: str                # "step" | "scan"
    closed: Any              # jax ClosedJaxpr
    capacity: int
    k: int = 1               # fused dispatch K (kind == "scan")
    shards: int = 1          # shard count the program will run under
    replay: bool = False     # deterministic-replay (supervised) context

    @property
    def path(self) -> str:
        """Baseline identity path (the lint Finding ``path`` slot)."""
        return f"{self.target}/{self.kind}"


def abstract_batch(capacity: int, payload_spec) -> Any:
    """A ``Batch`` of ``ShapeDtypeStruct`` leaves — the abstract twin of
    ``Batch.empty`` (zero allocation, zero device)."""
    from ..batch import Batch, CTRL_DTYPE
    import jax.numpy as jnp

    def mk(leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = getattr(leaf, "dtype", jnp.float32)
        return jax.ShapeDtypeStruct((capacity,) + shape, dtype)

    ctrl = jax.ShapeDtypeStruct((capacity,), CTRL_DTYPE)
    return Batch(key=ctrl, id=ctrl, ts=ctrl,
                 payload=jax.tree.map(mk, payload_spec),
                 valid=jax.ShapeDtypeStruct((capacity,), jnp.bool_))


def _abstract_states(chain) -> tuple:
    """The chain's operator states as ShapeDtypeStructs (never reads the
    device buffers)."""
    return tuple(jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype),
        st) for st in chain.states)


def trace_step(chain, capacity: int):
    """Closed jaxpr of the full-chain per-push step — the same body
    ``CompiledChain._step_fn(0)`` jits, traced abstractly."""
    states = _abstract_states(chain)
    b = abstract_batch(capacity, chain.specs[0])

    def step(states, batch):
        states = list(states)
        for j in range(len(chain.ops)):
            states[j], batch = chain.ops[j].apply(states[j], batch)
        return tuple(states), batch

    return jax.make_jaxpr(step)(states, b)


def trace_scan(chain, k: int, capacity: int):
    """Closed jaxpr of the K-fused scan program — the same body
    ``CompiledChain._scan_fn(0)`` jits (``lax.scan`` over the per-batch
    step with operator states as carry), traced abstractly."""
    states = _abstract_states(chain)
    b = abstract_batch(capacity, chain.specs[0])
    stacked = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct((int(k),) + tuple(s.shape), s.dtype),
        b)

    def scan_step(states, stacked):
        def body(carry, batch):
            carry = list(carry)
            for j in range(len(chain.ops)):
                carry[j], batch = chain.ops[j].apply(carry[j], batch)
            return tuple(carry), batch
        return jax.lax.scan(body, tuple(states), stacked)

    return jax.make_jaxpr(scan_step)(states, stacked)


def chain_programs(chain, capacity: int = None, k: int = 1,
                   shards: int = 1, replay: bool = False,
                   target: str = "chain") -> List[Program]:
    """The programs a driver will actually dispatch for ``chain`` under the
    given config: the per-push step, plus the K-fused scan when scan
    dispatch is on (k > 1) — the ``warm``/``warm_scan`` surface."""
    if capacity is None:
        from ..basic import DEFAULT_BATCH_SIZE
        from ..runtime.pipeline import resolve_batch_hint
        capacity = resolve_batch_hint(chain.ops) or DEFAULT_BATCH_SIZE
    out = [Program(target=target, kind="step",
                   closed=trace_step(chain, capacity),
                   capacity=capacity, k=1, shards=shards, replay=replay)]
    if k and int(k) > 1:
        out.append(Program(target=target, kind="scan",
                           closed=trace_scan(chain, int(k), capacity),
                           capacity=capacity, k=int(k), shards=shards,
                           replay=replay))
    return out


# ----------------------------------------------------------- jaxpr walking


def _sub_jaxprs(eqn) -> List[Tuple[str, Any]]:
    """Every (param_name[index], jaxpr-like) nested under one equation —
    covers scan/pjit (``jaxpr``), cond (``branches``), while
    (``cond_jaxpr``/``body_jaxpr``), custom derivatives, remat: anything
    whose param value walks like a jaxpr."""
    out = []
    for name, val in eqn.params.items():
        vals = val if isinstance(val, (list, tuple)) else (val,)
        for i, v in enumerate(vals):
            j = getattr(v, "jaxpr", None)         # ClosedJaxpr
            if j is not None and hasattr(j, "eqns"):
                out.append((f"{name}[{i}]" if len(vals) > 1 else name, v))
            elif hasattr(v, "eqns"):              # bare Jaxpr
                out.append((f"{name}[{i}]" if len(vals) > 1 else name, v))
    return out


def iter_eqns(closed) -> Iterator[Tuple[Any, str]]:
    """Depth-first ``(eqn, path)`` over a closed jaxpr and every sub-jaxpr;
    ``path`` names the nesting (``scan.jaxpr/cond.branches[1]``) so a
    finding can point INTO the program."""
    def walk(jaxpr, prefix):
        for eqn in jaxpr.eqns:
            yield eqn, prefix
            for pname, sub in _sub_jaxprs(eqn):
                inner = getattr(sub, "jaxpr", sub)
                yield from walk(inner,
                                f"{prefix}/{eqn.primitive.name}.{pname}"
                                if prefix else f"{eqn.primitive.name}.{pname}")
    yield from walk(getattr(closed, "jaxpr", closed), "")


def _is_inexact(aval) -> bool:
    import jax.numpy as jnp
    dt = getattr(aval, "dtype", None)
    return dt is not None and jnp.issubdtype(dt, jnp.inexact)


def _aval_str(v) -> str:
    aval = getattr(v, "aval", None)
    if aval is None:
        return "?"
    w = "~w" if getattr(aval, "weak_type", False) else ""
    return f"{getattr(aval, 'dtype', '?')}{list(getattr(aval, 'shape', []))}{w}"


# ----------------------------------------------------------------- rules


#: callback primitives that force a host round trip inside a compiled body
_CALLBACK_PRIMS = ("io_callback", "debug_callback", "pure_callback")

#: float reductions whose result depends on accumulation grouping
#: (max/min/and/or are associative-exact and never flagged)
_GROUPING_REDUCTIONS = ("reduce_sum", "reduce_prod", "cumsum", "cumprod",
                        "dot_general", "reduce_window_sum", "add_any")


def _finding(prog: Program, code: str, severity: str, line: int,
             message: str, text: str) -> Finding:
    return Finding(code=code, severity=severity, path=prog.path,
                   line=line, message=message, text=text)


def analyze_program(prog: Program) -> List[Finding]:
    """All WF30x findings for one traced program."""
    out: List[Finding] = []
    flagged_301: set = set()
    n = 0
    for eqn, where in iter_eqns(prog.closed):
        n += 1
        name = eqn.primitive.name
        at = f"@{where}" if where else "@top"

        # WF300 — order-dependent float accumulation under replay
        if name == "scatter-add" and prog.replay \
                and not eqn.params.get("unique_indices", False) \
                and any(_is_inexact(o.aval) for o in eqn.outvars):
            out.append(_finding(
                prog, "WF300", "error", n,
                f"scatter-add on {_aval_str(eqn.outvars[0])} with "
                f"possibly-duplicate indices ({at}) in a deterministic-"
                f"replay program: colliding float adds accumulate in an "
                f"order XLA may change per backend/geometry — replay is "
                f"bitwise-reproducible only by luck. Use integer "
                f"accumulation, unique_indices=True where provable, or "
                f"sort-then-segment",
                text=f"scatter-add {_aval_str(eqn.outvars[0])} {at}"))

        # WF301 — unordered host effects in compiled bodies
        if name == "io_callback" and eqn.params.get("ordered") is not True:
            flagged_301.add(id(eqn))
            out.append(_finding(
                prog, "WF301", "error", n,
                f"io_callback without ordered=True ({at}) in a compiled "
                f"{prog.kind} body: under scan-fused dispatch the K "
                f"bodies' host effects interleave freely, breaking "
                f"byte-identical replay — pass ordered=True (the "
                f"jaxpr-level complement of WF262, which only sees "
                f"direct AST call sites)",
                text=f"io_callback unordered {at}"))
        elif name == "debug_callback" \
                and "OrderedDebug" not in str(eqn.params.get("effect", "")):
            flagged_301.add(id(eqn))
            out.append(_finding(
                prog, "WF301", "error", n,
                f"debug_callback without ordered=True ({at}) in a "
                f"compiled {prog.kind} body: effect order is unspecified "
                f"across fused scan iterations — pass "
                f"jax.debug.print(..., ordered=True) or drop it from the "
                f"compiled path",
                text=f"debug_callback unordered {at}"))

        # WF302 — host sync in the per-push hot path (skip eqns already
        # carrying the stronger WF301 verdict)
        if name in _CALLBACK_PRIMS and id(eqn) not in flagged_301:
            cb = eqn.params.get("callback")
            cb_name = getattr(cb, "callback_func", cb)
            cb_name = getattr(cb_name, "__qualname__",
                              getattr(cb_name, "__name__", "<callback>"))
            out.append(_finding(
                prog, "WF302", "warning", n,
                f"{name} -> {cb_name} ({at}): a blocking D2H round trip "
                f"on EVERY push, outside the maintain/settle surfaces — "
                f"the device idles at this equation until the host "
                f"answers. Rank against wf_health's per-stage "
                f"dispatch_ratio as a whole-graph fusion candidate "
                f"(ROADMAP item 2), or move the exchange to the "
                f"maintain path",
                text=f"{name} {cb_name} {at}"))

        # WF303 (in-program half) — Python-scalar promotion inside the body
        if name == "convert_element_type" \
                and eqn.params.get("weak_type", False):
            out.append(_finding(
                prog, "WF303", "warning", n,
                f"weak-typed promotion to "
                f"{eqn.params.get('new_dtype')} ({at}): a Python-scalar "
                f"closure constant entered the traced program — if the "
                f"Python value varies per call the program retraces per "
                f"value; pin it with jnp.asarray(x, dtype)",
                text=f"weak convert_element_type "
                     f"{eqn.params.get('new_dtype')} {at}"))

        # WF305 — grouping-variant float reductions under composition
        if (prog.k > 1 or prog.shards > 1) \
                and name in _GROUPING_REDUCTIONS \
                and any(_is_inexact(o.aval) for o in eqn.outvars):
            geom = (f"dispatch K={prog.k}" if prog.k > 1 else "") + \
                   (" and " if prog.k > 1 and prog.shards > 1 else "") + \
                   (f"shards={prog.shards}" if prog.shards > 1 else "")
            out.append(_finding(
                prog, "WF305", "warning", n,
                f"{name} on {_aval_str(eqn.outvars[0])} ({at}) in a "
                f"program composed under {geom}: float accumulation is "
                f"non-associative, so a grouping change with the "
                f"composition geometry can change the bytes — the exact "
                f"evidence WF115 retirement needs (prove the grouping "
                f"fixed, cast to integer, or keep the pairing rejected)",
                text=f"{name} {_aval_str(eqn.outvars[0])} {at}"))

        # WF304 — donated buffer read after its aliasing equation
        donated = eqn.params.get("donated_invars")
        if donated and any(donated):
            out += _check_donation(prog, eqn, n, at)

    out += _check_weak_signature(prog)
    return out


def _check_donation(prog: Program, eqn, n: int, at: str) -> List[Finding]:
    """WF304 for one pjit equation with donated inputs: (a) a donated
    outer var consumed again by a LATER equation or returned (XLA aliases
    the buffer into this call's outputs — the later read is
    use-after-free); (b) inside the sub-jaxpr, a donated input aliased
    into two outputs (one buffer cannot back both)."""
    out: List[Finding] = []
    donated = eqn.params["donated_invars"]
    jaxpr = getattr(prog.closed, "jaxpr", prog.closed)
    dvars = [v for v, d in zip(eqn.invars, donated)
             if d and hasattr(v, "aval") and not hasattr(v, "val")]

    def uses(e, v):
        return any(u is v for u in e.invars)

    # (a) read-after-donation in the enclosing frame
    seen = False
    for other in jaxpr.eqns:
        if other is eqn:
            seen = True
            continue
        if not seen:
            continue
        for v in dvars:
            if uses(other, v):
                out.append(_finding(
                    prog, "WF304", "error", n,
                    f"donated input {_aval_str(v)} is read by a later "
                    f"`{other.primitive.name}` after "
                    f"`{eqn.params.get('name', eqn.primitive.name)}` "
                    f"({at}) donates it: XLA aliases the buffer into the "
                    f"donated call's outputs, so the later read sees "
                    f"freed/overwritten memory — copy before donating or "
                    f"drop the donation",
                    text=f"donated {_aval_str(v)} read after "
                         f"{eqn.primitive.name} {at}"))
    for v in dvars:
        if any(o is v for o in jaxpr.outvars):
            out.append(_finding(
                prog, "WF304", "error", n,
                f"donated input {_aval_str(v)} is also returned by the "
                f"enclosing program ({at}): the caller receives an alias "
                f"of a buffer XLA already reused — copy before donating",
                text=f"donated {_aval_str(v)} returned {at}"))
    # (b) aliased into two outputs inside the called jaxpr
    sub = eqn.params.get("jaxpr")
    inner = getattr(sub, "jaxpr", sub)
    if inner is not None and hasattr(inner, "outvars"):
        for v, d in zip(inner.invars, donated):
            if not d:
                continue
            hits = sum(1 for o in inner.outvars if o is v)
            if hits > 1:
                out.append(_finding(
                    prog, "WF304", "error", n,
                    f"donated input {_aval_str(v)} is aliased into "
                    f"{hits} outputs of "
                    f"`{eqn.params.get('name', eqn.primitive.name)}` "
                    f"({at}): one donated buffer cannot back two "
                    f"outputs — at most one output can alias it",
                    text=f"donated {_aval_str(v)} x{hits} outputs {at}"))
    return out


def _check_weak_signature(prog: Program) -> List[Finding]:
    """WF303 (signature half): weak-typed top-level inputs/consts — the
    caller-side scalar that silently retraces when strongly typed."""
    out: List[Finding] = []
    jaxpr = getattr(prog.closed, "jaxpr", prog.closed)
    for group, vs in (("input", jaxpr.invars), ("const", jaxpr.constvars)):
        weak = [i for i, v in enumerate(vs)
                if getattr(getattr(v, "aval", None), "weak_type", False)]
        if weak:
            out.append(_finding(
                prog, "WF303", "warning", 0,
                f"{len(weak)} weak-typed program {group}(s) at "
                f"position(s) {weak}: the signature was traced from a "
                f"Python scalar — the same chain retraces (new "
                f"executable, new cache entry) the first time a caller "
                f"passes the leaf strongly typed; pin with "
                f"jnp.asarray(x, dtype) at the boundary",
                text=f"weak {group}s {weak}"))
    return out


def analyze_programs(programs: Sequence[Program]) -> List[Finding]:
    out: List[Finding] = []
    for p in programs:
        out += analyze_program(p)
    return sorted(out, key=lambda x: (x.path, x.line, x.code, x.text))


# ------------------------------------------------------- the fingerprint


_ADDR_RE = re.compile(r" at 0x[0-9a-fA-F]+")


def _digest_value(v) -> str:
    """Stable digest of a constant array/scalar (values matter: two
    programs differing only in a baked-in table differ)."""
    import numpy as np
    try:
        a = np.asarray(v)
        if a.dtype == object:              # not a value array: repr-scrub
            return _ADDR_RE.sub("", repr(v))
        return (f"{a.dtype}{list(a.shape)}:"
                f"{hashlib.sha256(a.tobytes()).hexdigest()[:16]}")
    except Exception:  # noqa: BLE001 — non-array consts degrade to repr
        return _ADDR_RE.sub("", repr(v))


def _canon_param(v) -> str:
    """Canonical, address-free rendering of one eqn param (sub-jaxprs are
    rendered by the caller; callables reduce to their qualname)."""
    if hasattr(v, "eqns") or hasattr(getattr(v, "jaxpr", None), "eqns"):
        return "<jaxpr>"                     # rendered via recursion
    if isinstance(v, (list, tuple)):
        return "(" + ",".join(_canon_param(x) for x in v) + ")"
    import jax.core
    if isinstance(v, jax.core.AbstractValue):
        # an aval param (io_callback result_avals etc.): structural only
        w = "~w" if getattr(v, "weak_type", False) else ""
        return (f"aval:{getattr(v, 'dtype', '?')}"
                f"{list(getattr(v, 'shape', []))}{w}")
    if callable(v) or type(v).__name__ == "_FlatCallback":
        fn = getattr(v, "callback_func", v)
        return f"fn:{getattr(fn, '__qualname__', getattr(fn, '__name__', type(fn).__name__))}"
    if hasattr(v, "dtype") and hasattr(v, "shape"):
        return _digest_value(v)
    return _ADDR_RE.sub("", repr(v))


def _canon_jaxpr(jaxpr, consts, h) -> None:
    """Feed a canonical rendering of ``jaxpr`` into hash ``h``: variables
    numbered in first-use order (never by id), params normalized, consts
    digested by value, sub-jaxprs recursed in param order."""
    ids: Dict[int, int] = {}

    def vid(v) -> str:
        if hasattr(v, "val"):                # Literal: value, not identity
            return f"lit({_digest_value(v.val)}:{_aval_str(v)})"
        k = id(v)
        if k not in ids:
            ids[k] = len(ids)
        return f"v{ids[k]}:{_aval_str(v)}"

    h.update(b"in[")
    for v in jaxpr.invars:
        h.update(vid(v).encode())
        h.update(b",")
    h.update(b"]const[")
    for v, c in zip(jaxpr.constvars, consts or [None] * len(jaxpr.constvars)):
        h.update(vid(v).encode())
        if c is not None:
            h.update(b"=")
            h.update(_digest_value(c).encode())
        h.update(b",")
    h.update(b"]")
    for eqn in jaxpr.eqns:
        h.update(eqn.primitive.name.encode())
        h.update(b"(")
        for v in eqn.invars:
            h.update(vid(v).encode())
            h.update(b",")
        h.update(b")->(")
        for v in eqn.outvars:
            h.update(vid(v).encode())
            h.update(b",")
        h.update(b"){")
        for pname in sorted(eqn.params):
            h.update(pname.encode())
            h.update(b"=")
            h.update(_canon_param(eqn.params[pname]).encode())
            h.update(b";")
            pv = eqn.params[pname]
            pvs = pv if isinstance(pv, (list, tuple)) else (pv,)
            for sub in pvs:
                inner = getattr(sub, "jaxpr", sub)
                if hasattr(inner, "eqns"):
                    h.update(b"<<")
                    _canon_jaxpr(inner, getattr(sub, "consts", None), h)
                    h.update(b">>")
        h.update(b"}")
    h.update(b"out[")
    for v in jaxpr.outvars:
        h.update(vid(v).encode())
        h.update(b",")
    h.update(b"]")


def program_fingerprint(closed) -> str:
    """Canonical structural sha256 of a (closed) jaxpr — a pure function
    of the program: same equations, params, avals, topology and constant
    values => same hex digest, in any process (no ids, no addresses)."""
    h = hashlib.sha256()
    jaxpr = getattr(closed, "jaxpr", closed)
    _canon_jaxpr(jaxpr, getattr(closed, "consts", None), h)
    return h.hexdigest()


def step_fingerprint(chain, capacity: int = None) -> str:
    """Fingerprint of the chain's per-push step program — THE toggle-OFF
    identity gate primitive (tests/test_program_fingerprint.py)."""
    if capacity is None:
        from ..basic import DEFAULT_BATCH_SIZE
        from ..runtime.pipeline import resolve_batch_hint
        capacity = resolve_batch_hint(chain.ops) or DEFAULT_BATCH_SIZE
    return program_fingerprint(trace_step(chain, capacity))


# --------------------------------------------------------------- baseline


def baseline_path(root: str = None) -> str:
    """``WF_PROGCHECK_BASELINE`` (run time, CLI/validate invocation)
    overrides the checked-in ``analysis/progcheck_baseline.json``;
    ``root=None`` resolves next to this module (validate() runs from any
    cwd), a root resolves repo-relative (the CLI convention)."""
    override = os.environ.get("WF_PROGCHECK_BASELINE", "")
    if override:
        return override if os.path.isabs(override) \
            else os.path.join(root or ".", override)
    if root is None:
        return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "progcheck_baseline.json")
    return os.path.join(root, "windflow_tpu", "analysis",
                        "progcheck_baseline.json")


def load_baseline(path: str) -> Tuple[Dict[tuple, int], List[str]]:
    """(suppression counts, problems).  Problems are entries without a
    non-empty ``rationale`` — the gate REFUSES to ride them (the WF26x
    discipline: a suppression is an argued decision)."""
    if not os.path.exists(path):
        return {}, []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    counts: Dict[tuple, int] = {}
    problems: List[str] = []
    for e in data.get("findings", ()):
        k = (e["code"], e["path"], e.get("text", ""))
        if not str(e.get("rationale", "")).strip():
            problems.append(f"{e['code']} {e['path']} {e.get('text', '')!r}")
            continue
        counts[k] = counts.get(k, 0) + 1
    return counts, problems


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    """Write the baseline from ``findings``, PRESERVING rationales already
    written for entries that still match (an --update-baseline must never
    erase the written record of why a finding is accepted)."""
    old: Dict[tuple, List[str]] = {}
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            for e in json.load(f).get("findings", ()):
                k = (e["code"], e["path"], e.get("text", ""))
                r = str(e.get("rationale", "")).strip()
                if r:
                    old.setdefault(k, []).append(r)
    entries = []
    for x in findings:
        k = x.key()
        kept = old.get(k)
        entries.append({
            "code": x.code, "path": x.path, "text": x.text,
            "message": x.message,
            "rationale": kept.pop(0) if kept else "",
        })
    payload = {
        "comment": "audited wf_progcheck findings suppressed from the gate; "
                   "EVERY entry must carry a written rationale (empty "
                   "rationale = gate failure). Regenerate with "
                   "scripts/wf_progcheck.py --update-baseline (existing "
                   "rationales are preserved for entries that still match).",
        "findings": entries,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")


def apply_baseline(findings: Sequence[Finding],
                   counts: Dict[tuple, int]) -> List[Finding]:
    """Findings not suppressed (count-aware, the lint.py semantics)."""
    remaining = dict(counts)
    fresh = []
    for x in findings:
        k = x.key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
        else:
            fresh.append(x)
    return fresh


# ----------------------------------------------------------- audit surface


def _mk_chain(src, ops, capacity: int):
    from ..runtime.pipeline import CompiledChain
    return CompiledChain(list(ops), src.payload_spec(),
                         batch_capacity=capacity)


def _nexmark_programs() -> List[Program]:
    """The Nexmark query set: every query's step program, the K-fused scan
    for the dispatch surface, and the q3 tiered variant (the host-callback
    production path), all under replay semantics (every query runs under
    the supervised drivers in tier-1)."""
    from ..nexmark import queries as q
    out: List[Program] = []
    for name in q.QUERIES:
        src, ops = q.make_query(name, total=512)
        chain = _mk_chain(src, ops, 64)
        out += chain_programs(chain, capacity=64, k=4, replay=True,
                              target=f"nexmark:{name}")
    src, ops = q.q3_enrich_join(512, tiered=True)
    out += chain_programs(_mk_chain(src, ops, 64), capacity=64, k=1,
                          replay=True, target="nexmark:q3_tiered")
    return out


def _ysb_programs() -> List[Program]:
    from ..benchmarks import ysb
    out: List[Program] = []
    for label, mk in (("ysb", ysb.make_ops), ("ysb_wmr", ysb.make_ops_wmr)):
        src = ysb.make_source(total=2048)
        chain = _mk_chain(src, mk(), 1024)
        out += chain_programs(chain, capacity=1024, k=4, replay=True,
                              target=f"bench:{label}")
    return out


def _mp_matrix_programs() -> List[Program]:
    """The mp_test matrix topologies (tests/test_mp_matrix.py CASES): every
    window-pattern family at its tier-1 geometry, step programs under
    replay (the chaos suites replay all of them)."""
    import jax.numpy as jnp
    import windflow_tpu as wf
    from ..basic import win_type_t
    from ..operators.window import WindowSpec
    from ..operators.win_seq import Win_Seq
    from ..operators.win_patterns import (Win_Farm, Key_Farm, Key_FFAT,
                                          Pane_Farm, Win_MapReduce)
    K = 3
    cases = {
        "win_seq_cb": lambda: Win_Seq(lambda wid, it: it.sum("v"),
                                      WindowSpec(8, 4, win_type_t.CB),
                                      num_keys=K),
        "win_seq_tb": lambda: Win_Seq(lambda wid, it: it.sum("v"),
                                      WindowSpec(12, 6, win_type_t.TB),
                                      num_keys=K),
        "win_farm_cb": lambda: Win_Farm(lambda wid, it: it.sum("v"),
                                        WindowSpec(10, 5, win_type_t.CB),
                                        parallelism=4, num_keys=K),
        "key_farm_cb": lambda: Key_Farm(lambda wid, it: it.max("v"),
                                        WindowSpec(6, 3, win_type_t.CB),
                                        parallelism=3, num_keys=K),
        "key_ffat_cb": lambda: Key_FFAT(lambda t: t.v, jnp.add,
                                        spec=WindowSpec(8, 2, win_type_t.CB),
                                        num_keys=K),
        "pane_farm_cb": lambda: Pane_Farm(lambda pid, it: it.sum("v"),
                                          lambda wid, it: it.sum(),
                                          WindowSpec(9, 3, win_type_t.CB),
                                          num_keys=K),
        "wmr_cb": lambda: Win_MapReduce(lambda wid, it: it.sum("v"),
                                        lambda wid, it: it.sum(),
                                        WindowSpec(8, 8, win_type_t.CB),
                                        map_parallelism=2, num_keys=K),
    }
    out: List[Program] = []
    for label, mk in sorted(cases.items()):
        src = wf.Source(lambda i: {"v": ((i * 13) % 23)
                                   .astype(jnp.float32)},
                        total=240, num_keys=K)
        ops = mk()
        if not isinstance(ops, (list, tuple)):
            ops = [ops]
        chain = _mk_chain(src, list(ops), 48)
        out += chain_programs(chain, capacity=48, k=1, replay=True,
                              target=f"mp:{label}")
    return out


def _example_programs() -> List[Program]:
    """The example topologies (examples/01..06), rebuilt as op chains: the
    examples themselves are self-running scripts, so the audit mirrors
    their graphs from the same builders they use."""
    import jax.numpy as jnp
    import windflow_tpu as wf
    out: List[Program] = []
    # 01_wordcount: FlatMap -> Map -> KeyBy -> Accumulator
    VOCAB = 50

    def make_words(i):
        return {"w": jnp.stack([(i * 7) % VOCAB, (i * 13) % VOCAB,
                                (i * 29) % VOCAB])}

    def split_words(t, shipper):
        for j in range(3):
            shipper.push({"word": t.w[j]})

    src = wf.Source(make_words, total=512)
    ops = [wf.FlatMap(split_words, max_fanout=3),
           wf.Map(lambda t: {"one": jnp.ones((), jnp.int32),
                             "word": t.word}),
           wf.KeyBy(lambda t: t.word, num_keys=VOCAB),
           wf.Accumulator(lambda t: t.data["one"], init_value=0,
                          num_keys=VOCAB)]
    out += chain_programs(_mk_chain(src, ops, 64), capacity=64, k=1,
                          replay=True, target="example:wordcount")
    # 02 rides the YSB chains and 06 the nexmark q1 chain already audited;
    # 03/05 use the Key_FFAT/Win_Seq topologies the mp-matrix target owns.
    # 04 is the multichip launcher: audit ITS geometry — the same Key_FFAT
    # chain under shards=2 (the WF305 shard axis)
    from ..operators.window import WindowSpec
    from ..basic import win_type_t
    src = wf.Source(lambda i: {"v": ((i * 7) % 31).astype(jnp.float32)},
                    total=4096, num_keys=8)
    op = wf.Key_FFAT(lambda t: t.v, jnp.add,
                     spec=WindowSpec(8, 4, win_type_t.CB), num_keys=8)
    out += chain_programs(_mk_chain(src, [op], 256), capacity=256, k=1,
                          shards=2, replay=True, target="example:multichip")
    # 06 is the serving wrapper around a Pipeline chain, audited here via
    # its default echo graph
    src = wf.Source(lambda i: {"v": (i % 97).astype(jnp.int32)}, total=512,
                    num_keys=8)
    out += chain_programs(
        _mk_chain(src, [wf.Map(lambda t: {"v": t.v * 2})], 64),
        capacity=64, k=1, replay=True, target="example:serving_echo")
    return out


#: the audited whole-repo target set — ``scripts/wf_progcheck.py`` runs all
#: of these by default; tests exercise them one family at a time
AUDIT_TARGETS: Dict[str, Callable[[], List[Program]]] = {
    "nexmark": _nexmark_programs,
    "ysb": _ysb_programs,
    "mp-matrix": _mp_matrix_programs,
    "examples": _example_programs,
}


def run_progcheck(targets: Optional[Sequence[str]] = None) -> List[Finding]:
    """Trace + analyze every audit target (or the named subset)."""
    programs: List[Program] = []
    for name in (targets or sorted(AUDIT_TARGETS)):
        if name not in AUDIT_TARGETS:
            raise ValueError(f"unknown progcheck target {name!r}; "
                             f"registered: {sorted(AUDIT_TARGETS)}")
        programs += AUDIT_TARGETS[name]()
    return analyze_programs(programs)


def progcheck_repo(root: str = ".", targets: Optional[Sequence[str]] = None,
                   ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """(fresh, suppressed, baseline_problems) — THE gate semantics shared
    by the CLI and tests; ``baseline_problems`` (entries without a
    rationale) must fail the gate."""
    findings = run_progcheck(targets)
    counts, problems = load_baseline(baseline_path(root))
    fresh = apply_baseline(findings, counts)
    fresh_ids = {id(x) for x in fresh}
    return fresh, [x for x in findings if id(x) not in fresh_ids], problems
