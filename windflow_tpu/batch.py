"""The micro-batch: the unit of data flow in the whole framework.

The reference moves ONE heap-allocated tuple at a time between operator threads
(``new tuple_t()`` per emitted tuple, ``wf/source.hpp:184``, ``wf/shipper.hpp:87``) and
only its GPU operators batch (``wf/win_seq_gpu.hpp:352-560``). On TPU the only winning
model is micro-batch-at-a-time with structure-of-arrays buffers, so the *stream itself*
is a sequence of fixed-capacity :class:`Batch` values:

- ``key``/``id``/``ts`` are the reference's tuple control-field contract
  ``getControlFields() -> (key, id, ts)`` (``wf/window.hpp:132``,
  ``src/graph_test/graph_common.hpp:69-80``) lifted to arrays.
- ``payload`` is an arbitrary pytree of ``[C, ...]`` arrays — the user tuple fields.
- ``valid`` is the occupancy mask: fixed capacity + mask is how every dynamic-shape
  problem (filtering, flatmap fan-out, partial flush at EOS) is made XLA-static.

A :class:`Batch` is a JAX pytree, so it flows through ``jit``/``vmap``/``shard_map``
unchanged; sharding the leading (capacity) axis over a mesh is the data-parallel
replication of the reference (every operator's ``parallelism`` replicas,
``wf/source.hpp:284-296``).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

#: dtype used for the (key, id, ts) control fields. int32: TPU-native word size; per-key
#: ids and relative-usecs timestamps fit comfortably for streaming benchmarks.
CTRL_DTYPE = jnp.int32

#: Host-side sidecar metadata convention (the causal-tracing trace id,
#: ``observability/tracing.py``): metadata rides on the *Python* Batch object
#: under this attribute — set via ``object.__setattr__`` on the frozen
#: dataclass, NEVER as a pytree field, so compiled programs, cached
#: executables, and checkpoints are byte-identical with tracing on or off.
#: The sidecar does not survive jit/``jax.tree.map``/``dataclasses.replace``
#: (those build new objects); driver loops re-attach it across operator hops
#: with ``observability.tracing.carry`` (tracing.py mirrors this attribute
#: name as a literal — it must stay importable without JAX, so it cannot
#: import this module).  Rebatching (``split_batch``/``concat_batches``)
#: intentionally drops it: a merged or split batch is no longer the ingested
#: unit the id names.
TRACE_META_ATTR = "_wf_trace"


def trace_meta(batch):
    """The batch's host-side trace metadata (trace id), or None — the
    user-facing reader (e.g. inside a Sink callback over a host batch);
    runtime attach/propagate lives in ``observability.tracing``."""
    return getattr(batch, TRACE_META_ATTR, None)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Batch:
    """Fixed-capacity SoA micro-batch of tuples.

    All leaves share the leading capacity axis ``C``. Lanes where ``valid`` is False
    are padding: operators must ignore them and must produce masked-out garbage only
    in invalid lanes.
    """

    key: jax.Array       # i32[C] — key slot in [0, max_keys)
    id: jax.Array        # i32[C] — per-key progressive id (control field "id")
    ts: jax.Array        # i32[C] — timestamp (control field "ts")
    payload: Any         # pytree of [C, ...] arrays
    valid: jax.Array     # bool[C]

    # -- introspection ----------------------------------------------------------------

    @property
    def capacity(self) -> int:
        return self.key.shape[0]

    def count(self) -> jax.Array:
        """Number of live tuples (traced scalar)."""
        return jnp.sum(self.valid.astype(jnp.int32))

    # -- construction -----------------------------------------------------------------

    @staticmethod
    def empty(capacity: int, payload_spec: Any) -> "Batch":
        """An all-invalid batch. ``payload_spec`` is a pytree of
        ``jax.ShapeDtypeStruct`` (without the capacity axis) or example arrays."""
        def mk(leaf):
            shape = getattr(leaf, "shape", ())
            dtype = getattr(leaf, "dtype", jnp.float32)
            return jnp.zeros((capacity,) + tuple(shape), dtype)
        return Batch(
            key=jnp.zeros((capacity,), CTRL_DTYPE),
            id=jnp.zeros((capacity,), CTRL_DTYPE),
            ts=jnp.zeros((capacity,), CTRL_DTYPE),
            payload=jax.tree.map(mk, payload_spec),
            valid=jnp.zeros((capacity,), jnp.bool_),
        )

    @staticmethod
    def of(payload: Any, key=None, id=None, ts=None, valid=None) -> "Batch":
        """Build a batch from payload arrays (host or device)."""
        leaves = jax.tree.leaves(payload)
        if not leaves:
            raise ValueError("payload must contain at least one array")
        c = np.shape(leaves[0])[0]
        z = jnp.zeros((c,), CTRL_DTYPE)
        return Batch(
            key=z if key is None else jnp.asarray(key, CTRL_DTYPE),
            id=z if id is None else jnp.asarray(id, CTRL_DTYPE),
            ts=z if ts is None else jnp.asarray(ts, CTRL_DTYPE),
            payload=jax.tree.map(jnp.asarray, payload),
            valid=jnp.ones((c,), jnp.bool_) if valid is None else jnp.asarray(valid, jnp.bool_),
        )

    # -- transforms -------------------------------------------------------------------

    def replace(self, **kw) -> "Batch":
        return dataclasses.replace(self, **kw)

    def with_payload(self, payload: Any) -> "Batch":
        return dataclasses.replace(self, payload=payload)

    def mask(self, keep: jax.Array) -> "Batch":
        """Intersect the validity mask with ``keep`` (the Filter primitive)."""
        return dataclasses.replace(self, valid=self.valid & keep)

    def compact(self) -> "Batch":
        """Pack live tuples to the front (stable). Counterpart of the reference GPU
        emitter's prescan + ``create_sub_batch`` compaction
        (``wf/standard_nodes_gpu.hpp:52-238``, scan suite ``wf/gpu_utils.hpp:330-417``).

        Invalid lanes are moved to the tail and zero-masked. Shape is unchanged."""
        c = self.capacity
        # stable partition: sort by (!valid, position)
        order = jnp.argsort(jnp.where(self.valid, 0, 1), stable=True)
        take = lambda a: jnp.take(a, order, axis=0)
        return Batch(
            key=take(self.key), id=take(self.id), ts=take(self.ts),
            payload=jax.tree.map(take, self.payload),
            valid=take(self.valid),
        )

    def select(self, idx: jax.Array, valid: jax.Array) -> "Batch":
        """Gather lanes ``idx`` with a new validity mask (size may differ)."""
        take = lambda a: jnp.take(a, idx, axis=0)
        return Batch(
            key=take(self.key), id=take(self.id), ts=take(self.ts),
            payload=jax.tree.map(take, self.payload),
            valid=valid & take(self.valid),
        )

    def sorted_by(self, *, by: str = "ts") -> "Batch":
        """Stable sort live tuples by ``ts`` or ``id`` (invalid lanes to the tail).
        The batch-level counterpart of the reference ``Ordering_Node``
        (``wf/ordering_node.hpp:124-280``): DETERMINISTIC-mode order restoration."""
        k = self.ts if by == "ts" else self.id
        big = jnp.iinfo(CTRL_DTYPE).max
        order = jnp.argsort(jnp.where(self.valid, k, big), stable=True)
        take = lambda a: jnp.take(a, order, axis=0)
        return Batch(
            key=take(self.key), id=take(self.id), ts=take(self.ts),
            payload=jax.tree.map(take, self.payload),
            valid=take(self.valid),
        )

    # -- host side --------------------------------------------------------------------

    def to_host(self) -> "Batch":
        return jax.tree.map(np.asarray, self)

    def live_payload(self) -> Any:
        """Host-side: payload restricted to live lanes (numpy)."""
        v = np.asarray(self.valid)
        return jax.tree.map(lambda a: np.asarray(a)[v], self.payload)


def hash_key_to_slot(key, num_slots: int):
    """Map arbitrary user keys (strings, large ints, numpy arrays of ints) to key
    slots in ``[0, num_slots)`` — the reference's ``hash(key) % n`` routing contract
    (``wf/standard_emitter.hpp:88-99``) applied at ingest time. Deterministic across
    runs (unlike Python's salted ``hash``)."""
    if isinstance(key, (str, bytes)):
        return _fnv1a(key) % num_slots
    if isinstance(key, (int, np.integer)):
        # same arithmetic as the array branch: Knuth multiply in uint64 wraparound
        k = int(key) & 0xFFFFFFFFFFFFFFFF
        return int((k * 2654435761) % (1 << 64) % num_slots)
    arr = np.asarray(key)
    if arr.dtype.kind in "USiu" and arr.ndim > 0:
        # array path: one native C pass when the library is built (bit-identical
        # FNV-1a / Knuth arithmetic, windflow_tpu/native/ingest.cpp)
        from .native import hash_keys_native
        slots = hash_keys_native(arr, num_slots)
        if slots is not None:
            return slots
    if arr.dtype.kind in "USO":                        # strings / bytes / objects
        # hash each distinct key once (batches typically repeat few keys)
        uniq, inv = np.unique(arr.ravel(), return_inverse=True)
        slots = np.asarray([hash_key_to_slot(u, num_slots) for u in uniq.tolist()],
                           np.int32)
        return slots[inv].reshape(arr.shape)
    if arr.dtype.kind not in "iu":
        raise TypeError(
            f"hash_key_to_slot: keys must be ints, strings, or bytes, got dtype "
            f"{arr.dtype} (float keys would silently truncate and merge)")
    return ((arr.astype(np.uint64) * np.uint64(2654435761)) % np.uint64(num_slots)
            ).astype(np.int32)


def _fnv1a(s) -> int:
    if isinstance(s, str):
        data = s.encode()
    elif isinstance(s, bytes):
        data = s
    else:
        raise TypeError(f"hash_key_to_slot: unhashable key {s!r} "
                        f"(expected str/bytes, got {type(s).__name__})")
    h = 2166136261
    for ch in data:
        h = ((h ^ ch) * 16777619) & 0xFFFFFFFF         # FNV-1a
    return h


def concat_batches(a: Batch, b: Batch) -> Batch:
    """Concatenate two batches along the capacity axis (merge primitive)."""
    cat = lambda x, y: jnp.concatenate([x, y], axis=0)
    return Batch(
        key=cat(a.key, b.key), id=cat(a.id, b.id), ts=cat(a.ts, b.ts),
        payload=jax.tree.map(cat, a.payload, b.payload),
        valid=cat(a.valid, b.valid),
    )


def stack_batches(batches) -> Batch:
    """Stack K same-capacity batches along a NEW leading axis: every leaf
    ``[C, ...]`` becomes ``[K, C, ...]``. The scan-dispatch transport
    (``CompiledChain.push_many``): the stacked pytree is the ``xs`` of a
    ``lax.scan`` over the per-batch step, so K batches ride ONE host dispatch.
    Inverse of :func:`unstack_batches`; lane content is preserved verbatim
    (stack/unstack is a pure reshape-move, so scanned results are
    byte-identical to K sequential pushes). The host-side trace sidecar does
    NOT ride along (same stance as ``split_batch``/``concat_batches``):
    drivers re-attach ids to the unstacked outputs with ``tracing.carry``."""
    batches = list(batches)
    if not batches:
        raise ValueError("stack_batches: need at least one batch")
    c0 = batches[0].capacity
    for b in batches[1:]:
        if b.capacity != c0:
            raise ValueError(
                f"stack_batches: mixed capacities {c0} vs {b.capacity} — a "
                f"scanned executable is traced for ONE (K, capacity) shape; "
                f"the MicrobatchAccumulator groups same-capacity runs")
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *batches)


def unstack_batches(stacked: Batch, k: int = None) -> list:
    """Split a stacked batch (leaves ``[K, C, ...]``) back into K capacity-C
    batches — the inverse of :func:`stack_batches`, applied to the stacked
    ``ys`` a scanned dispatch returns."""
    leaves = jax.tree.leaves(stacked)
    if k is None:
        k = leaves[0].shape[0]
    return [jax.tree.map(lambda a: a[i], stacked) for i in range(k)]


def split_batch(batch: Batch, capacity: int) -> list:
    """Slice a batch into ``capacity``-sized pieces along the capacity axis —
    the inverse of :func:`concat_batches` and the counterpart of the reference
    GPU emitter's ``create_sub_batch`` (``wf/standard_nodes_gpu.hpp``). Lane
    content (including the validity mask) is preserved verbatim, so results
    are invariant to the split. Requires exact divisibility: the control
    plane's capacity ladder is built so every down-rung divides the base."""
    c = batch.capacity
    capacity = int(capacity)
    if capacity < 1 or c % capacity:
        raise ValueError(f"split_batch: capacity {capacity} does not divide "
                         f"the batch capacity {c}")
    if capacity == c:
        return [batch]
    cut = lambda a, s: a[s:s + capacity]
    return [Batch(key=cut(batch.key, s), id=cut(batch.id, s),
                  ts=cut(batch.ts, s),
                  payload=jax.tree.map(lambda a: cut(a, s), batch.payload),
                  valid=cut(batch.valid, s))
            for s in range(0, c, capacity)]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class TupleRef:
    """Per-tuple view handed to user functions under ``vmap`` — the counterpart of the
    reference passing ``tuple_t&`` into the user lambda. ``key``/``id``/``ts`` are the
    control fields; payload fields are reachable as attributes (dict payloads) or via
    ``.data`` (any pytree)."""

    key: jax.Array
    id: jax.Array
    ts: jax.Array
    data: Any

    def __getattr__(self, name):
        data = object.__getattribute__(self, "data")
        if isinstance(data, dict) and name in data:
            return data[name]
        raise AttributeError(name)


def tuple_refs(batch: Batch) -> TupleRef:
    """Batched TupleRef (each field keeps its capacity axis; vmap strips it)."""
    return TupleRef(key=batch.key, id=batch.id, ts=batch.ts, data=batch.payload)


class MutableTupleRef:
    """Mutable per-tuple view backing the reference's *in-place* signatures
    (``void(tuple_t&)`` Map, ``wf/map.hpp:64-74``): payload attribute writes are
    recorded during tracing and become the output payload. Control fields stay
    read-only (the reference mutates them only via ``setControlFields``, which
    routing owns here). Requires a dict payload (named fields)."""

    __slots__ = ("_ctrl", "_data")

    def __init__(self, ref: TupleRef):
        object.__setattr__(self, "_ctrl",
                           {"key": ref.key, "id": ref.id, "ts": ref.ts})
        data = ref.data
        if not isinstance(data, dict):
            raise TypeError(
                "in-place map functions need a dict payload (named fields); "
                "return a new payload instead for pytree payloads")
        object.__setattr__(self, "_data", dict(data))

    def __getattr__(self, name):
        ctrl = object.__getattribute__(self, "_ctrl")
        if name in ctrl:
            return ctrl[name]
        data = object.__getattribute__(self, "_data")
        if name == "data":
            return data
        if name in data:
            return data[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if name in ("key", "id", "ts"):
            raise TypeError(
                f"control field '{name}' is read-only in user functions (the "
                f"reference owns setControlFields in its routing layer)")
        object.__getattribute__(self, "_data")[name] = value

    def _payload(self):
        return dict(object.__getattribute__(self, "_data"))
