"""Small-table lookups without per-element gathers.

Measured on TPU v5e (slope-timed, 1M indices): ``jnp.take`` from a small table costs
~5.6 ns/element (XLA lowers dynamic gather to a serial loop), while a select-based
one-hot reduction runs on the VPU at ~0.002 ns/element/table-row. For tables up to a
few thousand rows the select form wins by 3-30x — this is the TPU counterpart of the
reference's per-tuple hash-map lookups (e.g. the YSB campaign join) and of per-key
state-table reads.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: table sizes up to this use the select-based path (break-even ~2800 rows measured)
SELECT_MAX_ROWS = 2048


def table_lookup(table: jax.Array, idx: jax.Array) -> jax.Array:
    """``table[idx]`` with the fastest strategy for the table size.

    ``table``: ``[K, ...]``; ``idx``: ``[C]`` int32 in [0, K). Out-of-range indices
    return row 0 contributions only in the select path; clamp beforehand if needed."""
    K = table.shape[0]
    if K > SELECT_MAX_ROWS or table.ndim > 2:
        return jnp.take(table, idx, axis=0)
    oh = idx[:, None] == jnp.arange(K, dtype=idx.dtype)[None, :]      # [C, K]
    if table.ndim == 1:
        return jnp.sum(jnp.where(oh, table[None, :], jnp.zeros((), table.dtype)),
                       axis=1)
    # [C, K, V] select-reduce for small trailing dims
    return jnp.sum(jnp.where(oh[:, :, None], table[None, :, :],
                             jnp.zeros((), table.dtype)), axis=1)
