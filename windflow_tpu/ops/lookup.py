"""Small-table lookups without per-element gathers.

Measured on TPU v5e (slope-timed, 1M indices): ``jnp.take`` from a small table costs
~5.6 ns/element (XLA lowers dynamic gather to a serial loop), while a select-based
one-hot reduction runs on the VPU at ~0.002 ns/element/table-row. For tables up to a
few thousand rows the select form wins by 3-30x — this is the TPU counterpart of the
reference's per-tuple hash-map lookups (e.g. the YSB campaign join) and of per-key
state-table reads.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: 1-D table sizes up to this use the one-shot select path
SELECT_MAX_ROWS = 128
#: 2-D tables keep the select-reduce up to this many rows (break-even ~2800 measured)
SELECT_MAX_ROWS_2D = 2048
#: factored path handles tables up to this many rows (cost ~ C * 2 * sqrt(K))
FACTORED_MAX_ROWS = 1 << 16


def _exact_in_f32(table: jax.Array) -> bool:
    """True when every table value is exactly representable in float32 (so a one-hot
    f32 matmul — a sum with a single nonzero term — reproduces it bit-exactly)."""
    if table.dtype in (jnp.float32, jnp.bfloat16, jnp.float16):
        return True
    if jnp.issubdtype(table.dtype, jnp.integer):
        bits = jnp.iinfo(table.dtype).bits
        return bits <= 16          # |v| <= 2^16 < 2^24: exact in f32
    return table.dtype == jnp.bool_


def table_lookup(table: jax.Array, idx: jax.Array, *,
                 impl: str = None) -> jax.Array:
    """``table[idx]`` with the fastest strategy for the table size.

    Strategies (1-D tables): tiny tables use a select-reduce on the VPU; larger ones
    factor the index as ``hi * K2 + lo`` and select the row with a one-hot matmul
    then the column with a select-reduce — O(C * (K1 + K2)) work instead of the
    O(C * K) select or the ~5.6 ns/element serialized gather ``jnp.take`` lowers to.
    int32 tables with values that may exceed 2^24 fall back to ``take`` (f32 selection
    would round them).

    ``impl``: "xla" (default) or "pallas" — routes the factored path through
    :func:`_pallas_factored_lookup` (rows intermediate VMEM-resident) when the
    capacity geometry allows. Defaults from the per-backend kernel registry
    (``ops/registry.py``: ``WF_KERNEL_IMPL``, the deprecated
    ``WF_LOOKUP_IMPL`` alias, or a persisted autotuned winner) so whole
    chains can be A/B'd without code changes.

    ``table``: ``[K, ...]``; ``idx``: ``[C]`` int32 in [0, K). Out-of-range indices
    return 0 in the select/factored paths; clamp beforehand if needed."""
    from .registry import resolve_impl
    K = table.shape[0]
    # NOTE: resolution happens at TRACE time — a cached jitted executable
    # built before an env/registry change keeps the old impl within the
    # process (an A/B or a monkeypatch.setenv against a shared jitted step
    # would silently measure the same implementation twice). The registry
    # records this choice; validate() reports disagreements as WF109. The
    # old WF_LOOKUP_IMPL toggle is honored as a deprecated alias there.
    impl = resolve_impl(
        "lookup", impl=impl,
        spec_key=f"C{getattr(idx, 'shape', ('?',))[0]}xK{K}:{table.dtype}")

    def factored(t, i):
        if impl == "pallas" and i.ndim == 1 and _pallas_block(i.shape[0]):
            return _pallas_factored_lookup(t, i)
        return _factored_lookup(t, i)

    if table.ndim == 1 and SELECT_MAX_ROWS < K <= FACTORED_MAX_ROWS:
        import numpy as np
        concrete = table.size and not isinstance(table, jax.core.Tracer)
        if jnp.issubdtype(table.dtype, jnp.floating):
            # 0 * inf = NaN in the one-hot matmul would poison other rows:
            # only concretely all-finite float tables take the factored path
            if concrete and bool(np.isfinite(np.asarray(table)).all()):
                return factored(table, idx)
        elif _exact_in_f32(table):
            return factored(table, idx)
        elif (jnp.issubdtype(table.dtype, jnp.integer) and concrete
                and np.abs(np.asarray(table)).max() < (1 << 24)):
            return factored(table, idx)
        # factored path unavailable (traced table / values beyond f32-exact range):
        # the select-reduce below is exact in the table's own dtype and still beats
        # the serialized gather up to the 2-D break-even
        if K > SELECT_MAX_ROWS_2D:
            return jnp.take(table, idx, axis=0)
    else:
        limit = SELECT_MAX_ROWS if table.ndim == 1 else SELECT_MAX_ROWS_2D
        if K > limit or table.ndim > 2:
            return jnp.take(table, idx, axis=0)
    oh = idx[:, None] == jnp.arange(K, dtype=idx.dtype)[None, :]      # [C, K]
    if table.ndim == 1:
        return jnp.sum(jnp.where(oh, table[None, :], jnp.zeros((), table.dtype)),
                       axis=1)
    # [C, K, V] select-reduce for small trailing dims
    return jnp.sum(jnp.where(oh[:, :, None], table[None, :, :],
                             jnp.zeros((), table.dtype)), axis=1)


def _pallas_block(C: int) -> int:
    """Lane count per Pallas lookup kernel invocation; 0 if the capacity can't
    be blocked (fall back to the XLA factored form)."""
    if C >= 8192 and C % 8192 == 0:
        return 8192
    if 128 <= C < 8192 and C % 128 == 0:
        return C
    return 0


def _pallas_factored_lookup(table: jax.Array, idx: jax.Array, *,
                            interpret: bool = False) -> jax.Array:
    """Factored lookup as ONE Pallas kernel: row-select by one-hot matmul over
    ``K1 = ceil(K/128)`` coarse rows, column-select by compare+where reduce
    over ``K2 = 128`` lanes — with the ``[BLK, K2]`` rows intermediate living
    its whole life in VMEM. The XLA factored form (:func:`_factored_lookup`)
    materializes rows as a ``[C, K2]`` HBM tensor (one write + one read ≈
    2 × C × 512 B), which bounds it at ~0.3 ms for C = 1M; in-kernel the HBM
    traffic is just idx in + out out (8 B/lane). Same exactness envelope as
    the XLA form: callers must have checked the table is f32-exact.

    Selected by ``table_lookup`` when ``WF_LOOKUP_IMPL=pallas`` (or
    ``impl="pallas"``) and the geometry allows (C a multiple of 128)."""
    import jax.experimental.pallas as pl

    C, K = idx.shape[0], table.shape[0]
    BLK = _pallas_block(C)
    assert BLK, f"capacity {C} not blockable; caller must gate on _pallas_block"
    K2 = 128
    K1 = (K + K2 - 1) // K2
    t2 = jnp.pad(table, (0, K1 * K2 - K)).astype(jnp.float32).reshape(K1, K2)
    interpret = interpret or jax.default_backend() == "cpu"

    def kern(t_ref, i_ref, o_ref):
        idxb = i_ref[...]
        hi = idxb // K2
        lo = idxb - hi * K2
        ohhi = (hi[:, None] == jax.lax.broadcasted_iota(
            idxb.dtype, (BLK, K1), 1)).astype(jnp.float32)
        rows = jax.lax.dot_general(ohhi, t_ref[...],
                                   (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
        ohlo = lo[:, None] == jax.lax.broadcasted_iota(
            idxb.dtype, (BLK, K2), 1)
        o_ref[...] = jnp.sum(jnp.where(ohlo, rows, 0.0), axis=1)

    out = pl.pallas_call(
        kern,
        grid=(C // BLK,),
        in_specs=[pl.BlockSpec((K1, K2), lambda i: (0, 0)),
                  pl.BlockSpec((BLK,), lambda i: (i,))],
        out_specs=pl.BlockSpec((BLK,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((C,), jnp.float32),
        interpret=interpret,
    )(t2, idx)
    return out.astype(table.dtype)


# ------------------------------------------------------ stream-table probe

#: largest key table the fused probe kernel accepts (the [BLK, K] one-hot
#: tile is the VMEM budget: 128 lanes x 2048 keys x 4 B = 1 MB)
JOIN_PROBE_MAX_ROWS = 2048


def join_probe(table_keys: jax.Array, table_vals: jax.Array,
               probe: jax.Array, valid: jax.Array, *,
               impl: str = None, interpret: bool = False):
    """Stream-table join probe: for each probe lane, find its row in an
    unordered key table. Returns ``(vals i32/f32[C], hit bool[C])`` —
    ``vals[i] = table_vals[j]`` where ``table_keys[j] == probe[i]`` (0 on
    miss), ``hit[i]`` whether a row matched. The TPU restatement of the
    reference's per-tuple hash-map probe (the YSB campaign join walks a
    contiguous fixture, so ``table_lookup`` suffices there; a real
    stream-table join probes ARBITRARY key material — this op is the probe
    the round-5 join work left pending, and the primitive ROADMAP item 1's
    join-state table builds on).

    PRECONDITION: table keys are unique (a key table, not a multimap) —
    then each probe row matches at most once and the select-reduce is exact
    in the value dtype (a sum with a single nonzero term), so the impls are
    byte-identical for ANY dtype. Invalid lanes return (0, False).

    The ``"join_probe"`` kernel of the per-backend registry: ``xla`` =
    select-reduce over the broadcast ``[C, K]`` compare; ``pallas`` = the
    same contraction as ONE kernel, the ``[BLK, K]`` one-hot tile living in
    VMEM (the XLA form materializes it to HBM in large programs)."""
    from .registry import resolve_impl
    C, K = probe.shape[0], table_keys.shape[0]
    impl = resolve_impl("join_probe", impl=impl,
                        spec_key=f"C{C}xK{K}:{table_vals.dtype}")
    if (impl == "pallas" and K <= JOIN_PROBE_MAX_ROWS and _pallas_block(C)):
        return _join_probe_pallas(table_keys, table_vals, probe, valid,
                                  interpret=interpret)
    return _join_probe_xla(table_keys, table_vals, probe, valid)


def _join_probe_xla(table_keys, table_vals, probe, valid):
    """Reference impl: one broadcast compare + masked select-reduce."""
    oh = (probe[:, None] == table_keys[None, :]) & valid[:, None]   # [C, K]
    hit = jnp.any(oh, axis=1)
    vals = jnp.sum(jnp.where(oh, table_vals[None, :],
                             jnp.zeros((), table_vals.dtype)), axis=1)
    return vals, hit


def _join_probe_pallas(table_keys, table_vals, probe, valid, *,
                       interpret: bool = False):
    import jax.experimental.pallas as pl

    C, K = probe.shape[0], table_keys.shape[0]
    BLK = _pallas_block(C)
    assert BLK, f"capacity {C} not blockable; caller must gate on _pallas_block"
    vdt = table_vals.dtype
    interpret = interpret or jax.default_backend() != "tpu"

    def kern(tk_ref, tv_ref, p_ref, ok_ref, vals_ref, hit_ref):
        p = p_ref[...]
        ok = ok_ref[...] != 0
        oh = (p[:, None] == tk_ref[...][None, :]) & ok[:, None]  # [BLK, K]
        hit_ref[...] = jnp.any(oh, axis=1).astype(jnp.int32)
        vals_ref[...] = jnp.sum(
            jnp.where(oh, tv_ref[...][None, :], jnp.zeros((), vdt)), axis=1)

    vals, hit = pl.pallas_call(
        kern,
        grid=(C // BLK,),
        in_specs=[pl.BlockSpec((K,), lambda i: (0,)),
                  pl.BlockSpec((K,), lambda i: (0,)),
                  pl.BlockSpec((BLK,), lambda i: (i,)),
                  pl.BlockSpec((BLK,), lambda i: (i,))],
        out_specs=[pl.BlockSpec((BLK,), lambda i: (i,)),
                   pl.BlockSpec((BLK,), lambda i: (i,))],
        out_shape=[jax.ShapeDtypeStruct((C,), vdt),
                   jax.ShapeDtypeStruct((C,), jnp.int32)],
        interpret=interpret,
    )(table_keys, table_vals, probe, valid.astype(jnp.int32))
    return vals, hit != 0


# ------------------------------------- versioned, watermark-consistent table

#: key value marking an unused table slot / an impossible probe. User join
#: keys must be strictly inside (INT32_MIN, INT32_MAX) — INT32_MIN is this
#: sentinel, INT32_MAX is the upsert path's sort sentinel; any non-negative
#: key below 2^31-1 qualifies.
JOIN_KEY_SENTINEL = -(1 << 31)


def _scalar_leaves(val_spec):
    leaves = jax.tree.leaves(val_spec)
    if not leaves:
        raise ValueError("JoinTable: value spec must have at least one leaf")
    for leaf in leaves:
        if tuple(getattr(leaf, "shape", ())) != ():
            raise ValueError(
                f"JoinTable values must be pytrees of SCALAR leaves (each "
                f"probed through the registry's join_probe kernel as one "
                f"[K] column); got leaf shape {getattr(leaf, 'shape', '?')}")
    return leaves


def join_table_init(num_slots: int, pending: int, val_spec) -> dict:
    """State pytree of a **versioned, watermark-consistent join-state
    table** — the HBM key table of this module grown into the join-state
    primitive of ROADMAP item 1. Upserts are *versioned by event time*: an
    upsert (key, val, ts, id) parks in a bounded pending ring until the
    build-side watermark (max ts seen) passes ``ts + delay``, then applies
    in ``(ts, id, arrival)`` order with last-writer-wins per key — so a
    probe at watermark W reads the table state **as-of W**, deterministically
    under any batch interleave the watermark contract allows (every tuple
    with ts <= W - delay has arrived). The state is a plain pytree: it rides
    the existing checkpoint/restore + exactly-once outbox paths unchanged.

    ``val_spec``: pytree of scalar examples/ShapeDtypeStructs — the per-key
    value columns (each probed via the ``join_probe`` registry kernel)."""
    K, P = int(num_slots), int(pending)
    if K < 1 or P < 1:
        raise ValueError("join_table_init: num_slots and pending must be >= 1")
    imin = jnp.iinfo(jnp.int32).min

    def zcol(n):
        return jax.tree.map(
            lambda s: jnp.zeros((n,), getattr(s, "dtype",
                                              jnp.result_type(s))), val_spec)
    _scalar_leaves(val_spec)
    return {
        # the table proper: one row per key, latest applied version
        "key": jnp.full((K,), JOIN_KEY_SENTINEL, jnp.int32),
        "val": zcol(K),
        "ver": jnp.full((K,), imin, jnp.int32),     # version event time
        "vid": jnp.full((K,), imin, jnp.int32),     # version tuple id
        "vseq": jnp.full((K,), imin, jnp.int32),    # version arrival seq
        "used": jnp.zeros((K,), jnp.bool_),
        # pending ring: upserts not yet watermark-eligible (prefix-compacted)
        "pkey": jnp.zeros((P,), jnp.int32), "pval": zcol(P),
        "pts": jnp.zeros((P,), jnp.int32), "pid": jnp.zeros((P,), jnp.int32),
        "pseq": jnp.zeros((P,), jnp.int32),
        "pok": jnp.zeros((P,), jnp.bool_),
        "wm": jnp.asarray(imin, jnp.int32),         # build-side watermark
        "seq": jnp.asarray(0, jnp.int32),           # arrival stamp source
        "version": jnp.asarray(0, jnp.int32),       # applied upserts (gauge)
        "dropped": jnp.asarray(0, jnp.int32),       # ring/table overflow drops
    }


def join_table_upsert(state: dict, key: jax.Array, val, ts: jax.Array,
                      tid: jax.Array, ok: jax.Array, *,
                      delay: int = 0, divert: bool = False) -> dict:
    """Buffer the batch's build-side tuples and apply every upsert the
    watermark has made eligible (``ts <= wm - delay``). Fixed-shape, fully
    vectorized (no serial per-row loop): per-key last-writer-wins is ONE
    lexsort of the ring by ``(key, ts, id, arrival)`` taking each key
    group's last entry (O(P log P) — no quadratic dominance matrix), fresh
    keys claim free slots in deterministic ``(ts, id, arrival)`` order, and
    a late-but-eligible upsert can never roll a slot back below its applied
    version. Duplicate-key upserts are
    therefore last-writer-wins BY EVENT TIME (ties broken by tuple id, then
    arrival), not by scatter luck — the determinism contract the chaos
    suite pins. Overflowing the pending ring or a full table *drops* the
    upsert and counts it in ``state["dropped"]``."""
    imin = jnp.iinfo(jnp.int32).min
    big = jnp.iinfo(jnp.int32).max
    P = state["pkey"].shape[0]
    K = state["key"].shape[0]
    ok = ok.astype(jnp.bool_)
    key = key.astype(jnp.int32)
    ts = ts.astype(jnp.int32)
    tid = tid.astype(jnp.int32)

    # 1. append to the pending ring (prefix-compacted invariant: live entries
    #    occupy a prefix, so the insert cursor is the live count)
    cnt = jnp.sum(state["pok"].astype(jnp.int32))
    csum = jnp.cumsum(ok.astype(jnp.int32))
    pos = cnt + csum - 1
    keep = ok & (pos < P)
    dropped = count_drops(state["dropped"], "overflow_drops",
                          jnp.sum((ok & ~keep).astype(jnp.int32)))
    slot = jnp.where(keep, pos, P)
    arrive = state["seq"] + csum - 1
    pkey = state["pkey"].at[slot].set(key, mode="drop")
    pts = state["pts"].at[slot].set(ts, mode="drop")
    pid = state["pid"].at[slot].set(tid, mode="drop")
    pseq = state["pseq"].at[slot].set(arrive, mode="drop")
    pval = jax.tree.map(lambda t, v: t.at[slot].set(v.astype(t.dtype),
                                                    mode="drop"),
                        state["pval"], val)
    pok = state["pok"].at[slot].set(True, mode="drop")
    seq = state["seq"] + jnp.sum(ok.astype(jnp.int32))

    # 2. advance the build-side watermark
    wm = jnp.maximum(state["wm"], jnp.max(jnp.where(ok, ts, imin)))

    # 3. eligible entries + per-key last-writer winners over (ts, id, seq):
    #    ONE lexsort by (key, version) and take each key group's last entry
    #    — O(P log P), no [P, P] dominance matrix (at the operators' default
    #    pending = 2 * batch capacity a quadratic compare would materialize
    #    GiB-scale intermediates per step)
    elig = pok & (pts <= wm - int(delay))

    def lex_gt(a_ts, a_id, a_seq, b_ts, b_id, b_seq):
        """(b_ts, b_id, b_seq) strictly > (a_ts, a_id, a_seq)."""
        return ((b_ts > a_ts)
                | ((b_ts == a_ts) & (b_id > a_id))
                | ((b_ts == a_ts) & (b_id == a_id) & (b_seq > a_seq)))

    keysort = jnp.where(elig, pkey, big)        # ineligible sort to the end
    vperm = jnp.lexsort((pseq, pid, pts, keysort))
    sk_sorted = keysort[vperm]
    nxt_key = jnp.concatenate([sk_sorted[1:],
                               jnp.full((1,), big, sk_sorted.dtype)])
    # last entry of an eligible key group = that key's max (ts, id, seq);
    # works because ineligible entries (key big) sort strictly after (user
    # keys are < INT32_MAX — the sentinel contract)
    win_sorted = (sk_sorted != big) & (sk_sorted != nxt_key)
    win = jnp.zeros((P,), jnp.bool_).at[vperm].set(win_sorted)

    # 4. slot resolution: existing row wins, else the r-th fresh key (in
    #    (ts, id, seq) order) claims the r-th free slot (ascending slot index)
    used = state["used"]
    eq = (state["key"][None, :] == pkey[:, None]) & used[None, :]    # [P, K]
    has_slot = jnp.any(eq, axis=1)
    slot_old = jnp.argmax(eq, axis=1)
    need_new = win & ~has_slot
    order = jnp.lexsort((pseq, pid, jnp.where(need_new, pts, big)))
    rnk = jnp.zeros((P,), jnp.int32).at[order].set(
        jnp.arange(P, dtype=jnp.int32))
    free = ~used
    free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1               # [K]
    oh = free[None, :] & (free_rank[None, :] == rnk[:, None])        # [P, K]
    got_new = jnp.any(oh, axis=1)
    slot_new = jnp.argmax(oh, axis=1)
    lost = need_new & ~got_new
    if divert:
        # tiered table, saturated: the winning upsert is NOT lost — it is
        # diverted straight to the cold tier through the spill outbox (its
        # version triplet rides along, so cross-tier LWW stays exact); only
        # outbox exhaustion still drops, and that is counted
        S_ob = state["okey"].shape[0]
        drank = jnp.cumsum(lost.astype(jnp.int32)) - 1
        fits = lost & (state["ocnt"] + drank < S_ob)
        div_pos = jnp.where(fits, state["ocnt"] + drank, S_ob)
        div_n = jnp.sum(fits.astype(jnp.int32))
        dropped = count_drops(dropped, "overflow_drops",
                              jnp.sum((lost & ~fits).astype(jnp.int32)))
    else:
        dropped = count_drops(dropped, "overflow_drops",
                              jnp.sum(lost.astype(jnp.int32)))

    # 5. never roll back: the pending version must beat the slot's applied one
    beats = lex_gt(state["ver"][slot_old], state["vid"][slot_old],
                   state["vseq"][slot_old], pts, pid, pseq)
    write = win & jnp.where(has_slot, beats, got_new)
    widx = jnp.where(write, jnp.where(has_slot, slot_old, slot_new), K)
    out = dict(state)
    out["key"] = state["key"].at[widx].set(pkey, mode="drop")
    out["ver"] = state["ver"].at[widx].set(pts, mode="drop")
    out["vid"] = state["vid"].at[widx].set(pid, mode="drop")
    out["vseq"] = state["vseq"].at[widx].set(pseq, mode="drop")
    out["used"] = used.at[widx].set(True, mode="drop")
    out["val"] = jax.tree.map(lambda t, v: t.at[widx].set(v, mode="drop"),
                              state["val"], pval)
    out["version"] = state["version"] + jnp.sum(write.astype(jnp.int32))
    if divert:
        out["okey"] = state["okey"].at[div_pos].set(pkey, mode="drop")
        out["oval"] = jax.tree.map(
            lambda t, v: t.at[div_pos].set(v, mode="drop"),
            state["oval"], pval)
        out["over"] = state["over"].at[div_pos].set(pts, mode="drop")
        out["ovid"] = state["ovid"].at[div_pos].set(pid, mode="drop")
        out["ovseq"] = state["ovseq"].at[div_pos].set(pseq, mode="drop")
        out["ocnt"] = state["ocnt"] + div_n
        out["spills"] = state["spills"] + div_n

    # 6. every eligible entry leaves the ring; recompact survivors (stable)
    pok2 = pok & ~elig
    order2 = jnp.argsort(jnp.where(pok2, 0, 1), stable=True)
    take = lambda a: jnp.take(a, order2, axis=0)
    out["pkey"], out["pts"], out["pid"], out["pseq"] = (
        take(pkey), take(pts), take(pid), take(pseq))
    out["pval"] = jax.tree.map(take, pval)
    out["pok"] = take(pok2)
    out["wm"], out["seq"], out["dropped"] = wm, seq, dropped
    return out


def join_table_probe(state: dict, key: jax.Array, ok: jax.Array, *,
                     impl: str = None):
    """Probe the applied (watermark-visible) table state: returns
    ``(vals pytree[C], hit bool[C])``. Every value column resolves through
    the kernel registry's ``join_probe`` (``xla`` select-reduce reference /
    fused Pallas one-hot — byte-identical under the unique-key invariant the
    table maintains by construction). Oversize tables (``K >`` the Pallas
    ``JOIN_PROBE_MAX_ROWS`` envelope) route to the XLA reference *inside*
    :func:`join_probe` — selection is an optimization, never an error."""
    tk = jnp.where(state["used"], state["key"], JOIN_KEY_SENTINEL)
    key = key.astype(jnp.int32)
    leaves, treedef = jax.tree.flatten(state["val"])
    if len(leaves) == 1:
        v, hit = join_probe(tk, leaves[0], key, ok, impl=impl)
        return jax.tree.unflatten(treedef, [v]), hit
    # multi-column values: run the [C, K] contraction ONCE, probing for the
    # slot index, then gather every column — same registry-resolved kernel,
    # one probe regardless of column count (a gather per column beats a
    # full contraction per column)
    K = tk.shape[0]
    slot, hit = join_probe(tk, jnp.arange(K, dtype=jnp.int32), key, ok,
                           impl=impl)
    vals = [jnp.where(hit, jnp.take(tv, slot, axis=0),
                      jnp.zeros((), tv.dtype)) for tv in leaves]
    return jax.tree.unflatten(treedef, vals), hit


def join_table_pending(state: dict) -> jax.Array:
    """Live pending-ring entries (traced scalar) — upserts parked behind the
    watermark."""
    return jnp.sum(state["pok"].astype(jnp.int32))


def join_table_stats(state: dict) -> dict:
    """Host-side health snapshot of one JoinTable state (event-time
    observability, snapshot-time only — a few small D2H reads, never on the
    hot path): build watermark, applied version, table occupancy, pending-
    ring depth, and overflow drops.  The numbers behind the ``event_time``
    sections of ``StreamTableJoin``/``Distinct`` snapshot rows and the
    ``wf_state.py`` state-pressure report."""
    import numpy as np
    K = int(state["key"].shape[0])
    P = int(state["pkey"].shape[0])
    used = int(np.asarray(state["used"]).sum())
    pending = int(np.asarray(state["pok"]).sum())
    return {
        "watermark_ts": int(np.asarray(state["wm"])),
        "applied_version": int(np.asarray(state["version"])),
        "table_slots": K,
        "table_used": used,
        "occupancy_pct": round(100.0 * used / K, 2),
        "pending_depth": pending,
        "pending_capacity": P,
        "overflow_drops": int(np.asarray(state["dropped"])),
    }


# ---------------------------------------------------- tiered state hooks

def count_drops(counter: jax.Array, name: str, n) -> jax.Array:
    """THE shared drop-accounting helper: every stateful operator's drop
    path (JoinTable ``overflow_drops``, IntervalJoin ``arch_drops``/
    ``match_drops``, session/TopN overflow + OLD drops, and the tiered
    admission-overflow paths) adds through here, so tiered and untiered
    counters can never fork names — ``name`` is validated against the
    ``observability/names.py::STAGE_COUNTERS`` registry at TRACE time (a
    typo'd counter fails the first compile, not a dashboard)."""
    from ..observability.names import STAGE_COUNTERS
    if name not in STAGE_COUNTERS:
        raise ValueError(
            f"count_drops: {name!r} is not registered in observability/"
            f"names.py::STAGE_COUNTERS — register it there (the emission "
            f"registries the linter gates)")
    return counter + n


def join_table_tier_init(state: dict, outbox: int, val_spec) -> dict:
    """Grow a :func:`join_table_init` state with the tiered-state fields:
    per-key last-access positions (``lap``/``tick`` — the PositionBucket
    convention: batch positions, never wall clock), the bounded spill
    outbox (``okey``/``oval``/``over``/``ovid``/``ovseq``/``ocnt``), and
    the device-side movement counters. Only ever called with ``tiered=``
    on — the OFF state pytree (and therefore every compiled program and
    checkpoint layout) is byte-for-byte unchanged."""
    imin = jnp.iinfo(jnp.int32).min
    K = state["key"].shape[0]
    S = int(outbox)
    if S < 1:
        raise ValueError("join_table_tier_init: outbox must be >= 1")

    def zcol(n):
        return jax.tree.map(
            lambda s: jnp.zeros((n,), getattr(s, "dtype",
                                              jnp.result_type(s))), val_spec)
    out = dict(state)
    out["lap"] = jnp.zeros((K,), jnp.int32)
    out["tick"] = jnp.asarray(0, jnp.int32)
    out["okey"] = jnp.full((S,), JOIN_KEY_SENTINEL, jnp.int32)
    out["oval"] = zcol(S)
    out["over"] = jnp.full((S,), imin, jnp.int32)
    out["ovid"] = jnp.full((S,), imin, jnp.int32)
    out["ovseq"] = jnp.full((S,), imin, jnp.int32)
    out["ocnt"] = jnp.asarray(0, jnp.int32)
    out["spills"] = jnp.asarray(0, jnp.int32)
    out["readmits"] = jnp.asarray(0, jnp.int32)
    return out


def _outbox_find(state: dict, keys: jax.Array, need: jax.Array):
    """Newest spill-outbox entry per wanted key: ``(found [R], clamped
    index [R])`` — appends are chronological, so max index = newest."""
    S = state["okey"].shape[0]
    olive = jnp.arange(S, dtype=jnp.int32) < state["ocnt"]
    eq = (keys[:, None] == state["okey"][None, :]) & olive[None, :]
    oidx = jnp.max(jnp.where(eq, jnp.arange(S, dtype=jnp.int32)[None, :],
                             -1), axis=1)
    return need & (oidx >= 0), jnp.maximum(oidx, 0)


def join_table_tier_fallback(state: dict, keys: jax.Array,
                             miss: jax.Array) -> tuple:
    """Post-upsert read fallback: a probe lane that still misses the hot
    table reads the NEWEST outbox entry of its key (covers upserts the
    saturated table diverted cold THIS batch, plus evicted rows whose
    spill has not settled) — the last link making probe results
    independent of tier placement. Returns ``(vals [R] pytree, hit [R])``."""
    keys = keys.astype(jnp.int32)
    hit, idx = _outbox_find(state, keys, miss.astype(jnp.bool_)
                            & (keys != JOIN_KEY_SENTINEL))
    vals = jax.tree.map(lambda leaf: jnp.take(leaf, idx, axis=0),
                        state["oval"])
    return vals, hit


def join_table_tier_resolve(state: dict, keys: jax.Array, ok: jax.Array,
                            lookup_cb) -> tuple:
    """The miss -> readmit round of a tiered table, INSIDE the compiled
    program so probe results are independent of tier placement: every
    wanted key missing from the hot table is searched in the spill outbox
    (newest entry wins — entries still in flight to the host store live
    here, which is what makes the async spill lossless), then in the host
    store through ONE ordered ``io_callback`` (``lookup_cb``), and found
    rows are re-admitted through the deterministic fresh-slot discipline
    the JoinTable already uses (the r-th readmitted key claims the r-th
    free slot). Hot hits are touched (``lap = tick``).

    Returns ``(state, fb_vals, fb_ok)`` — per-lane fallback values for the
    oversubscription corner where a row's value is known but no hot slot
    was free (the caller patches probe misses with them, so even a
    saturated hot table never *mis-reads*; only upserts can drop, and
    those are counted)."""
    from jax.experimental import io_callback
    from .segment import segment_rank
    R = keys.shape[0]
    K = state["key"].shape[0]
    S = state["okey"].shape[0]
    keys = keys.astype(jnp.int32)
    ok = ok.astype(jnp.bool_) & (keys != JOIN_KEY_SENTINEL)
    tick = state["tick"]
    leaves, treedef = jax.tree.flatten(state["val"])

    # hot-table search + last-access touch for every present key
    tk = jnp.where(state["used"], state["key"], JOIN_KEY_SENTINEL)
    eq = keys[:, None] == tk[None, :]                       # [R, K]
    in_tab = jnp.any(eq, axis=1) & ok
    slot_tab = jnp.argmax(eq, axis=1)
    lap = state["lap"].at[
        jnp.where(in_tab, slot_tab, K)].set(tick, mode="drop")
    need = ok & ~in_tab
    # spill-outbox search: the NEWEST entry of a key wins (a key evicted,
    # readmitted, and evicted again within one un-drained window has two
    # outbox entries; appends are chronological, so max index = newest)
    in_ob, oidxc = _outbox_find(state, keys, need)
    ob_leaves = [jnp.take(leaf, oidxc, axis=0)
                 for leaf in jax.tree.leaves(state["oval"])]
    ob_m = (jnp.take(state["over"], oidxc), jnp.take(state["ovid"], oidxc),
            jnp.take(state["ovseq"], oidxc))
    # cold-tier lookup: ONE ordered host callback for the still-missing
    # keys (ordered => scan-fused dispatch and supervised replay walk the
    # identical sequence; an all-False mask is a host no-op, so warm()'s
    # functional dry-runs never touch the store). Duplicate lanes look up
    # independently (same row) — only ADMISSION dedups.
    need_host = need & ~in_ob
    shapes = ([jax.ShapeDtypeStruct((R,), jnp.bool_)]
              + [jax.ShapeDtypeStruct((R,), jnp.int32)] * 3
              + [jax.ShapeDtypeStruct((R,), leaf.dtype) for leaf in leaves])
    res = io_callback(lookup_cb, shapes, keys, need_host, ordered=True)
    found = res[0] & need_host
    hm = res[1:4]
    h_leaves = list(res[4:])
    # merge the two cold sources (outbox beats host: outbox entries are
    # chronologically newer than everything already applied to the store)
    fb_ok = in_ob | found
    mrg = lambda o, h: jnp.where(in_ob, o, h)
    adm_leaves = [jnp.where(in_ob, o, h).astype(o.dtype)
                  for o, h in zip(ob_leaves, h_leaves)]
    m0, m1, m2 = (mrg(ob_m[0], hm[0]), mrg(ob_m[1], hm[1]),
                  mrg(ob_m[2], hm[2]))
    # deterministic fresh-slot re-admission (the join_table_upsert rule:
    # r-th readmitted key -> r-th free slot, ascending slot index); one
    # slot per DISTINCT key — duplicate lanes ride the first occurrence
    adm = fb_ok & (segment_rank(keys, fb_ok) == 0)
    rank = jnp.cumsum(adm.astype(jnp.int32)) - 1
    free = ~state["used"]
    free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
    oh3 = free[None, :] & (free_rank[None, :] == rank[:, None])   # [R, K]
    got = jnp.any(oh3, axis=1) & adm
    widx = jnp.where(got, jnp.argmax(oh3, axis=1), K)
    out = dict(state)
    out["key"] = state["key"].at[widx].set(keys, mode="drop")
    out["val"] = jax.tree.unflatten(treedef, [
        t.at[widx].set(v, mode="drop")
        for t, v in zip(jax.tree.leaves(state["val"]), adm_leaves)])
    out["ver"] = state["ver"].at[widx].set(m0, mode="drop")
    out["vid"] = state["vid"].at[widx].set(m1, mode="drop")
    out["vseq"] = state["vseq"].at[widx].set(m2, mode="drop")
    out["used"] = state["used"].at[widx].set(True, mode="drop")
    out["lap"] = lap.at[widx].set(tick, mode="drop")
    out["readmits"] = state["readmits"] + jnp.sum(got.astype(jnp.int32))
    fb_vals = jax.tree.unflatten(treedef, adm_leaves)
    return out, fb_vals, fb_ok


def join_table_tier_touch(state: dict, keys: jax.Array,
                          ok: jax.Array) -> dict:
    """Refresh last-access positions for a batch's keys AFTER the upsert
    applied (fresh upserts claimed new slots the resolve pass could not
    see) — one compare + scatter, the access half of the eviction policy."""
    K = state["key"].shape[0]
    keys = keys.astype(jnp.int32)
    tk = jnp.where(state["used"], state["key"], JOIN_KEY_SENTINEL)
    eq = keys[:, None] == tk[None, :]
    hit = jnp.any(eq, axis=1) & ok.astype(jnp.bool_) \
        & (keys != JOIN_KEY_SENTINEL)
    idx = jnp.where(hit, jnp.argmax(eq, axis=1), K)
    out = dict(state)
    out["lap"] = state["lap"].at[idx].set(state["tick"], mode="drop")
    return out


def join_table_tier_evict(state: dict, hot_target: int) -> dict:
    """Pressure eviction — the deterministic tier-assignment policy: when
    occupancy exceeds ``hot_target``, the coldest ``used - hot_target``
    keys (ordered by last-access position, slot index breaking ties) are
    packed into the spill outbox and their slots freed, bounded by the
    outbox's free space. A pure function of (occupancy, last-access
    positions) — never wall clock — so supervised replay re-derives
    identical tier assignments. Closes the batch by advancing ``tick``."""
    imax = jnp.iinfo(jnp.int32).max
    K = state["key"].shape[0]
    S = state["okey"].shape[0]
    used = state["used"]
    used_n = jnp.sum(used.astype(jnp.int32))
    free_ob = S - state["ocnt"]
    need = jnp.clip(used_n - jnp.asarray(int(hot_target), jnp.int32),
                    0, free_ob)
    sortkey = jnp.where(used, state["lap"], imax)
    perm = jnp.lexsort((jnp.arange(K, dtype=jnp.int32), sortkey))
    r = jnp.arange(K, dtype=jnp.int32)
    sel = (r < need) & jnp.take(used, perm)
    opos = jnp.where(sel, state["ocnt"] + r, S)
    out = dict(state)
    out["okey"] = state["okey"].at[opos].set(jnp.take(state["key"], perm),
                                             mode="drop")
    out["oval"] = jax.tree.map(
        lambda t, src: t.at[opos].set(jnp.take(src, perm, axis=0),
                                      mode="drop"),
        state["oval"], state["val"])
    out["over"] = state["over"].at[opos].set(jnp.take(state["ver"], perm),
                                             mode="drop")
    out["ovid"] = state["ovid"].at[opos].set(jnp.take(state["vid"], perm),
                                             mode="drop")
    out["ovseq"] = state["ovseq"].at[opos].set(jnp.take(state["vseq"], perm),
                                               mode="drop")
    cleared = jnp.where(sel, perm, K)
    out["used"] = used.at[cleared].set(False, mode="drop")
    out["key"] = out["key"].at[cleared].set(JOIN_KEY_SENTINEL, mode="drop")
    n = jnp.sum(sel.astype(jnp.int32))
    out["ocnt"] = state["ocnt"] + n
    out["spills"] = state["spills"] + n
    out["tick"] = state["tick"] + 1
    return out


def join_table_tier_stats(state: dict) -> dict:
    """Device-side tier numbers beside :func:`join_table_stats` (snapshot
    time only): hot occupancy, outbox depth, and the spill/readmit
    movement counters carried in the state pytree."""
    import numpy as np
    K = int(state["key"].shape[0])
    S = int(state["okey"].shape[0])
    used = int(np.asarray(state["used"]).sum())
    return {
        "hot_slots": K,
        "hot_used": used,
        "hot_pct": round(100.0 * used / K, 2),
        "outbox_slots": S,
        "outbox_depth": int(np.asarray(state["ocnt"])),
        "state_spills": int(np.asarray(state["spills"])),
        "state_readmits": int(np.asarray(state["readmits"])),
    }


def _factored_lookup(table: jax.Array, idx: jax.Array) -> jax.Array:
    """Row-select by one-hot matmul over K1, column-select on the VPU over K2."""
    import math
    K = table.shape[0]
    K2 = 1 << max(1, (K - 1).bit_length() // 2)        # ~sqrt(K), power of two
    K1 = (K + K2 - 1) // K2
    pad = K1 * K2 - K
    t2 = jnp.pad(table, (0, pad)).reshape(K1, K2).astype(jnp.float32)
    hi = idx // K2
    lo = idx - hi * K2
    ohhi = (hi[:, None] == jnp.arange(K1, dtype=idx.dtype)).astype(jnp.float32)
    rows = jax.lax.dot_general(ohhi, t2, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)   # [C, K2]
    ohlo = lo[:, None] == jnp.arange(K2, dtype=idx.dtype)
    out = jnp.sum(jnp.where(ohlo, rows, 0.0), axis=1)
    return out.astype(table.dtype)


# ------------------------------------------------------------- registration

from .registry import register_kernel  # noqa: E402  (registration footer)

register_kernel("lookup", "xla", _factored_lookup, reference=True,
                backends=("xla",), default=True)
register_kernel("lookup", "pallas", _pallas_factored_lookup,
                backends=("pallas-tpu", "pallas-interpret"))
register_kernel("join_probe", "xla", _join_probe_xla, reference=True,
                backends=("xla",), default=True)
register_kernel("join_probe", "pallas", _join_probe_pallas,
                backends=("pallas-tpu", "pallas-interpret"))
