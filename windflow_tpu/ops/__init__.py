# Importing the package wires every kernel module's registration footer into
# the per-backend registry — resolve_impl() must see the full impl table no
# matter which op a caller reaches first.
from . import bitonic, compaction, histogram, lookup, registry, segment  # noqa: F401
