from . import compaction, segment  # noqa: F401
