"""Segmented (per-key) reductions and scans over micro-batches.

This is the device-side replacement for the reference's KEYBY routing
(``wf/standard_emitter.hpp:85-110``: hash(key) -> replica queue): instead of scattering
tuples to per-key threads, a whole batch stays on device and per-key semantics are
recovered with segment operations. The reference's own GPU scattering study found
sort-by-key the winning strategy at high fan-out
(``src/GPU_Tests/scattering/results_scattering.org``) — which is exactly the plan here.

TPU cost discipline (docs/ARCHITECTURE.md §5): permutation gathers cost ~5.6 ns/elem,
so sorting carries companion arrays through multi-operand ``lax.sort`` (one fused sort,
no ``take(order)``), per-key bases come from scatter-min first-occurrence + small-table
lookups, and results return to stream order with a single scatter.

All functions are mask-aware: invalid lanes contribute the combine identity.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp


def _bmask(valid, v):
    """Broadcast a [C] mask against a [C, ...] value."""
    return valid.reshape(valid.shape + (1,) * (v.ndim - 1))


def _sort_by_key(keys, valid, arrays):
    """Stable multi-operand sort by (invalid, key): returns
    (sorted_key_or_max, original_index, sorted arrays...). One fused sort — the
    companion arrays ride along instead of being permutation-gathered afterwards."""
    big = jnp.iinfo(keys.dtype).max
    sort_key = jnp.where(valid, keys, big)
    iota = jnp.arange(keys.shape[0], dtype=jnp.int32)
    flat, treedef = jax.tree.flatten(arrays)
    rides = [l for l in flat if l.ndim == 1]       # lax.sort needs equal shapes
    out = jax.lax.sort((sort_key, iota, *rides), num_keys=1, is_stable=True)
    sorted_keys, orig_idx = out[0], out[1]
    it = iter(out[2:])
    sorted_flat = [next(it) if l.ndim == 1 else jnp.take(l, orig_idx, axis=0)
                   for l in flat]
    return sorted_keys, orig_idx, jax.tree.unflatten(treedef, sorted_flat)


def segment_rank(keys: jax.Array, valid: jax.Array) -> jax.Array:
    """Rank of each live lane among live lanes with the same key (0-based), in stream
    order. Sort-pairs + first-occurrence subtraction; one sort, one scatter-min, one
    small-table lookup, one scatter back to stream order."""
    c = keys.shape[0]
    # rank only needs segment grouping: sort (key, index) pairs, segment starts from
    # boundaries, propagate the start index with a cummax, subtract
    sorted_keys, orig_idx, _ = _sort_by_key(keys, valid, ())
    iota = jnp.arange(c, dtype=jnp.int32)
    starts = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                              sorted_keys[1:] != sorted_keys[:-1]])
    seg_start_idx = jax.lax.cummax(jnp.where(starts, iota, 0))
    rank_sorted = iota - seg_start_idx
    # back to stream order with one scatter
    return jnp.zeros((c,), jnp.int32).at[orig_idx].set(rank_sorted)


def segment_reduce(values: Any, keys: jax.Array, valid: jax.Array, num_keys: int,
                   combine: Callable = None, identity=0) -> Any:
    """Per-key reduction of a batch: returns a pytree of ``[num_keys, ...]`` arrays.

    Default combine is addition (lowered to ``segment_sum``); max/min use scatter
    fast paths; a custom associative ``combine`` uses sort + segmented scan."""
    if combine is None:
        def red(v):
            v = jnp.where(_bmask(valid, v), v, 0)
            return jax.ops.segment_sum(v, keys, num_segments=num_keys)
        return jax.tree.map(red, values)
    if combine in (jnp.maximum, jnp.minimum):
        seg = jax.ops.segment_max if combine is jnp.maximum else jax.ops.segment_min
        def red(v):
            v = jnp.where(_bmask(valid, v), v, jnp.asarray(identity, v.dtype))
            out = seg(v, keys, num_segments=num_keys)
            touched = jax.ops.segment_sum(valid.astype(jnp.int32), keys,
                                          num_segments=num_keys) > 0
            return jnp.where(_bmask(touched, out), out,
                             jnp.asarray(identity, v.dtype))
        return jax.tree.map(red, values)
    # general associative combine: sorted segmented scan, then scatter each segment's
    # last element into its key row
    scanned, seg_keys, seg_valid, _ = _sorted_segment_scan(
        values, keys, valid, combine, identity)
    nxt = jnp.concatenate([seg_keys[1:], jnp.full((1,), -1, seg_keys.dtype)])
    is_last = (seg_keys != nxt) & seg_valid
    out_idx = jnp.where(is_last, jnp.minimum(seg_keys, num_keys), num_keys)

    def scatter(v):
        shape = (num_keys + 1,) + v.shape[1:]
        init = jnp.broadcast_to(jnp.asarray(identity, v.dtype), shape)
        return init.at[out_idx].set(v, mode="drop")[:num_keys]
    return jax.tree.map(scatter, scanned)


def _sorted_segment_scan(values, keys, valid, combine, identity):
    """Multi-operand sort by key, then segmented inclusive associative scan.

    Returns (scanned values in sorted order, sorted keys, sorted valid,
    original indices)."""
    seg_keys, orig_idx, sv = _sort_by_key(keys, valid, values)
    big = jnp.iinfo(keys.dtype).max
    seg_valid = seg_keys != big
    sv = jax.tree.map(lambda v: jnp.where(_bmask(seg_valid, v), v,
                                          jnp.asarray(identity, v.dtype)), sv)
    starts = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                              seg_keys[1:] != seg_keys[:-1]])

    def seg_combine(a, b):
        a_f, a_v = a
        b_f, b_v = b
        v = jax.tree.map(
            lambda x, y: jnp.where(_bmask(b_f, y), y, combine(x, y)), a_v, b_v)
        return (a_f | b_f, v)

    _, scanned = jax.lax.associative_scan(seg_combine, (starts, sv), axis=0)
    return scanned, seg_keys, seg_valid, orig_idx


def segment_prefix_scan(values: Any, keys: jax.Array, valid: jax.Array,
                        combine: Callable, identity=0, *, carry_in: Any = None) -> Any:
    """Per-key *inclusive* prefix scan in stream order: lane i receives the combine of
    all earlier live same-key lanes (plus an optional per-key ``carry_in`` table
    ``[num_keys, ...]``), returned in original batch positions.

    Batched counterpart of the reference Accumulator's per-key rolling reduce
    (``wf/accumulator.hpp:61``, keyMap ``:103-104``) for associative user combines.
    Addition gets a cumsum fast path (segment prefix = cumsum - segment-start base);
    general combines use the segmented ``associative_scan``."""
    from .lookup import table_lookup
    c = keys.shape[0]
    if combine in (jnp.add,):
        seg_keys, orig_idx, sv = _sort_by_key(keys, valid, values)
        big = jnp.iinfo(keys.dtype).max
        seg_valid = seg_keys != big
        iota = jnp.arange(c, dtype=jnp.int32)
        starts = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                                  seg_keys[1:] != seg_keys[:-1]])
        seg_start_idx = jax.lax.cummax(jnp.where(starts, iota, 0))

        def one(v):
            v = jnp.where(_bmask(seg_valid, v), v, jnp.asarray(identity, v.dtype))
            cs = jnp.cumsum(v, axis=0)
            base = jnp.take(cs, jnp.maximum(seg_start_idx - 1, 0), axis=0)
            base = jnp.where(_bmask(seg_start_idx > 0, base), base,
                             jnp.zeros_like(base))
            # subtract the running total up to the lane before the segment start
            pref = cs - base
            return jnp.zeros_like(pref).at[orig_idx].set(pref)
        out = jax.tree.map(one, sv)
    else:
        scanned, _, _, orig_idx = _sorted_segment_scan(
            values, keys, valid, combine, identity)
        out = jax.tree.map(
            lambda v: jnp.zeros_like(v).at[orig_idx].set(v), scanned)
    if carry_in is not None:
        # associativity: fold(carry, v1..vr) == combine(carry, fold(v1..vr))
        out = jax.tree.map(
            lambda v, t: combine(table_lookup(t, keys), v), out, carry_in)
    return out
