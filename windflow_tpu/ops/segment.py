"""Segmented (per-key) reductions and scans over micro-batches.

This is the device-side replacement for the reference's KEYBY routing
(``wf/standard_emitter.hpp:85-110``: hash(key) -> replica queue): instead of scattering
tuples to per-key threads, a whole batch stays on device and per-key semantics are
recovered with segment operations. The reference's own GPU scattering study found
sort-by-key the winning strategy at high fan-out
(``src/GPU_Tests/scattering/results_scattering.org``) — which is exactly the plan here.

All functions are mask-aware: invalid lanes contribute the combine identity.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp


def _bmask(valid, v):
    """Broadcast a [C] mask against a [C, ...] value."""
    return valid.reshape(valid.shape + (1,) * (v.ndim - 1))


def _sorted_segment_scan(values, keys, valid, combine, identity):
    """Stable sort by (invalid, key), then segmented inclusive associative scan.

    Returns (scanned values in sorted order, sort order, sorted keys, sorted valid)."""
    sort_key = jnp.where(valid, keys, jnp.iinfo(keys.dtype).max)
    order = jnp.argsort(sort_key, stable=True)
    seg_keys = jnp.take(sort_key, order)
    seg_valid = jnp.take(valid, order)
    sv = jax.tree.map(lambda v: jnp.take(v, order, axis=0), values)
    sv = jax.tree.map(lambda v: jnp.where(_bmask(seg_valid, v), v,
                                          jnp.asarray(identity, v.dtype)), sv)
    starts = jnp.concatenate([jnp.ones((1,), jnp.bool_), seg_keys[1:] != seg_keys[:-1]])

    def seg_combine(a, b):
        # flag = True once a segment boundary has been crossed in the combined range;
        # when b starts its own segment, discard a's contribution.
        a_f, a_v = a
        b_f, b_v = b
        v = jax.tree.map(
            lambda x, y: jnp.where(_bmask(b_f, y), y, combine(x, y)), a_v, b_v)
        return (a_f | b_f, v)

    _, scanned = jax.lax.associative_scan(seg_combine, (starts, sv), axis=0)
    return scanned, order, seg_keys, seg_valid


def segment_reduce(values: Any, keys: jax.Array, valid: jax.Array, num_keys: int,
                   combine: Callable = None, identity=0) -> Any:
    """Per-key reduction of a batch: returns a pytree of ``[num_keys, ...]`` arrays.

    Default combine is addition (lowered to ``segment_sum``); a custom associative
    ``combine(a, b)`` uses sort-by-key + segmented associative scan."""
    if combine is None:
        def red(v):
            v = jnp.where(_bmask(valid, v), v, 0)
            return jax.ops.segment_sum(v, keys, num_segments=num_keys)
        return jax.tree.map(red, values)
    # scatter-combine fast paths (XLA scatter-max/min — no sort)
    if combine in (jnp.maximum, jnp.minimum):
        seg = jax.ops.segment_max if combine is jnp.maximum else jax.ops.segment_min
        def red(v):
            v = jnp.where(_bmask(valid, v), v, jnp.asarray(identity, v.dtype))
            out = seg(v, keys, num_segments=num_keys)
            # untouched segments come back as the dtype's +-inf/min; reset to identity
            touched = jax.ops.segment_sum(valid.astype(jnp.int32), keys,
                                          num_segments=num_keys) > 0
            return jnp.where(_bmask(touched, out), out,
                             jnp.asarray(identity, v.dtype))
        return jax.tree.map(red, values)
    scanned, order, seg_keys, seg_valid = _sorted_segment_scan(
        values, keys, valid, combine, identity)
    # last live position of each segment: where the next sorted key differs
    nxt = jnp.concatenate([seg_keys[1:], jnp.full((1,), -1, seg_keys.dtype)])
    is_last = (seg_keys != nxt) & seg_valid
    out_idx = jnp.where(is_last, seg_keys, num_keys)  # non-lasts go to an overflow row

    def scatter(v):
        shape = (num_keys + 1,) + v.shape[1:]
        init = jnp.broadcast_to(jnp.asarray(identity, v.dtype), shape)
        return init.at[out_idx].set(v, mode="drop")[:num_keys]
    return jax.tree.map(scatter, scanned)


def segment_prefix_scan(values: Any, keys: jax.Array, valid: jax.Array,
                        combine: Callable, identity=0, *, carry_in: Any = None) -> Any:
    """Per-key *inclusive* prefix scan in stream order: lane i receives the combine of
    all earlier live same-key lanes (plus an optional per-key ``carry_in`` table
    ``[num_keys, ...]``), returned in original batch positions.

    Batched counterpart of the reference Accumulator's per-key rolling reduce
    (``wf/accumulator.hpp:61``, keyMap ``:103-104``) for associative user combines:
    stable sort-by-key (stream order preserved within key) + segmented
    ``associative_scan`` + unsort."""
    scanned, order, _, _ = _sorted_segment_scan(values, keys, valid, combine, identity)
    inv = jnp.argsort(order)
    out = jax.tree.map(lambda v: jnp.take(v, inv, axis=0), scanned)
    if carry_in is not None:
        # associativity: fold(carry, v1..vr) == combine(carry, fold(v1..vr)), so the
        # per-key carry is applied once, after the in-batch scan
        from .lookup import table_lookup
        out = jax.tree.map(
            lambda v, t: combine(table_lookup(t, keys), v), out, carry_in)
    return out


def segment_rank(keys: jax.Array, valid: jax.Array) -> jax.Array:
    """Rank of each live lane among live lanes with the same key (0-based), in stream
    order. Used to assign per-key progressive positions (archive slots, CB indices)."""
    ones = valid.astype(jnp.int32)
    incl = segment_prefix_scan(ones, keys, valid, jnp.add, 0)
    return incl - ones  # exclusive
