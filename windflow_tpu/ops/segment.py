"""Segmented (per-key) reductions and scans over micro-batches.

This is the device-side replacement for the reference's KEYBY routing
(``wf/standard_emitter.hpp:85-110``: hash(key) -> replica queue): instead of scattering
tuples to per-key threads, a whole batch stays on device and per-key semantics are
recovered with segment operations. The reference's own GPU scattering study found
sort-by-key the winning strategy at high fan-out
(``src/GPU_Tests/scattering/results_scattering.org``) — which is exactly the plan here.

TPU cost discipline (docs/ARCHITECTURE.md §5): permutation gathers cost ~5.6 ns/elem,
so sorting carries companion arrays through multi-operand ``lax.sort`` (one fused sort,
no ``take(order)``), per-key bases come from scatter-min first-occurrence + small-table
lookups, and results return to stream order with a single scatter.

All functions are mask-aware: invalid lanes contribute the combine identity.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp


def _bmask(valid, v):
    """Broadcast a [C] mask against a [C, ...] value."""
    return valid.reshape(valid.shape + (1,) * (v.ndim - 1))


# ------------------------------------------------------- fused window fold

#: lanes per grid step of the Pallas segment fold
FOLD_CHUNK = 1024
#: segment-axis tile inside the kernel (bounds the [chunk, S_TILE] one-hot)
FOLD_S_TILE = 512
#: largest segment space the fused fold accepts (beyond this the C*S one-hot
#: matmul work exceeds what the scatter path costs — and the per-chunk
#: accumulator stops paying for itself)
FOLD_MAX_SEGMENTS = 4096


def segment_fold(values: jax.Array, seg: jax.Array, valid: jax.Array,
                 num_segments: int, *, impl: str = None,
                 interpret: bool = False) -> jax.Array:
    """Masked 1-D segment sum ``out[s] = sum(values[i] : seg[i]==s, valid[i])``
    — the Win_SeqFFAT pane-fold primitive (``operators/win_seqffat.py``
    ``_insert``/``_g_insert`` reduce every batch into its ``[K*P]`` pane
    partials through this op via :func:`segment_reduce`).

    The ``"segment_fold"`` kernel of the per-backend registry:

    - ``xla`` (reference): ``jax.ops.segment_sum`` — XLA lowers the scatter
      to a serialized per-update loop (~18 ns/update measured on v5e, the
      same pathology ``ops/histogram.py`` documents for the count path).
    - ``pallas``: the fold as one-hot matmuls on the MXU — one kernel owns
      the whole ``[C] -> [S]`` accumulation, the per-chunk one-hot and the
      running ``[S]`` partial living in VMEM throughout (one grid step per
      :data:`FOLD_CHUNK` lanes; TPU grids run sequentially so read-modify-
      write accumulation across steps is sound).

    Exactness: the Pallas path takes INTEGER values (itemsize <= 4) and is
    byte-identical to ``segment_sum`` for the FULL int32 domain — each value
    is split into 11-bit limbs so every per-chunk one-hot matmul sums are
    f32-exact, and limbs recombine/accumulate with wrapping int32 adds (the
    same two's-complement semantics XLA's integer segment_sum has on
    overflow). Floats route to the XLA reference inside the same call —
    selection is an optimization, never a semantics change. Invalid lanes
    contribute 0; out-of-range segment ids are dropped (both impls)."""
    from .registry import resolve_impl
    C, S = values.shape[0], int(num_segments)
    impl = resolve_impl("segment_fold", impl=impl,
                        spec_key=f"C{C}xS{S}:{values.dtype}")
    if (impl == "pallas" and jnp.issubdtype(values.dtype, jnp.integer)
            and jnp.dtype(values.dtype).itemsize <= 4
            and C % FOLD_CHUNK == 0 and C >= FOLD_CHUNK
            and S <= FOLD_MAX_SEGMENTS):
        return _pallas_segment_fold(values, seg, valid, S,
                                    interpret=interpret)
    return _xla_segment_fold(values, seg, valid, S)


def _xla_segment_fold(values, seg, valid, S):
    """Reference impl: masked ``segment_sum`` (the pre-registry formulation
    of ``segment_reduce``'s default path, verbatim)."""
    v = jnp.where(valid, values, 0)
    return jax.ops.segment_sum(v, seg, num_segments=S)


def _pallas_segment_fold(values, seg, valid, S, *, interpret: bool = False):
    """One kernel: per chunk, one-hot ``[chunk, S_tile]`` f32 tiles contract
    against the masked values on the MXU and accumulate into the resident
    ``[8, S_pad]`` i32 output block (8 sublanes — 7 dead rows, the Mosaic
    1-D-output workaround of ``ops/pallas_kernels.py``).

    Exact for the FULL int32 domain: each masked value splits into 11-bit
    limbs ``v = l2*2^22 + l1*2^11 + l0`` (``l0``/``l1`` unsigned low bits,
    ``l2`` the arithmetic-shift top — sign rides there), so every per-chunk
    limb matmul sums at most ``2^11 * FOLD_CHUNK = 2^21 < 2^24`` and stays
    f32-exact. Limbs recombine and accumulate across chunks with WRAPPING
    int32 adds — two's-complement mod-2^32 arithmetic is associative, so the
    result equals XLA's integer ``segment_sum`` bit-for-bit, including on
    overflow and after the final cast to a narrower input dtype."""
    import jax.experimental.pallas as pl

    C = values.shape[0]
    dtype = values.dtype
    S_pad = -(-S // FOLD_S_TILE) * FOLD_S_TILE
    R = C // FOLD_CHUNK
    interpret = interpret or jax.default_backend() != "tpu"

    def kern(v_ref, s_ref, ok_ref, out_ref):
        r = pl.program_id(0)

        @pl.when(r == 0)
        def _zero():
            out_ref[...] = jnp.zeros_like(out_ref)

        sg = s_ref[...]
        ok = ok_ref[...] != 0
        vi = jnp.where(ok, v_ref[...].astype(jnp.int32), 0)
        limbs = [(vi & 0x7FF).astype(jnp.float32),
                 ((vi >> 11) & 0x7FF).astype(jnp.float32),
                 (vi >> 22).astype(jnp.float32)]
        for s0 in range(0, S_pad, FOLD_S_TILE):
            oh = (((sg[:, None] - s0) == jax.lax.broadcasted_iota(
                sg.dtype, (FOLD_CHUNK, FOLD_S_TILE), 1)) &
                  ok[:, None]).astype(jnp.float32)
            p0, p1, p2 = (jax.lax.dot_general(
                l[None, :], oh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(jnp.int32)
                for l in limbs)                            # [1, S_TILE] each
            part = p0 + (p1 << 11) + (p2 << 22)            # wrapping i32
            out_ref[:, s0:s0 + FOLD_S_TILE] += jnp.broadcast_to(
                part, (8, FOLD_S_TILE))

    out = pl.pallas_call(
        kern,
        grid=(R,),
        in_specs=[pl.BlockSpec((FOLD_CHUNK,), lambda r: (r,)),
                  pl.BlockSpec((FOLD_CHUNK,), lambda r: (r,)),
                  pl.BlockSpec((FOLD_CHUNK,), lambda r: (r,))],
        out_specs=pl.BlockSpec((8, S_pad), lambda r: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, S_pad), jnp.int32),
        interpret=interpret,
    )(values, seg, valid.astype(jnp.int32))
    return out[0, :S].astype(dtype)


def _sort_by_key(keys, valid, arrays):
    """Stable multi-operand sort by (invalid, key): returns
    (sorted_key_or_max, original_index, sorted arrays...). One fused sort — the
    companion arrays ride along instead of being permutation-gathered afterwards."""
    big = jnp.iinfo(keys.dtype).max
    sort_key = jnp.where(valid, keys, big)
    iota = jnp.arange(keys.shape[0], dtype=jnp.int32)
    flat, treedef = jax.tree.flatten(arrays)
    rides = [l for l in flat if l.ndim == 1]       # lax.sort needs equal shapes
    out = jax.lax.sort((sort_key, iota, *rides), num_keys=1, is_stable=True)
    sorted_keys, orig_idx = out[0], out[1]
    it = iter(out[2:])
    sorted_flat = [next(it) if l.ndim == 1 else jnp.take(l, orig_idx, axis=0)
                   for l in flat]
    return sorted_keys, orig_idx, jax.tree.unflatten(treedef, sorted_flat)


def segment_rank(keys: jax.Array, valid: jax.Array) -> jax.Array:
    """Rank of each live lane among live lanes with the same key (0-based), in stream
    order. Sort-pairs + first-occurrence subtraction; one sort, one scatter-min, one
    small-table lookup, one scatter back to stream order."""
    c = keys.shape[0]
    # rank only needs segment grouping: sort (key, index) pairs, segment starts from
    # boundaries, propagate the start index with a cummax, subtract
    sorted_keys, orig_idx, _ = _sort_by_key(keys, valid, ())
    iota = jnp.arange(c, dtype=jnp.int32)
    starts = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                              sorted_keys[1:] != sorted_keys[:-1]])
    seg_start_idx = jax.lax.cummax(jnp.where(starts, iota, 0))
    rank_sorted = iota - seg_start_idx
    # back to stream order with one scatter
    return jnp.zeros((c,), jnp.int32).at[orig_idx].set(rank_sorted)


def segment_reduce(values: Any, keys: jax.Array, valid: jax.Array, num_keys: int,
                   combine: Callable = None, identity=0) -> Any:
    """Per-key reduction of a batch: returns a pytree of ``[num_keys, ...]`` arrays.

    Default combine is addition (lowered to ``segment_sum``); max/min use scatter
    fast paths; a custom associative ``combine`` uses sort + segmented scan."""
    if combine is None:
        def red(v):
            if v.ndim == 1:
                # the Win_SeqFFAT fold path: registry-selectable impl
                # (xla segment_sum / fused Pallas one-hot matmul)
                return segment_fold(v, keys, valid, num_keys)
            v = jnp.where(_bmask(valid, v), v, 0)
            return jax.ops.segment_sum(v, keys, num_segments=num_keys)
        return jax.tree.map(red, values)
    if combine in (jnp.maximum, jnp.minimum):
        seg = jax.ops.segment_max if combine is jnp.maximum else jax.ops.segment_min
        def red(v):
            v = jnp.where(_bmask(valid, v), v, jnp.asarray(identity, v.dtype))
            out = seg(v, keys, num_segments=num_keys)
            touched = jax.ops.segment_sum(valid.astype(jnp.int32), keys,
                                          num_segments=num_keys) > 0
            return jnp.where(_bmask(touched, out), out,
                             jnp.asarray(identity, v.dtype))
        return jax.tree.map(red, values)
    # general associative combine: sorted segmented scan, then scatter each segment's
    # last element into its key row
    scanned, seg_keys, seg_valid, _ = _sorted_segment_scan(
        values, keys, valid, combine, identity)
    nxt = jnp.concatenate([seg_keys[1:], jnp.full((1,), -1, seg_keys.dtype)])
    is_last = (seg_keys != nxt) & seg_valid
    out_idx = jnp.where(is_last, jnp.minimum(seg_keys, num_keys), num_keys)

    def scatter(v):
        shape = (num_keys + 1,) + v.shape[1:]
        init = jnp.broadcast_to(jnp.asarray(identity, v.dtype), shape)
        return init.at[out_idx].set(v, mode="drop")[:num_keys]
    return jax.tree.map(scatter, scanned)


def _sorted_segment_scan(values, keys, valid, combine, identity):
    """Multi-operand sort by key, then segmented inclusive associative scan.

    Returns (scanned values in sorted order, sorted keys, sorted valid,
    original indices)."""
    seg_keys, orig_idx, sv = _sort_by_key(keys, valid, values)
    big = jnp.iinfo(keys.dtype).max
    seg_valid = seg_keys != big
    sv = jax.tree.map(lambda v: jnp.where(_bmask(seg_valid, v), v,
                                          jnp.asarray(identity, v.dtype)), sv)
    starts = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                              seg_keys[1:] != seg_keys[:-1]])

    def seg_combine(a, b):
        a_f, a_v = a
        b_f, b_v = b
        v = jax.tree.map(
            lambda x, y: jnp.where(_bmask(b_f, y), y, combine(x, y)), a_v, b_v)
        return (a_f | b_f, v)

    _, scanned = jax.lax.associative_scan(seg_combine, (starts, sv), axis=0)
    return scanned, seg_keys, seg_valid, orig_idx


def segment_prefix_scan(values: Any, keys: jax.Array, valid: jax.Array,
                        combine: Callable, identity=0, *, carry_in: Any = None) -> Any:
    """Per-key *inclusive* prefix scan in stream order: lane i receives the combine of
    all earlier live same-key lanes (plus an optional per-key ``carry_in`` table
    ``[num_keys, ...]``), returned in original batch positions.

    Batched counterpart of the reference Accumulator's per-key rolling reduce
    (``wf/accumulator.hpp:61``, keyMap ``:103-104``) for associative user combines.
    Addition gets a cumsum fast path (segment prefix = cumsum - segment-start base);
    general combines use the segmented ``associative_scan``."""
    from .lookup import table_lookup
    c = keys.shape[0]
    if combine in (jnp.add,):
        seg_keys, orig_idx, sv = _sort_by_key(keys, valid, values)
        big = jnp.iinfo(keys.dtype).max
        seg_valid = seg_keys != big
        iota = jnp.arange(c, dtype=jnp.int32)
        starts = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                                  seg_keys[1:] != seg_keys[:-1]])
        seg_start_idx = jax.lax.cummax(jnp.where(starts, iota, 0))

        def one(v):
            v = jnp.where(_bmask(seg_valid, v), v, jnp.asarray(identity, v.dtype))
            cs = jnp.cumsum(v, axis=0)
            base = jnp.take(cs, jnp.maximum(seg_start_idx - 1, 0), axis=0)
            base = jnp.where(_bmask(seg_start_idx > 0, base), base,
                             jnp.zeros_like(base))
            # subtract the running total up to the lane before the segment start
            pref = cs - base
            return jnp.zeros_like(pref).at[orig_idx].set(pref)
        out = jax.tree.map(one, sv)
    else:
        scanned, _, _, orig_idx = _sorted_segment_scan(
            values, keys, valid, combine, identity)
        out = jax.tree.map(
            lambda v: jnp.zeros_like(v).at[orig_idx].set(v), scanned)
    if carry_in is not None:
        # associativity: fold(carry, v1..vr) == combine(carry, fold(v1..vr))
        out = jax.tree.map(
            lambda v, t: combine(table_lookup(t, keys), v), out, carry_in)
    return out


# ------------------------------------------------------------- registration

from .registry import register_kernel  # noqa: E402  (registration footer)

register_kernel("segment_fold", "xla", _xla_segment_fold, reference=True,
                backends=("xla",), default=True)
register_kernel("segment_fold", "pallas", _pallas_segment_fold,
                backends=("pallas-tpu", "pallas-interpret"))
