"""Pallas TPU kernels for dense hot ops.

Scope note (measured; see docs/ARCHITECTURE.md §5): the framework's irregular ops —
scatter-add pane folds, dynamic gathers — are NOT expressible efficiently in Mosaic
(dynamic VMEM indexing must be provably tile-aligned; a random-index store fails with
"cannot statically prove that index ... is a multiple of 1024"), and XLA's scatter
emitter is the fastest available path. Pallas is used where its tiling model fits:
dense batched reductions over the fired-window axis — the compute inside the
reference GPU engine's ``ComputeBatch_Kernel`` (one thread per window,
``wf/win_seq_gpu.hpp:57-82``), here one *tile row* per window.

``masked_window_reduce``: given window contents ``[W, L]`` + occupancy mask, produce
per-window sums — the hot aggregation of Win_Seq non-incremental sum windows. Falls
back to the XLA formulation off-TPU (and under ``interpret=True`` in tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    HAVE_PALLAS = True
except Exception:                                     # pragma: no cover
    HAVE_PALLAS = False

#: row-tile height per grid step (W axis); L is processed whole per row-tile.
ROW_TILE = 256


def _reduce_kernel(vals_ref, mask_ref, out_ref):
    v = vals_ref[...]
    m = mask_ref[...]
    out_ref[...] = jnp.sum(jnp.where(m, v, jnp.zeros_like(v)), axis=1)


def _xla_masked_sum(vals, mask):
    return jnp.sum(jnp.where(mask, vals, jnp.zeros_like(vals)), axis=1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def masked_window_reduce(vals: jax.Array, mask: jax.Array, *,
                         interpret: bool = False) -> jax.Array:
    """Per-window masked sum of ``vals [W, L]`` under ``mask [W, L]`` -> ``[W]``."""
    W, L = vals.shape
    if not HAVE_PALLAS or W % ROW_TILE or L % 128:
        return _xla_masked_sum(vals, mask)
    try:
        return pl.pallas_call(
            _reduce_kernel,
            grid=(W // ROW_TILE,),
            in_specs=[pl.BlockSpec((ROW_TILE, L), lambda i: (i, 0)),
                      pl.BlockSpec((ROW_TILE, L), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((ROW_TILE,), lambda i: (i,)),
            out_shape=jax.ShapeDtypeStruct((W,), vals.dtype),
            interpret=interpret,
        )(vals, mask)
    except Exception:                                  # lowering unsupported: fall back
        return _xla_masked_sum(vals, mask)
