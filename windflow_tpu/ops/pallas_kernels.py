"""Pallas TPU kernels for dense hot ops.

Scope note (measured; see docs/ARCHITECTURE.md §5): the framework's irregular ops —
scatter-add pane folds, dynamic gathers — are NOT expressible efficiently in Mosaic
(dynamic VMEM indexing must be provably tile-aligned; a random-index store fails with
"cannot statically prove that index ... is a multiple of 1024"), and XLA's scatter
emitter is the fastest available path. Pallas is used where its tiling model fits:
dense batched reductions over the fired-window axis — the compute inside the
reference GPU engine's ``ComputeBatch_Kernel`` (one thread per window,
``wf/win_seq_gpu.hpp:57-82``), here one *tile row* per window.

``masked_window_reduce``: given window contents ``[W, L]`` + occupancy mask, produce
per-window sums — the hot aggregation of Win_Seq non-incremental sum windows. Falls
back to the XLA formulation off-TPU (and under ``interpret=True`` in tests).

A/B verdict (measured on TPU v5 lite, 2026-07-30, min over 5×100 async iters):
XLA 10.1/10.9/13.3 µs vs Pallas 15.7/12.2/14.4 µs at [1024,1024]/[4096,512]/
[8192,256]. The op reads ~8-12 MB per call — it is HBM-bandwidth-bound and XLA's
fused where+reduce already runs at the roofline, so the data path keeps the XLA
formulation (``Iterable.sum``) and this kernel stands as the documented negative
result the decision rule in BASELINE.md calls for. ``bench.py::bench_pallas_ab``
re-measures every capture; adopt if a future libtpu flips the verdict.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    HAVE_PALLAS = True
except Exception:                                     # pragma: no cover
    HAVE_PALLAS = False

#: row-tile height per grid step (W axis); L is processed whole per row-tile.
ROW_TILE = 256


def _reduce_kernel(vals_ref, mask_ref, out_ref):
    v = vals_ref[...]
    m = mask_ref[...]
    s = jnp.sum(jnp.where(m, v, jnp.zeros_like(v)), axis=1, keepdims=True)
    out_ref[...] = jnp.broadcast_to(s.T, out_ref.shape)


def _xla_masked_sum(vals, mask):
    return jnp.sum(jnp.where(mask, vals, jnp.zeros_like(vals)), axis=1)


_xla_jit = jax.jit(_xla_masked_sum)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_masked_sum(vals, mask, *, interpret=False):
    # The [W] result is produced as an [8, W] lane-oriented buffer: a 1-D out
    # operand would get XLA's T(1024) linear tiling, which Mosaic's
    # (sublane, lane) block model cannot match ("XLA layout {0:T(1024)} does
    # not match Mosaic layout {0:T(256)}"), and a (1, T) block violates the
    # sublane-divisible-by-8 rule. 8 sublanes × ROW_TILE lanes satisfies both;
    # the extra 7 rows are dead writes (W*28 B — noise next to the W*L*4 read).
    W, L = vals.shape
    out = pl.pallas_call(
        _reduce_kernel,
        grid=(W // ROW_TILE,),
        in_specs=[pl.BlockSpec((ROW_TILE, L), lambda i: (i, 0)),
                  pl.BlockSpec((ROW_TILE, L), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, ROW_TILE), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((8, W), vals.dtype),
        interpret=interpret,
    )(vals, mask)
    return out[0]


#: (W, L, interpret) -> False once Mosaic refused the shape (compile errors
#: surface at first call, AFTER jit tracing — they cannot be caught inside the
#: jitted body, so the XLA fallback lives out here).
_pallas_ok: dict = {}


def masked_window_reduce(vals: jax.Array, mask: jax.Array, *,
                         interpret: bool = False) -> jax.Array:
    """Per-window masked sum of ``vals [W, L]`` under ``mask [W, L]`` -> ``[W]``."""
    W, L = vals.shape
    key = (W, L, interpret)
    if (not HAVE_PALLAS or W % ROW_TILE or L % 128
            or not _pallas_ok.get(key, True)
            # Under an enclosing trace the Mosaic compile error would surface
            # at the OUTER jit's compile, past this try/except, and the
            # trace-time success line would poison the cache — so traced calls
            # take the XLA formulation (which is also the measured winner).
            or isinstance(vals, jax.core.Tracer)):
        return _xla_jit(vals, mask)
    try:
        out = _pallas_masked_sum(vals, mask, interpret=interpret)
        _pallas_ok[key] = True
        return out
    except Exception:                                  # lowering unsupported
        _pallas_ok[key] = False
        return _xla_jit(vals, mask)
