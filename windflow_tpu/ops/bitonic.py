"""Bitonic compare-exchange networks — the Ordering_Node merge/sort kernel.

``parallel/ordering.py`` merges each incoming batch into its sorted backlog
with a bitonic merge network: ``log2(n)`` vectorized compare-exchange stages
over a 4-tuple composite key (primary, secondary, channel, unique index).
The XLA formulation (:func:`merge_network`) emits one reshape + lex-compare +
two selects per stride — ``log2(n)`` separate fusions whose intermediates
round-trip HBM between stages in a large program. The critical-path reports
of ``scripts/wf_trace.py`` name the ordering stage's service time as
merge-dominated under DETERMINISTIC modes, so this module adds the fused
restatement (:func:`merge_network_pallas`): ONE Pallas kernel owns all
stages, the four key arrays living in VMEM for the network's entire life
(n=8192: 4 arrays x 32 KB — far under the ~16 MB VMEM budget).

Also here: the full bitonic SORT network (:func:`sort_network` /
:func:`sort_network_pallas`) for ``_sort_batch``'s unsorted-batch branch —
stages ``k = 2, 4, .., n`` of the same compare-exchange butterfly. Because
the composite key always ends in a UNIQUE index lane (``idx``), the order is
total: the network's output is exactly the stable ``jnp.lexsort``
permutation, so the impls are interchangeable byte-for-byte (the parity
property tier-1 asserts in interpret mode).

Registered with the kernel registry as ``"ordering_merge"`` (impls ``xla`` /
``pallas``); ``Ordering_Node`` resolves the impl once at construction — the
jitted cores are cached per (mode, impl), so selection is trace-time like
every other kernel toggle (WF109 catches stale executables).

Exactness: all four lanes are i32 and every op is a compare/select —
bit-exact in any mode, no accumulation-order concerns.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

#: largest network the fused kernel accepts (4 i32 arrays + pair views must
#: fit VMEM with headroom; 1<<15 lanes = 512 KB of key state)
PALLAS_MAX_LANES = 1 << 15


def _lex_lt(a: Tuple, b: Tuple):
    """Strict lexicographic < over equal-length tuples of i32 arrays (the
    ordering composite-key compare, shared by both impls)."""
    out = None
    eq = None
    for x, y in zip(a, b):
        term = (x < y) if eq is None else (eq & (x < y))
        out = term if out is None else (out | term)
        eq = (x == y) if eq is None else (eq & (x == y))
    return out


def _butterfly(arrs, d: int, ascending=None):
    """One stride-``d`` butterfly: pair i with i^d via the [n/(2d), 2, d]
    reshape (positions differing exactly in bit d are CONTIGUOUS under it —
    element [b, s, m] is lane b*2d + s*d + m — so the exchange is slicing +
    elementwise selects, no gather). ``ascending``: None = every pair sorts
    ascending (merge), else a [n/(2d), d] bool direction mask."""
    n = arrs[0].shape[0]
    rs = [a.reshape(n // (2 * d), 2, d) for a in arrs]
    lt = _lex_lt(tuple(r[:, 0] for r in rs), tuple(r[:, 1] for r in rs))
    lo_takes_0 = lt if ascending is None else jnp.where(ascending, lt, ~lt)

    def sel(r):
        lo = jnp.where(lo_takes_0, r[:, 0], r[:, 1])
        hi = jnp.where(lo_takes_0, r[:, 1], r[:, 0])
        return jnp.stack([lo, hi], axis=1).reshape(n)
    return [sel(r) for r in rs]


def _merge_stages(prim, sec, chan, idx):
    """The merge network body (bitonic input -> ascending): shared verbatim
    by the XLA form and the Pallas kernel so the two cannot drift."""
    arrs = [prim, sec, chan, idx]
    n = prim.shape[0]
    d = n // 2
    while d >= 1:
        arrs = _butterfly(arrs, d)
        d //= 2
    return tuple(arrs)


def _sort_stages(prim, sec, chan, idx):
    """The full sort network body (arbitrary input -> ascending): stages
    ``k = 2..n``; within stage ``k`` the pair direction alternates by bit
    ``k`` of the lane index — for the [n/(2d), 2, d] pairing that bit is a
    pure function of the BLOCK index (both pair members agree on it), so the
    direction mask is one broadcast compare, no gather."""
    arrs = [prim, sec, chan, idx]
    n = prim.shape[0]
    k = 2
    while k <= n:
        d = k // 2
        while d >= 1:
            nb = n // (2 * d)
            # ascending iff bit k of the lane index is 0; lane = b*2d + s*d + m
            # and d <= k/2, so bit k is carried entirely by the block index b
            blk = jax.lax.broadcasted_iota(jnp.int32, (nb, d), 0)
            asc = ((blk * (2 * d)) & k) == 0
            arrs = _butterfly(arrs, d, asc)
            d //= 2
        k *= 2
    return tuple(arrs)


# ------------------------------------------------------------------ XLA form


def merge_network(prim, sec, chan, idx):
    """Merge a bitonic (ascending++descending) composite-key sequence into
    ascending order — the XLA reference impl (``log2(n)`` fused
    compare-exchange stages). ``idx`` is the unique tie-break AND the gather
    index that moves the actual rows once at the end."""
    return _merge_stages(prim, sec, chan, idx)


def sort_network(prim, sec, chan, idx):
    """Full bitonic sort of an arbitrary composite-key sequence — the XLA
    network form. Value-identical to ``jnp.lexsort((chan, sec, prim))``
    applied to all four arrays, because ``idx`` makes the key total (network
    output is THE unique ascending order, which equals the stable sort)."""
    return _sort_stages(prim, sec, chan, idx)


# --------------------------------------------------------------- Pallas form


def _pallas_network(prim, sec, chan, idx, stages_fn, interpret: bool):
    import jax.experimental.pallas as pl

    n = prim.shape[0]
    interpret = interpret or jax.default_backend() != "tpu"

    def kern(p_ref, s_ref, c_ref, i_ref, po_ref, so_ref, co_ref, io_ref):
        p, s, c, i = stages_fn(p_ref[...], s_ref[...], c_ref[...], i_ref[...])
        po_ref[...] = p
        so_ref[...] = s
        co_ref[...] = c
        io_ref[...] = i

    shape = jax.ShapeDtypeStruct((n,), prim.dtype)
    ishape = jax.ShapeDtypeStruct((n,), idx.dtype)
    return pl.pallas_call(
        kern,
        out_shape=[shape, shape, shape, ishape],
        interpret=interpret,
    )(prim, sec, chan, idx)


def merge_network_pallas(prim, sec, chan, idx, *, interpret: bool = False):
    """:func:`merge_network` as ONE fused Pallas kernel: every stage's
    intermediates stay in VMEM (the XLA form materializes 4 arrays per stage
    between fusions in a large program). Falls back to the XLA form when the
    network exceeds :data:`PALLAS_MAX_LANES` or n is not a power of two.
    ``interpret=True`` (auto off-TPU) runs the kernel on CPU — the tier-1
    parity gate."""
    n = prim.shape[0]
    if n & (n - 1) or n > PALLAS_MAX_LANES or n < 2:
        return merge_network(prim, sec, chan, idx)
    return tuple(_pallas_network(prim, sec, chan, idx, _merge_stages,
                                 interpret))


def sort_network_pallas(prim, sec, chan, idx, *, interpret: bool = False):
    """:func:`sort_network` fused into one Pallas kernel (``log2(n)^2/2``
    compare-exchange substages, zero HBM round-trips between them). Same
    fallback envelope as :func:`merge_network_pallas`."""
    n = prim.shape[0]
    if n & (n - 1) or n > PALLAS_MAX_LANES or n < 2:
        return sort_network(prim, sec, chan, idx)
    return tuple(_pallas_network(prim, sec, chan, idx, _sort_stages,
                                 interpret))


# ------------------------------------------------------------- registration

from .registry import register_kernel  # noqa: E402  (registration footer)

register_kernel("ordering_merge", "xla", merge_network, reference=True,
                backends=("xla",), default=True)
register_kernel("ordering_merge", "pallas", merge_network_pallas,
                backends=("pallas-tpu", "pallas-interpret"))
