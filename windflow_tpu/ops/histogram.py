"""Keyed-pane histograms on the MXU — the FFAT-insert hot path.

The reference's incremental window engines fold each tuple into a per-(key, pane)
partial (``wf/flatfat.hpp:134-240`` leaf update; ``wf/win_seqffat.hpp:389-396``).
The direct TPU translation is a scatter-add, but XLA lowers scatter to a serialized
per-update loop (~18 ns/update measured on v5e) — at 1M-tuple batches that is the
whole step budget.

This module computes the same ``[K, P]`` accumulation as two one-hot matmuls that run
on the MXU:

1. **Chunk-local histogram.** The batch is viewed as ``[R, chunk]`` rows. Event
   timestamps in a stream are *locally clustered*: the panes touched inside one chunk
   of consecutive lanes span a tiny range ``L`` (for a time-ordered stream,
   ``chunk/rate`` time units). Per chunk we take ``base_r = min(pane)`` and build two
   one-hots — key ``[R, chunk, K]`` and local pane ``[R, chunk, L]`` — whose batched
   contraction ``einsum('rck,rcl->rkl')`` is an MXU matmul producing per-chunk
   ``[K, L]`` histograms. 0/1 inputs with f32 accumulation are exact (sums ≤ chunk).
2. **Ring placement.** ``[R, K, L] -> [K, P]`` is one more matmul against the one-hot
   of ``(base_r + l) % P`` — column placement into the pane ring, wrap-around
   included. f32 accumulation stays exact while every count ≤ 2^24.

Batches that violate the locality bound (a chunk spanning ≥ L panes — wildly
out-of-order timestamps) are detected on device and routed through the exact
scatter-add path with ``lax.cond``: the fast path is an optimization, never a
semantics change.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

#: default lanes per chunk-local histogram row
DEFAULT_CHUNK = 1024
#: default pane-locality bound per chunk (panes spanned by one chunk)
DEFAULT_L = 8
#: key-axis tile for the chunk-local one-hot (caps transient memory at ~C*K_TILE B)
K_TILE = 512


def keyed_pane_histogram(key: jax.Array, pane: jax.Array, valid: jax.Array,
                         num_keys: int, ring: int, *,
                         chunk: int = DEFAULT_CHUNK, locality: int = DEFAULT_L,
                         impl: str = None,
                         ) -> jax.Array:
    """Count histogram ``out[k, pane % ring] = #{lanes: key==k, pane==p}``.

    ``key``: i32[C] in [0, num_keys); ``pane``: i32[C] (arbitrary, ring-mapped);
    ``valid``: bool[C]. Returns i32[num_keys, ring]. Exact for any input (locality
    violations fall back to scatter-add inside the same compiled program).

    ``impl``: "xla" (default; the inline einsum formulation below) or "pallas"
    (:func:`keyed_pane_histogram_pallas`'s kernel as the fast branch — same
    locality cond, same scatter fallback). Defaults from the per-backend
    kernel registry (``ops/registry.py``: ``WF_KERNEL_IMPL``, the deprecated
    ``WF_HISTOGRAM_IMPL`` alias, or a persisted autotuned winner) so a whole
    chain can be A/B'd without code changes.
    """
    C = key.shape[0]
    K, P = int(num_keys), int(ring)
    if C % chunk != 0 or C < chunk:
        # odd capacities: scatter path (capacities are powers of two in practice)
        return _scatter_hist(key, pane, valid, K, P)
    # Force the inputs to materialize before the one-hot tiles consume them.
    # In a fused chain `key` is often itself the result of a matmul-formulated
    # lookup (e.g. the YSB campaign join); without the barrier XLA re-fuses
    # that producer into EVERY K_TILE/locality tile of the histogram,
    # multiplying the producer's cost by the tile count (measured: the same
    # histogram is 15 us standalone vs ~5 ms fused in the YSB chain).
    # Semantics-neutral.
    key, pane, valid = jax.lax.optimization_barrier((key, pane, valid))
    R = C // chunk

    pane_r = pane.reshape(R, chunk)
    valid_r = valid.reshape(R, chunk)
    big = jnp.iinfo(pane.dtype).max
    base = jnp.min(jnp.where(valid_r, pane_r, big), axis=1)      # [R]
    base = jnp.where(base == big, 0, base)
    local = pane_r - base[:, None]                               # [R, chunk]
    ok_local = valid_r & (local < locality)

    in_bounds = jnp.all(ok_local == valid_r)

    def fast(_):
        lr = jnp.where(ok_local, local, 0)
        key_r = key.reshape(R, chunk)
        ohl = ((lr[:, :, None] == jnp.arange(locality, dtype=lr.dtype))
               & ok_local[:, :, None]).astype(jnp.bfloat16)
        # tile the key axis: bounds the transient [R, chunk, K_tile] one-hot to
        # ~C * K_TILE bytes instead of C * K (K can be thousands)
        tiles = []
        for k0 in range(0, K, K_TILE):
            kn = min(K_TILE, K - k0)
            ohk = ((key_r[:, :, None]
                    == jnp.arange(k0, k0 + kn, dtype=key.dtype))
                   & ok_local[:, :, None]).astype(jnp.bfloat16)
            tiles.append(jnp.einsum("rck,rcl->rkl", ohk, ohl,
                                    preferred_element_type=jnp.float32))
        h3 = tiles[0] if len(tiles) == 1 else jnp.concatenate(tiles, axis=1)
        # place chunk histograms into ring columns: one-hot of (base+l) % P
        slot = (base[:, None] + jnp.arange(locality, dtype=base.dtype)) % P
        ohp = (slot.reshape(-1)[:, None]
               == jnp.arange(P, dtype=slot.dtype)).astype(jnp.float32)  # [R*L, P]
        flat = jnp.transpose(h3, (1, 0, 2)).reshape(K, R * locality)
        out = jax.lax.dot_general(flat, ohp, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return out.astype(jnp.int32)

    # NOTE: selection (and the WF_HISTOGRAM_FORCE_FAST read below) happens at
    # TRACE time — a jitted executable compiled before the env change keeps
    # the old impl for the life of the process (XLA caches the traced
    # program, not the env). The registry records this choice and validate()
    # reports disagreements as WF109; for A/B runs force a retrace (fresh
    # jit / different shapes) or pass impl= explicitly. The old
    # WF_HISTOGRAM_IMPL toggle is honored as a deprecated registry alias.
    from .registry import resolve_impl
    impl = resolve_impl("histogram", impl=impl,
                        spec_key=f"C{C}xK{K}xP{P}c{chunk}l{locality}")
    # '0'/empty = off — the WF_ORDERING_SKIP_SORTED convention (a bare bool()
    # of the string made '0' ENABLE the wrong-answer diagnostic bypass)
    force_fast = os.environ.get("WF_HISTOGRAM_FORCE_FAST", "0") not in ("", "0")
    if impl.startswith("pallas"):
        if P < locality:
            # the Pallas kernel's single-fold wrap (padded[:, :P] += padded[:,
            # P:]) assumes locality <= ring; for P < L the [K,P] target vs
            # [K,L] addend shapes mismatch — route to the exact scatter path
            # (the XLA fast branch handles any P via % P, but keeping both
            # guards identical keeps the impls interchangeable)
            return _scatter_hist(key, pane, valid, K, P)
        # "pallas": dynamic-slice store of the [K, L] chunk histogram into the
        # ring (8-wide store at a traced lane offset — Mosaic may refuse the
        # minor-dim dynamic slice on some generations). "pallas_mm": placement
        # by one-hot matmul into the full [K, P+L] block (static stores only —
        # guaranteed to lower, more VPU adds per chunk).
        placement = "mm" if impl == "pallas_mm" else "ds"
        fast = lambda _: _pallas_fast(key, pane, valid, K, P,  # noqa: E731
                                      chunk, locality, placement=placement)
    if force_fast:
        # DIAGNOSTIC ONLY (WF_HISTOGRAM_FORCE_FAST): skip the locality cond and
        # run the fast path unconditionally. If XLA flattens the cond in a
        # larger program (select-both-branches), the serialized scatter branch
        # executes every step even though in_bounds is always true — this
        # bypass isolates that hypothesis in the per-prefix ablation. WRONG for
        # inputs that violate chunk locality; never set it in production.
        return fast(None)
    return jax.lax.cond(in_bounds, fast,
                        lambda _: _scatter_hist(key, pane, valid, K, P), None)


def _scatter_hist(key, pane, valid, K, P):
    seg = jnp.where(valid, key * P + pane % P, K * P)
    return jax.ops.segment_sum(valid.astype(jnp.int32), seg,
                               num_segments=K * P).reshape(K, P)


def keyed_pane_histogram_pallas(key: jax.Array, pane: jax.Array,
                                valid: jax.Array, num_keys: int, ring: int, *,
                                chunk: int = DEFAULT_CHUNK,
                                locality: int = DEFAULT_L,
                                placement: str = "ds",
                                interpret: bool = False) -> jax.Array:
    """Pallas formulation of :func:`keyed_pane_histogram`'s fast path: one
    kernel owns the whole ``[C] -> [K, P]`` accumulation, so the chunk one-hots
    and per-chunk ``[K, L]`` partials live in VMEM for their entire life — no
    fusion decision XLA can get wrong in a larger program (the YSB chain
    measures the XLA form at ~5 ms in-chain vs 15 us standalone; this kernel
    exists to make the standalone cost the only cost).

    Grid = one step per chunk (TPU grids run sequentially, so read-modify-write
    accumulation into the output ref across steps is sound). Ring wrap-around
    is handled by padding the ring with ``locality`` spill columns the kernel
    stores into contiguously (``base % P`` never wraps past ``P + L``) and
    folding them back afterwards — no in-kernel modular scatter.

    PRECONDITION (caller-enforced, same as the XLA fast path): every chunk
    spans < ``locality`` panes among its valid lanes. The framework wraps both
    implementations in the same ``lax.cond`` locality check with the exact
    scatter path as fallback (``keyed_pane_histogram(..., impl="pallas")``).
    ``interpret=True`` runs the kernel in Pallas interpret mode (CPU-testable;
    auto-enabled on the CPU backend)."""
    C = key.shape[0]
    K, P = int(num_keys), int(ring)
    if C % chunk != 0 or C < chunk or P < locality:
        # P < locality: the kernel's single-fold wrap-around (one [K, L] spill
        # block folded onto the ring head) is shape-mismatched and arithmetically
        # wrong when the spill spans the ring more than once — exact scatter
        return _scatter_hist(key, pane, valid, K, P)
    return _pallas_fast(key, pane, valid, K, P, chunk, locality,
                        placement=placement, interpret=interpret)


def _pallas_fast(key, pane, valid, K, P, chunk, locality, *,
                 placement: str = "ds", interpret: bool = False):
    import jax.experimental.pallas as pl

    C = key.shape[0]
    L = int(locality)
    R = C // chunk
    big = jnp.iinfo(pane.dtype).max
    interpret = interpret or jax.default_backend() == "cpu"

    def kern(key_ref, pane_ref, valid_ref, out_ref):
        r = pl.program_id(0)

        @pl.when(r == 0)
        def _zero():
            out_ref[...] = jnp.zeros_like(out_ref)

        kc = key_ref[...]
        pc = pane_ref[...]
        vc = valid_ref[...] != 0
        base = jnp.min(jnp.where(vc, pc, big))
        base = jnp.where(base == big, 0, base)
        local = pc - base
        ok = vc & (local < L)
        lr = jnp.where(ok, local, 0)
        ohk = ((kc[:, None] == jax.lax.broadcasted_iota(
            kc.dtype, (chunk, K), 1)) & ok[:, None]).astype(jnp.bfloat16)
        ohl = ((lr[:, None] == jax.lax.broadcasted_iota(
            lr.dtype, (chunk, L), 1)) & ok[:, None]).astype(jnp.bfloat16)
        h = jax.lax.dot_general(ohk, ohl, (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [K, L]
        start = base % P                      # [0, P): contiguous in P + L cols
        if placement == "ds":
            cur = out_ref[:, pl.ds(start, L)]
            out_ref[:, pl.ds(start, L)] = cur + h.astype(jnp.float32)
        else:
            # static-store placement: one-hot [L, P+L] matmul scatters the L
            # columns; the accumulate touches the whole block but every memory
            # op has a static shape and offset (always lowers)
            ohp = (jax.lax.broadcasted_iota(jnp.int32, (L, P + L), 1)
                   == start + jax.lax.broadcasted_iota(
                       jnp.int32, (L, P + L), 0)).astype(jnp.float32)
            out_ref[...] += jax.lax.dot_general(
                h, ohp, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    padded = pl.pallas_call(
        kern,
        grid=(R,),
        in_specs=[pl.BlockSpec((chunk,), lambda r: (r,)),
                  pl.BlockSpec((chunk,), lambda r: (r,)),
                  pl.BlockSpec((chunk,), lambda r: (r,))],
        out_specs=pl.BlockSpec((K, P + L), lambda r: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((K, P + L), jnp.float32),
        interpret=interpret,
    )(key, pane, valid.astype(jnp.int32))
    # fold the spill columns back onto the ring head (wrap-around completion)
    out = padded[:, :P].at[:, :L].add(padded[:, P:])
    return out.astype(jnp.int32)


# ------------------------------------------------------------- registration

from .registry import register_kernel  # noqa: E402  (registration footer)

register_kernel("histogram", "xla", keyed_pane_histogram, reference=True,
                backends=("xla",), default=True)
register_kernel("histogram", "pallas", keyed_pane_histogram_pallas,
                backends=("pallas-tpu", "pallas-interpret"))
register_kernel("histogram", "pallas_mm", keyed_pane_histogram_pallas,
                backends=("pallas-tpu", "pallas-interpret"))
