"""Keyed-pane histograms on the MXU — the FFAT-insert hot path.

The reference's incremental window engines fold each tuple into a per-(key, pane)
partial (``wf/flatfat.hpp:134-240`` leaf update; ``wf/win_seqffat.hpp:389-396``).
The direct TPU translation is a scatter-add, but XLA lowers scatter to a serialized
per-update loop (~18 ns/update measured on v5e) — at 1M-tuple batches that is the
whole step budget.

This module computes the same ``[K, P]`` accumulation as two one-hot matmuls that run
on the MXU:

1. **Chunk-local histogram.** The batch is viewed as ``[R, chunk]`` rows. Event
   timestamps in a stream are *locally clustered*: the panes touched inside one chunk
   of consecutive lanes span a tiny range ``L`` (for a time-ordered stream,
   ``chunk/rate`` time units). Per chunk we take ``base_r = min(pane)`` and build two
   one-hots — key ``[R, chunk, K]`` and local pane ``[R, chunk, L]`` — whose batched
   contraction ``einsum('rck,rcl->rkl')`` is an MXU matmul producing per-chunk
   ``[K, L]`` histograms. 0/1 inputs with f32 accumulation are exact (sums ≤ chunk).
2. **Ring placement.** ``[R, K, L] -> [K, P]`` is one more matmul against the one-hot
   of ``(base_r + l) % P`` — column placement into the pane ring, wrap-around
   included. f32 accumulation stays exact while every count ≤ 2^24.

Batches that violate the locality bound (a chunk spanning ≥ L panes — wildly
out-of-order timestamps) are detected on device and routed through the exact
scatter-add path with ``lax.cond``: the fast path is an optimization, never a
semantics change.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: default lanes per chunk-local histogram row
DEFAULT_CHUNK = 1024
#: default pane-locality bound per chunk (panes spanned by one chunk)
DEFAULT_L = 8
#: key-axis tile for the chunk-local one-hot (caps transient memory at ~C*K_TILE B)
K_TILE = 512


def keyed_pane_histogram(key: jax.Array, pane: jax.Array, valid: jax.Array,
                         num_keys: int, ring: int, *,
                         chunk: int = DEFAULT_CHUNK, locality: int = DEFAULT_L,
                         ) -> jax.Array:
    """Count histogram ``out[k, pane % ring] = #{lanes: key==k, pane==p}``.

    ``key``: i32[C] in [0, num_keys); ``pane``: i32[C] (arbitrary, ring-mapped);
    ``valid``: bool[C]. Returns i32[num_keys, ring]. Exact for any input (locality
    violations fall back to scatter-add inside the same compiled program).
    """
    C = key.shape[0]
    K, P = int(num_keys), int(ring)
    if C % chunk != 0 or C < chunk:
        # odd capacities: scatter path (capacities are powers of two in practice)
        return _scatter_hist(key, pane, valid, K, P)
    # Force the inputs to materialize before the one-hot tiles consume them.
    # In a fused chain `key` is often itself the result of a matmul-formulated
    # lookup (e.g. the YSB campaign join); without the barrier XLA re-fuses
    # that producer into EVERY K_TILE/locality tile of the histogram,
    # multiplying the producer's cost by the tile count (measured: the same
    # histogram is 15 us standalone vs ~5 ms fused in the YSB chain).
    # Semantics-neutral.
    key, pane, valid = jax.lax.optimization_barrier((key, pane, valid))
    R = C // chunk

    pane_r = pane.reshape(R, chunk)
    valid_r = valid.reshape(R, chunk)
    big = jnp.iinfo(pane.dtype).max
    base = jnp.min(jnp.where(valid_r, pane_r, big), axis=1)      # [R]
    base = jnp.where(base == big, 0, base)
    local = pane_r - base[:, None]                               # [R, chunk]
    ok_local = valid_r & (local < locality)

    in_bounds = jnp.all(ok_local == valid_r)

    def fast(_):
        lr = jnp.where(ok_local, local, 0)
        key_r = key.reshape(R, chunk)
        ohl = ((lr[:, :, None] == jnp.arange(locality, dtype=lr.dtype))
               & ok_local[:, :, None]).astype(jnp.bfloat16)
        # tile the key axis: bounds the transient [R, chunk, K_tile] one-hot to
        # ~C * K_TILE bytes instead of C * K (K can be thousands)
        tiles = []
        for k0 in range(0, K, K_TILE):
            kn = min(K_TILE, K - k0)
            ohk = ((key_r[:, :, None]
                    == jnp.arange(k0, k0 + kn, dtype=key.dtype))
                   & ok_local[:, :, None]).astype(jnp.bfloat16)
            tiles.append(jnp.einsum("rck,rcl->rkl", ohk, ohl,
                                    preferred_element_type=jnp.float32))
        h3 = tiles[0] if len(tiles) == 1 else jnp.concatenate(tiles, axis=1)
        # place chunk histograms into ring columns: one-hot of (base+l) % P
        slot = (base[:, None] + jnp.arange(locality, dtype=base.dtype)) % P
        ohp = (slot.reshape(-1)[:, None]
               == jnp.arange(P, dtype=slot.dtype)).astype(jnp.float32)  # [R*L, P]
        flat = jnp.transpose(h3, (1, 0, 2)).reshape(K, R * locality)
        out = jax.lax.dot_general(flat, ohp, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return out.astype(jnp.int32)

    return jax.lax.cond(in_bounds, fast,
                        lambda _: _scatter_hist(key, pane, valid, K, P), None)


def _scatter_hist(key, pane, valid, K, P):
    seg = jnp.where(valid, key * P + pane % P, K * P)
    return jax.ops.segment_sum(valid.astype(jnp.int32), seg,
                               num_segments=K * P).reshape(K, P)
