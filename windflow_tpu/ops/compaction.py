"""Stream compaction: pack live lanes to the front of a fixed-capacity buffer.

Counterpart of the reference's device prefix-scan suite (Blelloch ``prescan``,
``gather_sums``, ``map_to_target`` at ``wf/gpu_utils.hpp:323-417``) used by the GPU
emitter to build per-destination sub-batches (``wf/standard_nodes_gpu.hpp:52-238``).
On TPU we express the same thing with ``cumsum`` + scatter/gather, which XLA lowers
well; a sort-based stable partition is provided as the robust default (the reference's
own scattering study crowns sort-by-key at high fan-out,
``src/GPU_Tests/scattering/results_scattering.org``).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp


def exclusive_scan(x: jax.Array) -> jax.Array:
    """Exclusive prefix sum (the reference's ``prescan``, ``wf/gpu_utils.hpp:330-360``)."""
    return jnp.cumsum(x) - x


def compact_indices(valid: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Return (gather_idx, out_valid): positions such that taking ``gather_idx`` packs
    live lanes to the front in stable order; ``out_valid[i] = i < count``."""
    c = valid.shape[0]
    # stable partition via argsort on the invalid flag
    order = jnp.argsort(jnp.where(valid, 0, 1), stable=True)
    count = jnp.sum(valid.astype(jnp.int32))
    out_valid = jnp.arange(c, dtype=jnp.int32) < count
    return order, out_valid


def scatter_compact(values: Any, valid: jax.Array, capacity: int = None) -> Tuple[Any, jax.Array]:
    """Scatter-based compaction: live lane i goes to position exclusive_scan(valid)[i].
    Returns (packed pytree, out_valid). ``capacity`` defaults to the input size."""
    c = valid.shape[0]
    cap = capacity or c
    pos = exclusive_scan(valid.astype(jnp.int32))
    tgt = jnp.where(valid, pos, cap)  # dead lanes dropped via OOB scatter

    def one(v):
        out = jnp.zeros((cap,) + v.shape[1:], v.dtype)
        return out.at[tgt].set(v, mode="drop")
    count = jnp.sum(valid.astype(jnp.int32))
    out_valid = jnp.arange(cap, dtype=jnp.int32) < count
    return jax.tree.map(one, values), out_valid


def partition_by_destination(dest: jax.Array, valid: jax.Array, n_dest: int,
                             capacity_per_dest: int, return_counts: bool = False):
    """Group lanes by destination: returns (gather_idx ``[n_dest, cap]``, out_valid
    ``[n_dest, cap]``). The device-side counterpart of the GPU keyed-scatter emitter
    building per-destination sub-batches (``wf/standard_nodes_gpu.hpp:60-238``).

    A destination with more than ``capacity_per_dest`` live lanes overflows: the
    overflowing lanes are NOT in the gather table. With ``return_counts=True`` the
    UNCLAMPED per-destination live counts ``[n_dest]`` are returned as a third value
    so the caller can detect overflow (``counts > capacity_per_dest``) and re-route
    the residue — the bounded-queue backpressure discipline of the reference
    (``FF_BOUNDED_BUFFER`` blocks, it never drops). :class:`~..parallel.emitters.
    Standard_Emitter` uses this to make routing lossless."""
    c = dest.shape[0]
    # out-of-range destinations (a user routing_func may return anything,
    # including negatives, which would sort BEFORE bucket 0 and shift every
    # offset) are dropped via the discarded n_dest bucket
    key = jnp.where(valid & (dest >= 0) & (dest < n_dest), dest, n_dest)
    order = jnp.argsort(key, stable=True)          # lanes grouped by destination
    sorted_key = jnp.take(key, order)
    # per-destination counts and offsets
    counts = jax.ops.segment_sum(jnp.ones((c,), jnp.int32),
                                 jnp.minimum(sorted_key, n_dest), num_segments=n_dest + 1)[:n_dest]
    offsets = jnp.cumsum(counts) - counts
    lane = jnp.arange(capacity_per_dest, dtype=jnp.int32)
    gather_idx = offsets[:, None] + lane[None, :]
    out_valid = lane[None, :] < counts[:, None]
    gather_idx = jnp.clip(gather_idx, 0, c - 1)
    if return_counts:
        return jnp.take(order, gather_idx), out_valid, counts
    return jnp.take(order, gather_idx), out_valid


def partition_by_destination_onehot(dest: jax.Array, valid: jax.Array,
                                    n_dest: int, capacity_per_dest: int,
                                    return_counts: bool = False):
    """Sort-free variant of :func:`partition_by_destination` for SMALL fan-out:
    each lane's within-destination rank comes from a one-hot cumsum ([C, D]
    sequential-memory traffic instead of the sort network's log^2 passes), then
    one scatter builds the [n_dest, cap] gather table. Same contract as the
    sort-based form. This is the framework's V1-vs-sort counterpart of the
    reference's scattering study (``src/GPU_Tests/scattering``); ``bench.py``
    A/Bs the two and the emitter keeps the sort as default until the on-chip
    number says otherwise."""
    c = dest.shape[0]
    cap = capacity_per_dest
    # out-of-range destinations are dropped, exactly like the sort variant
    # (which maps them to the discarded n_dest bucket)
    valid = valid & (dest >= 0) & (dest < n_dest)
    oh = ((dest[:, None] == jnp.arange(n_dest, dtype=dest.dtype)[None, :])
          & valid[:, None])
    ranks = jnp.cumsum(oh.astype(jnp.int32), axis=0)        # [C, D] inclusive
    rank = jnp.take_along_axis(ranks, jnp.clip(dest, 0, n_dest - 1)[:, None],
                               axis=1)[:, 0] - 1            # within-dest position
    counts = ranks[-1]
    tgt = jnp.where(valid & (rank < cap),
                    jnp.clip(dest, 0, n_dest - 1) * cap + rank,
                    n_dest * cap)                           # OOB -> dropped
    gather_idx = (jnp.zeros((n_dest * cap,), jnp.int32)
                  .at[tgt].set(jnp.arange(c, dtype=jnp.int32), mode="drop")
                  .reshape(n_dest, cap))
    lane = jnp.arange(cap, dtype=jnp.int32)
    out_valid = lane[None, :] < jnp.minimum(counts, cap)[:, None]
    if return_counts:
        return gather_idx, out_valid, counts
    return gather_idx, out_valid
