"""Per-backend kernel registry — ONE selection point for every hot-op impl.

PRs 1-5 grew ad-hoc trace-time env toggles (``WF_HISTOGRAM_IMPL``,
``WF_LOOKUP_IMPL``) next to each kernel. This module promotes them into a
real portability layer (the selection architecture of arXiv:2601.17526):
every kernel family with more than one implementation — the XLA reference
formulation, a fused Pallas kernel, its interpret-mode fallback — registers
here, and the op entry points resolve their implementation through
:func:`resolve_impl` instead of reading ``os.environ`` themselves.

Selection is keyed on (kernel, shape/dtype spec key, device kind) and
resolves in precedence order:

1. an explicit ``impl=`` argument at the call site (always wins);
2. ``WF_KERNEL_IMPL`` — per-kernel (``"histogram=pallas,lookup=xla"``) or
   global (``"pallas"``) override;
3. the deprecated per-kernel aliases (``WF_HISTOGRAM_IMPL``,
   ``WF_LOOKUP_IMPL``) — still honored, read HERE and nowhere else;
4. a persisted autotuned winner from the PR 3 :class:`~windflow_tpu.control.
   autotune.TuningCache` (``attach_tuning_cache``), so chains warm-start
   with the best known impl for this (kernel, spec, device);
5. the kernel's registered default (the XLA reference).

TRACE-TIME HAZARD (the documented footgun of ``ops/lookup.py``/``ops/
histogram.py``, now checkable): resolution happens at TRACE time, so a
jitted executable compiled before an env/cache change keeps the old impl
for the life of the process (XLA caches the traced program, not the env).
Every resolution is therefore RECORDED under its (kernel, spec key, device)
key; :func:`stale_selections` recomputes the current selection for each
record and reports disagreements, and ``analysis/validate.py`` surfaces
them as WF109 diagnostics.

Kernel and impl names are gated by the linter (WF250) against the central
``observability/names.py::KERNELS``/``KERNEL_IMPLS`` registries — a typo'd
name would silently fork the env-override/tuning-cache/WF109 namespaces.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

def _deprecated_alias_choice(kernel: str) -> Optional[str]:
    """The deprecated pre-registry toggles (docs/ENV_FLAGS.md marks them
    deprecated aliases), read HERE and nowhere else — at TRACE time, like
    everything in this module. One literal read per flag: the WF201 env
    inventory scanner ties each flag to its ``os.environ`` line. ``''``/
    ``'0'`` = no override (the repo-wide off convention, matching
    WF_KERNEL_IMPL); anything else must be a registered impl name."""
    if kernel == "histogram":
        value = os.environ.get("WF_HISTOGRAM_IMPL", "")
    elif kernel == "lookup":
        value = os.environ.get("WF_LOOKUP_IMPL", "")
    else:
        return None
    return None if value in ("", "0") else value


class KernelImpl:
    """One registered implementation of a kernel family."""

    __slots__ = ("kernel", "name", "fn", "reference", "backends")

    def __init__(self, kernel: str, name: str, fn: Optional[Callable],
                 reference: bool, backends: Tuple[str, ...]):
        self.kernel = kernel
        self.name = name
        self.fn = fn
        self.reference = reference
        self.backends = backends

    def __repr__(self) -> str:
        return (f"KernelImpl({self.kernel}:{self.name}"
                f"{' [ref]' if self.reference else ''})")


def device_kind() -> str:
    """``platform:device_kind`` of the default backend — delegates to
    ``control/autotune.py::device_kind`` so kernel entries and capacity
    plans key the ONE shared TuningCache file with the same device string
    (a format change there cannot fork the two namespaces)."""
    from ..control.autotune import device_kind as _dk
    return _dk()


def pallas_backend() -> str:
    """Which Pallas execution mode a ``pallas`` impl would use right now:
    ``"pallas-tpu"`` on a TPU backend, ``"pallas-interpret"`` elsewhere (the
    kernels all auto-enable ``interpret=True`` off-TPU)."""
    try:
        import jax
        return ("pallas-tpu" if jax.default_backend() == "tpu"
                else "pallas-interpret")
    except Exception:                         # noqa: BLE001 — no backend
        return "pallas-interpret"


def _parse_kernel_impl_env(value: str) -> Dict[str, str]:
    """``WF_KERNEL_IMPL`` grammar: ``"pallas"`` (global default under key
    ``"*"``) or ``"histogram=pallas,lookup=xla"`` (per-kernel); entries
    without ``=`` set the global default. ``''``/``'0'`` = no override (the
    WF_ORDERING_SKIP_SORTED off convention)."""
    out: Dict[str, str] = {}
    if value in ("", "0"):
        return out
    for part in value.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            k, _, v = part.partition("=")
            out[k.strip()] = v.strip()
        else:
            out["*"] = part
    return out


class KernelRegistry:
    """The per-backend kernel registry. One process-wide instance
    (:data:`REGISTRY`) backs the module-level convenience functions — the
    class exists so tests can build isolated registries."""

    def __init__(self):
        self._impls: Dict[str, Dict[str, KernelImpl]] = {}
        self._default: Dict[str, str] = {}
        self._cache = None                      # control.autotune.TuningCache
        # (kernel, spec_key, device) -> EVERY impl resolved at trace time
        # (a set, not last-wins: each resolution may live on in a cached
        # executable, so a later re-resolution must not silence the WF109
        # staleness check for the earlier one)
        self._records: Dict[Tuple[str, str, str], set] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------ registration

    def register_kernel(self, kernel: str, impl: str,
                        fn: Optional[Callable] = None, *,
                        reference: bool = False,
                        backends: Tuple[str, ...] = ("xla",),
                        default: bool = False) -> None:
        """Register ``impl`` for ``kernel``. ``reference`` marks the
        byte-identical oracle every other impl is parity-tested against;
        ``default`` (implied by the first registration) is the selection
        when nothing overrides. Re-registration replaces (module reload)."""
        with self._lock:
            fam = self._impls.setdefault(kernel, {})
            fam[impl] = KernelImpl(kernel, impl, fn, reference, backends)
            if default or kernel not in self._default:
                self._default[kernel] = impl

    def kernels(self) -> Tuple[str, ...]:
        return tuple(sorted(self._impls))

    def impls(self, kernel: str) -> Tuple[str, ...]:
        return tuple(sorted(self._impls.get(kernel, ())))

    def reference_impl(self, kernel: str) -> Optional[str]:
        for impl in self._impls.get(kernel, {}).values():
            if impl.reference:
                return impl.name
        return None

    # --------------------------------------------------------------- tuning

    def attach_tuning_cache(self, cache) -> None:
        """Warm-start selection from (and persist winners to) a PR 3
        ``TuningCache``. ``None`` detaches."""
        with self._lock:
            self._cache = cache

    def persist_winner(self, kernel: str, spec_key: str, impl: str,
                       tps: Optional[float] = None) -> None:
        """Record an autotuned winning impl in the attached TuningCache so
        later processes warm-start on it (schema: ``{"impl": ..., "tps":
        ..., "kernel": ...}`` under the kernel tuning key)."""
        self._require_impl(kernel, impl)
        if self._cache is None:
            return
        from ..control.autotune import kernel_tuning_key
        entry = {"impl": impl, "kernel": kernel, "spec": spec_key}
        if tps is not None:
            entry["tps"] = float(tps)
        self._cache.put(kernel_tuning_key(kernel, spec_key, device_kind()),
                        entry)

    def _cached_winner(self, kernel: str, spec_key: str) -> Optional[str]:
        if self._cache is None:
            return None
        from ..control.autotune import kernel_tuning_key
        hit = self._cache.get(
            kernel_tuning_key(kernel, spec_key, device_kind()))
        if hit and isinstance(hit.get("impl"), str):
            return hit["impl"]
        return None

    # ------------------------------------------------------------- selection

    def _require_impl(self, kernel: str, impl: str) -> str:
        fam = self._impls.get(kernel)
        if not fam:
            raise ValueError(
                f"unknown kernel {kernel!r}; registered kernels: "
                f"{', '.join(self.kernels()) or '(none)'}")
        if impl not in fam:
            raise ValueError(
                f"kernel {kernel!r} has no impl {impl!r}; registered impls: "
                f"{', '.join(self.impls(kernel))}")
        return impl

    def _select(self, kernel: str, spec_key: str,
                explicit: Optional[str]) -> str:
        if explicit:
            return self._require_impl(kernel, explicit)
        env = _parse_kernel_impl_env(os.environ.get("WF_KERNEL_IMPL", ""))
        choice = env.get(kernel) or env.get("*")
        if not choice:
            choice = _deprecated_alias_choice(kernel)
        if not choice:
            choice = self._cached_winner(kernel, spec_key)
        if not choice:
            choice = self._default.get(kernel)
        return self._require_impl(kernel, choice)

    def resolve_impl(self, kernel: str, *, spec_key: str = "",
                     impl: Optional[str] = None, record: bool = True) -> str:
        """Resolve the implementation for ``kernel`` (precedence: explicit
        ``impl=`` > ``WF_KERNEL_IMPL`` > deprecated alias > tuning-cache
        winner > registered default) and — because resolution happens at
        TRACE time and the compiled executable keeps it — record the choice
        under (kernel, spec_key, device) for the WF109 staleness check.
        Explicit ``impl=`` choices are NOT recorded: they are pinned in
        code, so an env change can neither invalidate them nor make the
        staleness comparison meaningful."""
        choice = self._select(kernel, spec_key, impl)
        if record and impl is None:
            dk = device_kind()
            with self._lock:
                self._records.setdefault(
                    (kernel, spec_key, dk), set()).add(choice)
            # runtime-health ledger (observability/device_health.py): a
            # resolution observed while a ledger is active journals a
            # kernel_resolve event — the compile ledger's record of WHICH
            # impl each executable was traced with (the WF109 evidence,
            # live). Lazy import + None check: trace-time-rare path, and
            # this module must stay importable before observability.
            try:
                from ..observability import device_health as _dh
            except ImportError:            # minimal/fixture trees
                _dh = None
            if _dh is not None:
                _dh.note_kernel_resolve(kernel, spec_key, choice, device=dk)
        return choice

    # ------------------------------------------------------- WF109 records

    def trace_records(self) -> Dict[Tuple[str, str, str], frozenset]:
        """Snapshot of every (kernel, spec_key, device) -> set of impls
        resolved this process (≈ the impls baked into cached jitted
        executables — ALL of them, not just the latest)."""
        with self._lock:
            return {k: frozenset(v) for k, v in self._records.items()}

    def stale_selections(self) -> List[dict]:
        """Recorded trace-time impls the CURRENT selection (env/cache as of
        now; explicit args excluded — those are pinned in code) no longer
        agrees with. One entry per disagreeing impl — an executable compiled
        under it may still be cached — each feeding one WF109 diagnostic."""
        out = []
        for (kernel, spec_key, device), recorded in \
                sorted(self.trace_records().items()):
            try:
                current = self._select(kernel, spec_key, None)
            except ValueError:
                continue                      # kernel/impl unregistered now
            for impl in sorted(recorded - {current}):
                out.append({"kernel": kernel, "spec_key": spec_key,
                            "device": device, "recorded": impl,
                            "current": current})
        return out

    def reset_records(self) -> None:
        """Forget trace records (tests; a fresh process does this by
        construction)."""
        with self._lock:
            self._records.clear()


#: the process-wide registry instance the op modules register into
REGISTRY = KernelRegistry()


def register_kernel(kernel: str, impl: str, fn: Optional[Callable] = None, *,
                    reference: bool = False,
                    backends: Tuple[str, ...] = ("xla",),
                    default: bool = False) -> None:
    REGISTRY.register_kernel(kernel, impl, fn, reference=reference,
                             backends=backends, default=default)


def resolve_impl(kernel: str, *, spec_key: str = "",
                 impl: Optional[str] = None, record: bool = True) -> str:
    return REGISTRY.resolve_impl(kernel, spec_key=spec_key, impl=impl,
                                 record=record)


def attach_tuning_cache(cache) -> None:
    REGISTRY.attach_tuning_cache(cache)


def persist_winner(kernel: str, spec_key: str, impl: str,
                   tps: Optional[float] = None) -> None:
    REGISTRY.persist_winner(kernel, spec_key, impl, tps)


def stale_selections() -> List[dict]:
    return REGISTRY.stale_selections()
