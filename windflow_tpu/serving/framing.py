"""Serving wire format — length-framed binary record frames.

The ingest counterpart of the fleet telemetry plane's WFT1 frames
(``observability/fleet.py``): same magic + hex-length + resync discipline,
but the payload carries **binary records** (rows of one fixed numpy
structured dtype — the ``RecordSource`` AoS framing), not JSON snapshots.

Frame grammar (all ASCII except the record bytes)::

    b"WFS1 " <8 hex digits: payload length> b"\\n" <payload> b"\\n"
    payload := <meta JSON line terminated by b"\\n"> <raw record bytes>

The meta line names the frame's **tenant** (the multi-tenant label every
downstream plane keys on), a per-tenant monotonically increasing **seq**
(the dedup coordinate — a reconnecting client may re-send its unacked tail
and the receiver drops already-seen seqs, so peer kills degrade to replay,
never duplication), a **kind** (``data`` / ``eos`` / ``swap``) and the
record byte count (cross-checked against the frame — a length that lies is
a torn frame, resync'd like any other).

A reader that lands mid-stream (or receives torn/garbage bytes from a
killed peer) skips to the next ``WFS1 `` magic and counts the gap in
``frames_torn`` — the stream self-heals at the next intact frame, the
``FrameDecoder.feed`` contract.

Stdlib only and loadable by file path (the ``wf_state.py`` convention):
``scripts/wf_serve.py`` drives the loopback selftest through this module
without JAX or numpy installed.
"""

from __future__ import annotations

import json
import socket
import time
from typing import Dict, List, Optional, Tuple

#: frame magic — the resync point for readers that land mid-stream
MAGIC = b"WFS1 "
_LEN_DIGITS = 8
_HEADER_LEN = len(MAGIC) + _LEN_DIGITS + 1
#: hard per-frame cap: a corrupt length field must not make the decoder
#: buffer gigabytes waiting for a frame that never completes
MAX_FRAME_BYTES = 64 << 20

#: frame kinds: "data" carries records, "eos" closes one tenant's stream,
#: "swap" requests a named-graph hot swap (``ServingRuntime.swap_graph``
#: driven over the wire — scripts/wf_serve.py swap)
KIND_DATA = "data"
KIND_EOS = "eos"
KIND_SWAP = "swap"
FRAME_KINDS = (KIND_DATA, KIND_EOS, KIND_SWAP)

#: the tenant label used when a client does not declare one — every
#: counter/SLO surface keys on SOME tenant, never on a missing label
DEFAULT_TENANT = "default"


def encode_record_frame(records: bytes = b"", *, tenant: str = DEFAULT_TENANT,
                        seq: int = 0, kind: str = KIND_DATA,
                        graph: Optional[str] = None,
                        t_send: Optional[float] = None,
                        span: Optional[str] = None) -> bytes:
    """One length-framed record frame (see the module docstring's grammar).
    ``graph`` names the swap target on ``kind="swap"`` frames.  ``t_send``
    (sender wall time) and ``span`` (a client-chosen span id) are OPTIONAL
    meta keys — the wire-to-sink tracing stamp; decoders that predate them
    pass unknown meta keys through untouched (the forward-compat pin in
    ``tests/test_serving.py``), so stamped frames need no flag day."""
    if kind not in FRAME_KINDS:
        raise ValueError(f"unknown frame kind {kind!r} "
                         f"(kinds: {', '.join(FRAME_KINDS)})")
    meta = {"tenant": str(tenant), "seq": int(seq), "kind": kind,
            "nbytes": len(records)}
    if graph is not None:
        meta["graph"] = str(graph)
    if t_send is not None:
        meta["t_send"] = round(float(t_send), 6)
    if span is not None:
        meta["span"] = str(span)
    head = json.dumps(meta, sort_keys=True).encode("utf-8") + b"\n"
    payload = head + bytes(records)
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(f"frame payload {len(payload)} bytes exceeds "
                         f"MAX_FRAME_BYTES={MAX_FRAME_BYTES}")
    return MAGIC + b"%0*x" % (_LEN_DIGITS, len(payload)) + b"\n" \
        + payload + b"\n"


class RecordFrameDecoder:
    """Incremental binary-frame parser, torn-input tolerant.

    ``feed(data)`` returns the complete ``(meta, record_bytes)`` pairs
    decoded so far; bytes that do not parse (mid-stream join, torn send,
    corrupt length, bad meta, a record count that disagrees with the frame
    length) are skipped to the next ``MAGIC`` and counted in
    ``frames_torn`` — the WFT1 resync discipline over a binary payload."""

    def __init__(self):
        self._buf = bytearray()
        self.frames_decoded = 0
        self.frames_torn = 0

    def feed(self, data: bytes) -> List[Tuple[dict, bytes]]:
        self._buf += data
        out: List[Tuple[dict, bytes]] = []
        while True:
            i = self._buf.find(MAGIC)
            if i < 0:
                # no magic in the buffer: keep only a possible magic PREFIX
                # at the tail, drop the rest as torn noise
                keep = len(MAGIC) - 1
                if len(self._buf) > keep:
                    del self._buf[:len(self._buf) - keep]
                    self.frames_torn += 1
                return out
            if i > 0:
                del self._buf[:i]          # resync: skip torn bytes
                self.frames_torn += 1
            if len(self._buf) < _HEADER_LEN:
                return out                 # header still in flight
            hexlen = self._buf[len(MAGIC):len(MAGIC) + _LEN_DIGITS]
            try:
                n = int(bytes(hexlen), 16)
            except ValueError:
                n = -1
            if (n < 0 or n > MAX_FRAME_BYTES
                    or self._buf[_HEADER_LEN - 1:_HEADER_LEN] != b"\n"):
                del self._buf[:len(MAGIC)]  # corrupt header: resync past it
                self.frames_torn += 1
                continue
            if len(self._buf) < _HEADER_LEN + n + 1:
                return out                 # payload still in flight
            payload = bytes(self._buf[_HEADER_LEN:_HEADER_LEN + n])
            trailer = self._buf[_HEADER_LEN + n:_HEADER_LEN + n + 1]
            if trailer != b"\n":
                del self._buf[:len(MAGIC)]  # length lied: resync
                self.frames_torn += 1
                continue
            del self._buf[:_HEADER_LEN + n + 1]
            nl = payload.find(b"\n")
            meta = None
            if nl >= 0:
                try:
                    meta = json.loads(payload[:nl])
                except ValueError:
                    meta = None
            if not isinstance(meta, dict) or meta.get("kind") not in FRAME_KINDS:
                self.frames_torn += 1
                continue
            # meta is attacker-supplied JSON: a null/non-numeric nbytes or
            # seq is a torn frame, never an exception out of feed() — one
            # malformed frame must not kill the client connection loop
            try:
                nbytes = int(meta.get("nbytes", -1))
                meta["seq"] = int(meta.get("seq", 0))
            except (ValueError, TypeError):
                self.frames_torn += 1
                continue
            if nbytes != len(payload) - nl - 1:
                self.frames_torn += 1
                continue
            meta.setdefault("tenant", DEFAULT_TENANT)
            self.frames_decoded += 1
            out.append((meta, payload[nl + 1:]))


def parse_endpoint(endpoint: str) -> Tuple[str, ...]:
    """``("tcp", host, port)`` / ``("unix", path)`` from a serving endpoint
    string — the exact telemetry-endpoint grammar (``tcp://HOST:PORT``,
    bare ``HOST:PORT``, ``unix://PATH`` / ``unix:PATH``); duplicated here
    (not imported) so this module stays loadable by file path alone."""
    s = str(endpoint or "").strip()
    if not s:
        raise ValueError("empty serving endpoint (expected tcp://HOST:PORT, "
                         "HOST:PORT, or unix://PATH)")
    if s.startswith("unix://"):
        path = s[len("unix://"):]
    elif s.startswith("unix:"):
        path = s[len("unix:"):]
    else:
        path = None
    if path is not None:
        if not path:
            raise ValueError(f"unix endpoint {endpoint!r} has an empty path")
        return ("unix", path)
    if s.startswith("tcp://"):
        s = s[len("tcp://"):]
    host, sep, port_s = s.rpartition(":")
    if not sep or not host:
        raise ValueError(f"unparseable serving endpoint {endpoint!r} "
                         f"(expected tcp://HOST:PORT, HOST:PORT, or "
                         f"unix://PATH)")
    try:
        port = int(port_s)
    except ValueError:
        raise ValueError(f"serving endpoint {endpoint!r}: port {port_s!r} "
                         f"is not an integer") from None
    if not 0 <= port <= 65535:
        raise ValueError(f"serving endpoint {endpoint!r}: port {port} "
                         f"out of range")
    return ("tcp", host.strip("[]"), port)


def connect(endpoint: str, timeout: float = 5.0) -> socket.socket:
    """Client-side connect to a serving endpoint (tests, examples, the
    ``wf_serve swap`` control path)."""
    parsed = parse_endpoint(endpoint)
    if parsed[0] == "unix":
        sk = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sk.settimeout(timeout)
        sk.connect(parsed[1])
    else:
        sk = socket.create_connection((parsed[1], parsed[2]),
                                      timeout=timeout)
    sk.settimeout(timeout)
    return sk


class RecordClient:
    """Minimal framing client: per-tenant monotone seqs, reconnect-aware.

    Each ``send`` frames one chunk of raw record bytes under a tenant label
    with the tenant's next seq.  After a peer kill, ``reconnect()`` opens a
    fresh socket and the caller may re-send its unacked tail — overlapping
    seqs are deduped server-side, so replay is idempotent (the tentpole's
    peer-kill contract)."""

    def __init__(self, endpoint: str, timeout: float = 5.0,
                 stamp: bool = True):
        self.endpoint = endpoint
        self.timeout = timeout
        #: wire-to-sink tracing stamp: when on (default), every data frame's
        #: meta carries ``t_send`` (sender wall time) + a deterministic
        #: client ``span`` id (``tenant/seq``) — old servers ignore both
        #: (unknown-meta-key forward compat), so the stamp has no flag day.
        #: ``stamp=False`` reproduces pre-stamp clients exactly (the
        #: backward-compat regression path).
        self.stamp = bool(stamp)
        self._seq: Dict[str, int] = {}
        self._sock: Optional[socket.socket] = None

    def _ensure(self) -> socket.socket:
        if self._sock is None:
            self._sock = connect(self.endpoint, self.timeout)
        return self._sock

    def send(self, records: bytes, *, tenant: str = DEFAULT_TENANT,
             seq: Optional[int] = None) -> int:
        """Frame + send one record chunk; returns the seq used.  An explicit
        ``seq`` re-sends that coordinate (the reconnect-overlap path)."""
        if seq is None:
            seq = self._seq.get(tenant, -1) + 1
        self._seq[tenant] = max(self._seq.get(tenant, -1), seq)
        kw = {}
        if self.stamp:
            kw = {"t_send": time.time(),  # wf-lint: allow[wall-clock] cross-process wire timing needs wall time
                  "span": f"{tenant}/{seq}"}
        self._ensure().sendall(
            encode_record_frame(records, tenant=tenant, seq=seq, **kw))
        return seq

    def send_eos(self, tenant: str = DEFAULT_TENANT) -> None:
        seq = self._seq.get(tenant, -1) + 1
        self._seq[tenant] = seq
        self._ensure().sendall(
            encode_record_frame(b"", tenant=tenant, seq=seq, kind=KIND_EOS))

    def send_swap(self, graph: str) -> None:
        """Request a hot swap to the named registered graph (control frame —
        rides outside every tenant's data seq space)."""
        self._ensure().sendall(
            encode_record_frame(b"", tenant="", seq=0, kind=KIND_SWAP,
                                graph=graph))

    def send_garbage(self, data: bytes) -> None:
        """Inject raw non-frame bytes (chaos/selftest: the decoder must
        resync and count them torn, never desync the following frames)."""
        self._ensure().sendall(data)

    def kill(self) -> None:
        """Abrupt peer kill: close without EOS (chaos_sweep --serve)."""
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def reconnect(self) -> None:
        self.kill()
        self._ensure()

    def close(self) -> None:
        self.kill()
