"""Multi-tenant admission — per-tenant token buckets over one serving run.

Every ingest frame carries a tenant label (``serving/framing.py``); this
module turns a declarative :class:`TenantSpec` set into one
:class:`~windflow_tpu.control.admission.AdmissionController` per tenant, so
one noisy tenant is rate-limited/shed inside its OWN bucket while its
neighbors' budgets are untouched.  The per-tenant counters surface as the
snapshot's ``serving.tenants`` section (``names.py::TENANT_GAUGES``), the
SLO engine reads them through the tenant-labelled signal family
(``observability/slo.py::TENANT_SIGNALS``), and the fleet fold keeps them
per-tenant (``device_health.merge_snapshots``) — the whole isolation story
rides one label dimension end to end.

Bucket flavours follow the admission plane's replay discipline exactly:
``rate_tps`` builds a wall-clock :class:`TokenBucket` (live drivers only);
``refill_per_batch`` builds the deterministic :class:`PositionBucket`
supervised replay requires — a supervised serving run with a wall-clock
tenant bucket is a construction-time error here and WF119 pre-run.

``TenantSpec``/``resolve_tenants``/``tenant_problems`` are stdlib-only and
the module is loadable by file path (the ``wf_state.py`` convention) —
``scripts/wf_serve.py`` resolves/validates tenant sets without JAX; only
:func:`build_registry` imports the control plane.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

#: shed policies a tenant may declare (the admission plane's registry;
#: duplicated as data so this module stays path-loadable without package
#: imports — control/admission.py raises on anything else anyway)
SHED_POLICIES = ("drop_newest", "drop_oldest_ts")


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's admission contract.

    Exactly one of ``rate_tps`` (wall-clock tuples/second — live serving
    only) or ``refill_per_batch`` (deterministic tokens per offered batch —
    REQUIRED under supervision) may be set; neither means the tenant is
    rate-unlimited (counted, never shed).  ``burst`` defaults to the
    admission plane's burst-sizing policy (4 base batches)."""

    id: str
    rate_tps: Optional[float] = None
    refill_per_batch: Optional[float] = None
    burst: Optional[float] = None
    shed_policy: str = "drop_newest"


def tenant_problems(spec: TenantSpec) -> List[str]:
    """Every reason this tenant spec cannot be honored — THE shared legality
    check of :func:`build_registry`, the WF119 validator, and ``wf_lint
    --explain WF119``'s story.  Empty list = clean."""
    out = []
    if not spec.id or not str(spec.id).strip():
        out.append("tenant has an empty id")
    if spec.rate_tps is not None and spec.refill_per_batch is not None:
        out.append(f"tenant {spec.id!r}: rate_tps and refill_per_batch are "
                   f"mutually exclusive — one bucket per tenant, one refill "
                   f"law")
    for fname in ("rate_tps", "refill_per_batch", "burst"):
        v = getattr(spec, fname)
        if v is not None and not float(v) > 0:
            out.append(f"tenant {spec.id!r}: {fname} must be > 0, got {v}")
    if spec.shed_policy not in SHED_POLICIES:
        out.append(f"tenant {spec.id!r}: unknown shed policy "
                   f"{spec.shed_policy!r} (policies: "
                   f"{', '.join(SHED_POLICIES)})")
    return out


def _spec_from_dict(d: dict) -> TenantSpec:
    allowed = {f.name for f in dataclasses.fields(TenantSpec)}
    unknown = set(d) - allowed
    if unknown:
        raise ValueError(f"unknown TenantSpec field(s) {sorted(unknown)} "
                         f"(allowed: {sorted(allowed)})")
    if "id" not in d:
        raise ValueError(f"a tenant spec needs at least an id, got "
                         f"{sorted(d)}")
    return TenantSpec(**d)


def resolve_tenants(tenants) -> Optional[List[TenantSpec]]:
    """Normalize a ``tenants=`` argument (after its ``WF_TENANTS`` env
    resolution): ``None``/``False``/``''``/``'0'`` = off (None), a
    list/tuple of ``TenantSpec``/dicts passes through, a string is inline
    JSON (when it starts with ``[``/``{``) or a JSON file path.  JSON top
    level: a list of tenant dicts, or ``{"tenants": [...]}``.  Raises
    ``ValueError`` on malformed input — surfaced pre-run as WF119."""
    if tenants is None or tenants is False:
        return None
    if isinstance(tenants, str):
        s = tenants.strip()
        if s in ("", "0"):
            return None
        if s.startswith("[") or s.startswith("{"):
            data = json.loads(s)
        else:
            with open(s) as f:
                data = json.load(f)
        if isinstance(data, dict):
            data = data.get("tenants")
        if not isinstance(data, list):
            raise ValueError(f"tenant JSON must be a list of tenant objects "
                             f"(or {{'tenants': [...]}}), got "
                             f"{type(data).__name__}")
        return [_spec_from_dict(dict(d)) for d in data]
    if isinstance(tenants, (list, tuple)):
        out = []
        for item in tenants:
            if isinstance(item, TenantSpec):
                out.append(item)
            elif isinstance(item, dict):
                out.append(_spec_from_dict(dict(item)))
            else:
                raise ValueError(f"tenants entries must be TenantSpec or "
                                 f"dict, got {type(item).__name__}")
        return out or None
    raise ValueError(f"tenants= accepts None/str/list, got "
                     f"{type(tenants).__name__}")


def registry_problems(specs: List[TenantSpec], *,
                      supervised: bool = False) -> List[str]:
    """Set-level legality: per-spec problems + duplicate ids + the
    supervised wall-clock rejection (the WF119 surface)."""
    out = []
    seen = set()
    for s in specs:
        out += [f"tenant[{s.id}]: {p}" for p in tenant_problems(s)]
        if s.id in seen:
            out.append(f"tenant[{s.id}]: duplicate tenant id — every "
                       f"counter/SLO/shed surface keys on it")
        seen.add(s.id)
        if supervised and s.rate_tps is not None:
            out.append(f"tenant[{s.id}]: wall-clock rate_tps under "
                       f"supervision — replay cannot re-derive clock-driven "
                       f"shed decisions; declare refill_per_batch instead "
                       f"(the PositionBucket discipline)")
    return out


class TenantRegistry:
    """Per-tenant admission controllers + the counters every plane reads.

    Built by :func:`build_registry`.  ``offer`` routes one batch through
    its tenant's controller (unknown tenants are admitted unlimited but
    counted — shedding traffic nobody declared a budget for would be a
    silent outage); ``counters`` renders the snapshot section;
    ``scale_rate`` is the per-tenant remediation actuator, and
    ``state``/``set_state`` ride the supervisor snapshot so replay re-sheds
    identically."""

    def __init__(self, specs: List[TenantSpec], controllers: Dict[str, object]):
        self.specs = list(specs)
        self._by_id = {s.id: s for s in specs}
        self._controllers = controllers         # tenant id -> controller|None
        # single-writer: the serving drive loop is one thread; the Reporter
        # only reads the ints (torn reads are fine for gauges)
        self._offered: Dict[str, int] = {s.id: 0 for s in specs}
        #: restore-spanning shed tuple totals, advanced by per-offer deltas
        #: of the controller's own shed ledger (ctl.shed_tuples) — NEVER by
        #: inferring shed from an empty offer() return, which conflates
        #: shed with held under drop_oldest_ts
        self._shed_tuples: Dict[str, int] = {s.id: 0 for s in specs}
        self.unknown_offered = 0

    @property
    def ids(self) -> List[str]:
        return [s.id for s in self.specs]

    def offer(self, tenant: str, batch, pos=None) -> list:
        ctl = self._controllers.get(tenant)
        if tenant not in self._by_id:
            self.unknown_offered += 1
            return [batch]
        self._offered[tenant] += 1
        if ctl is None:                         # declared, rate-unlimited
            return [batch]
        before = ctl.shed_tuples
        admitted = ctl.offer(batch, pos=pos, stream=tenant)
        # the controller's shed ledger is the only truth: an empty return
        # does NOT mean shed (drop_oldest_ts holds the batch for a later
        # offer()/drain() to admit), and a non-empty return may have shed
        # an older held batch
        self._shed_tuples[tenant] += ctl.shed_tuples - before
        return admitted

    def drain(self) -> list:
        out = []
        for ctl in self._controllers.values():
            if ctl is not None:
                out.extend(ctl.drain())
        return out

    def scale_rate(self, tenant: str, factor: float,
                   floor: float = 1.0) -> dict:
        """The ``tenant_rate`` remediation actuator: tighten ONE tenant's
        bucket, neighbors untouched (control/remediation.py binds here)."""
        ctl = self._controllers.get(tenant)
        if ctl is None:
            raise ValueError(f"tenant {tenant!r} has no rate bucket to "
                             f"scale (unknown id or rate-unlimited spec)")
        out = ctl.scale_rate(factor, floor)
        out["tenant"] = tenant
        return out

    def counters(self) -> Dict[str, dict]:
        """The ``serving.tenants`` snapshot rows (names.py::TENANT_GAUGES):
        offered/admitted/shed batch counts, shed tuple count, and the live
        bucket rate — everything the tenant SLO signals and the wf_top
        panel need."""
        out = {}
        for s in self.specs:
            ctl = self._controllers.get(s.id)
            row = {"offered": self._offered[s.id],
                   "admitted": (ctl.admitted if ctl is not None
                                else self._offered[s.id]),
                   "shed": ctl.shed if ctl is not None else 0,
                   "shed_tuples": self._shed_tuples[s.id]}
            if ctl is not None:
                row["rate"] = round(float(ctl.current_rate()), 3)
            out[s.id] = row
        return out

    # -- supervised snapshot/restore -----------------------------------

    def state(self) -> dict:
        # shed_tuples rides the registry (not the controller snapshot,
        # whose shape is pinned) — restored totals keep accumulating via
        # the per-offer delta discipline in offer()
        return {
            "tenants": {tid: ctl.state()
                        for tid, ctl in self._controllers.items()
                        if ctl is not None},
            "offered": dict(self._offered),
            "shed_tuples": dict(self._shed_tuples),
        }

    def set_state(self, st: dict) -> None:
        for tid, sub in (st.get("tenants") or {}).items():
            ctl = self._controllers.get(tid)
            if ctl is not None:
                ctl.set_state(sub)
        self._offered.update({k: int(v)
                              for k, v in (st.get("offered") or {}).items()})
        self._shed_tuples.update(
            {k: int(v) for k, v in (st.get("shed_tuples") or {}).items()})


def build_registry(tenants, base_capacity: int, *,
                   supervised: bool = False) -> Optional[TenantRegistry]:
    """Resolve + validate a tenant set and build its per-tenant controllers
    (None when tenants are off).  Raises ``ValueError`` on an unusable set
    — the validator reports the same problems as WF119 pre-run."""
    specs = resolve_tenants(tenants)
    if not specs:
        return None
    probs = registry_problems(specs, supervised=supervised)
    if probs:
        raise ValueError("invalid tenant set (the validator reports these "
                         "as WF119 before the run): " + "; ".join(probs))
    from ..control.admission import (AdmissionController, PositionBucket,
                                     TokenBucket)
    controllers: Dict[str, object] = {}
    for s in specs:
        burst = max(float(s.burst or 4 * base_capacity),
                    float(base_capacity))
        if s.refill_per_batch is not None:
            bucket = PositionBucket(s.refill_per_batch, burst)
        elif s.rate_tps is not None:
            bucket = TokenBucket(s.rate_tps, burst)
        else:
            controllers[s.id] = None            # declared, rate-unlimited
            continue
        controllers[s.id] = AdmissionController(
            bucket, s.shed_policy, driver=f"serving[{s.id}]")
    return TenantRegistry(specs, controllers)
