"""ServingRuntime — a PipeGraph chain as a long-running multi-tenant service.

The drive loop is the :class:`~windflow_tpu.runtime.pipeline.Pipeline`
discipline (one thread, batch-at-a-time, lazy monitoring resolution, the
same EOS cascade) with three serving-plane additions:

- **per-tenant admission**: each source batch is offered to its tenant's
  own :class:`~windflow_tpu.control.admission.AdmissionController`
  (``tenants.TenantRegistry``), so a noisy tenant sheds inside its OWN
  bucket; the per-tenant counters ride the snapshot's ``serving`` section,
  the tenant-labelled SLO signals read them, and the ``tenant_rate``
  remediation actuator tightens exactly one tenant's bucket.
- **hot swap**: :meth:`ServingRuntime.swap_graph` replaces the compiled
  chain at a batch boundary with zero downtime — quiesce (settle in-flight
  tiered spills; the PR 12 drain/seal stance applied at the chain level),
  warm the incoming programs BEFORE cutover (``swap_warm``, the
  autotuner's pre-compiled-ladder switch trick), carry the operator states
  across when the state pytrees are shape-identical (recompiled/equivalent
  chains — byte-identical results for tuples on either side of the cut),
  and journal the whole thing as a ``graph_swap`` span.  Swaps arrive from
  any thread (or over the wire as ``swap`` control frames naming a graph
  registered via :meth:`register_graph`) and are CONSUMED only at batch
  boundaries on the drive thread — no locking in the hot path.
- **journaled lifecycle**: ``serving_start``/``serving_end`` events frame
  the run; the snapshot carries endpoint/graph/swap/frame counters.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional, Sequence

import jax

from ..basic import DEFAULT_BATCH_SIZE
from ..observability import journal as _journal
from ..observability import tracing as _tracing
from ..runtime.pipeline import (CompiledChain, record_source_launch,
                                resolve_batch_hint)
from .config import ServingConfig, serving_problems
from .framing import DEFAULT_TENANT
from .tenants import build_registry


def _states_compatible(a, b) -> bool:
    """True when two chains' state pytrees are structurally identical
    (treedef + every leaf's shape/dtype) — the carry-state-across-a-swap
    precondition.  A swap to an incompatible graph resets state instead
    (documented; the journal span records which happened)."""
    if jax.tree.structure(a) != jax.tree.structure(b):
        return False
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        if (getattr(x, "shape", None) != getattr(y, "shape", None)
                or getattr(x, "dtype", None) != getattr(y, "dtype", None)):
            return False
    return True


class ServingRuntime:
    """Source -> ops... -> sink as a swappable, tenant-isolated service.

    Duck-compatible with :class:`Pipeline` where the observability plane
    cares (``.source``/``.chain``/``.sink`` — ``MetricsRegistry.
    register_pipeline`` walks exactly those), so every existing snapshot/
    topology/SLO surface sees a serving run as a pipeline plus a
    ``serving`` section."""

    def __init__(self, source, ops: Sequence, sink=None, *,
                 batch_size: Optional[int] = None, serving=None,
                 monitoring=None, supervised: bool = False,
                 name: str = "serving"):
        self.source = source
        self.sink = sink
        self.name = name
        if batch_size is None:
            batch_size = resolve_batch_hint(ops) or DEFAULT_BATCH_SIZE
        self.batch_size = batch_size
        self.config = ServingConfig.resolve(serving) or ServingConfig()
        self._monitoring_arg = monitoring
        self._supervised = bool(supervised)
        from ..observability import slo as _slo
        from ..observability import MonitoringConfig
        mcfg = MonitoringConfig.resolve(monitoring)
        probs = serving_problems(
            self.config, monitoring=monitoring, supervised=supervised,
            slo_specs=_slo.resolve_specs(mcfg.slo) if mcfg else None)
        if probs:
            raise ValueError("invalid serving setup (the validator reports "
                             "these as WF119 before the run): "
                             + "; ".join(probs))
        self._cap = getattr(source, "out_capacity",
                            lambda b: b)(batch_size)
        from ..observability import event_time_enabled
        self._event_time = event_time_enabled(monitoring)
        self.chain = CompiledChain(ops, source.payload_spec(),
                                   batch_capacity=self._cap,
                                   event_time=self._event_time)
        self.graph_label = "initial"
        #: named graphs a wire ``swap`` frame (or ``wf_serve swap``) may
        #: cut over to — single-writer: registered before run()
        self._graphs = {}
        #: pending swap requests, appended from ANY thread, consumed at
        #: batch boundaries on the drive thread (deque.append/popleft are
        #: atomic)                            # wf-lint: allow[unguarded]
        self._swap_queue: "collections.deque" = collections.deque()
        self.swaps_applied = 0
        self.swaps_rejected = 0
        self.registry = build_registry(
            self.config.resolved_tenants(), self._cap,
            supervised=supervised)
        self._monitor = None
        self._running = False                 # wf-lint: guarded-by[_run_lock]
        #: closes the swap_graph/run() TOCTOU: _running flips only under
        #: this lock, and swap_graph's not-running immediate apply holds it
        #: too — an apply on the caller thread can never overlap a drive
        #: loop that is just starting (or just ending)
        self._run_lock = threading.Lock()

    # -- graph management -----------------------------------------------

    def register_graph(self, label: str, ops: Sequence) -> None:
        """Name a candidate chain for wire-driven swaps (``swap`` control
        frames / ``wf_serve swap``)."""
        self._graphs[str(label)] = list(ops)

    def swap_graph(self, graph, label: Optional[str] = None) -> None:
        """Request a zero-downtime cutover to ``graph`` (an ops list, or
        the name of a registered graph).  Thread-safe: the request is
        queued and applied at the next batch boundary on the drive thread;
        when no run is live it applies immediately."""
        if isinstance(graph, str):
            label = label or graph
            ops = self._graphs.get(graph)
            if ops is None:
                raise ValueError(f"swap_graph: no graph registered under "
                                 f"{graph!r} (registered: "
                                 f"{', '.join(sorted(self._graphs)) or 'none'}"
                                 f")")
        else:
            ops = list(graph)
        self._swap_queue.append((label or f"swap{self.swaps_applied + 1}",
                                 ops))
        with self._run_lock:
            # under _run_lock either we see _running=True (the drive thread
            # consumes the queued request at its next batch boundary) or the
            # immediate apply completes before run() can flip _running and
            # start pushing batches
            if not self._running:
                self._consume_swaps()

    def _consume_swaps(self) -> None:
        """Batch-boundary swap point: drain API-queued requests plus any
        wire ``swap`` frames the socket source surfaced."""
        pop_wire = getattr(self.source, "pop_swap_request", None)
        while pop_wire is not None:
            label = pop_wire()
            if label is None:
                break
            if label in self._graphs:
                self._swap_queue.append((label, self._graphs[label]))
            else:
                self.swaps_rejected += 1
                _journal.record("graph_swap", graph=str(label),
                                rejected=True,
                                reason="unregistered graph name")
        while self._swap_queue:
            label, ops = self._swap_queue.popleft()
            self._apply_swap(label, ops)

    def _apply_swap(self, label: str, ops) -> None:
        t0 = time.perf_counter()  # wf-lint: allow[wall-clock] timing-only: swap metric
        with _journal.span("graph_swap", graph=str(label),
                           from_graph=self.graph_label):
            old = self.chain
            # quiesce: we are at a batch boundary (the only call site), so
            # the only in-flight device work is async tiered spills —
            # settle them before the old chain's states are read
            if old._tier_ops:
                old.tier_settle()
            new = CompiledChain(ops, self.source.payload_spec(),
                                batch_capacity=self._cap,
                                event_time=self._event_time)
            new.label = old.label
            if self.config.swap_warm:
                # compile the incoming programs BEFORE cutover — the swap
                # itself then only swaps pointers (the pre-compiled-ladder
                # switch trick); skipping this is a WF119 finding
                new.warm(self._cap)
            carried = _states_compatible(old.states, new.states)
            if carried:
                new.states = old.states
            self.chain = new
            self.graph_label = str(label)
            self.swaps_applied += 1
            _journal.record(
                "graph_swap", graph=str(label), applied=True,
                carried_state=carried, warmed=bool(self.config.swap_warm),
                quiesce_ms=round((time.perf_counter() - t0) * 1e3, 3))  # wf-lint: allow[wall-clock] timing-only: swap metric

    # -- observability surface ------------------------------------------

    def serving_section(self) -> dict:
        """The snapshot's ``serving`` section (``names.py``:
        SERVING_GAUGES + per-tenant TENANT_GAUGES rows)."""
        sec = {"graph": self.graph_label,
               "swaps_applied": self.swaps_applied,
               "swaps_rejected": self.swaps_rejected}
        ep = getattr(self.source, "endpoint", None)
        if ep is not None:
            sec["endpoint"] = ep
        for ctr in ("frames_decoded", "frames_torn", "frames_dup",
                    "clients_seen"):
            v = getattr(self.source, ctr, None)
            if v is not None:
                sec[ctr] = int(v)
        if self.registry is not None:
            sec["tenants"] = self.registry.counters()
            sec["unknown_offered"] = self.registry.unknown_offered
        return sec

    # -- the drive loop -------------------------------------------------

    def _bind_remediation(self, mon) -> None:
        """Bind the actuators a serving run owns: ``tenant_rate`` resolves
        the firing action's SLO spec to its tenant label and tightens THAT
        tenant's bucket only — the isolation contract."""
        if mon is None or mon.remediation is None:
            return
        if self.registry is None:
            return
        spec_by_name = {s.name: s
                        for s in (mon.slo.specs if mon.slo else [])}

        def _tenant_rate(a, _reg=self.registry, _specs=spec_by_name):
            spec = _specs.get(a.slo)
            tenant = getattr(spec, "tenant", None)
            if tenant is None:
                raise ValueError(
                    f"tenant_rate action {a.name!r}: SLO {a.slo!r} carries "
                    f"no tenant label — bind admission_rate for run-wide "
                    f"shedding instead")
            return _reg.scale_rate(tenant, a.factor, a.floor)

        mon.remediation.bind("tenant_rate", _tenant_rate)

    def run(self):
        """Drive the service to EOS (all tenants closed their streams).
        The Pipeline.run contract: returns the chain's terminal results."""
        from ..observability import Monitor, MonitoringConfig
        cfg = MonitoringConfig.resolve(self._monitoring_arg)
        if cfg is not None and self._monitor is None:
            self._monitor = Monitor(cfg, self.name)
            self._monitor.registry.register_pipeline(self)
            self._monitor.registry.attach_serving(self.serving_section)
            self._monitor.start()
        mon = self._monitor
        self._bind_remediation(mon)
        start = getattr(self.source, "start", None)
        if start is not None:
            start()
        _journal.record(
            "serving_start", runtime=self.name, graph=self.graph_label,
            endpoint=getattr(self.source, "endpoint", None),
            tenants=(self.registry.ids if self.registry is not None
                     else [DEFAULT_TENANT]))
        with self._run_lock:
            self._running = True
        try:
            n = 0
            n_offered = 0

            def drive(b, tenant=DEFAULT_TENANT, wire_s=0.0):
                nonlocal n
                sampled = (mon is not None and self.sink is not None
                           and mon.config.should_sample_e2e(n))
                t0 = time.perf_counter() if sampled else 0.0  # wf-lint: allow[wall-clock] timing-only: e2e sample
                span = _tracing.service(b, "chain")
                out = self.chain.push(b)
                if span is not None:
                    span.done()
                    _tracing.carry(b, out)
                if self.sink is not None:
                    sspan = _tracing.service(out, "sink")
                    self.sink.consume(out)
                    if sspan is not None:
                        sspan.done()
                if sampled:
                    dt = time.perf_counter() - t0  # wf-lint: allow[wall-clock] timing-only: e2e sample
                    ex = _tracing.tid_of(b)
                    mon.registry.record_e2e(dt, exemplar=ex)
                    if self.registry is not None:
                        # wire-to-sink per-tenant latency: the host service
                        # time plus the wire+source-queue segments measured
                        # at ingest (0 for unstamped/old clients) — feeds
                        # serving.tenants e2e_* and tenant_e2e_p99_ms
                        mon.registry.record_tenant_e2e(
                            tenant, dt + wire_s, exemplar=ex)
                n += 1

            # un-prefetched by design: last_tenant attribution requires
            # the drive thread to pull batches synchronously (sources.py)
            for batch in self.source.batches(self.batch_size):
                record_source_launch(self.source, batch)
                tenant = getattr(self.source, "last_tenant", DEFAULT_TENANT)
                wire = getattr(self.source, "last_wire", None)
                wire_s, extras = 0.0, None
                if wire is not None:
                    # wall clocks by design: t_send is the CLIENT's clock,
                    # t_recv this host's — a perf_counter pair could never
                    # cross the process boundary
                    t_recv = wire.get("t_recv")
                    t_send = wire.get("t_send")
                    extras = {"tenant": tenant, "seq": wire.get("seq")}
                    if t_recv is not None:
                        q_ms = max(time.time() - t_recv, 0.0) * 1e3  # wf-lint: allow[wall-clock] cross-process wire timing needs wall time
                        extras["queue_ms"] = round(q_ms, 3)
                        wire_s += q_ms / 1e3
                        if t_send is not None:
                            w_ms = max(t_recv - t_send, 0.0) * 1e3
                            extras["wire_ms"] = round(w_ms, 3)
                            wire_s += w_ms / 1e3
                    if wire.get("span") is not None:
                        extras["span"] = wire["span"]
                elif self.registry is not None:
                    extras = {"tenant": tenant}
                _tracing.ingest(batch, n_offered, extras=extras)
                self._consume_swaps()
                admitted = ([batch] if self.registry is None
                            else self.registry.offer(tenant, batch,
                                                     pos=n_offered))
                n_offered += 1
                for ab in admitted:
                    drive(ab, tenant, wire_s)
            _journal.record("eos", pipeline=self.name)
            self._consume_swaps()
            if self.registry is not None:
                for ab in self.registry.drain():
                    drive(ab)
            for out in self.chain.flush():
                if self.sink is not None:
                    self.sink.consume(out)
            if self.sink is not None:
                self.sink.consume(None)
            self.chain.sync_stats()
            _journal.record(
                "serving_end", runtime=self.name, graph=self.graph_label,
                batches=n, swaps=self.swaps_applied)
            for op in [self.source, *self.chain.ops,
                       *([self.sink] if self.sink is not None else [])]:
                op.close()
            return self.chain.result()
        finally:
            with self._run_lock:
                self._running = False
            if mon is not None:
                mon.finish(self)

    def run_background(self) -> threading.Thread:
        """Run the drive loop on a daemon thread (long-lived services; the
        caller joins or lets EOS end it).  Result/exception land on
        ``.background_result`` / ``.background_error``."""
        self.background_result = None
        self.background_error = None

        def _main():
            try:
                self.background_result = self.run()
            except BaseException as e:  # noqa: BLE001 — surfaced to joiner
                self.background_error = e

        # the spawned thread IS the drive thread — the caller hands the
        # driver role over and only joins/reads the result afterwards
        t = threading.Thread(target=_main, daemon=True,  # wf-lint: thread-role[driver]
                             name=f"wf-serve-drive[{self.name}]")
        t.start()
        return t
