"""Network ingest sources — the serving front door.

Both sources here are thin :class:`~windflow_tpu.operators.source.
RecordSource` subclasses: they only provide an ``it_factory`` that yields
numpy structured-array chunks, so EVERY downstream contract — the native
AoS->SoA transpose (``native/ingest.cpp``), ``SourceBase._frame``'s
zero-pad + progressive-id framing, ``cursor()`` checkpoints, trace-id
minting — runs unchanged.  Their factories declare ``from_batch``, so the
supervisor's ``_open_seek`` resumes them in O(1): :class:`SocketSource`
re-drives the committed-cursor gap from its bounded replay ring,
:class:`FileTailSource` seeks the file offset.

- :class:`SocketSource` — TCP/Unix listener decoding ``WFS1`` record
  frames (``serving/framing.py``: magic + resync + per-tenant seq dedup).
  One frame = one chunk = one batch, so tenant attribution is exact at
  batch granularity (``last_tenant``).  Torn bytes from a killed peer
  resync; a reconnecting client re-sending overlap is deduped by seq —
  peer kills degrade to replay, never loss or duplication.
- :class:`FileTailSource` — append-follow over a fixed-record binary file
  with rotation detection (inode change / truncation reopens at zero) and
  a marker-file EOS (``<path>.eos``).

The drive loop that consumes these sources is one thread (the Pipeline/
ServingRuntime discipline); ``last_tenant`` attribution relies on it, so
serving sources are driven un-prefetched.
"""

from __future__ import annotations

import collections
import os
import queue
import socket
import threading
import time
from typing import Dict, Optional, Sequence

import numpy as np

from ..operators.source import RecordSource
from . import framing


class SocketSource(RecordSource):
    """Length-framed record ingest over TCP or a Unix socket.

    ``endpoint`` follows the telemetry grammar (``tcp://HOST:PORT``, bare
    ``HOST:PORT``, ``unix://PATH``; port 0 binds ephemeral — read the
    resolved address back from :attr:`endpoint` after :meth:`start`).
    ``replay`` bounds the in-memory chunk ring that re-drives the
    committed-cursor gap on a supervised restart — size it to cover at
    least one checkpoint interval of chunks, or resume refuses loudly.
    ``eos_tenants`` lists the tenant ids whose ``eos`` control frames end
    the stream (default: the first ``eos`` frame from anyone ends it)."""

    def __init__(self, endpoint: str, record_dtype, *,
                 key_field: Optional[str] = None,
                 ts_field: Optional[str] = None,
                 num_keys: Optional[int] = None,
                 name: str = "socket_source", parallelism: int = 1,
                 framing_workers: int = 1, replay: int = 256,
                 eos_tenants: Optional[Sequence[str]] = None,
                 recv_bytes: int = 1 << 16):
        super().__init__(self._chunks_from_ring, record_dtype,
                         key_field=key_field, ts_field=ts_field,
                         num_keys=num_keys, name=name,
                         parallelism=parallelism,
                         framing_workers=framing_workers)
        self._parsed = framing.parse_endpoint(endpoint)
        self.endpoint = endpoint
        self.replay = max(1, int(replay))
        self.recv_bytes = int(recv_bytes)
        self._eos_needed = set(eos_tenants) if eos_tenants else None
        self._lock = threading.Lock()
        #: decoded chunks awaiting the drive loop; the ring keeps the last
        #: ``replay`` of them for gap re-drive after a supervised restart
        self._queue: "queue.Queue" = queue.Queue()
        self._ring: "collections.deque" = collections.deque(
            maxlen=self.replay)                  # wf-lint: guarded-by[_lock]
        self._next_chunk = 0                     # wf-lint: guarded-by[_lock]
        self._last_seq: Dict[str, int] = {}      # wf-lint: guarded-by[_lock]
        self._eos_seen: set = set()              # wf-lint: guarded-by[_lock]
        self._eos = threading.Event()
        self._stop = threading.Event()
        self._swaps: "collections.deque" = collections.deque()  # wf-lint: guarded-by[_lock]
        # mutated by start()/close() on the drive thread only; the accept
        # loop reads it once and a close() under its feet surfaces as a
        # clean OSError exit
        self._server: Optional[socket.socket] = None  # wf-lint: single-writer[driver]
        self._threads = []                       # wf-lint: guarded-by[_lock]
        #: wire-level counters (snapshot ``serving`` section); updated by
        #: every client thread, so increments happen under ``_lock``
        self.frames_decoded = 0                  # wf-lint: guarded-by[_lock]
        self.frames_torn = 0                     # wf-lint: guarded-by[_lock]
        self.frames_dup = 0                      # wf-lint: guarded-by[_lock]
        self.clients_seen = 0                    # wf-lint: single-writer[ingest]
        #: tenant of the chunk most recently handed to the drive loop —
        #: valid only under the single-threaded, un-prefetched drive
        #: contract (module docstring)
        self.last_tenant = framing.DEFAULT_TENANT
        #: wire timing of the most recently yielded chunk (same contract):
        #: ``{"seq", "t_send", "t_recv", "span"}`` — ``t_send``/``span``
        #: are None for frames from pre-stamp clients (meta keys absent),
        #: ``t_recv`` is this host's receipt wall time.  The drive loop
        #: turns the pair into wire/queue segment attribution for the
        #: per-tenant trace report.
        self.last_wire: Optional[dict] = None
        #: chunk idx -> wire timing, popped as chunks are yielded; bounded
        #: so a stalled drive loop can't grow it without bound
        self._wire: Dict[int, dict] = {}         # wf-lint: guarded-by[_lock]

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "SocketSource":
        """Bind + listen + spawn the acceptor; idempotent."""
        if self._server is not None:
            return self
        if self._parsed[0] == "unix":
            sk = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                os.unlink(self._parsed[1])
            except OSError:
                pass
            sk.bind(self._parsed[1])
        else:
            sk = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sk.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sk.bind((self._parsed[1], self._parsed[2]))
            host, port = sk.getsockname()[:2]
            self.endpoint = f"tcp://{host}:{port}"
        sk.listen(16)
        sk.settimeout(0.2)
        self._server = sk
        t = threading.Thread(  # wf-lint: thread-role[ingest]
            target=self._accept_loop, daemon=True,
            name=f"wf-serve-accept[{self.name}]")
        t.start()
        with self._lock:
            self._threads.append(t)
        return self

    def close(self) -> None:
        self._stop.set()
        self._eos.set()
        if self._server is not None:
            try:
                self._server.close()
            finally:
                self._server = None
            if self._parsed[0] == "unix":
                try:
                    os.unlink(self._parsed[1])
                except OSError:
                    pass
        with self._lock:
            threads, self._threads = self._threads, []
        for t in threads:
            t.join(timeout=2.0)
        super().close()

    # -- network side ---------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return                          # server socket closed
            self.clients_seen += 1
            t = threading.Thread(  # wf-lint: thread-role[ingest]
                target=self._client_loop, args=(conn,), daemon=True,
                name=f"wf-serve-client[{self.name}]")
            t.start()
            with self._lock:
                self._threads.append(t)

    def _client_loop(self, conn: socket.socket) -> None:
        dec = framing.RecordFrameDecoder()
        conn.settimeout(0.2)
        try:
            while not self._stop.is_set():
                try:
                    data = conn.recv(self.recv_bytes)
                except socket.timeout:
                    continue
                except OSError:
                    break
                if not data:                    # peer closed (or was killed)
                    break
                for meta, blob in dec.feed(data):
                    self._on_frame(meta, blob)
                # decoder counters are cumulative; publish deltas and reset
                # (under _lock — concurrent clients read-modify-write the
                # same shared counters)
                with self._lock:
                    self.frames_decoded += dec.frames_decoded
                    self.frames_torn += dec.frames_torn
                dec.frames_decoded = 0
                dec.frames_torn = 0
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _on_frame(self, meta: dict, blob: bytes) -> None:
        kind = meta.get("kind")
        tenant = str(meta.get("tenant") or framing.DEFAULT_TENANT)
        if kind == framing.KIND_SWAP:
            with self._lock:
                self._swaps.append(str(meta.get("graph") or ""))
            return
        seq = int(meta.get("seq", 0))
        with self._lock:
            last = self._last_seq.get(tenant)
            if last is not None and seq <= last:
                self.frames_dup += 1            # reconnect overlap: dedup
                return
            self._last_seq[tenant] = seq
            if kind == framing.KIND_EOS:
                self._eos_seen.add(tenant)
                done = (self._eos_needed is None
                        or self._eos_needed <= self._eos_seen)
                if done:
                    self._eos.set()
                return
            if len(blob) % self.dtype.itemsize:
                self.frames_torn += 1           # ragged record payload
                return
            rec = np.frombuffer(blob, dtype=self.dtype).copy()
            idx = self._next_chunk
            self._next_chunk += 1
            # wire receipt stamp: t_send/span ride the frame meta when the
            # client stamped them (framing.RecordClient); both are
            # attacker-supplied, so coercion failure degrades to "no stamp"
            # — never an exception out of the ingest thread
            t_send = meta.get("t_send")
            if t_send is not None:
                try:
                    t_send = float(t_send)
                except (TypeError, ValueError):
                    t_send = None
            span = meta.get("span")
            self._wire[idx] = {
                "seq": seq, "t_send": t_send,
                "t_recv": time.time(),  # wf-lint: allow[wall-clock] cross-process wire timing needs wall time
                "span": None if span is None else str(span)}
            while len(self._wire) > 4 * self.replay:
                self._wire.pop(next(iter(self._wire)))
            self._ring.append((idx, tenant, rec))
            # the put MUST stay inside the lock: with concurrent clients,
            # enqueueing outside would let a later idx land first and the
            # in-order consumer (_chunks_from_ring) would silently drop the
            # overtaken chunk; in-lock it also cannot land after an EOS
            # whose empty-queue check already passed
            self._queue.put((idx, tenant, rec))

    def pop_swap_request(self) -> Optional[str]:
        """Next pending wire swap request (ServingRuntime polls at batch
        boundaries), or None."""
        with self._lock:
            try:
                return self._swaps.popleft()
            except IndexError:
                return None

    # -- the RecordSource chunk factory --------------------------------

    def _chunks_from_ring(self, from_batch: int = 0):
        """The seekable ``it_factory``: chunks ``[from_batch, ...)`` in
        chunk-index order — ring replay first (the committed-cursor gap),
        then the live queue.  Declaring ``from_batch`` opts into
        ``SourceBase._open_seek``'s O(1) resume."""
        self.start()
        with self._lock:
            ring = list(self._ring)
            next_live = self._next_chunk
        if from_batch:
            ring_start = ring[0][0] if ring else next_live
            if from_batch < ring_start:
                raise RuntimeError(
                    f"{self.name}: resume at chunk {from_batch} but the "
                    f"replay ring starts at {ring_start} — size replay= "
                    f"(now {self.replay}) to cover at least one checkpoint "
                    f"interval of chunks")
        pos = from_batch
        for idx, tenant, rec in ring:
            if idx < pos:
                continue
            # replayed chunks were already dequeued by the pre-restart
            # incarnation; re-drive them from the ring in idx order
            self.last_tenant = tenant
            with self._lock:
                self.last_wire = self._wire.pop(idx, None)
            pos = idx + 1
            yield rec
        while True:
            # drain anything the live queue holds below pos (chunks the
            # ring already replayed) without blocking
            try:
                idx, tenant, rec = self._queue.get(timeout=0.05)
            except queue.Empty:
                if self._eos.is_set() and self._queue.empty():
                    return
                continue
            if idx < pos:
                continue
            self.last_tenant = tenant
            with self._lock:
                self.last_wire = self._wire.pop(idx, None)
            pos = idx + 1
            yield rec


class FileTailSource(RecordSource):
    """Append-follow ingest over a binary file of fixed-size records.

    Chunks of up to ``batch_records`` rows are read as the file grows
    (``poll_s`` cadence); a rotation (inode change or truncation) reopens
    at offset zero and the chunk index simply keeps counting.  EOS: create
    ``<path>.eos`` (or pass ``eos_marker=``) once the writer is done — the
    source drains to the final size and ends.  ``it_factory(from_batch=k)``
    seeks straight to ``k * batch_records`` rows: O(1) supervised resume
    against the CURRENT file incarnation (a cursor from before a rotation
    re-reads the rotated-in file — rotation resets content, not ids)."""

    def __init__(self, path: str, record_dtype, *,
                 batch_records: int = 64,
                 key_field: Optional[str] = None,
                 ts_field: Optional[str] = None,
                 num_keys: Optional[int] = None,
                 name: str = "file_tail_source", parallelism: int = 1,
                 framing_workers: int = 1, poll_s: float = 0.02,
                 eos_marker: Optional[str] = None):
        super().__init__(self._chunks_from_file, record_dtype,
                         key_field=key_field, ts_field=ts_field,
                         num_keys=num_keys, name=name,
                         parallelism=parallelism,
                         framing_workers=framing_workers)
        self.path = path
        self.batch_records = max(1, int(batch_records))
        self.poll_s = float(poll_s)
        self.eos_marker = eos_marker if eos_marker is not None \
            else path + ".eos"
        self.rotations = 0

    def _chunks_from_file(self, from_batch: int = 0):
        row = self.dtype.itemsize
        chunk_bytes = row * self.batch_records
        f = open(self.path, "rb")
        try:
            ino = os.fstat(f.fileno()).st_ino
            f.seek(from_batch * chunk_bytes)
            pending = b""
            while True:
                try:
                    st = os.stat(self.path)
                except FileNotFoundError:
                    st = None
                if st is not None and (st.st_ino != ino
                                       or st.st_size < f.tell()):
                    # rotation: a new inode, or the file shrank under us —
                    # reopen at zero; ids keep counting (the chunk index is
                    # stream position, not file position)
                    f.close()
                    f = open(self.path, "rb")
                    ino = os.fstat(f.fileno()).st_ino
                    pending = b""
                    self.rotations += 1
                data = f.read(chunk_bytes - len(pending))
                if data:
                    pending += data
                n_rows = len(pending) // row
                if n_rows and (n_rows >= self.batch_records or not data):
                    blob = pending[:n_rows * row]
                    pending = pending[n_rows * row:]
                    yield np.frombuffer(blob, dtype=self.dtype).copy()
                    continue
                if not data:
                    if os.path.exists(self.eos_marker):
                        if pending and len(pending) % row == 0:
                            yield np.frombuffer(pending,
                                                dtype=self.dtype).copy()
                        return
                    time.sleep(self.poll_s)
        finally:
            f.close()
