"""Serving front-end — network ingest, multi-tenant isolation, hot swap.

The layer that turns a compiled chain into a long-running service
(ROADMAP item 3; the source paper's live Source/Sink model with the host
as a thin ingest shim):

- ``framing.py`` — the ``WFS1`` binary record wire format (magic + resync
  + per-tenant seq dedup); stdlib-only, path-loadable (``wf_serve.py``).
- ``sources.py`` — :class:`SocketSource` / :class:`FileTailSource`, thin
  ``RecordSource`` factories riding the native SoA ingest path with O(1)
  supervised resume.
- ``tenants.py`` — :class:`TenantSpec` registry over per-tenant admission
  controllers; stdlib-only resolution/validation half, path-loadable.
- ``config.py`` — :class:`ServingConfig` (``WF_SERVE`` /
  ``WF_SERVE_ENDPOINT`` / ``WF_TENANTS``) + the shared WF119 check.
- ``runtime.py`` — :class:`ServingRuntime`: the Pipeline drive loop plus
  per-tenant admission, zero-downtime :meth:`~ServingRuntime.swap_graph`,
  and the ``serving`` snapshot section.
"""

from .config import DEFAULT_ENDPOINT, ServingConfig, serving_problems
from .framing import (DEFAULT_TENANT, FRAME_KINDS, KIND_DATA, KIND_EOS,
                      KIND_SWAP, MAGIC, RecordClient, RecordFrameDecoder,
                      connect, encode_record_frame, parse_endpoint)
from .runtime import ServingRuntime
from .sources import FileTailSource, SocketSource
from .tenants import (SHED_POLICIES, TenantRegistry, TenantSpec,
                      build_registry, registry_problems, resolve_tenants,
                      tenant_problems)

__all__ = [
    "DEFAULT_ENDPOINT", "DEFAULT_TENANT", "FRAME_KINDS", "KIND_DATA",
    "KIND_EOS", "KIND_SWAP", "MAGIC", "RecordClient", "RecordFrameDecoder",
    "SHED_POLICIES", "ServingConfig", "ServingRuntime", "SocketSource",
    "FileTailSource", "TenantRegistry", "TenantSpec", "build_registry",
    "connect", "encode_record_frame", "parse_endpoint", "registry_problems",
    "resolve_tenants", "serving_problems", "tenant_problems",
]
