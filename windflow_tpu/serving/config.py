"""Serving configuration — the ``serving=`` / ``WF_SERVE`` resolution.

One declarative object for the whole serving plane: where the front door
listens (``endpoint``), who may walk through it (``tenants`` — the
``tenants.py`` spec grammar), how deep the socket's chunk replay ring is
(``replay`` — the supervised-resume gap buffer), and whether an incoming
hot-swap chain is warmed before cutover (``swap_warm`` — compiling inside
the swap quiesce stalls live traffic, so ``False`` is a WF119 error).

Resolution follows the ``MonitoringConfig`` env convention exactly:
``serving=None`` consults ``WF_SERVE`` (``''``/``'0'`` off, ``'1'``
defaults, inline JSON / JSON file path / bare endpoint string otherwise);
``WF_SERVE_ENDPOINT`` supplies the endpoint when the config did not name
one, ``WF_TENANTS`` supplies the tenant set the same way.  All three are
read when the config resolves — at :class:`ServingRuntime` construction
or ``run()``, and by the WF119 validator with the run's exact arguments.

:func:`serving_problems` is THE shared legality check (the
``slo.spec_problems`` discipline): the :class:`ServingRuntime` constructor
raises on it, ``analysis/validate.py`` reports it as WF119 pre-run, and
``wf_lint --explain WF119`` tells its story.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional

from . import framing
from .tenants import registry_problems, resolve_tenants

DEFAULT_ENDPOINT = "tcp://127.0.0.1:0"


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Resolved serving settings for one :class:`ServingRuntime`."""

    #: where the socket front door listens (``framing.parse_endpoint``
    #: grammar; port 0 = ephemeral).  None = ``WF_SERVE_ENDPOINT`` or the
    #: loopback default — explicit config always wins over env.
    endpoint: Optional[str] = None
    #: the tenant set (``tenants.resolve_tenants`` grammar: spec list,
    #: inline JSON, file path).  None = consult ``WF_TENANTS``; resolving
    #: empty/off means single-tenant mode (everything under ``default``,
    #: never shed).
    tenants: object = None
    #: warm the incoming chain's programs BEFORE cutover (the autotuner's
    #: pre-compiled-ladder switch trick) — ``False`` compiles inside the
    #: swap quiesce, stalling live traffic: legal at runtime, WF119 pre-run
    swap_warm: bool = True
    #: SocketSource chunk replay-ring depth — must cover at least one
    #: supervised checkpoint interval of chunks for gap re-drive
    replay: int = 256

    def resolved_endpoint(self) -> str:
        if self.endpoint is not None:
            return self.endpoint
        return os.environ.get("WF_SERVE_ENDPOINT", "") or DEFAULT_ENDPOINT

    def resolved_tenants(self):
        """The tenant argument after its ``WF_TENANTS`` deferral (still the
        raw grammar — ``tenants.resolve_tenants`` turns it into specs)."""
        if self.tenants is not None:
            return self.tenants
        return os.environ.get("WF_TENANTS", "") or None

    @classmethod
    def resolve(cls, serving) -> Optional["ServingConfig"]:
        """Normalize the user-facing ``serving=`` argument.

        ``None`` consults ``WF_SERVE`` (``''``/``'0'`` off); ``False``
        forces off; ``True`` = defaults; a dict/config passes through; a
        string is inline JSON (``{...}``), a JSON file path (endswith
        ``.json``), or a bare endpoint.  Returns None when serving is
        off."""
        if serving is False:
            return None
        if isinstance(serving, ServingConfig):
            return serving
        if isinstance(serving, dict):
            return cls(**serving)
        if serving is None:
            serving = os.environ.get("WF_SERVE", "")
            if serving in ("", "0"):
                return None
        if serving is True or serving == "1":
            return cls()
        if isinstance(serving, str):
            s = serving.strip()
            if s in ("", "0"):
                return None
            if s == "1":
                return cls()
            if s.startswith("{"):
                return cls(**json.loads(s))
            if s.endswith(".json"):
                with open(s) as f:
                    return cls(**json.load(f))
            return cls(endpoint=s)
        raise ValueError(f"serving= accepts None/bool/str/dict/"
                         f"ServingConfig, got {type(serving).__name__}")


def serving_problems(cfg: Optional[ServingConfig], *, monitoring=None,
                     supervised: bool = False,
                     slo_specs=None) -> List[str]:
    """Every reason this serving setup cannot be honored — THE WF119 check.

    ``monitoring`` is the run's monitoring argument resolved exactly as the
    driver will resolve it; ``slo_specs`` the resolved SLO spec list (for
    the tenant-label cross-check); ``supervised`` rejects wall-clock tenant
    buckets (replay cannot re-derive clock-driven shed decisions)."""
    if cfg is None:
        return []
    out = []
    try:
        framing.parse_endpoint(cfg.resolved_endpoint())
    except ValueError as e:
        out.append(str(e))
    specs = None
    try:
        specs = resolve_tenants(cfg.resolved_tenants())
    except (ValueError, OSError) as e:
        out.append(f"tenants: {e}")
    if specs:
        out += registry_problems(specs, supervised=supervised)
    if int(cfg.replay) < 1:
        out.append(f"replay must be >= 1, got {cfg.replay}")
    if not cfg.swap_warm:
        out.append("swap_warm=false cuts over to an UN-WARMED chain — the "
                   "incoming programs compile inside the swap quiesce, "
                   "stalling live traffic; warm the incoming rungs before "
                   "cutover (the autotuner's pre-compiled-ladder switch "
                   "discipline)")
    from ..observability import MonitoringConfig
    try:
        mon = MonitoringConfig.resolve(monitoring)
    except (ValueError, TypeError):
        mon = None      # a broken monitoring config is WF11x's finding
    if mon is None:
        out.append("serving is on while monitoring resolves off — the "
                   "serving plane's tenant counters, SLO isolation, and "
                   "graph_swap spans all live in the monitoring snapshot/"
                   "journal (set monitoring=/WF_MONITORING)")
    ids = {s.id for s in (specs or [])}
    for spec in slo_specs or []:
        tenant = getattr(spec, "tenant", None)
        if tenant is not None and tenant not in ids:
            out.append(f"slo[{spec.name}]: tenant {tenant!r} is not a "
                       f"declared tenant id ({', '.join(sorted(ids)) or 'none'}"
                       f") — a label nobody emits idles the SLO at OK "
                       f"forever")
    return out
