"""Per-replica statistics — counterpart of ``Stats_Record`` (``wf/stats_record.hpp:50-156``).

The reference counts inputs/outputs/bytes and service times per replica, plus GPU
counters (kernels launched, H2D/D2H bytes, ``wf/stats_record.hpp:76-80``), dumped to
``log/<pid>_<op>_<replica>.log``. Here the equivalents are per-operator host-side
counters updated by the scheduler (batches are counted on host; per-tuple counts come
from batch occupancy), including device-program launches and host<->HBM transfer bytes.
Always on (cheap), dumped via ``dump_to_file`` like ``dump_toFile``
(``wf/stats_record.hpp:109-155``).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

from .observability.metrics import LogHistogram


# one record per operator replica, bumped only by the thread driving that
# replica's chain (driver or its owning segment/pipe thread); the reporter
# reads the plain int counters GIL-atomically and tolerates a one-batch lag
# (the LogHistogram field locks internally).  Recorded for the WF260 lint.
class Stats_Record:  # wf-lint: single-writer[driver, stage]
    def __init__(self, op_name: str, replica_id: int = 0):
        self.op_name = op_name
        self.replica_id = replica_id
        self.start_time = time.monotonic()
        self.inputs_received = 0
        self.bytes_received = 0
        self.outputs_sent = 0
        self.bytes_sent = 0
        self.batches_received = 0
        self.batches_sent = 0
        # device counters (reference GPU fields, wf/stats_record.hpp:76-80)
        self.num_kernels = 0          # compiled-program launches
        self.bytes_copied_hd = 0      # host -> HBM
        self.bytes_copied_dh = 0      # HBM -> host
        #: tuples discarded as OLD (behind the fired-window frontier) by TB
        #: window engines — synced from device state via ``collect_stats``
        self.tuples_dropped_old = 0
        self._service_time_sum = 0.0
        self._service_samples = 0
        #: log-bucket distribution of the sampled service times (p50/p95/p99
        #: via observability.MetricsRegistry; one bisect per SAMPLED launch)
        self.service_hist = LogHistogram()

    def record_input(self, n_tuples: int, n_bytes: int = 0):
        self.inputs_received += int(n_tuples)
        self.bytes_received += int(n_bytes)
        self.batches_received += 1

    def record_output(self, n_tuples: int, n_bytes: int = 0):
        self.outputs_sent += int(n_tuples)
        self.bytes_sent += int(n_bytes)
        self.batches_sent += 1

    def record_launch(self, service_time_s: float = None, hd_bytes: int = 0,
                      dh_bytes: int = 0, exemplar=None):
        """One compiled-program launch. ``service_time_s`` is a MEASURED
        dispatch->completion sample (the chain samples every Nth push with a
        block_until_ready so the async pipeline stays overlapped); pass None on
        unsampled launches — only real samples enter the average.
        ``exemplar`` (a trace id, when causal tracing is on) tags the
        histogram bucket the sample lands in, linking the service-time
        percentiles to a concrete batch in the flight recorder."""
        self.num_kernels += 1
        self.bytes_copied_hd += int(hd_bytes)
        self.bytes_copied_dh += int(dh_bytes)
        if service_time_s is not None:
            self._service_time_sum += float(service_time_s)
            self._service_samples += 1
            self.service_hist.record(service_time_s, exemplar=exemplar)

    @property
    def avg_service_time_us(self) -> float:
        if not self._service_samples:
            return 0.0
        return 1e6 * self._service_time_sum / self._service_samples

    def as_dict(self) -> dict:
        return {
            "operator": self.op_name,
            "replica": self.replica_id,
            "inputs_received": self.inputs_received,
            "outputs_sent": self.outputs_sent,
            "bytes_received": self.bytes_received,
            "bytes_sent": self.bytes_sent,
            "batches_received": self.batches_received,
            "batches_sent": self.batches_sent,
            "num_kernels": self.num_kernels,
            "bytes_copied_hd": self.bytes_copied_hd,
            "bytes_copied_dh": self.bytes_copied_dh,
            "tuples_dropped_old": self.tuples_dropped_old,
            "avg_service_time_us": self.avg_service_time_us,
            "service_time_us": self.service_hist.summary_us(),
            "uptime_s": time.monotonic() - self.start_time,
        }

    def dump_to_file(self, log_dir: str = "log"):
        os.makedirs(log_dir, exist_ok=True)
        path = os.path.join(log_dir,
                            f"{os.getpid()}_{self.op_name}_{self.replica_id}.json")
        with open(path, "w") as f:
            json.dump(self.as_dict(), f, indent=2)
        return path


#: the one profiler session JAX supports: who (if anyone) holds it.  Guarded
#: so a nested/concurrent ``xprof_trace`` fails with a clear message instead
#: of the raw ``start_trace`` error surfacing out of user code.
_xprof_lock = threading.Lock()
_xprof_logdir = None


@contextlib.contextmanager
def xprof_trace(logdir: str):
    """JAX profiler capture around a pipeline run — the Xprof half of the
    reference's tracing story (``TRACE_WINDFLOW`` counters are the other half;
    SURVEY §5). Produces a TensorBoard-loadable trace under ``logdir``::

        with wf.xprof_trace("/tmp/trace"):
            graph.run()

    Works on CPU and TPU backends; on TPU the trace includes per-HLO device
    timing, H2D/D2H transfers, and fusion boundaries — the ground truth behind
    the cost table in docs/ARCHITECTURE.md §5.  Pairs with the host-side
    flight recorder (``trace=`` / ``scripts/wf_trace.py``): load both files
    into Perfetto for device HLO timing beside the per-batch causal timeline.

    One session at a time: JAX's profiler is process-global, and a nested
    ``start_trace`` raises an opaque error from deep inside the profiler.
    This wrapper detects the active session FIRST and raises a
    ``RuntimeError`` that names the holder and the fix."""
    global _xprof_logdir
    import jax
    with _xprof_lock:
        if _xprof_logdir is not None:
            raise RuntimeError(
                f"xprof_trace({logdir!r}): a profiler session is already "
                f"active, capturing to {_xprof_logdir!r} — JAX supports one "
                f"trace per process; nest this region inside the existing "
                f"capture (one file is enough: the trace carries every "
                f"device event between start and stop) or close it first")
        try:
            jax.profiler.start_trace(logdir)
        except RuntimeError as e:
            # a session started OUTSIDE this wrapper (TensorBoard capture
            # button, a direct jax.profiler.start_trace) — same root cause,
            # same guidance, original error chained
            raise RuntimeError(
                f"xprof_trace({logdir!r}): jax.profiler.start_trace failed — "
                f"most likely another profiler session (TensorBoard capture, "
                f"a direct start_trace elsewhere in this process) is already "
                f"active; stop it before opening a new capture") from e
        _xprof_logdir = logdir
    try:
        yield logdir
    finally:
        # stop BEFORE releasing the guard: clearing first would open a
        # window where a concurrent xprof_trace passes the guard and hits
        # JAX's still-active profiler with the raw error again
        try:
            jax.profiler.stop_trace()
        finally:
            with _xprof_lock:
                _xprof_logdir = None
