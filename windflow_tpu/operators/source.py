"""Source — stream generation.

Counterpart of ``wf/source.hpp`` (``Source_Node::svc`` at ``:168-237``): the reference
supports an *itemized* signature ``bool(tuple&)`` (fill one tuple, return false at EOS)
and a *loop* signature ``bool(Shipper&)``, plus rich variants. Here a source produces
whole micro-batches; three flavours:

- ``GeneratorSource``: wraps a host Python generator yielding payload pytrees (numpy) —
  the general case; batches are device_put on the fly (async, double-buffered by JAX's
  dispatch).
- ``DeviceSource``: a jittable ``f(i) -> payload`` applied to the global tuple index
  array via ``vmap`` — generation happens *on device*, the idiomatic-TPU fast path for
  synthetic/benchmark streams (the reference's benchmark sources are CPU loops filling
  tuples, e.g. ``src/GPU_Tests/new_tests/benchmarks/gpu_map_stateful.cpp``).
- key/ts assignment: ``key_fn(i)``, ``ts_fn(i)`` or constants, mirroring
  ``setControlFields``.

EOS: a source declares ``total`` tuples (or the generator ends); the tail batch is
mask-padded, never shape-changed — the no-recompilation flush discipline.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..basic import routing_modes_t, DEFAULT_BATCH_SIZE
from ..batch import Batch, CTRL_DTYPE, hash_key_to_slot
from ..context import RuntimeContext
from ..meta import classify_source
from .base import Basic_Operator


def prefetch_to_device(host_batches: Iterator[Batch], depth: int = 3,
                       pause_event=None) -> Iterator[Batch]:
    """Double-buffered host->device ingest: a worker thread pulls host batches,
    starts their (asynchronous) ``jax.device_put`` transfers, and keeps up to
    ``depth`` in flight in a bounded queue — H2D transfer of batch N+1 overlaps
    device compute of batch N. This is the reference GPU operators' pinned-buffer
    ``cudaMemcpyAsync`` + double-buffering protocol (``wf/map_gpu_node.hpp:224-340``)
    at the source boundary. Exceptions in the worker re-raise at the consumer.

    ``pause_event``: optional ``threading.Event`` — while SET, the worker stops
    pulling host batches / starting new transfers (batches already in the
    bounded queue remain consumable). The backpressure governor's hook
    (``control/governor.py``): when a downstream stage falls behind, ingest
    pauses instead of piling transfers onto a congested device."""
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
    END, ERR = object(), object()
    stop = threading.Event()        # consumer gone: let the worker exit

    def put_guarded(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for hb in host_batches:
                while (pause_event is not None and pause_event.is_set()
                       and not stop.is_set()):
                    time.sleep(0.001)
                if not put_guarded(jax.device_put(hb)):
                    return
            put_guarded(END)
        except BaseException as e:      # noqa: BLE001 — re-raised at consumer
            put_guarded((ERR, e))

    threading.Thread(target=worker, daemon=True,  # wf-lint: thread-role[prefetch]
                     name="wf-prefetch").start()
    try:
        while True:
            item = q.get()
            if item is END:
                return
            if isinstance(item, tuple) and len(item) == 2 and item[0] is ERR:
                raise item[1]
            yield item
    finally:
        # runs on normal exhaustion AND on early close/GC of the generator:
        # unblocks (and thereby terminates) the worker, freeing queued batches
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break


class SourceBase(Basic_Operator):
    routing = routing_modes_t.NONE

    def batches(self, batch_size: int, cursor=None) -> Iterator[Batch]:
        """Yield the stream as device batches. ``cursor`` is an opaque resume
        token previously returned by :meth:`cursor` — the seekable-source
        contract the supervisor uses for O(1) recovery (instead of replaying
        ``pos`` batches through a fresh iterator; VERDICT r04 weak #6)."""
        raise NotImplementedError

    def out_capacity(self, batch_size: int) -> int:
        """Capacity of emitted batches (loop-flavour sources expand by fan-out)."""
        return batch_size

    def batches_prefetched(self, batch_size: int = DEFAULT_BATCH_SIZE,
                           depth: int = 3, pause_event=None) -> Iterator[Batch]:
        """The ingest-overlap path: host framing + H2D transfers run in a worker
        thread ``depth`` batches ahead of the consumer (bounded — backpressure).
        ``pause_event`` (a ``threading.Event``) suspends the worker while set —
        the backpressure governor's actuation hook."""
        host_iter = getattr(self, "_host_batches", None)
        src = host_iter(batch_size) if host_iter else self.batches(batch_size)
        return prefetch_to_device(src, depth, pause_event=pause_event)

    def payload_spec(self) -> Any:
        raise NotImplementedError

    def _ingest_key(self, key):
        """Key -> slot policy shared by every host source: hash to [0, num_keys)
        when ``num_keys`` is set (``hash(key) % n`` routing contract,
        ``wf/standard_emitter.hpp:88-99``); otherwise keys must already be integer
        slot indices."""
        if key is None:
            return None
        num_keys = getattr(self, "num_keys", None)
        if num_keys is not None:
            return hash_key_to_slot(key, num_keys)
        arr = np.asarray(key)
        if arr.dtype.kind not in "iu":
            raise TypeError(
                f"{self.name}: non-integer keys (dtype {arr.dtype}) require "
                f"num_keys=N to hash them into key slots")
        return arr

    def _open_seek(self, cursor):
        """Shared host-source resume: a cursor token is ``{"batch": k,
        "next_id": id}``. A factory that EXPLICITLY declares a parameter named
        ``from_batch`` is called with ``k`` (O(1) resume — the factory owns the
        real cursor, e.g. a file offset); any other factory is replayed with
        the first ``k`` items skipped frame-free. The opt-in-by-name contract
        matters: calling an arbitrary 1-arg factory (e.g. ``lambda seed=42``)
        with a batch index would silently resume a DIFFERENT stream. The
        progressive-id base always comes from the token — exact id continuity
        without re-measuring skipped chunks. Returns (items_to_skip, iterator)
        and primes the counters :meth:`cursor` reads."""
        import inspect
        tok = cursor or {}
        skip = int(tok.get("batch", 0))
        self._emitted = skip
        self._next_id = int(tok.get("next_id", 0))
        if skip:
            try:
                if "from_batch" in inspect.signature(self.it_factory).parameters:
                    return 0, self.it_factory(from_batch=skip)
            except (TypeError, ValueError):
                pass
        return skip, self.it_factory()

    def cursor(self):
        """Opaque resume token capturing the iteration position (valid at a
        batch boundary) for the supervisor's O(1) recovery. None = nothing
        emitted yet / not seekable — the supervisor then falls back to
        fast-forwarding a re-opened iterator. Host sources resume through
        :meth:`_open_seek`; DeviceSource overrides with index arithmetic."""
        if not getattr(self, "_emitted", 0):
            return None
        return {"batch": self._emitted, "next_id": getattr(self, "_next_id", 0)}

    def _frame(self, payload, key, ts, n: int, batch_size: int,
               next_id: int) -> Batch:
        """Shared host-batch assembly: zero-pad every column to ``batch_size``,
        assign progressive ids, mask the tail. ``payload`` is a pytree of numpy
        arrays with leading size ``n``; ``key``/``ts`` are [n] arrays or None.
        Returns a HOST batch (numpy leaves) — the caller device_puts it, so the
        prefetch path can overlap the transfer."""
        if n > batch_size:
            raise ValueError(f"{self.name}: chunk of {n} tuples > "
                             f"batch_size={batch_size}")
        pad = batch_size - n

        def pad_to(a):
            a = np.asarray(a)
            return np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
        ids = np.arange(next_id, next_id + batch_size, dtype=np.int32)
        return Batch(
            key=(pad_to(key).astype(np.int32) if key is not None
                 else np.zeros(batch_size, np.int32)),
            id=ids,
            ts=pad_to(ts).astype(np.int32) if ts is not None else ids,
            payload=jax.tree.map(pad_to, payload),
            valid=np.arange(batch_size) < n,
        )


class DeviceSource(SourceBase):
    """Synthetic on-device source: ``payload = vmap(f)(global_index)``.

    ``f`` runs inside the same compiled program as the downstream chain, so generation
    fuses with the first operators (zero host->device traffic).

    Both reference Source flavours are accepted, deduced from the signature
    (``wf/meta.hpp:49-88``, ``/root/reference/API`` SOURCE):

    - itemized ``f(i) -> payload`` — fill one tuple per index (``bool(tuple_t&)``);
    - loop ``f(i, shipper) -> None`` — push 0..``max_fanout`` tuples per index via
      :class:`~windflow_tpu.shipper.Shipper` (``bool(Shipper&)``); ``when=`` masks
      make the per-index emission count data-dependent with static shapes.
    """

    def __init__(self, fn: Callable, total: int, *, name: str = "source",
                 parallelism: int = 1, key_fn: Callable = None, ts_fn: Callable = None,
                 num_keys: int = 1, max_fanout: int = 4,
                 context: Optional[RuntimeContext] = None):
        super().__init__(name, parallelism)
        self.fn = fn
        from ..meta import classify_source_flavour
        self.is_loop, self.is_rich = classify_source_flavour(fn)
        self.total = int(total)
        self.key_fn = key_fn
        self.ts_fn = ts_fn
        self.num_keys = num_keys
        self.max_fanout = int(max_fanout)
        self.context = context or RuntimeContext(parallelism, 0)

    def out_capacity(self, batch_size: int) -> int:
        return batch_size * self.max_fanout if self.is_loop else batch_size

    def _loop_one(self, i, key, ts):
        """Loop flavour: record the pushes of one index (FlatMap-style stacking)."""
        from ..shipper import Shipper
        sh = Shipper(self.max_fanout)
        if self.is_rich:
            self.fn(i, sh, self.context)
        else:
            self.fn(i, sh)
        payloads, whens, keys, tss = sh._recorded()
        n = len(payloads)
        if n == 0:
            raise ValueError(f"{self.name}: loop source pushed nothing (need >=1 "
                             f"traced push; use when=False for no-emit)")
        F = self.max_fanout
        pay = payloads + [payloads[0]] * (F - n)
        whn = whens + [jnp.asarray(False)] * (F - n)
        ks = [k if k is not None else key for k in keys] + [key] * (F - n)
        xs = [x if x is not None else ts for x in tss] + [ts] * (F - n)
        stack = lambda seq: jax.tree.map(lambda *ls: jnp.stack(ls), *seq)
        return (stack(pay), jnp.stack(whn),
                jnp.stack([jnp.asarray(k, CTRL_DTYPE) for k in ks]),
                jnp.stack([jnp.asarray(x, CTRL_DTYPE) for x in xs]))

    def make_batch(self, start: jax.Array, batch_size: int) -> Batch:
        """Jittable: build the batch of global indices [start, start+batch_size)."""
        i = start + jnp.arange(batch_size, dtype=CTRL_DTYPE)
        key = (jax.vmap(self.key_fn)(i).astype(CTRL_DTYPE) if self.key_fn
               else (i % self.num_keys if self.num_keys > 1 else jnp.zeros_like(i)))
        ts = jax.vmap(self.ts_fn)(i).astype(CTRL_DTYPE) if self.ts_fn else i
        valid = i < self.total
        if self.is_loop:
            C, F = batch_size, self.max_fanout
            pay, when, ks, xs = jax.vmap(self._loop_one)(i, key, ts)
            flat = lambda a: a.reshape((C * F,) + a.shape[2:])
            return Batch(
                key=flat(ks),
                id=flat(i[:, None] * F + jnp.arange(F, dtype=CTRL_DTYPE)[None, :]),
                ts=flat(xs),
                payload=jax.tree.map(flat, pay),
                valid=flat(when & valid[:, None]))
        fn = (lambda x: self.fn(x, self.context)) if self.is_rich else self.fn
        payload = jax.vmap(fn)(i)
        return Batch(key=key, id=i, ts=ts, payload=payload, valid=valid)

    def payload_spec(self):
        i = jax.ShapeDtypeStruct((), CTRL_DTYPE)
        if self.is_loop:
            k = jax.ShapeDtypeStruct((), CTRL_DTYPE)
            pay, _, _, _ = jax.eval_shape(self._loop_one, i, k, k)
            # strip the fan-out axis
            return jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(s.shape[1:], s.dtype), pay)
        fn = (lambda x: self.fn(x, self.context)) if self.is_rich else self.fn
        out = jax.eval_shape(fn, i)
        return out

    def batches(self, batch_size: int = DEFAULT_BATCH_SIZE, cursor=None):
        # The stream cursor is DEVICE-RESIDENT and advanced in-program: one
        # host->device scalar upload at open (or seek), zero per batch. The
        # naive form — jnp.asarray(start) per batch — costs a 4 B H2D on every
        # push (~0.1 ms even on the CPU backend, an RTT-class cost through the
        # tunneled dev chip; profiled as a top per-batch driver term).
        if self.total > jnp.iinfo(CTRL_DTYPE).max:
            # the device cursor would silently WRAP past the dtype max inside
            # the jitted step (the old host-int form raised OverflowError);
            # fail loudly at open instead of corrupting ids mid-stream
            raise ValueError(
                f"DeviceSource total={self.total} exceeds the i32 control "
                f"dtype ({jnp.iinfo(CTRL_DTYPE).max}); chunk the stream into "
                f"multiple sources/runs")
        if not hasattr(self, "_step_jit"):
            self._step_jit = jax.jit(
                lambda c, n: (self.make_batch(c, n), c + n), static_argnums=1)
        self._pos = int(cursor or 0)            # O(1) seek: pure index arithmetic
        cur = jnp.asarray(self._pos * batch_size, CTRL_DTYPE)
        for _ in range(self._pos * batch_size, self.total, batch_size):
            # bump BEFORE yield: cursor() is read while suspended at the yield,
            # and must count the batch just handed out
            self._pos += 1
            b, cur = self._step_jit(cur, batch_size)
            yield b

    def cursor(self):
        return getattr(self, "_pos", 0)


class GeneratorSource(SourceBase):
    """Host source: wraps an iterator of payload pytrees (numpy arrays of equal leading
    size <= batch_size) or ``(payload, key, ts)`` triples. The general-ingest path.

    Arbitrary keys (strings, large/sparse ints — the reference's string-keyed tuple
    contract, ``src/mp_test_cpu`` ``*_str`` variants hashing via ``std::hash``):
    pass ``num_keys`` to hash every key into ``[0, num_keys)`` slots at ingest
    (``hash(key) % n``, ``wf/standard_emitter.hpp:88-99``). Without ``num_keys``,
    keys must already be integer slot indices."""

    def __init__(self, it_factory: Callable[[], Iterator], spec: Any, *,
                 name: str = "source", parallelism: int = 1,
                 num_keys: Optional[int] = None):
        super().__init__(name, parallelism)
        self.it_factory = it_factory
        self._spec = spec
        self.num_keys = num_keys

    def payload_spec(self):
        return self._spec

    def _host_batches(self, batch_size: int = DEFAULT_BATCH_SIZE, cursor=None):
        skip, it = self._open_seek(cursor)
        for i, item in enumerate(it):
            if i < skip:        # cheap replay skip: no framing, no transfer
                continue
            self._emitted += 1
            if isinstance(item, Batch):
                yield item
                continue
            if isinstance(item, tuple) and len(item) == 3:
                payload, key, ts = item
                key = self._ingest_key(key)
            else:
                payload, key, ts = item, None, None
            n = np.shape(jax.tree.leaves(payload)[0])[0]
            # advance counters BEFORE yield: cursor() is read at the suspension
            nid = self._next_id
            self._next_id += n
            yield self._frame(payload, key, ts, n, batch_size, nid)

    def batches(self, batch_size: int = DEFAULT_BATCH_SIZE, cursor=None):
        for hb in self._host_batches(batch_size, cursor=cursor):
            yield jax.device_put(hb)


class RecordSource(SourceBase):
    """AoS record ingest: wraps an iterator of numpy *structured arrays* (the framing
    of network/disk streams — one record per row) and transposes each chunk to SoA
    columns in one native C pass (``windflow_tpu/native/ingest.cpp::wf_unpack_records``
    — the counterpart of the reference's per-tuple Source/Shipper copy,
    ``wf/source.hpp:184``). Control fields come from named record fields:
    ``key_field`` (hashed to ``[0, num_keys)`` natively when non-integer),
    ``ts_field`` (default: tuple index). Remaining fields become the payload."""

    def __init__(self, it_factory: Callable[[], Iterator[np.ndarray]],
                 record_dtype: np.dtype, *, key_field: Optional[str] = None,
                 ts_field: Optional[str] = None, num_keys: Optional[int] = None,
                 name: str = "record_source", parallelism: int = 1,
                 framing_workers: int = 1):
        super().__init__(name, parallelism)
        self.it_factory = it_factory
        #: >1 shards the AoS->SoA transpose over threads (native pass per row
        #: slice, GIL released) — the reference's 1-14 source-thread sweep
        #: applied to framing; None = hardware_concurrency()
        self.framing_workers = framing_workers
        self.dtype = np.dtype(record_dtype)
        for role, fname in (("key_field", key_field), ("ts_field", ts_field)):
            if fname is not None and fname not in (self.dtype.names or ()):
                raise ValueError(f"{name}: {role}='{fname}' is not a field of "
                                 f"{self.dtype} (fields: {self.dtype.names})")
        self.key_field = key_field
        self.ts_field = ts_field
        self.num_keys = num_keys
        self.payload_fields = [f for f in self.dtype.names
                               if f not in (key_field, ts_field)]
        if not self.payload_fields:
            raise ValueError(f"{name}: no payload fields left in {self.dtype}")
        for f in self.payload_fields:
            fdt = self.dtype.fields[f][0]
            base = fdt.subdtype[0] if fdt.subdtype else fdt
            if base.kind not in "biufc":
                raise TypeError(
                    f"{name}: payload field '{f}' has dtype {base} — only numeric/"
                    f"bool fields can become device arrays (route string fields "
                    f"through key_field=, or drop them from the record dtype)")

    def payload_spec(self):
        spec = {}
        for f in self.payload_fields:
            fdt = self.dtype.fields[f][0]
            base, shape = ((fdt.subdtype[0], fdt.subdtype[1]) if fdt.subdtype
                           else (fdt, ()))
            spec[f] = jax.ShapeDtypeStruct(shape, jnp.dtype(base))
        return spec

    def _host_batches(self, batch_size: int = DEFAULT_BATCH_SIZE, cursor=None):
        from ..native import parallel_unpack, unpack_records
        unpack = (unpack_records if self.framing_workers == 1 else
                  lambda r: parallel_unpack(r, workers=self.framing_workers))
        skip, it = self._open_seek(cursor)
        for i, rec in enumerate(it):
            if i < skip:        # cheap replay skip: no unpack, no framing
                continue
            self._emitted += 1
            rec = np.asarray(rec, self.dtype)
            n = rec.shape[0]
            cols = unpack(rec)
            key = (self._ingest_key(cols[self.key_field])
                   if self.key_field else None)
            ts = cols[self.ts_field] if self.ts_field else None
            payload = {f: cols[f] for f in self.payload_fields}
            nid = self._next_id
            self._next_id += n
            yield self._frame(payload, key, ts, n, batch_size, nid)

    def batches(self, batch_size: int = DEFAULT_BATCH_SIZE, cursor=None):
        for hb in self._host_batches(batch_size, cursor=cursor):
            yield jax.device_put(hb)


# reference-style alias
Source = DeviceSource
