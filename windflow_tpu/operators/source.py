"""Source — stream generation.

Counterpart of ``wf/source.hpp`` (``Source_Node::svc`` at ``:168-237``): the reference
supports an *itemized* signature ``bool(tuple&)`` (fill one tuple, return false at EOS)
and a *loop* signature ``bool(Shipper&)``, plus rich variants. Here a source produces
whole micro-batches; three flavours:

- ``GeneratorSource``: wraps a host Python generator yielding payload pytrees (numpy) —
  the general case; batches are device_put on the fly (async, double-buffered by JAX's
  dispatch).
- ``DeviceSource``: a jittable ``f(i) -> payload`` applied to the global tuple index
  array via ``vmap`` — generation happens *on device*, the idiomatic-TPU fast path for
  synthetic/benchmark streams (the reference's benchmark sources are CPU loops filling
  tuples, e.g. ``src/GPU_Tests/new_tests/benchmarks/gpu_map_stateful.cpp``).
- key/ts assignment: ``key_fn(i)``, ``ts_fn(i)`` or constants, mirroring
  ``setControlFields``.

EOS: a source declares ``total`` tuples (or the generator ends); the tail batch is
mask-padded, never shape-changed — the no-recompilation flush discipline.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..basic import routing_modes_t, DEFAULT_BATCH_SIZE
from ..batch import Batch, CTRL_DTYPE, hash_key_to_slot
from ..context import RuntimeContext
from ..meta import classify_source
from .base import Basic_Operator


class SourceBase(Basic_Operator):
    routing = routing_modes_t.NONE

    def batches(self, batch_size: int) -> Iterator[Batch]:
        raise NotImplementedError

    def payload_spec(self) -> Any:
        raise NotImplementedError


class DeviceSource(SourceBase):
    """Synthetic on-device source: ``payload = vmap(f)(global_index)``.

    ``f`` runs inside the same compiled program as the downstream chain, so generation
    fuses with the first operators (zero host->device traffic)."""

    def __init__(self, fn: Callable, total: int, *, name: str = "source",
                 parallelism: int = 1, key_fn: Callable = None, ts_fn: Callable = None,
                 num_keys: int = 1, context: Optional[RuntimeContext] = None):
        super().__init__(name, parallelism)
        self.fn = fn
        self.is_rich = classify_source(fn)
        self.total = int(total)
        self.key_fn = key_fn
        self.ts_fn = ts_fn
        self.num_keys = num_keys
        self.context = context or RuntimeContext(parallelism, 0)

    def make_batch(self, start: jax.Array, batch_size: int) -> Batch:
        """Jittable: build the batch of global indices [start, start+batch_size)."""
        i = start + jnp.arange(batch_size, dtype=CTRL_DTYPE)
        fn = (lambda x: self.fn(x, self.context)) if self.is_rich else self.fn
        payload = jax.vmap(fn)(i)
        key = (jax.vmap(self.key_fn)(i).astype(CTRL_DTYPE) if self.key_fn
               else (i % self.num_keys if self.num_keys > 1 else jnp.zeros_like(i)))
        ts = jax.vmap(self.ts_fn)(i).astype(CTRL_DTYPE) if self.ts_fn else i
        valid = i < self.total
        return Batch(key=key, id=i, ts=ts, payload=payload, valid=valid)

    def payload_spec(self):
        i = jax.ShapeDtypeStruct((), CTRL_DTYPE)
        fn = (lambda x: self.fn(x, self.context)) if self.is_rich else self.fn
        out = jax.eval_shape(fn, i)
        return out

    def batches(self, batch_size: int = DEFAULT_BATCH_SIZE):
        make = jax.jit(self.make_batch, static_argnums=1)
        for start in range(0, self.total, batch_size):
            yield make(jnp.asarray(start, CTRL_DTYPE), batch_size)


class GeneratorSource(SourceBase):
    """Host source: wraps an iterator of payload pytrees (numpy arrays of equal leading
    size <= batch_size) or ``(payload, key, ts)`` triples. The general-ingest path.

    Arbitrary keys (strings, large/sparse ints — the reference's string-keyed tuple
    contract, ``src/mp_test_cpu`` ``*_str`` variants hashing via ``std::hash``):
    pass ``num_keys`` to hash every key into ``[0, num_keys)`` slots at ingest
    (``hash(key) % n``, ``wf/standard_emitter.hpp:88-99``). Without ``num_keys``,
    keys must already be integer slot indices."""

    def __init__(self, it_factory: Callable[[], Iterator], spec: Any, *,
                 name: str = "source", parallelism: int = 1,
                 num_keys: Optional[int] = None):
        super().__init__(name, parallelism)
        self.it_factory = it_factory
        self._spec = spec
        self.num_keys = num_keys

    def _ingest_key(self, key):
        if key is None:
            return None
        if self.num_keys is not None:
            return hash_key_to_slot(key, self.num_keys)
        arr = np.asarray(key)
        if arr.dtype.kind not in "iu":
            raise TypeError(
                f"{self.name}: non-integer keys (dtype {arr.dtype}) require "
                "GeneratorSource(..., num_keys=N) to hash them into key slots")
        return arr

    def payload_spec(self):
        return self._spec

    def batches(self, batch_size: int = DEFAULT_BATCH_SIZE):
        next_id = 0
        for item in self.it_factory():
            if isinstance(item, Batch):
                yield item
                continue
            if isinstance(item, tuple) and len(item) == 3:
                payload, key, ts = item
                key = self._ingest_key(key)
            else:
                payload, key, ts = item, None, None
            n = np.shape(jax.tree.leaves(payload)[0])[0]
            if n > batch_size:
                raise ValueError(f"generator yielded {n} > batch_size={batch_size}")
            pad = batch_size - n

            def pad_to(a):
                a = np.asarray(a)
                return np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
            ids = np.arange(next_id, next_id + batch_size, dtype=np.int32)
            next_id += n
            yield Batch(
                key=jnp.asarray(pad_to(key) if key is not None else np.zeros(batch_size, np.int32)),
                id=jnp.asarray(ids),
                ts=jnp.asarray(pad_to(ts) if ts is not None else ids),
                payload=jax.tree.map(lambda a: jnp.asarray(pad_to(a)), payload),
                valid=jnp.asarray(np.arange(batch_size) < n),
            )


# reference-style alias
Source = DeviceSource
