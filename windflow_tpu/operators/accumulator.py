"""Accumulator — per-key rolling reduce.

Counterpart of ``wf/accumulator.hpp`` (class at ``:61``, per-key state map
``:103-104``): ``void(const tuple&, result&)`` folds each tuple into its key's
accumulator (seeded with ``init_value``) and emits the updated value per input tuple;
routing is always KEYBY (``wf/pipegraph.hpp:1817-1820``).

TPU formulation: the per-key accumulator table lives in HBM (``[K, ...]``); each batch
runs a *segmented inclusive prefix scan* in stream order carrying the table in
(associative combines — sort-by-key + ``associative_scan`` + unsort, see
``ops/segment.py``), then scatters each key's last value back. For non-associative
fold functions the general per-rank round loop of ``KeyedMap`` applies; the common
streaming aggregations (sum/count/min/max — YSB counts campaigns) are associative.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..basic import routing_modes_t, DEFAULT_MAX_KEYS
from ..batch import Batch, tuple_refs
from ..ops.segment import segment_prefix_scan, segment_reduce
from .base import Basic_Operator


class Accumulator(Basic_Operator):
    """Associative rolling reduce.

    ``value_fn(t) -> pytree`` extracts the value to fold from each tuple;
    ``combine(a, b) -> pytree`` is the associative fold (default add);
    ``init_value`` seeds every key (reference init_value ctor arg).
    Emits per input tuple the post-fold accumulator (payload = accumulator pytree)."""

    routing = routing_modes_t.KEYBY

    def __init__(self, value_fn: Callable, *, init_value: Any = 0.0,
                 combine: Callable = None, identity: Any = 0,
                 num_keys: int = DEFAULT_MAX_KEYS, name: str = "accumulator",
                 parallelism: int = 1):
        super().__init__(name, parallelism)
        self.value_fn = value_fn
        self.combine = combine or jnp.add
        self.identity = identity
        self.init_value = init_value
        self.num_keys = int(num_keys)

    def init_state(self, payload_spec: Any):
        val = jax.eval_shape(self.value_fn, _ref_spec(payload_spec))
        return jax.tree.map(
            lambda s: jnp.broadcast_to(jnp.asarray(self.init_value, s.dtype),
                                       (self.num_keys,) + s.shape).copy(), val)

    def out_spec(self, payload_spec: Any) -> Any:
        return jax.eval_shape(self.value_fn, _ref_spec(payload_spec))

    def apply(self, state, batch: Batch):
        vals = jax.vmap(self.value_fn)(tuple_refs(batch))
        # inclusive per-key prefix in stream order, seeded by the HBM table
        prefix = segment_prefix_scan(vals, batch.key, batch.valid, self.combine,
                                     self.identity, carry_in=state)
        # update the table with each key's total fold for this batch
        batch_red = segment_reduce(vals, batch.key, batch.valid, self.num_keys,
                                   combine=None if self.combine is jnp.add else self.combine,
                                   identity=self.identity)
        if self.combine is jnp.add:
            state = jax.tree.map(jnp.add, state, batch_red)
        else:
            touched = segment_reduce(
                jnp.ones_like(batch.key), batch.key, batch.valid, self.num_keys) > 0
            state = jax.tree.map(
                lambda t, r: jnp.where(
                    touched.reshape(touched.shape + (1,) * (r.ndim - 1)),
                    self.combine(t, r), t),
                state, batch_red)
        return state, batch.with_payload(prefix)


def _ref_spec(payload_spec):
    from ..batch import TupleRef
    return TupleRef(key=jax.ShapeDtypeStruct((), jnp.int32),
                    id=jax.ShapeDtypeStruct((), jnp.int32),
                    ts=jax.ShapeDtypeStruct((), jnp.int32), data=payload_spec)
