"""Parallel window patterns: Win_Farm, Key_Farm, Key_FFAT, Pane_Farm, Win_MapReduce.

The reference implements each pattern as a distinct thread topology around ``Win_Seq``
workers (``wf/win_farm.hpp``, ``wf/key_farm.hpp``, ``wf/key_ffat.hpp``,
``wf/pane_farm.hpp``, ``wf/win_mapreduce.hpp``). On TPU the *batched window axis* plays
the role of the worker pool — every fired window is a row processed in parallel by one
compiled program — so each pattern reduces to a configuration/composition of the
vectorized engines plus a sharding recipe for multi-chip (``parallel/sharding.py``):

- **Win_Farm** (``wf/win_farm.hpp:65-666``): N replicas each own every N-th window
  (private slide = slide*N, ``:165-175``), fed by a multicast WF_Emitter
  (``wf/wf_nodes.hpp:110-204``). Here: windows are already independent rows of the
  [W] axis — "ownership" is row index; multi-chip shards the W axis (window w on
  device w % p — the emitter arithmetic as a sharding rule). No tuple multicast
  exists because the archive is shared in HBM rather than copied per replica.
- **Key_Farm** (``wf/key_farm.hpp:68-641``): whole keys routed to replicas
  (KF_Emitter, ``wf/kf_nodes.hpp:43-111``). Here: the [K] state axis; multi-chip
  shards the key-state tables (key k on device hash(k) % p).
- **Key_FFAT** (``wf/key_ffat.hpp:65-246``): Key_Farm whose workers are Win_SeqFFAT —
  directly ``Win_SeqFFAT`` with key-axis sharding.
- **Pane_Farm** (``wf/pane_farm.hpp:66-1012``): pane decomposition, PLQ computes
  pane partials (pane_len = gcd(win, slide), ``:175``), WLQ combines pane results
  per window. Here: PLQ = tumbling Win_Seq over panes, WLQ = Win_Seq over the pane
  result stream — two engines fused in one compiled program (the LEVEL2 flattening,
  ``:222-260``, is the default and only mode).
- **Win_MapReduce** (``wf/win_mapreduce.hpp:63-1002``): each window's content is
  round-robin partitioned across ``map_parallelism`` workers (WinMap_Emitter,
  ``wf/wm_nodes.hpp:45-181``), partials reduced. Here: gather the window row [L],
  reshape to [M, L/M] partitions, vmap MAP over partitions, tree-reduce with REDUCE —
  all inside the window-axis vmap; multi-chip shards the M axis with a psum-style
  combine over ICI.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..basic import routing_modes_t, role_t, pattern_t, DEFAULT_MAX_KEYS
from ..batch import Batch, CTRL_DTYPE, TupleRef
from .base import Basic_Operator
from .window import Iterable, WindowSpec
from .win_seq import Win_Seq
from .win_seqffat import Win_SeqFFAT


def _check_nesting_args(outer: str, args, kw) -> None:
    """The nesting ctors take only parallelism/name — the window geometry and key
    capacity belong to the inner pattern (as in the reference, where the outer farm
    replicates the inner pattern verbatim, ``wf/win_farm.hpp:266-355``). Reject
    anything else rather than silently ignoring it."""
    extra = [repr(a) for a in args] + [k for k in kw if k not in ("parallelism", "name")]
    if extra:
        raise TypeError(
            f"{outer}(inner_pattern, ...): nesting accepts only parallelism= and "
            f"name= — the window spec / num_keys come from the inner pattern; got "
            f"extra argument(s): {', '.join(extra)}")


class Win_Farm(Win_Seq):
    """Keyless (or keyed) window parallelism. ``parallelism`` declares the number of
    window-axis shards for multi-chip; single-chip, the [W] axis is already the farm.
    The reference's emitter math (window w owned by replica (hash(key)%p + w) % p,
    ``wf/wf_nodes.hpp:182-204``) becomes the sharding rule of the W axis.

    Nesting (``wf/win_farm.hpp:266-355``): pass a :class:`Pane_Farm` or
    :class:`Win_MapReduce` instance as the first argument to replicate that whole
    pattern as the worker — ``Win_Farm(Pane_Farm(...))``."""

    pattern = pattern_t.WF_CPU
    shard_axis = "window"

    def __new__(cls, win_fn=None, *args, **kw):
        if isinstance(win_fn, (Pane_Farm, Win_MapReduce)):
            _check_nesting_args(cls.__name__, args, kw)
            return Nested_Farm(win_fn, shard_axis="window", pattern=pattern_t.WF_CPU,
                               parallelism=kw.get("parallelism", 1),
                               name=kw.get("name", f"win_farm[{win_fn.name}]"))
        return super().__new__(cls)

    def __init__(self, win_fn, spec: WindowSpec, *, parallelism: int = 1,
                 num_keys: int = 1, name: str = "win_farm", **kw):
        super().__init__(win_fn, spec, num_keys=num_keys, name=name,
                         parallelism=parallelism, **kw)
        self.routing = routing_modes_t.COMPLEX


class Key_Farm(Win_Seq):
    """Keyed window parallelism: keys partitioned over replicas, each key's windows
    computed sequentially in order (``wf/key_farm.hpp``). The [K] state axis is the
    farm; multi-chip shards it.

    Nesting (``wf/key_farm.hpp:155-167`` worker variants): pass a
    :class:`Pane_Farm` or :class:`Win_MapReduce` instance as the first argument."""

    pattern = pattern_t.KF_CPU
    shard_axis = "key"

    def __new__(cls, win_fn=None, *args, **kw):
        if isinstance(win_fn, (Pane_Farm, Win_MapReduce)):
            _check_nesting_args(cls.__name__, args, kw)
            return Nested_Farm(win_fn, shard_axis="key", pattern=pattern_t.KF_CPU,
                               parallelism=kw.get("parallelism", 1),
                               name=kw.get("name", f"key_farm[{win_fn.name}]"))
        return super().__new__(cls)

    def __init__(self, win_fn, spec: WindowSpec, *, parallelism: int = 1,
                 num_keys: int = DEFAULT_MAX_KEYS, name: str = "key_farm", **kw):
        super().__init__(win_fn, spec, num_keys=num_keys, name=name,
                         parallelism=parallelism, **kw)


class Key_FFAT(Win_SeqFFAT):
    """Key_Farm with FlatFAT-style associative incremental workers
    (``wf/key_ffat.hpp:65-246``): pane-partial sharing + key-axis sharding."""

    pattern = pattern_t.KFF_CPU
    shard_axis = "key"

    def __init__(self, lift, combine, *, spec: WindowSpec, parallelism: int = 1,
                 num_keys: int = DEFAULT_MAX_KEYS, name: str = "key_ffat", **kw):
        super().__init__(lift, combine, spec=spec, num_keys=num_keys, name=name,
                         parallelism=parallelism, **kw)


class Nested_Farm(Basic_Operator):
    """Composition of an outer distribution pattern (Win_Farm / Key_Farm) with an
    inner computation pattern (Pane_Farm / Win_MapReduce) — the reference's nesting
    ctors replicate the whole inner pattern as the farm worker
    (``wf/win_farm.hpp:266-355``, ``wf/key_farm.hpp:155-167``; flattened by
    ``optimize_*`` LEVEL2 into one network, ``wf/win_farm.hpp:188-230``).

    Here flattening is inherent: the inner pattern's batched window axis IS the
    worker pool, and the outer pattern contributes only the multi-chip shard axis
    ("window" for WF, "key" for KF) plus parallelism metadata."""

    def __init__(self, inner, *, shard_axis: str, pattern, parallelism: int = 1,
                 name: str | None = None):
        super().__init__(name or f"nested[{inner.name}]", parallelism)
        self.inner = inner
        self.shard_axis = shard_axis
        self.pattern = pattern
        self.routing = inner.routing
        self.spec = inner.spec
        self.num_keys = getattr(inner, "num_keys", None)

    def bind_geometry(self, batch_capacity: int) -> None:
        self.inner.bind_geometry(batch_capacity)

    def out_capacity(self, in_capacity: int) -> int:
        return self.inner.out_capacity(in_capacity)

    def init_state(self, payload_spec: Any):
        return self.inner.init_state(payload_spec)

    def out_spec(self, payload_spec: Any) -> Any:
        return self.inner.out_spec(payload_spec)

    def apply(self, state, batch: Batch):
        return self.inner.apply(state, batch)

    def flush(self, state):
        return self.inner.flush(state)

    def set_window_sharding(self, mesh, axis: str) -> None:
        if hasattr(self.inner, "set_window_sharding"):
            self.inner.set_window_sharding(mesh, axis)


class Pane_Farm(Basic_Operator):
    """Pane decomposition (Li et al. SIGMOD'05; ``wf/pane_farm.hpp``).

    ``plq_fn(pane_id, iterable) -> pane_result`` runs once per pane;
    ``wlq_fn(wid, iterable_of_pane_results) -> result`` combines the panes of each
    window. Sliding windows only (slide < win_len, enforced like ``:170-173``).
    Composed of two vectorized engines executing in the same program."""

    routing = routing_modes_t.KEYBY
    pattern = pattern_t.PF_CPU

    def __init__(self, plq_fn: Callable, wlq_fn: Callable, spec: WindowSpec, *,
                 num_keys: int = DEFAULT_MAX_KEYS, name: str = "pane_farm",
                 plq_parallelism: int = 1, wlq_parallelism: int = 1, **kw):
        import math
        super().__init__(name, max(plq_parallelism, wlq_parallelism))
        if spec.slide >= spec.win_len:
            raise ValueError("Pane_Farm requires sliding windows (slide < win_len), "
                             "wf/pane_farm.hpp:170-173")
        self.spec = spec
        self.num_keys = num_keys
        self.shard_axis = "key"
        self.pane_len = math.gcd(spec.win_len, spec.slide)
        self.wpanes = spec.win_len // self.pane_len
        self.spanes = spec.slide // self.pane_len
        # PLQ: tumbling windows of one pane, same window type as the outer spec
        plq_spec = WindowSpec(self.pane_len, self.pane_len, spec.wtype, spec.delay)
        self.plq = Win_Seq(plq_fn, plq_spec, num_keys=num_keys, role=role_t.PLQ,
                           name=f"{name}_plq", **kw)
        # WLQ consumes the pane-result stream: CB windows counted in pane results
        # (panes arrive per key in ascending order without gaps for CB; for TB, pane
        # results carry ts = pane end time and WLQ windows stay time-based)
        if spec.is_cb:
            wlq_spec = WindowSpec(self.wpanes, self.spanes)
        else:
            wlq_spec = WindowSpec(spec.win_len, spec.slide, spec.wtype)
        self.wlq = Win_Seq(wlq_fn, wlq_spec, num_keys=num_keys, role=role_t.WLQ,
                           name=f"{name}_wlq")
        self._wlq_id_fix = spec.is_cb

    def bind_geometry(self, batch_capacity: int) -> None:
        self.plq.bind_geometry(batch_capacity)
        self.wlq.bind_geometry(self.plq.out_capacity(batch_capacity))

    def out_capacity(self, in_capacity: int) -> int:
        return self.wlq.out_capacity(self.plq.out_capacity(in_capacity))

    def init_state(self, payload_spec: Any):
        return {"plq": self.plq.init_state(payload_spec),
                "wlq": self.wlq.init_state(self.plq.out_spec(payload_spec))}

    def out_spec(self, payload_spec: Any) -> Any:
        return self.wlq.out_spec(self.plq.out_spec(payload_spec))

    def set_window_sharding(self, mesh, axis: str) -> None:
        self.plq.set_window_sharding(mesh, axis)
        self.wlq.set_window_sharding(mesh, axis)

    # Pane results enter WLQ directly: Win_Seq already stamps TB pane results
    # with the pane close time, so no ts fix-up is needed between the stages.

    def apply(self, state, batch: Batch):
        st_p, panes = self.plq.apply(state["plq"], batch)
        st_w, out = self.wlq.apply(state["wlq"], panes)
        return {"plq": st_p, "wlq": st_w}, out

    def flush(self, state):
        st_p, panes = self.plq.flush(state["plq"])
        if panes is not None:
            st_w, out = self.wlq.apply(state["wlq"], panes)
            return {"plq": st_p, "wlq": st_w}, out
        st_w, out = self.wlq.flush(state["wlq"])
        return {"plq": st_p, "wlq": st_w}, out


class Win_MapReduce(Basic_Operator):
    """Window partitioning: each window's content is split round-robin across
    ``map_parallelism`` partitions, MAP computes per-partition partials, REDUCE
    combines them (``wf/win_mapreduce.hpp:63-230``, emitters ``wf/wm_nodes.hpp``).

    ``map_fn(wid, iterable) -> partial`` per partition;
    ``reduce_fn(wid, iterable_of_partials) -> result`` over the M partials.
    Supports CB and TB windows: partitioning is round-robin by window-row position
    (the reference scatters by arrival order, ``wf/wm_nodes.hpp:45-181``; its TB
    nesting case broadcasts + drops to the same effect, ``wf/pipegraph.hpp:1922-1930``
    — here the mask-aware row makes both cases the same reshape)."""

    routing = routing_modes_t.KEYBY
    pattern = pattern_t.WMR_CPU

    def __init__(self, map_fn: Callable, reduce_fn: Callable, spec: WindowSpec, *,
                 map_parallelism: int = 2, num_keys: int = DEFAULT_MAX_KEYS,
                 name: str = "win_mapreduce", **kw):
        super().__init__(name, map_parallelism)
        if map_parallelism < 2:
            raise ValueError("Win_MapReduce requires map_parallelism >= 2 "
                             "(wf/win_mapreduce.hpp:160-166)")
        self.spec = spec
        self.M = int(map_parallelism)
        self.map_fn = map_fn
        self.reduce_fn = reduce_fn
        self.num_keys = num_keys
        self.shard_axis = "key"
        # the underlying archive/firing machinery is a Win_Seq whose window function
        # does partition-map + reduce inside the per-window vmap
        self.engine = Win_Seq(self._window_fn, spec, num_keys=num_keys,
                              name=f"{name}_engine", role=role_t.MAP, **kw)

    def _window_fn(self, wid, it: Iterable):
        M = self.M
        L = it.mask.shape[0]                  # static row length (win_len for CB,
        P = -(-L // M)                        # archive ring for TB); pad to P*M
        def part(a):
            pad = [(0, P * M - L)] + [(0, 0)] * (a.ndim - 1)
            a = jnp.pad(a, pad) if P * M != L else a
            # round-robin: partition p gets positions p, p+M, p+2M, ...
            # (WinMap_Emitter scatter): reshape [PM] -> [P, M] -> transpose [M, P]
            return jnp.swapaxes(a.reshape((P, M) + a.shape[1:]), 0, 1)
        sub = Iterable(data=jax.tree.map(part, it.data), ids=part(it.ids),
                       ts=part(it.ts), mask=part(it.mask))
        partials = jax.vmap(lambda s: self.map_fn(wid, s))(sub)
        # REDUCE over the M partials (CB window of length M in the reference,
        # wf/win_mapreduce.hpp:180-230). A partition that received no tuples
        # contributes no partial — mask it out so identity values (e.g. 0 from an
        # empty sum) can't poison non-sum reduces like min.
        red_it = Iterable(
            data=partials,
            ids=jnp.arange(M, dtype=CTRL_DTYPE),
            ts=jnp.broadcast_to(jnp.asarray(0, CTRL_DTYPE), (M,)),
            mask=jnp.any(part(it.mask), axis=1))
        return self.reduce_fn(wid, red_it)

    def bind_geometry(self, batch_capacity: int) -> None:
        self.engine.bind_geometry(batch_capacity)

    def out_capacity(self, in_capacity: int) -> int:
        return self.engine.out_capacity(in_capacity)

    def init_state(self, payload_spec: Any):
        return self.engine.init_state(payload_spec)

    def out_spec(self, payload_spec: Any) -> Any:
        return self.engine.out_spec(payload_spec)

    def set_window_sharding(self, mesh, axis: str) -> None:
        self.engine.set_window_sharding(mesh, axis)

    def apply(self, state, batch: Batch):
        return self.engine.apply(state, batch)

    def flush(self, state):
        return self.engine.flush(state)
