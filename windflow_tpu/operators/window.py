"""Windowing core: window descriptors, triggerers, the Iterable view.

Counterparts of ``wf/window.hpp`` (``Triggerer_CB`` ``:48-80``, ``Triggerer_TB``
``:83-121``, ``Window`` ``:124-298``), ``wf/stream_archive.hpp`` and
``wf/iterable.hpp``. The reference triggers one window event per tuple; here the same
arithmetic is *batch-level*:

- CB window ``w`` covers per-key arrival positions ``[w*slide, w*slide + win_len)``;
  a key with ``count`` archived tuples has every window with
  ``w*slide + win_len <= count`` FIRED (``Triggerer_CB`` semantics).
- TB window ``w`` covers timestamps ``[w*slide, w*slide + win_len)``; with per-key
  watermark ``wm`` (max ts seen) and lateness ``delay``, every window with
  ``w*slide + win_len <= wm - delay + 1`` is FIRED; tuples older than a fired+purged
  window are OLD and dropped (``Triggerer_TB`` semantics incl. ``triggering_delay``).

:class:`WindowSpec` carries (win_len, slide, type, delay) — the builder-visible window
definition (``withCBWindows``/``withTBWindows``, ``wf/builders.hpp``).
:class:`Iterable` is the random-access view over one fired window's content handed to
non-incremental user functions (``wf/iterable.hpp:52-245``), mask-aware because TB
windows have variable occupancy inside a fixed capacity.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from ..basic import win_type_t


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    win_len: int
    slide: int
    wtype: win_type_t = win_type_t.CB
    delay: int = 0            # TB lateness (triggering_delay, wf/window.hpp:83-121)

    def __post_init__(self):
        if self.win_len <= 0 or self.slide <= 0:
            raise ValueError("win_len and slide must be positive")
        if self.delay < 0:
            raise ValueError("delay (lateness) must be >= 0")

    @staticmethod
    def session(gap: int, delay: int = 0) -> "WindowSpec":
        """A data-dependent-gap session window: a per-key session stays open
        while consecutive events arrive within ``gap`` time units of each
        other and closes once the gap is exceeded. Unlike the CB/TB
        triggerers — whose firing lattice is fixed by (win_len, slide) — the
        session firing bound is a *function of the observed inter-arrival
        gaps* (:meth:`fired_session`). ``delay`` is the usual TB-style
        lateness allowance. Consumed by
        :class:`~windflow_tpu.operators.session.SessionWindow`."""
        return WindowSpec(int(gap), int(gap), win_type_t.SESSION, int(delay))

    @property
    def is_cb(self):
        return self.wtype == win_type_t.CB

    @property
    def is_session(self):
        return self.wtype == win_type_t.SESSION

    @property
    def gap(self) -> int:
        """Session inter-arrival gap (win_len doubles as the gap — a session
        is a window whose length grows with its content)."""
        return self.win_len

    # batch-level triggerer arithmetic ------------------------------------------------

    def fired_hi_cb(self, count):
        """Exclusive upper bound of FIRED window ids for a key with ``count`` tuples."""
        return jnp.maximum(0, (count - self.win_len) // self.slide + 1)

    def fired_hi_tb(self, watermark):
        """Exclusive upper bound of FIRED window ids under per-key watermark (max ts)."""
        return jnp.maximum(0, (watermark - self.delay - self.win_len) // self.slide + 1)

    def flush_hi_cb(self, count):
        """At EOS every window with any content fires (partial allowed)."""
        return jnp.where(count > 0, (count - 1) // self.slide + 1, 0)

    def flush_hi_tb(self, max_ts, has_any):
        return jnp.where(has_any, max_ts // self.slide + 1, 0)

    def fired_session(self, last_ts, watermark):
        """SESSION triggerer: whether a session whose newest event is
        ``last_ts`` is FIRED under ``watermark`` (max ts seen). The firing
        bound is data-dependent — it moves with every arrival, so unlike
        :meth:`fired_hi_tb` there is no static window-id lattice: the session
        closes exactly when no event within ``gap`` of its newest member can
        still arrive, i.e. ``watermark - delay > last_ts + gap``. Batched
        and masked like the TB path (callers evaluate it over the whole
        ``[K]`` open-session table in one fixed-shape program)."""
        return watermark - self.delay > last_ts + self.gap


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Iterable:
    """View over one fired window's content (under ``vmap``: one row).

    ``data``: payload pytree ``[L, ...]``; ``ids``/``ts``: ``[L]``; ``mask``: ``[L]``
    (False = absent slot — TB windows and EOS-flushed partial CB windows).
    Mirrors ``wf/iterable.hpp`` (begin/end/at/size) in mask-aware form."""

    data: Any
    ids: jax.Array
    ts: jax.Array
    mask: jax.Array

    def __getattr__(self, name):
        data = object.__getattribute__(self, "data")
        if isinstance(data, dict) and name in data:
            return data[name]
        raise AttributeError(name)

    def size(self):
        return jnp.sum(self.mask.astype(jnp.int32))

    def at(self, i):
        """The i-th LIVE tuple of the window in order (reference ``at``/
        ``operator[]``, ``wf/iterable.hpp``). Gather-free: one-hot select over the
        row. Out-of-range i returns zeros (mask-discipline: pair with ``size()``)."""
        from ..batch import TupleRef
        pos = jnp.cumsum(self.mask.astype(jnp.int32)) - 1
        onehot = self.mask & (pos == i)

        def pick(x):
            oh = onehot.reshape(onehot.shape + (1,) * (x.ndim - 1))
            return jnp.sum(jnp.where(oh, x, jnp.zeros((), x.dtype)), axis=0)
        return TupleRef(key=None, id=pick(self.ids), ts=pick(self.ts),
                        data=jax.tree.map(pick, self.data))

    __getitem__ = at

    def first(self):
        """First live tuple (reference begin())."""
        return self.at(0)

    def last(self):
        """Last live tuple (reference end()-1)."""
        return self.at(self.size() - 1)

    # mask-aware reductions (the common window aggregations)
    def _masked(self, v, fill):
        m = self.mask.reshape(self.mask.shape + (1,) * (v.ndim - 1))
        return jnp.where(m, v, jnp.asarray(fill, v.dtype))

    def sum(self, field=None):
        v = self.data[field] if field else self.data
        return jax.tree.map(lambda x: jnp.sum(self._masked(x, 0), axis=0), v)

    def max(self, field=None):
        v = self.data[field] if field else self.data
        return jax.tree.map(
            lambda x: jnp.max(self._masked(x, jnp.finfo(x.dtype).min
                                           if jnp.issubdtype(x.dtype, jnp.floating)
                                           else jnp.iinfo(x.dtype).min), axis=0), v)

    def min(self, field=None):
        v = self.data[field] if field else self.data
        return jax.tree.map(
            lambda x: jnp.min(self._masked(x, jnp.finfo(x.dtype).max
                                           if jnp.issubdtype(x.dtype, jnp.floating)
                                           else jnp.iinfo(x.dtype).max), axis=0), v)

    def mean(self, field=None):
        s = self.sum(field)
        n = jnp.maximum(1, self.size())
        return jax.tree.map(lambda x: x / n.astype(x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32), s)
