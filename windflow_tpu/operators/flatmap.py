"""FlatMap — one-to-many transformation.

Counterpart of ``wf/flatmap.hpp`` (class at ``:61``; per-replica Shipper member
``:90-91``): the reference signature is ``void(const tuple&, Shipper<result>&)``
(+rich). Here the same push-style API works under tracing: the user function receives a
:class:`~windflow_tpu.shipper.Shipper` and calls ``shipper.push(payload, when=...)`` up
to ``max_fanout`` times; pushes are recorded at trace time and stacked, producing an
output batch of capacity ``C * max_fanout`` with a validity mask (data-dependent counts
via the ``when`` mask — XLA-static shapes, no recompilation).

Output control fields: pushed tuples inherit the input's ``(key, ts)`` unless
overridden per push; ``id`` is re-derived downstream (windowed consumers renumber —
reference emit_counter semantics, ``wf/win_seq.hpp:433-441``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..basic import routing_modes_t
from ..batch import Batch, TupleRef, tuple_refs
from ..context import RuntimeContext
from ..meta import classify_flatmap
from ..shipper import Shipper
from .base import Basic_Operator


class FlatMap(Basic_Operator):
    def __init__(self, fn: Callable, *, max_fanout: int, name: str = "flatmap",
                 parallelism: int = 1, context: Optional[RuntimeContext] = None):
        super().__init__(name, parallelism)
        self.fn = fn
        self.is_rich = classify_flatmap(fn)
        self.max_fanout = int(max_fanout)
        self.context = context or RuntimeContext(parallelism, 0)

    def out_capacity(self, in_capacity: int) -> int:
        return in_capacity * self.max_fanout

    def _per_tuple(self, t: TupleRef):
        """Run the user fn for one tuple; returns stacked (payload[F], when[F], key[F], ts[F])."""
        sh = Shipper(self.max_fanout)
        if self.is_rich:
            self.fn(t, sh, self.context)
        else:
            self.fn(t, sh)
        payloads, whens, keys, tss = sh._recorded()
        n = len(payloads)
        if n == 0:
            raise ValueError("FlatMap function pushed nothing (need >=1 traced push; "
                             "use when=False for conditional no-emit)")
        # pad up to max_fanout with copies of slot 0, masked off
        F = self.max_fanout
        pay = payloads + [payloads[0]] * (F - n)
        whn = whens + [jnp.asarray(False)] * (F - n)
        key = [k if k is not None else t.key for k in keys] + [t.key] * (F - n)
        ts = [x if x is not None else t.ts for x in tss] + [t.ts] * (F - n)
        stack = lambda xs: jax.tree.map(lambda *ls: jnp.stack(ls), *xs)
        return (stack(pay), jnp.stack(whn),
                jnp.stack([jnp.asarray(k, jnp.int32) for k in key]),
                jnp.stack([jnp.asarray(x, jnp.int32) for x in ts]))

    def out_spec(self, payload_spec: Any) -> Any:
        t = TupleRef(key=jax.ShapeDtypeStruct((), jnp.int32),
                     id=jax.ShapeDtypeStruct((), jnp.int32),
                     ts=jax.ShapeDtypeStruct((), jnp.int32), data=payload_spec)
        out, _, _, _ = jax.eval_shape(self._per_tuple, t)
        # strip the fan-out axis
        return jax.tree.map(lambda s: jax.ShapeDtypeStruct(s.shape[1:], s.dtype), out)

    def apply(self, state, batch: Batch):
        C, F = batch.capacity, self.max_fanout
        pay, when, key, ts = jax.vmap(self._per_tuple)(tuple_refs(batch))
        flat = lambda a: a.reshape((C * F,) + a.shape[2:])
        out = Batch(
            key=flat(key),
            id=flat(jnp.broadcast_to(batch.id[:, None], (C, F)) * F
                    + jnp.arange(F, dtype=jnp.int32)[None, :]),
            ts=flat(ts),
            payload=jax.tree.map(flat, pay),
            valid=flat(when & batch.valid[:, None]),
        )
        return state, out
