"""Incremental rank operators: per-key top-N and streaming distinct.

The rank-query half of the Nexmark-class operator family (PAPER.md survey
§2.4 lists rank/distinct beside joins and sessions):

- :class:`TopN` keeps a bounded on-device leaderboard ``[K, N]`` per key and
  merges every batch's candidates with the **bitonic sort networks of
  ``ops/bitonic.py``** — one vmapped compare-exchange network per batch over
  ``[K, pow2(N + C)]`` composite keys ``(-score, id, idx)``, so the rank
  state update is a fixed-shape device program with a total order (score
  desc, id asc; the unique ``idx`` lane makes the network output equal the
  stable lexsort, the same property ``Ordering_Node`` relies on). Evicted
  candidates are counted (``topn_evictions``).
- :class:`Distinct` suppresses duplicates exactly once per distinct value:
  in-batch duplicates fall to a ``segment_rank`` first-occurrence test, and
  cross-batch duplicates probe the **JoinTable** (``ops/lookup.py``) through
  the registry's ``join_probe`` kernel before the batch's new values upsert
  (delay 0: a value is visible to every later batch).

Both states are plain pytrees — checkpoint/restore + supervised replay carry
them unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..basic import routing_modes_t, DEFAULT_MAX_KEYS
from ..batch import Batch, CTRL_DTYPE, TupleRef, tuple_refs
from ..ops.bitonic import sort_network
from ..ops.lookup import join_table_init, join_table_probe, join_table_upsert
from ..ops.segment import segment_rank
from .base import Basic_Operator

#: empty-slot score: sorts after every real candidate under the negated
#: composite key (user scores must be > INT32_MIN + 1)
TOPN_SENTINEL = -(1 << 31) + 1


def _ref_spec(payload_spec):
    s = jax.ShapeDtypeStruct((), CTRL_DTYPE)
    return TupleRef(key=s, id=s, ts=s, data=payload_spec)


class TopN(Basic_Operator):
    """Incremental per-key top-N by an i32 score.

    ``score_fn(t) -> i32`` (must be > INT32_MIN + 1). Every apply emits the
    UPDATED leaderboard rows of the keys the batch touched (``[K * N]``
    lanes: key = key slot, id = the ranked tuple's id, payload
    ``{"score", "rank"}``); ``flush`` emits the final leaderboard for every
    key. Ties break deterministically by tuple id (earlier wins)."""

    routing = routing_modes_t.KEYBY

    def __init__(self, score_fn: Callable, n: int, *,
                 num_keys: int = DEFAULT_MAX_KEYS, name: str = "topn",
                 parallelism: int = 1):
        super().__init__(name, parallelism)
        self.score_fn = score_fn
        self.n = int(n)
        self.num_keys = int(num_keys)
        if self.n < 1:
            raise ValueError(f"{name}: n must be >= 1")
        self._evict_synced = 0

    def out_capacity(self, in_capacity: int) -> int:
        return self.num_keys * self.n

    def out_spec(self, payload_spec: Any) -> Any:
        i = jax.ShapeDtypeStruct((), CTRL_DTYPE)
        return {"score": i, "rank": i}

    def init_state(self, payload_spec: Any):
        K, N = self.num_keys, self.n
        return {"score": jnp.full((K, N), TOPN_SENTINEL, jnp.int32),
                "tid": jnp.zeros((K, N), jnp.int32),
                "evict": jnp.asarray(0, jnp.int32),
                "eos": jnp.asarray(0, jnp.int32)}

    def _merge(self, state, keymat, scores, ids):
        """Merge [K, C] candidates into the [K, N] leaderboard via one
        vmapped bitonic sort network over the padded composite key."""
        K, N = self.num_keys, self.n
        cscore = jnp.where(keymat, scores[None, :], TOPN_SENTINEL)
        cid = jnp.where(keymat, ids[None, :], 0)
        alls = jnp.concatenate([state["score"], cscore], axis=1)
        alli = jnp.concatenate([state["tid"], cid], axis=1)
        L = 1 << max(1, (alls.shape[1] - 1).bit_length())
        pad = L - alls.shape[1]
        alls = jnp.pad(alls, ((0, 0), (0, pad)),
                       constant_values=TOPN_SENTINEL)
        alli = jnp.pad(alli, ((0, 0), (0, pad)))
        zero = jnp.zeros((L,), jnp.int32)
        iota = jnp.arange(L, dtype=jnp.int32)
        neg, sid, _, _ = jax.vmap(
            lambda p, s: sort_network(p, s, zero, iota))(-alls, alli)
        return -neg[:, :N], sid[:, :N]

    def apply(self, state, batch: Batch):
        K, N = self.num_keys, self.n
        refs = tuple_refs(batch)
        scores = jax.vmap(self.score_fn)(refs).astype(jnp.int32)
        keymat = ((batch.key[None, :]
                   == jnp.arange(K, dtype=jnp.int32)[:, None])
                  & batch.valid[None, :])                      # [K, C]
        filled = jnp.sum((state["score"] != TOPN_SENTINEL).astype(jnp.int32),
                         axis=1)
        cands = jnp.sum(keymat.astype(jnp.int32), axis=1)
        new_score, new_tid = self._merge(state, keymat, scores, batch.id)
        kept = jnp.sum((new_score != TOPN_SENTINEL).astype(jnp.int32),
                       axis=1)
        evict = state["evict"] + jnp.sum(filled + cands - kept)
        touched = jnp.any(keymat, axis=1)
        state = {"score": new_score, "tid": new_tid, "evict": evict,
                 "eos": state["eos"]}
        return state, self._rows(state, touched)

    def _rows(self, state, keep_key):
        K, N = self.num_keys, self.n
        flat = lambda a: a.reshape(K * N)
        keyv = jnp.repeat(jnp.arange(K, dtype=jnp.int32), N)
        rank = jnp.tile(jnp.arange(N, dtype=jnp.int32), K)
        score = flat(state["score"])
        valid = flat(keep_key[:, None]
                     & (state["score"] != TOPN_SENTINEL))
        return Batch(key=keyv, id=flat(state["tid"]),
                     ts=jnp.zeros((K * N,), jnp.int32),
                     payload={"score": score, "rank": rank}, valid=valid)

    def flush(self, state):
        import numpy as np
        if state is None or int(np.asarray(state["eos"])):
            return state, None
        state = dict(state)
        state["eos"] = jnp.asarray(1, jnp.int32)
        self.collect_stats(state)
        return state, self._rows(state, jnp.ones((self.num_keys,),
                                                 jnp.bool_))

    def collect_stats(self, state: Any = None) -> None:
        if state is None:
            return
        import numpy as np
        from ..control import _state as _cstate
        ev = int(np.asarray(state["evict"]))
        if ev > self._evict_synced:
            _cstate.bump("topn_evictions", ev - self._evict_synced)
            self._evict_synced = ev
        self._publish_stage_counters({"topn_evictions": ev})

    def event_time_stats(self, state: Any = None):
        """Watermark-map section: leaderboard fill + eviction pressure
        (TopN has no event-time frontier — scores, not timestamps)."""
        if state is None:
            return None
        import numpy as np
        filled = int((np.asarray(state["score"]) != TOPN_SENTINEL).sum())
        slots = self.num_keys * self.n
        return {"leaderboard_slots": slots,
                "leaderboard_filled": filled,
                "occupancy_pct": round(100.0 * filled / slots, 2),
                "topn_evictions": int(np.asarray(state["evict"]))}


class Distinct(Basic_Operator):
    """Pass each distinct value through exactly once.

    ``value_fn(t) -> i32`` extracts the distinctness key (default: the
    tuple's key slot; values must be > INT32_MIN). In-batch duplicates keep
    the first occurrence in ``(key, stream-position)`` order
    (``segment_rank``); cross-batch duplicates are suppressed by probing the
    JoinTable *before* the batch's new values upsert. ``num_slots`` bounds
    the distinct cardinality — overflow values are dropped from the table
    (counted in ``state["dropped"]``) and would re-emit; size it to the
    domain."""

    routing = routing_modes_t.KEYBY

    def __init__(self, value_fn: Optional[Callable] = None, *,
                 num_slots: int = DEFAULT_MAX_KEYS, name: str = "distinct",
                 parallelism: int = 1):
        super().__init__(name, parallelism)
        self.value_fn = value_fn or (lambda t: t.key)
        self.num_slots = int(num_slots)
        self._pending = None

    def bind_geometry(self, batch_capacity: int) -> None:
        self._pending = int(batch_capacity)

    def init_state(self, payload_spec: Any):
        pending = self._pending or self.num_slots
        return join_table_init(self.num_slots, pending,
                               {"one": jax.ShapeDtypeStruct((), jnp.int32)})

    def apply(self, state, batch: Batch):
        refs = tuple_refs(batch)
        dk = jax.vmap(self.value_fn)(refs).astype(jnp.int32)
        firsts = batch.valid & (segment_rank(dk, batch.valid) == 0)
        _, hit = join_table_probe(state, dk, firsts)
        keep = firsts & ~hit
        ones = jnp.ones((batch.capacity,), jnp.int32)
        state = join_table_upsert(state, dk, {"one": ones}, batch.ts,
                                  batch.id, keep, delay=0)
        return state, batch.mask(keep)

    def collect_stats(self, state: Any = None) -> None:
        if state is None:
            return
        self._publish_stage_counters(self.drop_counters(state))

    def drop_counters(self, state: Any = None) -> dict:
        if state is None:
            return {}
        import numpy as np
        return {"overflow_drops": int(np.asarray(state["dropped"]))}

    def event_time_stats(self, state: Any = None):
        """Watermark-map section: distinct-table occupancy + overflow drops
        (the delay-0 JoinTable underneath)."""
        if state is None:
            return None
        from ..ops.lookup import join_table_stats
        out = join_table_stats(state)
        out["delay"] = 0
        return out
