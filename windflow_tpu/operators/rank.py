"""Incremental rank operators: per-key top-N and streaming distinct.

The rank-query half of the Nexmark-class operator family (PAPER.md survey
§2.4 lists rank/distinct beside joins and sessions):

- :class:`TopN` keeps a bounded on-device leaderboard ``[K, N]`` per key and
  merges every batch's candidates with the **bitonic sort networks of
  ``ops/bitonic.py``** — one vmapped compare-exchange network per batch over
  ``[K, pow2(N + C)]`` composite keys ``(-score, id, idx)``, so the rank
  state update is a fixed-shape device program with a total order (score
  desc, id asc; the unique ``idx`` lane makes the network output equal the
  stable lexsort, the same property ``Ordering_Node`` relies on). Evicted
  candidates are counted (``topn_evictions``).
- :class:`Distinct` suppresses duplicates exactly once per distinct value:
  in-batch duplicates fall to a ``segment_rank`` first-occurrence test, and
  cross-batch duplicates probe the **JoinTable** (``ops/lookup.py``) through
  the registry's ``join_probe`` kernel before the batch's new values upsert
  (delay 0: a value is visible to every later batch).

Both states are plain pytrees — checkpoint/restore + supervised replay carry
them unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..basic import routing_modes_t, DEFAULT_MAX_KEYS
from ..batch import Batch, CTRL_DTYPE, TupleRef, tuple_refs
from ..ops.bitonic import sort_network
from ..ops.lookup import (count_drops, join_table_init, join_table_probe,
                          join_table_tier_evict, join_table_tier_init,
                          join_table_tier_resolve, join_table_tier_stats,
                          join_table_tier_touch, join_table_upsert)
from ..ops.segment import segment_rank
from .base import Basic_Operator
from .join import _tier_counters

#: empty-slot score: sorts after every real candidate under the negated
#: composite key (user scores must be > INT32_MIN + 1)
TOPN_SENTINEL = -(1 << 31) + 1


def _ref_spec(payload_spec):
    s = jax.ShapeDtypeStruct((), CTRL_DTYPE)
    return TupleRef(key=s, id=s, ts=s, data=payload_spec)


class TopN(Basic_Operator):
    """Incremental per-key top-N by an i32 score.

    ``score_fn(t) -> i32`` (must be > INT32_MIN + 1). Every apply emits the
    UPDATED leaderboard rows of the keys the batch touched (``[K * N]``
    lanes: key = key slot, id = the ranked tuple's id, payload
    ``{"score", "rank"}``); ``flush`` emits the final leaderboard for every
    key. Ties break deterministically by tuple id (earlier wins)."""

    routing = routing_modes_t.KEYBY

    def __init__(self, score_fn: Callable, n: int, *,
                 num_keys: int = DEFAULT_MAX_KEYS, tiered=None,
                 name: str = "topn", parallelism: int = 1):
        super().__init__(name, parallelism)
        self.score_fn = score_fn
        self.n = int(n)
        self.num_keys = int(num_keys)
        if self.n < 1:
            raise ValueError(f"{name}: n must be >= 1")
        self._evict_synced = 0
        # tiered keyed state: a key -> hot-slot directory in front of the
        # direct-indexed [K, N] leaderboard; cold leaderboards spill to the
        # host store and readmit on touch (state/tiered.py slot directory)
        from ..state import TierConfig
        self._tier_cfg = TierConfig.resolve(tiered)
        self._tier = None
        self._cap_resolved = None
        self._slots = (int(self._tier_cfg.hot_capacity or num_keys)
                       if self._tier_cfg is not None else self.num_keys)

    def bind_geometry(self, batch_capacity: int) -> None:
        self._cap_resolved = int(batch_capacity)

    def out_capacity(self, in_capacity: int) -> int:
        return self._slots * self.n

    def out_spec(self, payload_spec: Any) -> Any:
        i = jax.ShapeDtypeStruct((), CTRL_DTYPE)
        return {"score": i, "rank": i}

    def init_state(self, payload_spec: Any):
        K, N = self._slots, self.n
        state = {"score": jnp.full((K, N), TOPN_SENTINEL, jnp.int32),
                 "tid": jnp.zeros((K, N), jnp.int32),
                 "evict": jnp.asarray(0, jnp.int32),
                 "eos": jnp.asarray(0, jnp.int32)}
        if self._tier_cfg is not None:
            from ..state.tiered import SlotTableTier, slot_directory_init
            cap = self._cap_resolved or DEFAULT_MAX_KEYS
            self._hot_target = max(1, K - min(cap, K - 1))
            outbox = int(self._tier_cfg.outbox or 4 * cap)
            state.update(slot_directory_init(K, outbox, {
                "oscore": lambda s: jnp.full((s, N), TOPN_SENTINEL,
                                             jnp.int32),
                "otid": lambda s: jnp.zeros((s, N), jnp.int32)}))
            state["ovf"] = jnp.asarray(0, jnp.int32)
            self._tier = SlotTableTier(
                self.name,
                {"score": (jnp.int32, (N,)), "tid": (jnp.int32, (N,))},
                self._tier_cfg, count_key="ocnt",
                col_keys=["okey", "otick", "oscore", "otid"],
                state_to_store=lambda n, host: (
                    host["okey"], host["otick"],
                    {"score": host["oscore"], "tid": host["otid"]}),
                wm_key=None)
        return state

    def tier_controllers(self):
        return (self._tier.controller,) if self._tier is not None else ()

    def _merge(self, state, keymat, scores, ids):
        """Merge [K, C] candidates into the [K, N] leaderboard via one
        vmapped bitonic sort network over the padded composite key."""
        K, N = self._slots, self.n
        cscore = jnp.where(keymat, scores[None, :], TOPN_SENTINEL)
        cid = jnp.where(keymat, ids[None, :], 0)
        alls = jnp.concatenate([state["score"], cscore], axis=1)
        alli = jnp.concatenate([state["tid"], cid], axis=1)
        L = 1 << max(1, (alls.shape[1] - 1).bit_length())
        pad = L - alls.shape[1]
        alls = jnp.pad(alls, ((0, 0), (0, pad)),
                       constant_values=TOPN_SENTINEL)
        alli = jnp.pad(alli, ((0, 0), (0, pad)))
        zero = jnp.zeros((L,), jnp.int32)
        iota = jnp.arange(L, dtype=jnp.int32)
        neg, sid, _, _ = jax.vmap(
            lambda p, s: sort_network(p, s, zero, iota))(-alls, alli)
        return -neg[:, :N], sid[:, :N]

    def apply(self, state, batch: Batch):
        if self._tier is None:
            return self._apply_core(state, batch)
        from ..state.tiered import slot_directory_evict, \
            slot_directory_resolve
        K, N = self._slots, self.n
        state, slot, live = slot_directory_resolve(
            state, batch.key, batch.valid, self._tier.lookup_cb,
            self._host_shapes(), self._admit_write)
        # lanes whose key could not get a hot slot (directory saturated):
        # counted overflow, like an untiered table beyond num_keys
        state = dict(state, ovf=count_drops(
            state["ovf"], "overflow_drops",
            jnp.sum((batch.valid & ~live).astype(jnp.int32))))
        b2 = batch.replace(key=jnp.where(live, slot, 0), valid=live)
        state, out = self._apply_core(state, b2)
        out = out.replace(key=jnp.where(
            out.valid, jnp.take(state["hkey"],
                                jnp.clip(out.key, 0, K - 1)), out.key))
        state = slot_directory_evict(
            state, self._hot_target,
            evictable=jnp.ones((K,), jnp.bool_),
            discardable=jnp.all(state["score"] == TOPN_SENTINEL, axis=1),
            pack_write=self._pack_write)
        return state, out

    def _host_shapes(self):
        import jax as _jax
        R, N = None, self.n
        # shapes depend on the probe width — resolved lazily per call site
        def shapes(r):
            return [_jax.ShapeDtypeStruct((r,), jnp.bool_),
                    _jax.ShapeDtypeStruct((r, N), jnp.int32),
                    _jax.ShapeDtypeStruct((r, N), jnp.int32)]
        return shapes

    def _admit_write(self, out, widx, got, in_ob, oidx, host_res):
        """Write admitted slots' leaderboard rows: the cold row (outbox
        beats host — chronologically newer) or a fresh sentinel row."""
        _found, h_score, h_tid = host_res
        ob = in_ob[:, None]
        row_s = jnp.where(ob, jnp.take(out["oscore"], oidx, axis=0),
                          h_score)
        row_t = jnp.where(ob, jnp.take(out["otid"], oidx, axis=0), h_tid)
        cold = (in_ob | _found)[:, None]
        row_s = jnp.where(cold, row_s, TOPN_SENTINEL)
        row_t = jnp.where(cold, row_t, 0)
        out["score"] = out["score"].at[widx].set(row_s, mode="drop")
        out["tid"] = out["tid"].at[widx].set(row_t, mode="drop")
        return out

    def _pack_write(self, out, opos, perm, spill):
        out["oscore"] = out["oscore"].at[opos].set(
            jnp.take(out["score"], perm, axis=0), mode="drop")
        out["otid"] = out["otid"].at[opos].set(
            jnp.take(out["tid"], perm, axis=0), mode="drop")
        return out

    def _apply_core(self, state, batch: Batch):
        K, N = self._slots, self.n
        refs = tuple_refs(batch)
        scores = jax.vmap(self.score_fn)(refs).astype(jnp.int32)
        keymat = ((batch.key[None, :]
                   == jnp.arange(K, dtype=jnp.int32)[:, None])
                  & batch.valid[None, :])                      # [K, C]
        filled = jnp.sum((state["score"] != TOPN_SENTINEL).astype(jnp.int32),
                         axis=1)
        cands = jnp.sum(keymat.astype(jnp.int32), axis=1)
        new_score, new_tid = self._merge(state, keymat, scores, batch.id)
        kept = jnp.sum((new_score != TOPN_SENTINEL).astype(jnp.int32),
                       axis=1)
        evict = state["evict"] + jnp.sum(filled + cands - kept)
        touched = jnp.any(keymat, axis=1)
        state = dict(state, score=new_score, tid=new_tid, evict=evict)
        return state, self._rows(state, touched)

    def _rows(self, state, keep_key):
        K, N = self._slots, self.n
        flat = lambda a: a.reshape(K * N)
        keyv = jnp.repeat(jnp.arange(K, dtype=jnp.int32), N)
        rank = jnp.tile(jnp.arange(N, dtype=jnp.int32), K)
        score = flat(state["score"])
        valid = flat(keep_key[:, None]
                     & (state["score"] != TOPN_SENTINEL))
        return Batch(key=keyv, id=flat(state["tid"]),
                     ts=jnp.zeros((K * N,), jnp.int32),
                     payload={"score": score, "rank": rank}, valid=valid)

    def flush(self, state):
        import numpy as np
        if state is None:
            return state, None
        K, N = self._slots, self.n
        if not int(np.asarray(state["eos"])):
            if self._tier is not None:
                # settle first: leaderboards still in the spill outbox must
                # reach the store before the cold drain waves below
                state = self._tier.controller.settle(state)
            state = dict(state)
            state["eos"] = jnp.asarray(1, jnp.int32)
            self.collect_stats(state)
            if self._tier is None:
                return state, self._rows(state, jnp.ones((K,), jnp.bool_))
            # tiered: emit the HOT leaderboards (stale unadmitted slots
            # excluded), remapped slot -> key; cold waves follow. Keys
            # resident hot are remembered: the store may still hold a
            # SUPERSEDED copy of them (re-admission does not remove — the
            # one-tier-rule exception), which the waves must skip.
            hkey = np.asarray(state["hkey"])
            hused = np.asarray(state["hused"])
            self._flush_exclude = set(hkey[hused].tolist())
            out = self._rows(state, state["hused"])
            return state, out.replace(key=jnp.where(
                out.valid, jnp.take(jnp.asarray(state["hkey"]),
                                    jnp.clip(out.key, 0, K - 1)), out.key))
        if self._tier is None:
            return state, None
        # EOS drain waves: pop up to K cold keys per flush call (ascending
        # key order — deterministic, and replay-safe: a restore rewinds the
        # store manifest, so the waves re-derive) until the store is empty
        excl = getattr(self, "_flush_exclude", set())
        while True:
            keys, cols = self._tier.store.pop_keys(K)
            if len(keys) == 0:
                return state, None
            live = np.asarray([int(k) not in excl for k in keys], bool)
            if live.any():
                break
        n = len(keys)
        kv = np.zeros((K,), np.int32)
        kv[:n] = keys.astype(np.int32)
        sc = np.full((K, N), TOPN_SENTINEL, np.int32)
        sc[:n] = np.where(live[:, None], cols["score"], TOPN_SENTINEL)
        td = np.zeros((K, N), np.int32)
        td[:n] = cols["tid"]
        group = np.repeat(np.arange(K) < n, N)
        out = Batch(
            key=jnp.asarray(np.repeat(kv, N)),
            id=jnp.asarray(td.reshape(K * N)),
            ts=jnp.zeros((K * N,), jnp.int32),
            payload={"score": jnp.asarray(sc.reshape(K * N)),
                     "rank": jnp.tile(jnp.arange(N, dtype=jnp.int32), K)},
            valid=jnp.asarray(group & (sc.reshape(K * N) != TOPN_SENTINEL)))
        return state, out

    def collect_stats(self, state: Any = None) -> None:
        if state is None:
            return
        import numpy as np
        from ..control import _state as _cstate
        ev = int(np.asarray(state["evict"]))
        if ev > self._evict_synced:
            _cstate.bump("topn_evictions", ev - self._evict_synced)
            self._evict_synced = ev
        counters = {"topn_evictions": ev}
        if self._tier is not None:
            counters.update(_tier_counters(state, self._tier))
            counters["overflow_drops"] = int(np.asarray(state["ovf"]))
        self._publish_stage_counters(counters)

    def drop_counters(self, state: Any = None) -> dict:
        if state is None or self._tier is None:
            return {}
        import numpy as np
        return {"overflow_drops": int(np.asarray(state["ovf"]))}

    def event_time_stats(self, state: Any = None):
        """Watermark-map section: leaderboard fill + eviction pressure
        (TopN has no event-time frontier — scores, not timestamps)."""
        if state is None:
            return None
        import numpy as np
        filled = int((np.asarray(state["score"]) != TOPN_SENTINEL).sum())
        slots = self._slots * self.n
        out = {"leaderboard_slots": slots,
               "leaderboard_filled": filled,
               "occupancy_pct": round(100.0 * filled / slots, 2),
               "topn_evictions": int(np.asarray(state["evict"]))}
        if self._tier is not None:
            from ..state.tiered import slot_directory_stats
            out["tier"] = {**slot_directory_stats(state),
                           **self._tier.controller.stats()}
            out["overflow_drops"] = int(np.asarray(state["ovf"]))
        return out


class Distinct(Basic_Operator):
    """Pass each distinct value through exactly once.

    ``value_fn(t) -> i32`` extracts the distinctness key (default: the
    tuple's key slot; values must be > INT32_MIN). In-batch duplicates keep
    the first occurrence in ``(key, stream-position)`` order
    (``segment_rank``); cross-batch duplicates are suppressed by probing the
    JoinTable *before* the batch's new values upsert. ``num_slots`` bounds
    the distinct cardinality — overflow values are dropped from the table
    (counted in ``state["dropped"]``) and would re-emit; size it to the
    domain."""

    routing = routing_modes_t.KEYBY

    def __init__(self, value_fn: Optional[Callable] = None, *,
                 num_slots: int = DEFAULT_MAX_KEYS, tiered=None,
                 name: str = "distinct", parallelism: int = 1):
        super().__init__(name, parallelism)
        self.value_fn = value_fn or (lambda t: t.key)
        self.num_slots = int(num_slots)
        self._pending = None
        # tiered keyed state (ROADMAP 3): the distinct table is a delay-0
        # JoinTable, so it rides the same spill/readmit hooks
        from ..state import TierConfig
        self._tier_cfg = TierConfig.resolve(tiered)
        self._tier = None

    def bind_geometry(self, batch_capacity: int) -> None:
        self._pending = int(batch_capacity)

    def init_state(self, payload_spec: Any):
        pending = self._pending or self.num_slots
        vspec = {"one": jax.ShapeDtypeStruct((), jnp.int32)}
        if self._tier_cfg is not None:
            from ..state.tiered import JoinTableTier
            hot = int(self._tier_cfg.hot_capacity or self.num_slots)
            # delay-0 table: the ring empties every batch, so one batch of
            # distinct keys is the per-batch admission bound
            self._reserve = pending
            self._hot_target = max(1, hot - self._reserve)
            # actuator setpoint gauge (PR 17): built-with hot capacity —
            # last-write-wins, the join_table_version convention
            from ..control import _state as _cstate
            _cstate.set_gauge("hot_capacity", float(hot))
            outbox = int(self._tier_cfg.outbox or 4 * self._reserve)
            state = join_table_init(hot, pending, vspec)
            state = join_table_tier_init(state, outbox, vspec)
            self._tier = JoinTableTier(self.name, vspec, self._tier_cfg)
            return state
        return join_table_init(self.num_slots, pending, vspec)

    def tier_controllers(self):
        return (self._tier.controller,) if self._tier is not None else ()

    def apply(self, state, batch: Batch):
        refs = tuple_refs(batch)
        dk = jax.vmap(self.value_fn)(refs).astype(jnp.int32)
        firsts = batch.valid & (segment_rank(dk, batch.valid) == 0)
        fb_ok = None
        if self._tier is not None:
            # miss -> readmit: a value seen long ago lives in the cold
            # tier — resolve it back before the duplicate probe, so
            # suppression is independent of tier placement
            state, _fb_vals, fb_ok = join_table_tier_resolve(
                state, dk, batch.valid, self._tier.lookup_cb)
        _, hit = join_table_probe(state, dk, firsts)
        if fb_ok is not None:
            # a seen-value whose row could not re-admit (saturated hot
            # table) still counts as seen
            hit = hit | (fb_ok & firsts)
        keep = firsts & ~hit
        ones = jnp.ones((batch.capacity,), jnp.int32)
        state = join_table_upsert(state, dk, {"one": ones}, batch.ts,
                                  batch.id, keep, delay=0,
                                  divert=self._tier is not None)
        if self._tier is not None:
            state = join_table_tier_touch(state, dk, batch.valid)
            state = join_table_tier_evict(state, self._hot_target)
        return state, batch.mask(keep)

    def collect_stats(self, state: Any = None) -> None:
        if state is None:
            return
        counters = dict(self.drop_counters(state))
        if self._tier is not None:
            counters.update(_tier_counters(state, self._tier))
        self._publish_stage_counters(counters)

    def drop_counters(self, state: Any = None) -> dict:
        if state is None:
            return {}
        import numpy as np
        return {"overflow_drops": int(np.asarray(state["dropped"]))}

    def event_time_stats(self, state: Any = None):
        """Watermark-map section: distinct-table occupancy + overflow drops
        (the delay-0 JoinTable underneath)."""
        if state is None:
            return None
        from ..ops.lookup import join_table_stats
        out = join_table_stats(state)
        out["delay"] = 0
        if self._tier is not None:
            out["tier"] = {**join_table_tier_stats(state),
                           **self._tier.controller.stats()}
        return out
