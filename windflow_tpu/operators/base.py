"""Operator base class — uniform introspection over all operators.

Counterpart of ``Basic_Operator`` (``wf/basic_operator.hpp:47-79``): ``getName``,
``getParallelism``, ``getRoutingMode``, ``isUsed``, ``get_StatsRecords``. Here an
operator is additionally a *pure batch transform*: ``apply(state, batch) -> (state,
out_batch)`` traced into the enclosing compiled program. Chained operators therefore
fuse into one XLA program — the always-on analogue of the reference's ``ff_comb``
chaining (``wf/pipegraph.hpp:1272-1318``).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from ..basic import routing_modes_t
from ..batch import Batch
from ..stats import Stats_Record


class Basic_Operator:
    """Base of all operators.

    Lifecycle of the device-side state (the replacement for per-replica C++ member
    state): ``init_state(payload_spec)`` builds the state pytree; ``apply`` threads it
    through each micro-batch; ``flush`` drains residual state at EOS (the reference's
    ``eosnotify`` paths, e.g. ``wf/win_seq.hpp:468-529``)."""

    #: set by subclasses
    routing: routing_modes_t = routing_modes_t.FORWARD

    #: builder hints (withBatch / withDevice, the reference GPU builders'
    #: batch_len / gpu_id, ``wf/builders_gpu.hpp:115-130``): micro-batch
    #: capacity ceiling honored by CompiledChain/Pipeline, and the jax.Device
    #: the operator's state (and therefore its fused chain) is placed on.
    _batch_hint: int = None
    _device = None
    #: outcome of MultiPipe.chain() vs add(): True when the operator was fused
    #: queue-free (FORWARD, reference ``chain_operator`` success,
    #: ``wf/pipegraph.hpp:1272-1318``), False when it fell back to routed add;
    #: None before graph placement. Rendered by dump_DOTGraph.
    _chained = None
    #: event-time observability toggle (``MonitoringConfig.event_time``), set
    #: by CompiledChain BEFORE ``bind_geometry``/``init_state`` when the
    #: enclosing driver resolved the sub-toggle on.  Geometry-binding: when
    #: True, stateful event-time operators add an on-device lateness
    #: histogram to their state pytree and fold one masked reduction per
    #: batch into it (``observability/event_time.py``); when False (the
    #: default) state and compiled programs are byte-for-byte unchanged.
    _event_time = False

    def __init__(self, name: str, parallelism: int = 1):
        self._name = name
        self._parallelism = max(1, int(parallelism))
        self._used = False
        self._stats = [Stats_Record(name, i) for i in range(self._parallelism)]
        #: host callback run once per replica at teardown with that replica's
        #: RuntimeContext (reference closing_func at svc_end; withClosingFunction,
        #: wf/builders.hpp common methods)
        self.closing_func = None

    def close(self) -> None:
        """Invoke the closing function (if any) once per replica — the reference
        runs ``closing_func(RuntimeContext&)`` in every replica's ``svc_end``."""
        if self.closing_func is None:
            return
        from ..context import RuntimeContext
        own = getattr(self, "context", None)
        for i in range(self._parallelism):
            ctx = (own if own is not None and own.getReplicaIndex() == i
                   else RuntimeContext(self._parallelism, i))
            self.closing_func(ctx)

    # -- Basic_Operator surface (wf/basic_operator.hpp:47-79) -------------------------

    def getName(self) -> str:
        return self._name

    def getParallelism(self) -> int:
        return self._parallelism

    def getRoutingMode(self) -> routing_modes_t:
        return self.routing

    def isUsed(self) -> bool:
        return self._used

    def get_StatsRecords(self):
        return list(self._stats)

    def collect_stats(self, state: Any = None) -> None:
        """Sync device-resident counters carried in ``state`` into the host
        ``Stats_Record`` (e.g. Win_SeqFFAT's OLD-drop counter). Called by the
        metrics registry at snapshot time and by the drivers at EOS — a tiny
        D2H read off the hot path; no-op by default."""

    def _publish_stage_counters(self, counters: dict) -> None:
        """Stash per-operator counters/gauges for the snapshot's
        ``row["counters"]`` and the ``windflow_stage_*`` Prometheus surface.
        Names must be registered in ``observability/names.py`` — the
        WF240/241 one-source-of-truth discipline applied to the per-stage
        namespace (a typo'd name raises here instead of silently forking the
        exposition)."""
        from ..observability.names import STAGE_COUNTERS, STAGE_GAUGES
        for k in counters:
            if k not in STAGE_COUNTERS and k not in STAGE_GAUGES:
                raise ValueError(
                    f"{self._name}: stage counter {k!r} is not registered in "
                    f"observability/names.py::STAGE_COUNTERS/STAGE_GAUGES — "
                    f"register it there (the emission registries the linter "
                    f"gates)")
        self._stage_counters = dict(counters)

    def stage_counters(self) -> dict:
        """Most recently published per-operator counters (empty until the
        first ``collect_stats`` of an operator that publishes any)."""
        return dict(getattr(self, "_stage_counters", ()) or {})

    def event_time_stats(self, state: Any = None) -> Optional[dict]:
        """Event-time section of the monitoring snapshot's operator row
        (watermark frontier, state occupancy/pressure, lateness histograms)
        — None for operators without an event-time surface.  Called at
        snapshot time only (reporter thread / EOS): implementations may do
        small D2H reads of carried state, exactly like ``collect_stats``."""
        return None

    def drop_counters(self, state: Any = None) -> dict:
        """Host ints of the operator's device-resident drop counters, keyed
        by the ``names.py::STAGE_COUNTERS`` drop names — read by the chain's
        sampled-push readback (event_time monitoring only) to journal
        ``lateness_drop`` events with trace coordinates.  Empty by
        default."""
        return {}

    def tier_controllers(self) -> tuple:
        """The operator's tiered-state controllers (``state/tiered.py``
        ``TieredTable``, one per tiered table) — empty unless the operator
        was built with ``tiered=`` on.  ``CompiledChain`` runs their
        ``maintain`` after every push (the async spill settle point) and
        snapshots/restores their host stores with the operator states."""
        return ()

    # pythonic aliases
    name = property(getName)
    parallelism = property(getParallelism)

    # -- batch-transform surface ------------------------------------------------------

    def bind_geometry(self, batch_capacity: int) -> None:
        """Called once by the compiler with the incoming micro-batch capacity, before
        ``init_state`` — lets stateful operators size rings/budgets relative to the
        batch (the reference sizes GPU batches similarly from batch_len/slide gcds,
        ``wf/win_seq_gpu.hpp`` tuples_per_batch)."""

    def out_capacity(self, in_capacity: int) -> int:
        """Capacity of the outgoing batch (FlatMap expands by max_fanout; windowed
        operators emit max_wins rows)."""
        return in_capacity

    def init_state(self, payload_spec: Any) -> Any:
        """Device state pytree for this operator (None if stateless)."""
        return None

    def out_spec(self, payload_spec: Any) -> Any:
        """Output payload spec given the input payload spec (type propagation — the
        analogue of the reference's typeid check at add/chain time,
        ``wf/pipegraph.hpp:1573-1578``)."""
        return payload_spec

    def apply(self, state: Any, batch: Batch) -> Tuple[Any, Batch]:
        raise NotImplementedError

    def flush(self, state: Any) -> Tuple[Any, Optional[Batch]]:
        """Drain residual state at EOS. Returns (state, out_batch or None)."""
        return state, None

    def _mark_used(self):
        self._used = True

    def __repr__(self):
        return f"{type(self).__name__}({self._name!r}, parallelism={self._parallelism})"
