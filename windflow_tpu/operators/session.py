"""Session windows — data-dependent-gap firing (``WindowSpec.session``).

The third firing family beside the CB/TB triggerer lattice of
``operators/window.py``: a per-key session stays open while consecutive
events arrive within ``gap`` of each other and FIRES when the gap is
exceeded — the firing bound is a function of the *observed inter-arrival
gaps* (:meth:`WindowSpec.fired_session`), so there is no static window-id
grid to enumerate. The batched formulation keeps everything one fixed-shape
device program, masked like the TB path:

1. lanes sort by ``(key, ts, id)`` (one fused multi-operand ``lax.sort``);
2. in-batch session *fragments* fall out of a vectorized gap/boundary scan
   (``first | gap-break`` flags -> dense fragment ids -> ``segment_reduce``
   per-fragment aggregates in event-time order);
3. each key's first fragment merges with its carried open session where the
   gap chains; every non-final fragment is closed by in-batch evidence (a
   successor fragment *is* the observed gap);
4. each key's final fragment becomes/extends the carried open session; the
   ``fired_session`` triggerer then closes carried sessions the watermark
   has proven complete (``wm - delay > last + gap``) — evaluated over the
   whole ``[K]`` open-session table at once.

Ordering contract: arrival is assumed **event-time ordered per key**
(cross-key skew is fine — that is what the ``delay`` lateness allowance and
the watermark triggerer absorb; within one batch, intra-key disorder is
fully repaired by the sort). An in-batch successor fragment beyond the gap
is therefore *proof* the predecessor session ended, and closes it
immediately regardless of ``delay`` — keeping exactly ONE open session per
key in the ``[K]`` state. The cost of that bound: an intra-key straggler
that violates the contract *across batches* (its session already closed)
is OLD and dropped on device — the ``Win_SeqFFAT`` straggler convention,
surfaced through the same ``tuples_dropped_old`` stats field. Emission rows carry ``(key, session
ordinal, end ts)`` control fields and payload ``{"agg", "start", "end",
"n"}``. State is a plain pytree — checkpoint/restore and supervised replay
carry it unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..basic import routing_modes_t, DEFAULT_MAX_KEYS
from ..batch import Batch, CTRL_DTYPE, TupleRef, tuple_refs
from ..observability import event_time as _et
from ..ops.lookup import table_lookup
from ..ops.segment import segment_reduce
from .base import Basic_Operator
from .window import WindowSpec

_IMIN = -(1 << 31)
_IMAX = (1 << 31) - 1


def _ref_spec(payload_spec):
    s = jax.ShapeDtypeStruct((), CTRL_DTYPE)
    return TupleRef(key=s, id=s, ts=s, data=payload_spec)


class SessionWindow(Basic_Operator):
    """Per-key session aggregation under a :meth:`WindowSpec.session` spec.

    ``value_fn(t) -> pytree`` extracts the per-event contribution;
    ``combine`` folds contributions in event-time order (associative;
    default add). One output row per CLOSED session: ``key`` = key slot,
    ``id`` = per-key session ordinal, ``ts`` = session end, payload
    ``{"agg": <folded pytree>, "start", "end", "n"}``.

    Requires per-key event-time-ordered arrival (see the module docstring):
    ``spec.delay`` buys lateness against *cross-key* watermark skew; an
    intra-key straggler landing after its session closed drops as OLD."""

    routing = routing_modes_t.KEYBY

    def __init__(self, value_fn: Callable, spec: WindowSpec, *,
                 combine: Callable = None, identity: Any = 0,
                 num_keys: int = DEFAULT_MAX_KEYS, tiered=None,
                 name: str = "session", parallelism: int = 1):
        super().__init__(name, parallelism)
        if not spec.is_session:
            raise ValueError(
                f"{name}: SessionWindow needs a session spec — build it with "
                f"WindowSpec.session(gap, delay), got {spec.wtype}")
        self.value_fn = value_fn
        self.spec = spec
        self.combine = combine
        self.identity = identity
        self.num_keys = int(num_keys)
        self._cap: Optional[int] = None
        self._old_synced = 0
        self._closed_synced = 0
        # tiered keyed state: a key -> hot-slot directory in front of the
        # direct-indexed session table. OPEN sessions are PINNED hot (they
        # must fire through the in-graph triggerer); only closed keys'
        # floors/ordinals spill, and the watermark retires floors the
        # lateness contract proves can never flag an OLD again
        from ..state import TierConfig
        self._tier_cfg = TierConfig.resolve(tiered)
        self._tier = None
        self._slots = (int(self._tier_cfg.hot_capacity or num_keys)
                       if self._tier_cfg is not None else self.num_keys)

    # -- geometry / specs -------------------------------------------------

    def bind_geometry(self, batch_capacity: int) -> None:
        self._cap = int(batch_capacity)

    def out_capacity(self, in_capacity: int) -> int:
        # in-batch evidence closes (<= 2 row groups of C) + watermark closes
        return 2 * in_capacity + self._slots

    def _val_spec(self, payload_spec):
        return jax.eval_shape(self.value_fn, _ref_spec(payload_spec))

    def out_spec(self, payload_spec: Any) -> Any:
        i = jax.ShapeDtypeStruct((), CTRL_DTYPE)
        return {"agg": self._val_spec(payload_spec),
                "start": i, "end": i, "n": i}

    def init_state(self, payload_spec: Any):
        K = self._slots
        vspec = self._val_spec(payload_spec)
        acc = jax.tree.map(
            lambda s: jnp.zeros((K,) + tuple(s.shape), s.dtype), vspec)
        z = lambda fill=0: jnp.full((K,), fill, jnp.int32)
        state = {"open": jnp.zeros((K,), jnp.bool_),
                 "start": z(), "last": z(), "cnt": z(), "sid": z(),
                 "acc": acc, "floor": z(_IMIN),
                 "wm": jnp.asarray(_IMIN, jnp.int32),
                 "closed": jnp.asarray(0, jnp.int32),
                 "old": jnp.asarray(0, jnp.int32),
                 "eos": jnp.asarray(0, jnp.int32)}
        if self._tier_cfg is not None:
            from ..state.tiered import SlotTableTier, slot_directory_init
            cap = self._cap or DEFAULT_MAX_KEYS
            self._hot_target = max(1, K - min(cap, K - 1))
            outbox = int(self._tier_cfg.outbox or 4 * cap)
            state.update(slot_directory_init(K, outbox, {
                "ofloor": lambda s: jnp.full((s,), _IMIN, jnp.int32),
                "osid": lambda s: jnp.zeros((s,), jnp.int32)}))
            state["ovf"] = jnp.asarray(0, jnp.int32)
            gap, delay = self.spec.gap, self.spec.delay
            self._tier = SlotTableTier(
                self.name,
                {"floor": (jnp.int32, ()), "sid": (jnp.int32, ())},
                self._tier_cfg, count_key="ocnt",
                col_keys=["okey", "otick", "ofloor", "osid"],
                state_to_store=lambda n, host: (
                    host["okey"], host["otick"],
                    {"floor": host["ofloor"], "sid": host["osid"]}),
                # retire floors once no admissible arrival can be OLD:
                # floor + gap < wm - delay  =>  ts > floor + gap for every
                # future tuple the lateness contract admits
                compact_col="floor",
                compact_bound=lambda wm: wm - delay - gap,
                wm_key="wm")
        if self._event_time:
            # observed-lateness histogram (event-time monitoring only —
            # absent otherwise, so the off program is unchanged)
            state["lat_hist"] = _et.lateness_init()
        return state

    def tier_controllers(self):
        return (self._tier.controller,) if self._tier is not None else ()

    # -- the batched session step -----------------------------------------

    def _fold(self, a, b):
        fn = self.combine or jnp.add
        return jax.tree.map(fn, a, b)

    def apply(self, state, batch: Batch):
        if self._tier is None:
            return self._apply_core(state, batch)
        from ..ops.lookup import count_drops
        from ..state.tiered import slot_directory_evict, \
            slot_directory_resolve
        K = self._slots
        state, slot, live = slot_directory_resolve(
            state, batch.key, batch.valid, self._tier.lookup_cb,
            self._host_shapes, self._admit_write)
        # a lane whose key found no hot slot (directory saturated with
        # OPEN sessions) drops, counted — the untiered table would have
        # silently mangled any key >= num_keys
        state = dict(state, ovf=count_drops(
            state["ovf"], "overflow_drops",
            jnp.sum((batch.valid & ~live).astype(jnp.int32))))
        b2 = batch.replace(key=jnp.where(live, slot, 0), valid=live)
        state, out = self._apply_core(state, b2)
        out = out.replace(key=jnp.where(
            out.valid, jnp.take(state["hkey"],
                                jnp.clip(out.key, 0, K - 1)), out.key))
        # OPEN sessions are pinned hot — only closed keys' floors spill;
        # floors with nothing to remember free without outbox space
        state = slot_directory_evict(
            state, self._hot_target,
            evictable=~state["open"],
            discardable=state["floor"] == _IMIN,
            pack_write=self._pack_write)
        return state, out

    def _host_shapes(self, r):
        return [jax.ShapeDtypeStruct((r,), jnp.bool_),
                jax.ShapeDtypeStruct((r,), jnp.int32),
                jax.ShapeDtypeStruct((r,), jnp.int32)]

    def _admit_write(self, out, widx, got, in_ob, oidx, host_res):
        """Write admitted keys' carried fields: the cold (floor, session
        ordinal) pair — outbox beats host — or the fresh (_IMIN, 0)."""
        _found, h_floor, h_sid = host_res
        cold = in_ob | _found
        floor = jnp.where(in_ob, jnp.take(out["ofloor"], oidx), h_floor)
        sid = jnp.where(in_ob, jnp.take(out["osid"], oidx), h_sid)
        floor = jnp.where(cold, floor, _IMIN)
        sid = jnp.where(cold, sid, 0)
        out["floor"] = out["floor"].at[widx].set(floor, mode="drop")
        out["sid"] = out["sid"].at[widx].set(sid, mode="drop")
        # an admitted slot starts closed (stale open slots are never
        # evicted, so open is already False here by construction)
        out["open"] = out["open"].at[widx].set(False, mode="drop")
        return out

    def _pack_write(self, out, opos, perm, spill):
        out["ofloor"] = out["ofloor"].at[opos].set(
            jnp.take(out["floor"], perm), mode="drop")
        out["osid"] = out["osid"].at[opos].set(
            jnp.take(out["sid"], perm), mode="drop")
        return out

    def _apply_core(self, state, batch: Batch):
        K, C = self._slots, batch.capacity
        gap = self.spec.gap
        refs = tuple_refs(batch)
        vals = jax.vmap(self.value_fn)(refs)
        # OLD: the event predates (within gap of) the key's last closed end
        floor_k = table_lookup(state["floor"], batch.key)
        old = batch.valid & (floor_k > _IMIN) & (batch.ts <= floor_k + gap)
        live = batch.valid & ~old
        # one fused sort puts lanes in (key, event-time, id) order
        iota = jnp.arange(C, dtype=jnp.int32)
        skeys, sts, sids, orig = jax.lax.sort(
            (jnp.where(live, batch.key, _IMAX), batch.ts, batch.id, iota),
            num_keys=3, is_stable=True)
        sv = skeys != _IMAX
        sk = jnp.where(sv, skeys, 0)
        svals = jax.tree.map(lambda a: jnp.take(a, orig, axis=0), vals)
        first = sv & jnp.concatenate([jnp.ones((1,), jnp.bool_),
                                      skeys[1:] != skeys[:-1]])
        prev_ts = jnp.concatenate([jnp.zeros((1,), sts.dtype), sts[:-1]])
        brk = sv & ~first & (sts - prev_ts > gap)
        open_k = table_lookup(state["open"].astype(jnp.int32), sk) > 0
        last_k = table_lookup(state["last"], sk)
        cont = first & open_k & (sts - last_k <= gap)
        # dense fragment ids + per-fragment aggregates (event-time order)
        seg = jnp.maximum(jnp.cumsum((first | brk).astype(jnp.int32)) - 1, 0)
        red = lambda v, comb, ident: segment_reduce(
            v, seg, sv, C, combine=comb, identity=ident)
        fkey = red(sk, jnp.maximum, 0)
        fmin = red(sts, jnp.minimum, _IMAX)
        fmax = red(sts, jnp.maximum, _IMIN)
        fcnt = red(jnp.ones((C,), jnp.int32), None, 0)
        facc = segment_reduce(svals, seg, sv, C, combine=self.combine,
                              identity=self.identity)
        ffirst = red(first.astype(jnp.int32), jnp.maximum, 0) > 0
        fcont = red(cont.astype(jnp.int32), jnp.maximum, 0) > 0
        fvalid = fcnt > 0
        # carried open-session fields, per fragment key
        c_start = table_lookup(state["start"], fkey)
        c_last = table_lookup(state["last"], fkey)
        c_cnt = table_lookup(state["cnt"], fkey)
        c_acc = jax.tree.map(lambda t: table_lookup(t, fkey), state["acc"])
        c_open = table_lookup(state["open"].astype(jnp.int32), fkey) > 0
        mrg = lambda m, a, b: jnp.where(m, a, b)
        m_start = mrg(fcont, jnp.minimum(c_start, fmin), fmin)
        m_last = mrg(fcont, jnp.maximum(c_last, fmax), fmax)
        m_cnt = mrg(fcont, c_cnt + fcnt, fcnt)
        m_acc = jax.tree.map(
            lambda f, m: jnp.where(
                fcont.reshape(fcont.shape + (1,) * (f.ndim - 1)), m, f),
            facc, self._fold(c_acc, facc))
        # fragment topology per key
        minseg = segment_reduce(seg, sk, sv, K, combine=jnp.minimum,
                                identity=_IMAX)
        maxseg = segment_reduce(seg, sk, sv, K, combine=jnp.maximum,
                                identity=-1)
        frag_rank = iota - table_lookup(minseg, fkey)
        flast = fvalid & (iota == table_lookup(maxseg, fkey))
        # group 1: carried sessions closed by in-batch evidence (first
        # fragment of the key does NOT chain into the open session)
        g1 = fvalid & ffirst & ~fcont & c_open
        g1_id = table_lookup(state["sid"], fkey)
        # group 2: every non-final fragment is a closed session
        g2 = fvalid & ~flast
        n1 = segment_reduce(g1.astype(jnp.int32), fkey, fvalid, K)
        g2_id = (table_lookup(state["sid"], fkey) + table_lookup(n1, fkey)
                 + frag_rank)
        nclosed = segment_reduce(g2.astype(jnp.int32), fkey, fvalid, K)
        sid2 = state["sid"] + n1 + nclosed
        # floor: newest closed end per key
        ends1 = segment_reduce(jnp.where(g1, c_last, _IMIN), fkey, fvalid,
                               K, combine=jnp.maximum, identity=_IMIN)
        ends2 = segment_reduce(jnp.where(g2, m_last, _IMIN), fkey, fvalid,
                               K, combine=jnp.maximum, identity=_IMIN)
        floor2 = jnp.maximum(state["floor"], jnp.maximum(ends1, ends2))
        # final fragments become/extend the carried open session
        upd = jnp.where(flast, fkey, K)
        open2 = state["open"].at[upd].set(True, mode="drop")
        start2 = state["start"].at[upd].set(m_start, mode="drop")
        last2 = state["last"].at[upd].set(m_last, mode="drop")
        cnt2 = state["cnt"].at[upd].set(m_cnt, mode="drop")
        acc2 = jax.tree.map(lambda t, v: t.at[upd].set(v, mode="drop"),
                            state["acc"], m_acc)
        # group 3: the data-dependent triggerer over the [K] open table
        wm2 = jnp.maximum(state["wm"],
                          jnp.max(jnp.where(batch.valid, batch.ts, _IMIN)))
        g3 = open2 & self.spec.fired_session(last2, wm2)
        open3 = open2 & ~g3
        floor3 = jnp.where(g3, jnp.maximum(floor2, last2), floor2)
        sid3 = sid2 + g3.astype(jnp.int32)
        out = self._emit_rows(
            C, K,
            (g1, fkey, g1_id, c_last, c_start, c_cnt, c_acc),
            (g2, fkey, g2_id, m_last, m_start, m_cnt, m_acc),
            (g3, sid2, last2, start2, cnt2, acc2))
        from ..ops.lookup import count_drops
        new_state = dict(
            state, open=open3, start=start2, last=last2,
            cnt=cnt2, sid=sid3, acc=acc2, floor=floor3,
            wm=wm2,
            closed=state["closed"] + jnp.sum(g1.astype(jnp.int32))
            + jnp.sum(g2.astype(jnp.int32))
            + jnp.sum(g3.astype(jnp.int32)),
            old=count_drops(state["old"], "old_drops",
                            jnp.sum(old.astype(jnp.int32))))
        if self._event_time:
            # arrival lateness vs the post-batch watermark: one masked
            # reduction, state-only (results untouched).  delay >= the
            # recorded quantile keeps that fraction of arrivals inside their
            # session's lateness allowance.
            new_state["lat_hist"] = _et.lateness_update(
                state["lat_hist"], wm2, batch.ts, batch.valid)
        return new_state, out

    def _emit_rows(self, C, K, g1, g2, g3):
        """Assemble the [2C + K] output batch from the three close groups."""
        m1, k1, i1, e1, s1, n1, a1 = g1
        m2, k2, i2, e2, s2, n2, a2 = g2
        m3, i3, e3, s3, n3, a3 = g3
        kk = jnp.arange(K, dtype=jnp.int32)
        cat = lambda a, b, c: jnp.concatenate([a, b, c], axis=0)
        payload = {
            "agg": jax.tree.map(cat, a1, a2, a3),
            "start": cat(s1, s2, s3), "end": cat(e1, e2, e3),
            "n": cat(n1, n2, n3)}
        return Batch(key=cat(k1, k2, kk), id=cat(i1, i2, i3),
                     ts=cat(e1, e2, e3), payload=payload,
                     valid=cat(m1, m2, m3))

    def flush(self, state):
        """EOS fires every open session regardless of watermark (the
        ``flush_hi`` convention of the CB/TB paths)."""
        import numpy as np
        if state is None or int(np.asarray(state["eos"])):
            return state, None
        K = self._slots
        C = self._cap or K
        g3 = state["open"]
        z = jnp.zeros((C,), jnp.int32)
        zb = jnp.zeros((C,), jnp.bool_)
        zacc = jax.tree.map(
            lambda t: jnp.zeros((C,) + t.shape[1:], t.dtype), state["acc"])
        out = self._emit_rows(
            C, K,
            (zb, z, z, z, z, z, zacc), (zb, z, z, z, z, z, zacc),
            (g3, state["sid"], state["last"], state["start"], state["cnt"],
             state["acc"]))
        if self._tier is not None:
            # open sessions are pinned hot, so the EOS fire covers every
            # live session — emitted slot ids remap to their true keys
            out = out.replace(key=jnp.where(
                out.valid, jnp.take(state["hkey"],
                                    jnp.clip(out.key, 0, K - 1)), out.key))
        state = dict(state)
        state["closed"] = state["closed"] + jnp.sum(g3.astype(jnp.int32))
        state["sid"] = state["sid"] + g3.astype(jnp.int32)
        state["open"] = jnp.zeros_like(state["open"])
        state["eos"] = jnp.asarray(1, jnp.int32)
        self.collect_stats(state)
        return state, out

    def collect_stats(self, state: Any = None) -> None:
        if state is None:
            return
        import numpy as np
        from ..control import _state as _cstate
        old = int(np.asarray(state["old"]))
        self._stats[0].tuples_dropped_old = old
        closed = int(np.asarray(state["closed"]))
        if closed > self._closed_synced:
            _cstate.bump("sessions_closed", closed - self._closed_synced)
            self._closed_synced = closed
        counters = {"sessions_closed": closed, "old_drops": old}
        if self._tier is not None:
            from .join import _tier_counters
            counters.update(_tier_counters(state, self._tier))
            counters["overflow_drops"] = int(np.asarray(state["ovf"]))
        self._publish_stage_counters(counters)

    def drop_counters(self, state: Any = None) -> dict:
        if state is None:
            return {}
        import numpy as np
        out = {"old_drops": int(np.asarray(state["old"]))}
        if self._tier is not None:
            out["overflow_drops"] = int(np.asarray(state["ovf"]))
        return out

    def event_time_stats(self, state: Any = None):
        """Watermark-map section: open-session pressure (count + oldest-open
        age vs the watermark), close/drop totals, and the arrival-lateness
        histogram with its ``recommend_delay`` advice."""
        if state is None:
            return None
        import numpy as np
        wm = int(np.asarray(state["wm"]))
        open_mask = np.asarray(state["open"])
        n_open = int(open_mask.sum())
        out = {
            "watermark_ts": wm,
            "gap": self.spec.gap,
            "delay": self.spec.delay,
            "open_sessions": n_open,
            "key_slots": self._slots,
            "occupancy_pct": round(100.0 * n_open / self._slots, 2),
            "sessions_closed": int(np.asarray(state["closed"])),
            "old_drops": int(np.asarray(state["old"])),
        }
        if self._tier is not None:
            from ..state.tiered import slot_directory_stats
            out["tier"] = {**slot_directory_stats(state),
                           **self._tier.controller.stats()}
            out["overflow_drops"] = int(np.asarray(state["ovf"]))
        if n_open:
            # age of the longest-open session: how much event time the
            # watermark has advanced past its first event
            start = np.asarray(state["start"])
            out["oldest_open_age"] = max(0, wm - int(start[open_mask].min()))
        counts = _et.read_hist(state.get("lat_hist"))
        if counts is not None:
            out["lateness"] = {"in": _et.summarize(counts)}
        return out
