"""Filter — drop-by-predicate.

Counterpart of ``wf/filter.hpp`` (class at ``:60``, signature slots ``:63-76``): the
reference supports ``bool(tuple&)`` plus optional-returning transforming variants and
rich forms. Here the predicate ``f(t) -> bool`` runs under ``vmap`` and *intersects the
validity mask* — no data movement at all, the cheapest possible filter on TPU (the
reference's FilterGPU computes a mask then compacts with a device scan,
``wf/filter_gpu_node.hpp``; here compaction is a separate opt-in ``Compact`` operator
since downstream operators are mask-aware).

The transforming variant (reference ``optional<result>(const tuple&)``) is covered by
``FilterMap``: ``f(t) -> (payload, keep)``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..basic import routing_modes_t
from ..batch import Batch, TupleRef, tuple_refs
from ..context import RuntimeContext
from ..meta import classify_filter
from .base import Basic_Operator


class Filter(Basic_Operator):
    """Both reference Filter flavours through one constructor, deduced from the
    return value (``wf/filter.hpp:63-76``, ``/root/reference/API`` FILTER):

    - predicate  ``f(t) -> bool``: intersects the validity mask;
    - optional   ``f(t) -> (payload, keep)``: transform + drop in one op — the
      ``std::optional<result_t>(const tuple_t&)`` signature (``keep`` plays the
      optional's engaged flag; data-dependent ``None`` is untraceable).

    Rich variants append a context parameter."""

    def __init__(self, fn: Callable, *, name: str = "filter", parallelism: int = 1,
                 keyed: bool = False, context: Optional[RuntimeContext] = None):
        super().__init__(name, parallelism)
        self.fn = fn
        self.is_rich = classify_filter(fn)
        self.routing = routing_modes_t.KEYBY if keyed else routing_modes_t.FORWARD
        self.context = context or RuntimeContext(parallelism, 0)

    def _call(self, t):
        r = (self.fn(t, self.context) if self.is_rich else self.fn(t))
        if isinstance(r, tuple):
            if len(r) != 2:
                from ..meta import SignatureError
                raise SignatureError(
                    "Filter: accepted signatures are\n"
                    "  f(t[, ctx]) -> bool                (predicate)\n"
                    "  f(t[, ctx]) -> (payload, keep)     (optional/transforming)\n"
                    f"(catalogue: /root/reference/API FILTER); got a {len(r)}-tuple")
            return r
        return r

    def out_spec(self, payload_spec: Any) -> Any:
        t = TupleRef(key=jax.ShapeDtypeStruct((), jnp.int32),
                     id=jax.ShapeDtypeStruct((), jnp.int32),
                     ts=jax.ShapeDtypeStruct((), jnp.int32), data=payload_spec)
        out = jax.eval_shape(self._call, t)
        return out[0] if isinstance(out, tuple) else payload_spec

    def apply(self, state, batch: Batch):
        out = jax.vmap(self._call)(tuple_refs(batch))
        if isinstance(out, tuple):
            payload, keep = out
            return state, batch.with_payload(payload).mask(
                jnp.asarray(keep, jnp.bool_))
        return state, batch.mask(jnp.asarray(out, jnp.bool_))


class FilterMap(Filter):
    """Named alias for the transforming Filter flavour: ``f(t) -> (payload, keep)``
    — the reference's ``optional<result>(const tuple&)`` signature
    (``wf/filter.hpp:63-76``). :class:`Filter` deduces the same flavour from the
    return value; this class only fixes the default name."""

    def __init__(self, fn: Callable, *, name: str = "filtermap", parallelism: int = 1,
                 context: Optional[RuntimeContext] = None):
        super().__init__(fn, name=name, parallelism=parallelism, context=context)


class Compact(Basic_Operator):
    """Pack live lanes to the front (stable). Opt-in densification after filters with
    low selectivity — the explicit analogue of the reference GPU compaction pass
    (``wf/standard_nodes_gpu.hpp:52-238``)."""

    def __init__(self, *, name: str = "compact"):
        super().__init__(name, 1)

    def apply(self, state, batch: Batch):
        return state, batch.compact()
