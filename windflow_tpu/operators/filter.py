"""Filter — drop-by-predicate.

Counterpart of ``wf/filter.hpp`` (class at ``:60``, signature slots ``:63-76``): the
reference supports ``bool(tuple&)`` plus optional-returning transforming variants and
rich forms. Here the predicate ``f(t) -> bool`` runs under ``vmap`` and *intersects the
validity mask* — no data movement at all, the cheapest possible filter on TPU (the
reference's FilterGPU computes a mask then compacts with a device scan,
``wf/filter_gpu_node.hpp``; here compaction is a separate opt-in ``Compact`` operator
since downstream operators are mask-aware).

The transforming variant (reference ``optional<result>(const tuple&)``) is covered by
``FilterMap``: ``f(t) -> (payload, keep)``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..basic import routing_modes_t
from ..batch import Batch, tuple_refs
from ..context import RuntimeContext
from ..meta import classify_filter
from .base import Basic_Operator


class Filter(Basic_Operator):
    def __init__(self, fn: Callable, *, name: str = "filter", parallelism: int = 1,
                 keyed: bool = False, context: Optional[RuntimeContext] = None):
        super().__init__(name, parallelism)
        self.fn = fn
        self.is_rich = classify_filter(fn)
        self.routing = routing_modes_t.KEYBY if keyed else routing_modes_t.FORWARD
        self.context = context or RuntimeContext(parallelism, 0)

    def apply(self, state, batch: Batch):
        fn = (lambda x: self.fn(x, self.context)) if self.is_rich else self.fn
        keep = jax.vmap(fn)(tuple_refs(batch))
        return state, batch.mask(jnp.asarray(keep, jnp.bool_))


class FilterMap(Basic_Operator):
    """Transform + drop in one op: ``f(t) -> (payload, keep)`` — the reference's
    ``optional<result>(const tuple&)`` Filter signature (``wf/filter.hpp:63-76``)."""

    def __init__(self, fn: Callable, *, name: str = "filtermap", parallelism: int = 1,
                 context: Optional[RuntimeContext] = None):
        super().__init__(name, parallelism)
        self.fn = fn
        self.is_rich = classify_filter(fn)
        self.context = context or RuntimeContext(parallelism, 0)

    def out_spec(self, payload_spec: Any) -> Any:
        from ..batch import TupleRef
        t = TupleRef(key=jax.ShapeDtypeStruct((), jnp.int32),
                     id=jax.ShapeDtypeStruct((), jnp.int32),
                     ts=jax.ShapeDtypeStruct((), jnp.int32), data=payload_spec)
        fn = (lambda x: self.fn(x, self.context)) if self.is_rich else self.fn
        out, _ = jax.eval_shape(fn, t)
        return out

    def apply(self, state, batch: Batch):
        fn = (lambda x: self.fn(x, self.context)) if self.is_rich else self.fn
        payload, keep = jax.vmap(fn)(tuple_refs(batch))
        return state, batch.with_payload(payload).mask(jnp.asarray(keep, jnp.bool_))


class Compact(Basic_Operator):
    """Pack live lanes to the front (stable). Opt-in densification after filters with
    low selectivity — the explicit analogue of the reference GPU compaction pass
    (``wf/standard_nodes_gpu.hpp:52-238``)."""

    def __init__(self, *, name: str = "compact"):
        super().__init__(name, 1)

    def apply(self, state, batch: Batch):
        return state, batch.compact()
