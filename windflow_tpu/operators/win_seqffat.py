"""Win_SeqFFAT — incremental associative window engine with pane-level sharing.

Counterpart of ``wf/win_seqffat.hpp:57-694`` + ``wf/flatfat.hpp:52-400`` (FlatFAT,
Tangwongsan et al. VLDB'15) and their GPU versions (``wf/win_seqffat_gpu.hpp``,
``wf/flatfat_gpu.hpp:51-130``: per-level tree kernels + prefix/suffix walks). The goal
of FlatFAT is *sharing*: O(log n) incremental update instead of recomputing each
window from scratch.

TPU re-design: the tree is replaced by **pane partials** (gcd-free: pane = slide for
tumbling/sliding CB; configurable) — each tuple is lifted once (``lift(t) -> agg``) and
segment-reduced into its (key, pane) partial; a fired window combines its
``win_len/pane_len`` pane partials with a tree reduction over the pane axis. This is
the same work-sharing as FlatFAT (each tuple touches O(1) partials; each window
combines O(L/pane) —  with panes = slide that is the "no pane, no gain" decomposition
the reference's Pane_Farm uses, ``wf/pane_farm.hpp:175``), expressed as segment ops the
MXU/VPU likes instead of pointer-chasing tree levels. Non-commutative combines are
supported: pane partials are folded in ascending pane order by an order-preserving
tree reduction (association changes, operand order does not — the same guarantee as
FlatFAT's prefix/suffix walks; see ``tests/test_ffat_noncommutative.py``).

Requirements: ``combine`` associative with ``identity``; window result =
``fold(combine, lifted tuples in window)`` — the Win_SeqFFAT contract (winLift +
winComb functions, ``wf/builders.hpp`` WinSeqFFAT_Builder:950).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..basic import routing_modes_t, DEFAULT_MAX_KEYS
from ..batch import Batch, CTRL_DTYPE, TupleRef
from ..observability import event_time as _et
from ..ops.segment import segment_reduce
from .base import Basic_Operator
from .window import WindowSpec


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FFATState:
    panes: Any            # pytree [K, P, ...] ring of pane partials
    pane_count: jax.Array  # i32[K, P] tuples folded into each pane slot
    pane_of: jax.Array    # i32[K, P] pane id held by each ring slot (-1 empty)
    count: jax.Array      # i32[K] tuples seen per key (CB position source)
    wm: jax.Array         # i32[K] per-key max ts
    next_win: jax.Array   # i32[K]
    dropped_old: jax.Array  # i32[] tuples dropped as OLD (TB straggler drops)
    #: i32[NB] observed-lateness histogram (event-time monitoring only —
    #: None otherwise, an empty pytree subtree, so the off program is
    #: unchanged; observability/event_time.py)
    lat_hist: Any = None


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GFFATState:
    """State of the global-time TB fast path: the stream shares one event clock, so
    watermark/next-window are scalars and no per-tuple gather from per-key tables is
    needed — the insert is ONE scatter-add (plus one for occupancy counts)."""

    panes: Any            # pytree [K, P, ...] ring of pane partials
    cnt: jax.Array        # i32[K, P] tuples per pane slot (emptiness filter)
    wm: jax.Array         # i32[] global max ts seen
    next_win: jax.Array   # i32[] next window id to fire (global)
    dropped_old: jax.Array  # i32[] tuples dropped as OLD (pane < fired horizon)
    #: i32[NB] observed-lateness histogram (event-time monitoring only)
    lat_hist: Any = None


class Win_SeqFFAT(Basic_Operator):
    routing = routing_modes_t.KEYBY

    def __init__(self, lift: Callable, combine: Callable, *, spec: WindowSpec,
                 identity: Any = 0, num_keys: int = DEFAULT_MAX_KEYS,
                 pane_len: int = None, pane_capacity: int = None,
                 max_wins: int = None, name: str = "win_seqffat",
                 parallelism: int = 1, global_time: bool = None,
                 count_lift: bool = None):
        super().__init__(name, parallelism)
        import math
        # global_time (TB only): all keys share the event clock — watermark and the
        # fired-window frontier become scalars, removing every per-tuple gather from
        # the hot path (take() costs ~5.6 ns/elem on TPU; scatter-add ~7 — the insert
        # becomes two scatters total). Default on for TB: streaming benchmarks and
        # real event streams share one clock (the reference's TB windows likewise
        # advance on tuple timestamps, wf/window.hpp:83-121). CAVEAT: the frontier
        # advances on the GLOBAL watermark, so a key whose tuples lag more than
        # `delay` behind the fastest key's clock has its stragglers dropped as OLD
        # once their panes fall behind the fired horizon — per-key skew > delay DOES
        # change window contents (the per-key-watermark path only delays firing).
        # Drops are counted on device (state.dropped_old) and surfaced through
        # Stats_Record.tuples_dropped_old / the monitoring graph snapshot.
        self.global_time = (not spec.is_cb) if global_time is None else global_time
        if self.global_time and spec.is_cb:
            raise ValueError("global_time applies to TB windows only")
        self.lift = lift
        self.combine = combine
        self.identity = identity
        #: lift(t) == 1 for every t (windowed count): the pane-value update equals
        #: the occupancy histogram and rides the MXU path. None = auto-detect.
        self.count_lift = count_lift
        self.spec = spec
        self.num_keys = int(num_keys)
        # pane length: gcd(win, slide) — every window is a whole number of panes and
        # every pane belongs to a whole number of windows (wf/pane_farm.hpp:175)
        self.pane_len = pane_len or math.gcd(spec.win_len, spec.slide)
        if spec.win_len % self.pane_len or spec.slide % self.pane_len:
            raise ValueError("pane_len must divide both win_len and slide")
        self.wpanes = spec.win_len // self.pane_len     # panes per window
        self.spanes = spec.slide // self.pane_len       # panes per slide
        self._pane_capacity = pane_capacity
        self.P = None
        self.max_wins = max_wins
        self._w = None
        self.bind_geometry(256)        # provisional; compiler re-binds with real C

    def bind_geometry(self, batch_capacity: int) -> None:
        if self._pane_capacity is not None:
            self.P = _next_pow2(self._pane_capacity)
        elif self.spec.is_cb:
            # one batch on a single key touches at most C/pane_len + 1 new panes
            self.P = _next_pow2(self.wpanes + batch_capacity // self.pane_len + 2)
        else:
            # TB: panes indexed by ts//pane_len; a batch touches at most
            # ts_span/pane_len distinct panes — bounded by C but normally far fewer.
            # Default to C/pane_len + window span (override with pane_capacity for
            # very bursty timestamp distributions).
            self.P = _next_pow2(self.wpanes
                                + max(64, batch_capacity // self.pane_len) + 2)

    def out_capacity(self, in_capacity: int) -> int:
        if self.global_time:
            return self.num_keys * self._resolve_w(in_capacity)
        return self._resolve_w(in_capacity)

    # ------------------------------------------------------------------ state

    def _lift_spec(self, payload_spec):
        t = TupleRef(key=jax.ShapeDtypeStruct((), CTRL_DTYPE),
                     id=jax.ShapeDtypeStruct((), CTRL_DTYPE),
                     ts=jax.ShapeDtypeStruct((), CTRL_DTYPE), data=payload_spec)
        return jax.eval_shape(self.lift, t)

    def init_state(self, payload_spec: Any):
        K, P = self.num_keys, self.P
        agg = self._lift_spec(payload_spec)
        # lateness histogram: event-time monitoring on TB specs only (CB has
        # no event-time frontier); None = absent from the pytree
        lat = (_et.lateness_init()
               if self._event_time and not self.spec.is_cb else None)
        if self.global_time:
            return GFFATState(
                panes=jax.tree.map(
                    lambda s: jnp.broadcast_to(
                        jnp.asarray(self.identity, s.dtype),
                        (K, P) + s.shape).copy(), agg),
                cnt=jnp.zeros((K, P), CTRL_DTYPE),
                wm=jnp.asarray(-1, CTRL_DTYPE),
                next_win=jnp.asarray(0, CTRL_DTYPE),
                dropped_old=jnp.zeros((), CTRL_DTYPE),
                lat_hist=lat,
            )
        return FFATState(
            panes=jax.tree.map(
                lambda s: jnp.broadcast_to(
                    jnp.asarray(self.identity, s.dtype),
                    (K, P) + s.shape).copy(), agg),
            pane_count=jnp.zeros((K, P), CTRL_DTYPE),
            pane_of=jnp.full((K, P), -1, CTRL_DTYPE),
            count=jnp.zeros((K,), CTRL_DTYPE),
            wm=jnp.full((K,), -1, CTRL_DTYPE),
            next_win=jnp.zeros((K,), CTRL_DTYPE),
            dropped_old=jnp.zeros((), CTRL_DTYPE),
            lat_hist=lat,
        )

    def out_spec(self, payload_spec: Any) -> Any:
        return self._lift_spec(payload_spec)

    # ---------------------------------------------------- global-time fast path (TB)

    def _g_insert(self, state: GFFATState, batch: Batch):
        """Fold a batch into the [K, P] pane ring. The occupancy counts — and, for a
        count-like lift (lift(t) == 1, the YSB/windowed-count case), the partials
        themselves — go through the MXU histogram (``ops/histogram.py``) instead of a
        serialized scatter-add; other additive lifts take the segment-fold path
        (``ops/segment.py::segment_fold``). Both are kernel-registry families
        (``"histogram"``/``"segment_fold"``, ``ops/registry.py``) — the impl is
        resolved at trace time per (kernel, shape spec, device), so this fold
        call site A/Bs between XLA and the fused Pallas kernels via
        ``WF_KERNEL_IMPL`` with no code change here. Slot cleanliness is
        maintained by clear-on-fire in ``_g_emit`` so no pane-id bookkeeping is
        needed; OLD tuples (pane already fired) are dropped with a scalar
        horizon compare."""
        from ..ops.histogram import keyed_pane_histogram
        K, P = self.num_keys, self.P
        pane = batch.ts // self.pane_len
        horizon = state.next_win * self.spanes       # first un-fired pane (global)
        valid = batch.valid & (pane >= horizon)
        # stragglers behind the fired horizon are DROPPED, not merely delayed
        # (global clock: per-key skew > delay loses tuples) — count them
        n_dropped = jnp.sum((batch.valid & ~valid).astype(CTRL_DTYPE))
        cnt_upd = keyed_pane_histogram(batch.key, pane, valid, K, P)
        cnt = state.cnt + cnt_upd
        if self.count_lift is None:
            self.count_lift = _detect_count_lift(self.lift, batch)
        if self.count_lift and self.combine is jnp.add:
            # lift == 1: the value histogram IS the count histogram
            panes = jax.tree.map(
                lambda t: t + cnt_upd.astype(t.dtype), state.panes)
        else:
            slot = pane % P
            seg = jnp.where(valid, batch.key * P + slot, K * P)
            lifted = jax.vmap(self.lift)(TupleRef(
                key=batch.key, id=batch.id, ts=batch.ts, data=batch.payload))
            if self.combine is jnp.add:
                upd = segment_reduce(lifted, seg, valid, K * P)
                panes = jax.tree.map(
                    lambda t, u: t + u.reshape((K, P) + u.shape[1:]),
                    state.panes, upd)
            else:
                upd = segment_reduce(lifted, seg, valid, K * P,
                                     combine=self.combine, identity=self.identity)
                panes = jax.tree.map(
                    lambda t, u: self.combine(t, u.reshape((K, P) + u.shape[1:])),
                    state.panes, upd)
        wm_new = jnp.maximum(state.wm,
                             jnp.max(jnp.where(batch.valid, batch.ts, -1)))
        lat = state.lat_hist
        if lat is not None:
            # observed lateness vs the post-batch global watermark: one
            # masked reduction, state-only (event-time monitoring).  A
            # delay >= the recorded max keeps every straggler's pane ahead
            # of the fired horizon — zero OLD drops (recommend_delay).
            lat = _et.lateness_update(lat, wm_new, batch.ts, batch.valid)
        return dataclasses.replace(
            state,
            panes=panes,
            cnt=cnt,
            wm=wm_new,
            dropped_old=state.dropped_old + n_dropped,
            lat_hist=lat,
        )

    def _g_emit(self, state: GFFATState, W_n: int, flush: bool):
        """Grid emission: the fired window range [lo, hi) is shared by every key, so
        the output is a [W_n, K] grid flattened — no searchsorted, no index math.
        Fired panes are cleared back to identity (ring hygiene) with an elementwise
        cyclic-interval mask over the [K, P] table — no scatter."""
        K, P = self.num_keys, self.P
        s = self.spec
        lo = state.next_win
        if flush:
            hi = jnp.maximum(lo, state.wm // s.slide + 1)
        else:
            hi = jnp.maximum(lo, (state.wm - s.delay - s.win_len) // s.slide + 1)
        hi = jnp.minimum(hi, lo + W_n)
        n_w = hi - lo

        wid = lo + jnp.arange(W_n, dtype=CTRL_DTYPE)          # [W_n]
        w_valid = jnp.arange(W_n, dtype=CTRL_DTYPE) < n_w
        # The fired windows' panes form a CONTIGUOUS cyclic range starting at
        # lo*spanes: roll the ring so it starts at column 0, then extraction is a
        # static strided window — no dynamic gather at all. (Fallback to a dynamic
        # take when the static window would overrun the ring.)
        static_span = (W_n - 1) * self.spanes + self.wpanes
        if static_span <= P:
            shift = (lo * self.spanes) % P
            idx = (jnp.arange(W_n, dtype=CTRL_DTYPE)[:, None] * self.spanes
                   + jnp.arange(self.wpanes, dtype=CTRL_DTYPE)[None, :])

            def gat(tbl):                                     # tbl [K, P, ...]
                rolled = jnp.roll(tbl, -shift, axis=1)
                g = jnp.take(rolled, idx.reshape(-1), axis=1)  # static indices
                return g.reshape((K, W_n, self.wpanes) + tbl.shape[2:])
        else:
            pane_ids = wid[:, None] * self.spanes + jnp.arange(
                self.wpanes, dtype=CTRL_DTYPE)[None, :]       # [W_n, wpanes]
            slot = pane_ids % P

            def gat(tbl):                                     # tbl [K, P, ...]
                g = jnp.take(tbl, slot.reshape(-1), axis=1)   # [K, W_n*wpanes, ...]
                return g.reshape((K, W_n, self.wpanes) + tbl.shape[2:])
        cnts = gat(state.cnt)                                 # [K, W_n, wpanes]
        win_cnt = jnp.sum(cnts, axis=2)                       # [K, W_n]
        def reduce_w(tbl):
            g = gat(tbl)                                      # [K, W_n, wpanes, ...]
            if self.combine is jnp.add:
                m = (cnts > 0).reshape(cnts.shape + (1,) * (g.ndim - 3))
                return jnp.sum(jnp.where(m, g, 0), axis=2)
            return _tree_reduce(self.combine, g, axis=2)
        results = jax.tree.map(reduce_w, state.panes)         # [K, W_n, ...]

        valid = (win_cnt > 0) & w_valid[None, :]              # empty windows not emitted
        res_ts = wid * s.slide + (s.win_len - 1)              # [W_n]
        flat = lambda a: a.reshape((K * W_n,) + a.shape[2:])
        out = Batch(
            key=flat(jnp.broadcast_to(jnp.arange(K, dtype=CTRL_DTYPE)[:, None],
                                      (K, W_n))),
            id=flat(jnp.broadcast_to(wid[None, :], (K, W_n))),
            ts=flat(jnp.broadcast_to(res_ts[None, :], (K, W_n))),
            payload=jax.tree.map(flat, results),
            valid=flat(valid),
        )
        # clear fired panes [lo*spanes, hi*spanes) — cyclic interval mask over [P]
        first, last = lo * self.spanes, hi * self.spanes      # clear [first, last)
        pos = jnp.arange(P, dtype=CTRL_DTYPE)
        # slot s holds a fired pane iff exists p in [first,last) with p % P == s;
        # since last-first <= P, that is a cyclic interval test
        rel = (pos - first % P) % P
        clear = rel < (last - first)
        panes = jax.tree.map(
            lambda t: jnp.where(clear.reshape((1, P) + (1,) * (t.ndim - 2)),
                                jnp.asarray(self.identity, t.dtype), t),
            state.panes)
        cnt = jnp.where(clear[None, :], 0, state.cnt)
        return dataclasses.replace(state, panes=panes, cnt=cnt, next_win=hi), out

    # ------------------------------------------------------------------ insert

    def _insert(self, state: FFATState, batch: Batch):
        """Lift each tuple and fold it into its (key, pane) partial: the FlatFAT
        'update leaf + bubble' (wf/flatfat.hpp:134-240) collapsed into one segment
        reduction per batch. The additive folds (values, occupancy counts) route
        through the registry-selectable ``segment_fold`` kernel — see
        ``_g_insert`` for the selection contract."""
        from ..ops.segment import segment_rank
        from ..ops.lookup import table_lookup
        K, P = self.num_keys, self.P
        valid = batch.valid
        if self.spec.is_cb:
            rank = segment_rank(batch.key, valid)
            pos = table_lookup(state.count, batch.key) + rank
            pane = pos // self.pane_len
            n_dropped = jnp.zeros((), CTRL_DTYPE)    # CB never drops OLD tuples
        else:
            horizon = table_lookup(state.next_win, batch.key) * self.spec.slide
            kept = valid & (batch.ts >= horizon)
            n_dropped = jnp.sum((valid & ~kept).astype(CTRL_DTYPE))
            valid = kept
            pane = batch.ts // self.pane_len
        slot = pane % P
        seg = jnp.where(valid, batch.key * P + slot, K * P)

        lifted = jax.vmap(self.lift)(
            TupleRef(key=batch.key, id=batch.id, ts=batch.ts, data=batch.payload))
        # per-(key,pane-slot) partial of this batch
        upd = segment_reduce(lifted, seg, valid, K * P,
                             combine=None if self.combine is jnp.add else self.combine,
                             identity=self.identity)
        cnt_upd = segment_reduce(valid.astype(CTRL_DTYPE), seg, valid, K * P)
        pane_id_upd = segment_reduce(pane, seg, valid, K * P,
                                     combine=jnp.maximum, identity=-1)

        touched = cnt_upd.reshape(K, P) > 0
        new_pane_of = jnp.where(touched, pane_id_upd.reshape(K, P), state.pane_of)
        # a slot whose pane id advanced (ring wrap) restarts from identity
        fresh = touched & (new_pane_of != state.pane_of)

        def fold(tbl, u):
            u = u.reshape((K, P) + u.shape[1:])
            t = jnp.where(_b(fresh, tbl), jnp.asarray(self.identity, tbl.dtype), tbl)
            m = _b(touched, tbl)
            if self.combine is jnp.add:
                return jnp.where(m, t + u, t)
            return jnp.where(m, self.combine(t, u), t)

        counts_add = segment_reduce(valid.astype(CTRL_DTYPE), batch.key, valid, K)
        ts_max = segment_reduce(batch.ts, batch.key, valid, K,
                                combine=jnp.maximum, identity=-1)
        wm_new = jnp.maximum(state.wm, ts_max)
        lat = state.lat_hist
        if lat is not None:
            # per-key TB path: lateness vs the MAX per-key watermark — the
            # cross-key skew measure (a lagging key's tuples land in high
            # buckets even though its own frontier fires late)
            lat = _et.lateness_update(lat, jnp.max(wm_new), batch.ts,
                                      batch.valid)
        return dataclasses.replace(
            state,
            panes=jax.tree.map(fold, state.panes, upd),
            pane_count=jnp.where(fresh, 0, state.pane_count) + cnt_upd.reshape(K, P),
            pane_of=new_pane_of,
            count=state.count + counts_add,
            wm=wm_new,
            dropped_old=state.dropped_old + n_dropped,
            lat_hist=lat,
        )

    # ------------------------------------------------------------------ fire

    def _emit(self, state: FFATState, W: int, flush: bool):
        K, P = self.num_keys, self.P
        s = self.spec
        if s.is_cb:
            hi = (jnp.where(state.count > 0, (state.count - 1) // s.slide + 1, 0)
                  if flush else jnp.maximum(0, (state.count - s.win_len) // s.slide + 1))
        else:
            hi = (jnp.where(state.count > 0, state.wm // s.slide + 1, 0)
                  if flush else jnp.maximum(0, (state.wm - s.delay - s.win_len) // s.slide + 1))
        lo = state.next_win
        hi = jnp.maximum(hi, lo)
        n_f = hi - lo
        csum = jnp.cumsum(n_f)
        off = csum - n_f
        total = csum[-1]
        w_idx = jnp.arange(W, dtype=CTRL_DTYPE)
        k_of = jnp.searchsorted(csum, w_idx, side="right").astype(CTRL_DTYPE)
        k_safe = jnp.minimum(k_of, K - 1)
        wid = jnp.take(lo, k_safe) + (w_idx - jnp.take(off, k_safe))
        valid_w = w_idx < jnp.minimum(total, W)
        emitted_k = jnp.clip(jnp.minimum(total, W) - off, 0, n_f)

        # gather the wpanes panes of each window and tree-reduce (getResult():
        # wf/flatfat.hpp root read; here a log-depth reduction over the pane axis)
        pane0 = wid * self.spanes
        pane_ids = pane0[:, None] + jnp.arange(self.wpanes, dtype=CTRL_DTYPE)[None, :]
        slot = pane_ids % P
        gflat = k_safe[:, None] * P + slot                      # [W, wpanes]
        live = jnp.take(state.pane_of.reshape(K * P), gflat) == pane_ids
        live &= valid_w[:, None]

        def gat_reduce(tbl):
            g = jnp.take(tbl.reshape((K * P,) + tbl.shape[2:]), gflat, axis=0)
            g = jnp.where(_b(live, g), g, jnp.asarray(self.identity, g.dtype))
            if self.combine is jnp.add:
                return jnp.sum(g, axis=1)
            return _tree_reduce(self.combine, g, axis=1)

        results = jax.tree.map(gat_reduce, state.panes)
        res_ts = (wid * s.slide + s.win_len - 1 if not s.is_cb
                  else jnp.zeros_like(wid))
        out = Batch(key=k_safe, id=wid, ts=jnp.asarray(res_ts, CTRL_DTYPE),
                    payload=results, valid=valid_w)
        return dataclasses.replace(state, next_win=lo + emitted_k), out

    # ------------------------------------------------------------------ operator API

    def _resolve_w(self, capacity):
        if self.max_wins is not None:
            return self.max_wins
        if self.global_time:
            # windows drainable per step, bounded by what the pane ring can hold
            return max(4, (self.P - self.wpanes) // self.spanes)
        W = max(16, -(-capacity // self.spec.slide) + 64)
        if W * self.wpanes > (1 << 22):
            # same adversarial-slide guard as Win_Seq._resolve_w: a window
            # combines wpanes pane partials, so the default budget implies a
            # [W, wpanes] gather per batch — force an explicit budget
            raise ValueError(
                f"{self.name}: default fired-window budget W={W} with "
                f"{self.wpanes} panes/window implies a [{W}, {self.wpanes}] "
                f"gather per batch; pass max_wins= to bound it")
        return W

    def apply(self, state, batch: Batch):
        W = self._resolve_w(batch.capacity)
        self._w = W
        if self.global_time:
            state = self._g_insert(state, batch)
            return self._g_emit(state, W, flush=False)
        state = self._insert(state, batch)
        return self._emit(state, W, flush=False)

    def flush(self, state):
        W = self._w or self._resolve_w(256)
        if not hasattr(self, "_flush_jit"):
            emit = self._g_emit if self.global_time else self._emit
            self._flush_jit = jax.jit(lambda st: emit(st, W, flush=True))
        state, out = self._flush_jit(state)
        self.collect_stats(state)
        if not bool(jnp.any(out.valid)):
            return state, None
        return state, out

    def collect_stats(self, state=None) -> None:
        """Sync the device-resident OLD-drop counter into the Stats_Record
        (monitoring snapshot / EOS — one scalar D2H read, off the hot path)."""
        if state is None or not hasattr(state, "dropped_old"):
            return
        import numpy as np
        old = int(np.asarray(state.dropped_old))
        self._stats[0].tuples_dropped_old = old
        self._publish_stage_counters({"old_drops": old})

    def drop_counters(self, state=None) -> dict:
        if state is None or not hasattr(state, "dropped_old"):
            return {}
        import numpy as np
        return {"old_drops": int(np.asarray(state.dropped_old))}

    def event_time_stats(self, state=None):
        """Watermark-map section (TB specs): the event-time frontier, the
        fired-window horizon, arrived-but-unfired lag, OLD drops, and the
        observed-lateness histogram whose ``recommend_delay`` names the
        smallest ``delay=`` that would have kept the recorded stragglers."""
        if state is None or self.spec.is_cb:
            return None
        import numpy as np
        wm = int(np.asarray(state.wm).max())
        nxt = int(np.asarray(state.next_win).max())
        frontier = nxt * self.spec.slide
        out = {
            "watermark_ts": wm,
            "fire_frontier_ts": frontier,
            "lag": max(wm - frontier + 1, 0) if wm >= 0 else 0,
            "delay": self.spec.delay,
            "old_drops": int(np.asarray(state.dropped_old)),
        }
        counts = _et.read_hist(getattr(state, "lat_hist", None))
        if counts is not None:
            out["lateness"] = {"in": _et.summarize(counts)}
        return out


def _detect_count_lift(lift, batch) -> bool:
    """True iff ``lift`` provably returns the constant scalar 1 for every tuple:
    its jaxpr output must not depend on the input vars, and its value on a zero
    tuple must be 1. Conservative — any doubt returns False."""
    import numpy as np
    dummy = TupleRef(
        key=jax.ShapeDtypeStruct((), CTRL_DTYPE),
        id=jax.ShapeDtypeStruct((), CTRL_DTYPE),
        ts=jax.ShapeDtypeStruct((), CTRL_DTYPE),
        data=jax.tree.map(lambda l: jax.ShapeDtypeStruct(l.shape[1:], l.dtype),
                          batch.payload))
    try:
        from jax.extend import core as jex_core
        literal_t = jex_core.Literal
    except ImportError:
        from jax._src.core import Literal as literal_t
    try:
        closed = jax.make_jaxpr(lift)(dummy)
        jaxpr = closed.jaxpr
        tainted = {id(v) for v in jaxpr.invars}
        for eqn in jaxpr.eqns:
            if any(not isinstance(v, literal_t) and id(v) in tainted
                   for v in eqn.invars):
                tainted |= {id(v) for v in eqn.outvars}
        if any(not isinstance(v, literal_t) and id(v) in tainted
               for v in jaxpr.outvars):
            return False
        zero = TupleRef(
            key=np.zeros((), np.int32), id=np.zeros((), np.int32),
            ts=np.zeros((), np.int32),
            data=jax.tree.map(lambda l: np.zeros(l.shape[1:], l.dtype),
                              batch.payload))
        # Detection runs INSIDE the chain's jit trace, where every jnp op —
        # even a constant like jnp.ones(()) — returns a tracer of the ambient
        # trace and float() raises ConcretizationTypeError. Without the escape
        # hatch the blanket except returned False and the YSB/windowed-count
        # chain silently took the serialized segment-sum fallback for the
        # panes update (~5.4 ms/step at 1M batch, the whole window-stage
        # anomaly of BASELINE.md's ablation); standalone probes passed
        # detection and never saw it.
        with jax.ensure_compile_time_eval():
            out = jax.tree.leaves(lift(zero))
            return (len(out) == 1 and np.shape(out[0]) == ()
                    and float(out[0]) == 1.0)
    except Exception:
        return False


def _b(mask, v):
    return mask.reshape(mask.shape + (1,) * (v.ndim - mask.ndim))


def _tree_reduce(combine, x, axis):
    """Log-depth reduction with an arbitrary associative combine."""
    n = x.shape[axis]
    while n > 1:
        half = n // 2
        a = jax.lax.slice_in_dim(x, 0, half, axis=axis)
        b = jax.lax.slice_in_dim(x, half, 2 * half, axis=axis)
        rest = jax.lax.slice_in_dim(x, 2 * half, n, axis=axis)
        x = jnp.concatenate([combine(a, b), rest], axis=axis)
        n = half + (n - 2 * half)
    return jnp.squeeze(x, axis=axis)


def _next_pow2(n):
    p = 1
    while p < n:
        p *= 2
    return p
