"""Win_Seq — THE sequential window engine, vectorized.

Counterpart of ``wf/win_seq.hpp:56-567`` (svc ``:304-465``, EOS flush ``:468-529``)
with ``StreamArchive`` (``wf/stream_archive.hpp``) fused in: per-key archives live as
HBM ring buffers ``[K, A]``; each micro-batch (1) scatters its tuples into the rings,
(2) advances per-key counts/watermarks, (3) computes the FIRED window range per key
with batch-level triggerer arithmetic (``window.py``), (4) gathers up to ``max_wins``
fired windows as rows ``[W, L]`` and (5) applies the user window function across the
window axis with ``vmap`` — the direct TPU generalization of the reference GPU engine's
one-thread-per-window ``ComputeBatch_Kernel`` (``wf/win_seq_gpu.hpp:57-82,352-560``),
with the whole archive resident on device (no H2D flattening step at all).

User function flavours (``wf/meta.hpp`` window families):
- non-incremental: ``f(wid, iterable) -> result_payload`` over an :class:`Iterable`;
- incremental (fold): ``f(wid, t, acc) -> acc`` via ``lax.scan`` across the window axis
  (``winupdate_func`` semantics, ``wf/win_seq.hpp:389-397``).

CB windows index per-key *arrival position* (the reference's TS_RENUMBERING-style
progressive ids, ``wf/basic.hpp:129``); TB windows index timestamps with per-key
watermarks and ``delay`` lateness. Windows whose turn exceeds the per-batch ``max_wins``
budget defer to the next batch (``next_win`` only advances past emitted windows).

Emission order is per-key ascending window id — the ordered-collector guarantee of
``WF_Collector`` (``wf/wf_nodes.hpp:253-318``) by construction.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..basic import routing_modes_t, role_t, DEFAULT_MAX_KEYS
from ..batch import Batch, CTRL_DTYPE, TupleRef
from ..meta import classify_window, classify_winupdate
from ..ops.segment import segment_rank, segment_reduce
from .base import Basic_Operator
from .window import Iterable, WindowSpec


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class WinSeqState:
    arch_payload: Any     # pytree [K, A, ...]
    arch_id: jax.Array    # i32[K, A] global tuple id of each slot
    arch_ts: jax.Array    # i32[K, A]
    arch_pos: jax.Array   # i32[K, A] arrival position held by slot (-1 = empty)
    count: jax.Array      # i32[K] tuples archived per key
    wm: jax.Array         # i32[K] per-key max ts seen
    next_win: jax.Array   # i32[K] next window id to fire


class Win_Seq(Basic_Operator):
    routing = routing_modes_t.KEYBY

    def __init__(self, win_fn: Callable, spec: WindowSpec, *,
                 incremental: Optional[bool] = None, init_acc: Any = None,
                 num_keys: int = DEFAULT_MAX_KEYS, archive_capacity: int = None,
                 max_wins: int = None, tb_capacity: int = None,
                 name: str = "win_seq", parallelism: int = 1,
                 role: role_t = role_t.SEQ, context=None):
        super().__init__(name, parallelism)
        self.win_fn = win_fn
        self.spec = spec
        if incremental is None:
            # flavour deduced from the callable, like the reference's static
            # dispatch between Iterable and winupdate signatures (wf/meta.hpp
            # window families; catalogue /root/reference/API KEY_FARM/WIN_FARM)
            from ..meta import classify_window_flavour
            incremental, self.is_rich = classify_window_flavour(win_fn)
        elif incremental:
            self.is_rich = classify_winupdate(win_fn)
        else:
            self.is_rich = classify_window(win_fn)
        self.incremental = incremental
        self.init_acc = init_acc
        if incremental and init_acc is None:
            from ..meta import RICH_PARAM_NAMES
            raise ValueError(
                f"{name}: incremental window function f(wid, t, acc) -> acc "
                f"requires init_acc. (If this callable is actually a rich "
                f"NON-incremental f(wid, iterable, ctx), name its context "
                f"parameter one of {RICH_PARAM_NAMES} or pass incremental=False "
                f"— 3-positional-arg flavours are separated by the trailing "
                f"parameter's name.)")
        from ..context import RuntimeContext
        self.context = context or RuntimeContext(parallelism, 0)
        # resolve the rich flavour once: downstream code always calls self._fn
        # with the plain arity (wf/meta.hpp rich variants bind RuntimeContext)
        if self.is_rich and incremental:
            self._fn = lambda w, t, a: win_fn(w, t, a, self.context)
        elif self.is_rich:
            self._fn = lambda w, it: win_fn(w, it, self.context)
        else:
            self._fn = win_fn
        self.num_keys = int(num_keys)
        self.role = role
        self._archive_capacity = archive_capacity
        self._tb_capacity = tb_capacity
        self.A = None                  # resolved in bind_geometry
        self.max_wins = max_wins       # resolved at first apply if None
        self._w = None
        self._wshard = None            # (mesh, axis): shard the fired-window W axis
        self.bind_geometry(256)        # provisional; compiler re-binds with real C

    def bind_geometry(self, batch_capacity: int) -> None:
        L = self.spec.win_len
        if self._archive_capacity is not None:
            self.A = _next_pow2(self._archive_capacity)
        elif self.spec.is_cb:
            # ring must survive one whole batch landing on a single key before the
            # fire phase runs, plus the open-window span
            self.A = _next_pow2(L + batch_capacity)
        else:
            self.A = _next_pow2(self._tb_capacity or 2 * batch_capacity)

    # ------------------------------------------------------------------ state

    def init_state(self, payload_spec: Any):
        K, A = self.num_keys, self.A
        def mk(s):
            return jnp.zeros((K, A) + tuple(s.shape), s.dtype)
        return WinSeqState(
            arch_payload=jax.tree.map(mk, payload_spec),
            arch_id=jnp.zeros((K, A), CTRL_DTYPE),
            arch_ts=jnp.zeros((K, A), CTRL_DTYPE),
            arch_pos=jnp.full((K, A), -1, CTRL_DTYPE),
            count=jnp.zeros((K,), CTRL_DTYPE),
            wm=jnp.full((K,), -1, CTRL_DTYPE),
            next_win=jnp.zeros((K,), CTRL_DTYPE),
        )

    def out_spec(self, payload_spec: Any) -> Any:
        L = self.spec.win_len if self.spec.is_cb else self.A
        it = Iterable(
            data=jax.tree.map(lambda s: jax.ShapeDtypeStruct((L,) + s.shape, s.dtype),
                              payload_spec),
            ids=jax.ShapeDtypeStruct((L,), CTRL_DTYPE),
            ts=jax.ShapeDtypeStruct((L,), CTRL_DTYPE),
            mask=jax.ShapeDtypeStruct((L,), jnp.bool_),
        )
        wid = jax.ShapeDtypeStruct((), CTRL_DTYPE)
        if not self.incremental:
            return jax.eval_shape(self._fn, wid, it)
        t = TupleRef(key=wid, id=wid, ts=wid,
                     data=jax.tree.map(lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype),
                                       payload_spec))
        acc = jax.eval_shape(lambda: jax.tree.map(jnp.asarray, self.init_acc))
        return jax.eval_shape(self._fn, wid, t, acc)

    # ------------------------------------------------------------------ insert

    def _insert(self, state: WinSeqState, batch: Batch) -> WinSeqState:
        from ..ops.lookup import table_lookup
        K, A = self.num_keys, self.A
        valid = batch.valid
        if not self.spec.is_cb:
            # drop OLD tuples: they precede the purge horizon (already-fired windows)
            horizon = table_lookup(state.next_win, batch.key) * self.spec.slide
            valid = valid & (batch.ts >= horizon)
        rank = segment_rank(batch.key, valid)
        pos = table_lookup(state.count, batch.key) + rank
        slot = pos % A
        flat = jnp.where(valid, batch.key * A + slot, K * A)  # OOB -> dropped

        def scat(tbl, v):
            return tbl.reshape((K * A,) + tbl.shape[2:]).at[flat].set(
                v, mode="drop").reshape(tbl.shape)

        counts_add = segment_reduce(valid.astype(CTRL_DTYPE), batch.key, valid, K)
        ts_max = segment_reduce(batch.ts, batch.key, valid, K,
                                combine=jnp.maximum, identity=-1)
        return dataclasses.replace(
            state,
            arch_payload=jax.tree.map(scat, state.arch_payload, batch.payload),
            arch_id=scat(state.arch_id, batch.id),
            arch_ts=scat(state.arch_ts, batch.ts),
            arch_pos=scat(state.arch_pos, pos),
            count=state.count + counts_add,
            wm=jnp.maximum(state.wm, ts_max),
        )

    # ------------------------------------------------------------------ fire

    def _resolve_w(self, capacity: int) -> int:
        if self.max_wins is not None:
            return self.max_wins
        W = max(16, -(-capacity // self.spec.slide) + 64)
        L = self.spec.win_len if self.spec.is_cb else self.A
        if W * L > (1 << 22):
            # adversarial slide (e.g. slide=1 at large batch) would imply a [W, L]
            # gather per batch per payload leaf — force an explicit budget instead
            # of silently allocating it (the reference sizes this with batch_len,
            # wf/win_seq_gpu.hpp tuples_per_batch)
            raise ValueError(
                f"{self.name}: default fired-window budget W={W} with window row "
                f"length L={L} implies a [{W}, {L}] gather per batch "
                f"({W * L} elements per payload leaf); pass max_wins= to bound the "
                f"per-batch fired-window budget")
        return W

    def set_window_sharding(self, mesh, axis: str) -> None:
        """Cross-chip window parallelism (Win_Farm's distribution,
        ``wf/wf_nodes.hpp:157-204`` / ``wf/win_farm.hpp:165-175``): partition the
        fired-window [W] axis over mesh axis ``axis``. The archive stays replicated
        (every chip sees every tuple — the WF_Emitter multicast as a sharding rule);
        each chip gathers and computes only its W/p window rows."""
        self._wshard = (mesh, axis)

    def _wsc(self, a):
        """Constrain the leading (window) axis of ``a`` to the window mesh axis."""
        if self._wshard is None:
            return a
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh, axis = self._wshard
        spec = P(axis, *([None] * (a.ndim - 1)))
        return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, spec))

    def _fired_range(self, state: WinSeqState, flush: bool):
        s = self.spec
        if s.is_cb:
            hi = s.flush_hi_cb(state.count) if flush else s.fired_hi_cb(state.count)
        else:
            hi = (s.flush_hi_tb(state.wm, state.count > 0) if flush
                  else s.fired_hi_tb(state.wm))
        return state.next_win, jnp.maximum(hi, state.next_win)

    def _emit(self, state: WinSeqState, W: int, flush: bool):
        """Emit up to W fired windows (per-key ascending wid). Returns (state, Batch)."""
        K, A = self.num_keys, self.A
        s = self.spec
        lo, hi = self._fired_range(state, flush)
        n_f = hi - lo
        csum = jnp.cumsum(n_f)
        off = csum - n_f
        total = csum[-1] if K > 0 else jnp.asarray(0, CTRL_DTYPE)
        w_idx = self._wsc(jnp.arange(W, dtype=CTRL_DTYPE))
        k_of = jnp.searchsorted(csum, w_idx, side="right").astype(CTRL_DTYPE)
        k_safe = self._wsc(jnp.minimum(k_of, K - 1))
        wid = self._wsc(jnp.take(lo, k_safe) + (w_idx - jnp.take(off, k_safe)))
        valid_w = self._wsc(w_idx < jnp.minimum(total, W))

        # advance next_win past emitted windows
        emitted_k = jnp.clip(jnp.minimum(total, W) - off, 0, n_f)
        new_next = lo + emitted_k

        if s.is_cb:
            L = s.win_len
            p = wid[:, None] * s.slide + jnp.arange(L, dtype=CTRL_DTYPE)[None, :]
            slot = p % A
            gflat = k_safe[:, None] * A + slot                         # [W, L]
            def gat(tbl):
                return jnp.take(tbl.reshape((K * A,) + tbl.shape[2:]), gflat, axis=0)
            content_mask = (p < jnp.take(state.count, k_safe)[:, None]) & valid_w[:, None]
            # stale-slot guard: the slot must actually hold position p
            content_mask &= gat(state.arch_pos) == p
            data = jax.tree.map(gat, state.arch_payload)
            ids, tss = gat(state.arch_id), gat(state.arch_ts)
            res_ts = jnp.max(jnp.where(content_mask, tss, -1), axis=1)
        else:
            # TB: full-ring rows masked by ts-in-range
            def gat(tbl):
                return jnp.take(tbl, k_safe, axis=0)                   # [W, A, ...]
            tss = gat(state.arch_ts)
            poss = gat(state.arch_pos)
            w_start = (wid * s.slide)[:, None]
            content_mask = ((poss >= 0) & (tss >= w_start)
                            & (tss < w_start + s.win_len) & valid_w[:, None])
            # ring-overwrite guard: slot must hold a live (not yet overwritten) pos
            cnt = jnp.take(state.count, k_safe)[:, None]
            content_mask &= poss >= jnp.maximum(0, cnt - A)
            data = jax.tree.map(gat, state.arch_payload)
            ids = gat(state.arch_id)
            res_ts = wid * s.slide + (s.win_len - 1)

        if not s.is_cb:
            # TB: a window with no content never fires in the reference (Triggerer_TB
            # only triggers on tuples); filter empty windows from the emission
            valid_w = valid_w & jnp.any(content_mask, axis=1)

        it = Iterable(data=jax.tree.map(self._wsc, data), ids=self._wsc(ids),
                      ts=self._wsc(tss), mask=self._wsc(content_mask))
        if self.incremental:
            results = _fold_windows(self._fn, wid, it, self.init_acc)
        else:
            results = jax.vmap(self._fn)(wid, it)

        out = Batch(key=k_safe, id=wid,
                    ts=self._wsc(res_ts if s.is_cb
                                 else jnp.asarray(res_ts, CTRL_DTYPE)),
                    payload=jax.tree.map(self._wsc, results), valid=valid_w)
        return dataclasses.replace(state, next_win=new_next), out

    # ------------------------------------------------------------------ operator API

    def out_capacity(self, in_capacity: int) -> int:
        return self._resolve_w(in_capacity)

    def apply(self, state: WinSeqState, batch: Batch):
        W = self._resolve_w(batch.capacity)
        self._w = W
        state = self._insert(state, batch)
        return self._emit(state, W, flush=False)

    def flush(self, state: WinSeqState):
        W = self._w or self._resolve_w(256)
        if not hasattr(self, "_flush_jit"):
            self._flush_jit = jax.jit(lambda st: self._emit(st, W, flush=True))
        state, out = self._flush_jit(state)
        if not bool(jnp.any(out.valid)):
            return state, None
        return state, out


def _fold_windows(fn, wids, it: Iterable, init_acc):
    """Incremental path: lax.scan the user fold over the window axis, vmapped over
    windows. Absent slots (mask False) skip the fold (wf/win_seq.hpp:389-397)."""
    def one(wid, data, ids, ts, mask):
        acc0 = jax.tree.map(jnp.asarray, init_acc)

        def step(acc, x):
            d, i, t, m = x
            tref = TupleRef(key=wid, id=i, ts=t, data=d)
            new = fn(wid, tref, acc)
            acc = jax.tree.map(lambda a, n: jnp.where(m, n, a), acc, new)
            return acc, None

        acc, _ = jax.lax.scan(step, acc0, (data, ids, ts, mask))
        return acc

    return jax.vmap(one)(wids, it.data, it.ids, it.ts, it.mask)


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p
