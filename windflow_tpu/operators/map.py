"""Map — one-to-one transformation.

Counterpart of ``wf/map.hpp`` (class at ``:60``, signature slots ``:64-74``): the
reference accepts in-place ``void(tuple&)`` and non-in-place ``void(const tuple&,
result&)`` signatures, plus rich variants, with optional KEYBY routing. Here the user
function is per-tuple pure ``f(t) -> payload`` (or rich ``f(t, ctx)``), lifted over the
batch with ``vmap``; XLA fuses it with neighbours, which is what makes a chained
Source->Map->Filter->Sink pipeline one device program (the micro-batch analogue of the
reference's ``MapGPU`` kernels, ``wf/map_gpu_node.hpp:57-125``).

Keyed (stateful) Map — the reference fork's headline feature (``run_map_kernel_keyed_*``
per-key scratchpads, ``wf/map_gpu_node.hpp:216-222``) — takes ``state_spec`` +
``f(t, state) -> (payload, state)``: per-key state lives in an HBM table ``[K, ...]``
and is gather/scatter-updated per batch. Within one batch, tuples of the same key are
folded sequentially per key (matching the reference's per-key serialization semantics)
via a masked scan over the batch's per-key rank.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..basic import routing_modes_t, DEFAULT_MAX_KEYS
from ..batch import Batch, tuple_refs, TupleRef
from ..context import RuntimeContext
from ..meta import classify_map
from ..ops.lookup import table_lookup
from .base import Basic_Operator


class Map(Basic_Operator):
    """Both reference Map flavours through one constructor (``wf/map.hpp:64-74``,
    deduced like ``wf/meta.hpp``): *non-in-place* ``f(t) -> payload`` returns the
    new payload; *in-place* ``f(t) -> None`` mutates the tuple's payload fields
    (``t.v = t.v * 2``) via :class:`MutableTupleRef` — the ``void(tuple_t&)``
    signature. Rich variants append a context parameter."""

    def __init__(self, fn: Callable, *, name: str = "map", parallelism: int = 1,
                 keyed: bool = False, context: Optional[RuntimeContext] = None):
        super().__init__(name, parallelism)
        self.fn = fn
        self.is_rich = classify_map(fn)
        self.routing = routing_modes_t.KEYBY if keyed else routing_modes_t.FORWARD
        self.context = context or RuntimeContext(parallelism, 0)

    def _call(self, t: TupleRef):
        from ..batch import MutableTupleRef
        m = MutableTupleRef(t) if isinstance(t.data, dict) else t
        r = (self.fn(m, self.context) if self.is_rich else self.fn(m))
        if r is None:
            if not isinstance(m, MutableTupleRef):
                from ..meta import SignatureError
                raise SignatureError(
                    "Map: f returned None (in-place flavour) but the payload is "
                    "not a dict of named fields; return the new payload instead")
            return m._payload()
        return r

    def out_spec(self, payload_spec: Any) -> Any:
        t = TupleRef(key=jax.ShapeDtypeStruct((), jnp.int32),
                     id=jax.ShapeDtypeStruct((), jnp.int32),
                     ts=jax.ShapeDtypeStruct((), jnp.int32), data=payload_spec)
        return jax.eval_shape(self._call, t)

    def apply(self, state, batch: Batch):
        payload = jax.vmap(self._call)(tuple_refs(batch))
        return state, batch.with_payload(payload)


class KeyBy(Basic_Operator):
    """Re-key the stream: ``key = fn(t) % num_keys`` rewrites the batch's key
    control field.

    The reference re-keys by writing the key control field in user code
    (``setControlFields``, ``src/graph_test/graph_common.hpp:69-80``) and then
    routing KEYBY on ``std::hash(key) % n`` (``wf/standard_emitter.hpp:88-99``).
    Here the control fields live in the Batch, so re-keying is its own tiny
    operator that fuses to nothing; every keyed operator downstream
    (Accumulator, Key_Farm, Key_FFAT, KeyedMap...) routes on the new key.
    ``fn`` takes a :class:`TupleRef`; rich variant takes ``(t, ctx)``."""

    def __init__(self, fn: Callable, num_keys: int, *, name: str = "keyby",
                 parallelism: int = 1, context: Optional[RuntimeContext] = None):
        super().__init__(name, parallelism)
        self.fn = fn
        self.num_keys = int(num_keys)
        self.is_rich = classify_map(fn)
        self.routing = routing_modes_t.KEYBY
        self.context = context or RuntimeContext(parallelism, 0)

    def apply(self, state, batch: Batch):
        def one(t):
            k = (self.fn(t, self.context) if self.is_rich else self.fn(t))
            return k
        key = jax.vmap(one)(tuple_refs(batch)).astype(batch.key.dtype)
        return state, batch.replace(key=key % self.num_keys)


class BatchMap(Basic_Operator):
    """Batch-level map: ``fn(payload_pytree_of_[C,...]) -> payload_pytree`` — for
    transforms best expressed over whole arrays (joins via table lookups, projections,
    dtype casts). The per-batch analogue of writing a custom MapGPU kernel body."""

    def __init__(self, fn: Callable, *, name: str = "batch_map", parallelism: int = 1):
        super().__init__(name, parallelism)
        self.fn = fn

    def out_spec(self, payload_spec: Any) -> Any:
        def one(spec):
            return jax.ShapeDtypeStruct((1,) + tuple(spec.shape), spec.dtype)
        out = jax.eval_shape(self.fn, jax.tree.map(one, payload_spec))
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape[1:], s.dtype), out)

    def apply(self, state, batch: Batch):
        return state, batch.with_payload(self.fn(batch.payload))


class KeyedMap(Basic_Operator):
    """Stateful map with a per-key HBM state table.

    ``f(t, state_k) -> (payload, new_state_k)``; ``init_state_value`` is the per-key
    initial state pytree. Same-key tuples within a batch are always folded in stream
    order: each batch dynamically takes a single-round fast path when every live key
    is unique, else a multi-round in-order fold (``lax.cond`` between the two) — the
    per-key serialization the reference documents as its stateful floor
    (results.org:8,37), paid only within a batch and only when duplicates occur.

    ``max_key_multiplicity=1`` is a *static* promise that batches never hold same-key
    duplicates: the fallback branch is not even compiled. A violated promise fails
    loudly twice over: asynchronously at the next sync point (debug callback), AND
    deterministically at ``flush()`` — the violation is latched into the carried
    state as a device flag, so even if the process never syncs mid-stream the EOS
    flush raises. ``ordered`` is kept for API compatibility and no longer weakens
    semantics."""

    routing = routing_modes_t.KEYBY

    def __init__(self, fn: Callable, init_state_value: Any, *, num_keys: int = DEFAULT_MAX_KEYS,
                 name: str = "map_keyed", parallelism: int = 1, ordered: bool = True,
                 max_key_multiplicity: int = None):
        super().__init__(name, parallelism)
        self.fn = fn
        self.init_value = init_state_value
        self.num_keys = int(num_keys)
        self.ordered = ordered
        self.max_key_multiplicity = max_key_multiplicity

    def init_state(self, payload_spec: Any):
        tbl = jax.tree.map(
            lambda v: jnp.broadcast_to(jnp.asarray(v), (self.num_keys,) + jnp.shape(jnp.asarray(v))).copy(),
            self.init_value)
        return {"tbl": tbl, "bad": jnp.zeros((), jnp.bool_)}

    def out_spec(self, payload_spec: Any) -> Any:
        t = TupleRef(key=jax.ShapeDtypeStruct((), jnp.int32),
                     id=jax.ShapeDtypeStruct((), jnp.int32),
                     ts=jax.ShapeDtypeStruct((), jnp.int32), data=payload_spec)
        out, _ = jax.eval_shape(lambda tt: self.fn(tt, self.init_value), t)
        return out

    def apply(self, state, batch: Batch):
        from ..ops.segment import segment_rank
        bad = state["bad"]
        state = state["tbl"]
        refs = tuple_refs(batch)
        rank = segment_rank(batch.key, batch.valid)
        max_rank = jnp.max(jnp.where(batch.valid, rank, 0))

        def fast(st):
            # one gather-apply-scatter round — correct iff every live key is unique
            st_k = jax.tree.map(lambda tbl: table_lookup(tbl, batch.key), st)
            res, new_st = jax.vmap(self.fn)(refs, st_k)
            safe_key = jnp.where(batch.valid, batch.key, self.num_keys)
            st = jax.tree.map(
                lambda tbl, ns: tbl.at[safe_key].set(ns, mode="drop"), st, new_st)
            return st, res

        def multi(st):
            # round r processes the lanes whose per-key rank is r — in-order fold
            # of same-key duplicates, up to the observed max multiplicity
            def round_body(r, carry):
                st, out_payload = carry
                active = batch.valid & (rank == r)
                st_k = jax.tree.map(lambda tbl: table_lookup(tbl, batch.key), st)
                res, new_st = jax.vmap(self.fn)(refs, st_k)
                safe_key = jnp.where(active, batch.key, self.num_keys)
                st = jax.tree.map(
                    lambda tbl, ns: tbl.at[safe_key].set(ns, mode="drop"), st, new_st)
                out_payload = jax.tree.map(
                    lambda o, nv: jnp.where(
                        active.reshape(active.shape + (1,) * (nv.ndim - 1)), nv, o),
                    out_payload, res)
                return st, out_payload

            out_shape = jax.eval_shape(
                lambda s, b: jax.vmap(self.fn)(
                    tuple_refs(b),
                    jax.tree.map(lambda t: table_lookup(t, b.key), s))[0],
                st, batch)
            out0 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), out_shape)
            return jax.lax.fori_loop(0, max_rank + 1, round_body, (st, out0))

        if self.max_key_multiplicity == 1:
            # static promise: no fallback branch compiled; a violated promise
            # fails loudly early (async debug callback) and is ALSO latched
            # into the carried state so flush() raises deterministically
            jax.debug.callback(_reject_duplicate_keys, max_rank, self.name)
            bad = bad | (max_rank > 0)
            state, res = fast(state)
        else:
            state, res = jax.lax.cond(max_rank == 0, fast, multi, state)
        return {"tbl": state, "bad": bad}, batch.with_payload(res)

    def flush(self, state):
        """EOS: no residual output, but the guaranteed (synchronous) report
        point for a violated ``max_key_multiplicity=1`` promise."""
        import numpy as np
        if self.max_key_multiplicity == 1 and bool(np.asarray(state["bad"])):
            raise ValueError(
                f"KeyedMap '{self.name}': some batch held same-key duplicates, "
                f"violating the max_key_multiplicity=1 promise (the single-round "
                f"path dropped state updates); remove max_key_multiplicity=1 to "
                f"get the dynamic in-order fallback")
        return state, None


def _reject_duplicate_keys(max_rank, name):
    if int(max_rank) > 0:
        raise ValueError(
            f"KeyedMap '{name}': a batch holds {int(max_rank) + 1} tuples of one "
            f"key, violating the max_key_multiplicity=1 promise (the single-round "
            f"path would drop state updates); remove max_key_multiplicity=1 to get "
            f"the dynamic in-order fallback")
