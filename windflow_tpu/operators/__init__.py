from .base import Basic_Operator
from .source import Source, DeviceSource, GeneratorSource, RecordSource, SourceBase
from .map import Map, KeyedMap, KeyBy
from .filter import Filter, FilterMap, Compact
from .flatmap import FlatMap
from .accumulator import Accumulator
from .join import StreamTableJoin, IntervalJoin
from .session import SessionWindow
from .rank import TopN, Distinct
from .sink import Sink, ReduceSink

__all__ = [
    "Basic_Operator", "Source", "DeviceSource", "GeneratorSource", "RecordSource", "SourceBase",
    "Map", "KeyedMap", "KeyBy", "Filter", "FilterMap", "Compact", "FlatMap",
    "Accumulator", "StreamTableJoin", "IntervalJoin", "SessionWindow",
    "TopN", "Distinct", "Sink", "ReduceSink",
]
