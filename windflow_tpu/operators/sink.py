"""Sink — stream absorption.

Counterpart of ``wf/sink.hpp`` (class at ``:67``, signature slots ``:70-77``): the
reference calls ``void(optional<tuple>&)`` per tuple (empty optional at EOS). Two
TPU-native flavours:

- ``Sink``: host callback invoked once per *batch* with the live tuples as numpy
  arrays (``f(batch_view)`` / rich) — the general egress path. Called with ``None`` at
  EOS, mirroring the empty-optional convention.
- ``ReduceSink``: an in-graph reduction (e.g. global sum / count / collect-last) that
  stays on device and is fetched once at the end — this is what the reference test
  suites do with their ``atomic<long> global_sum`` oracle
  (``src/graph_test/graph_common.hpp:32``), and avoids D2H per batch entirely.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..basic import routing_modes_t
from ..batch import Batch, tuple_refs
from ..context import RuntimeContext
from ..meta import classify_sink
from .base import Basic_Operator


class Sink(Basic_Operator):
    """Host-callback sink. The callback receives a dict with numpy ``key/id/ts``,
    payload leaves restricted to live lanes.

    ``async_depth > 0`` routes batches through an
    :class:`~windflow_tpu.runtime.async_sink.AsyncResultShipper`: the
    device->host copy starts immediately and the callback fires once the copy of
    a batch ``async_depth`` ships old has landed — result transfer overlaps
    device compute instead of paying a blocking round trip per batch (the
    reference GPU D2H overlap, ``wf/win_seq_gpu.hpp:243-260,524``). Callback
    order stays FIFO; EOS (``None``) drains everything first."""

    def __init__(self, fn: Callable, *, name: str = "sink", parallelism: int = 1,
                 keyed: bool = False, async_depth: int = 0,
                 context: Optional[RuntimeContext] = None):
        super().__init__(name, parallelism)
        self.fn = fn
        self.is_rich = classify_sink(fn)
        self.routing = routing_modes_t.KEYBY if keyed else routing_modes_t.FORWARD
        self.async_depth = int(async_depth)
        self._shipper = None
        self.context = context or RuntimeContext(parallelism, 0)

    def _deliver(self, view):
        if self.is_rich:
            self.fn(view, self.context)
        else:
            self.fn(view)

    def _deliver_host(self, host: Batch):
        v = host.valid
        # the whole batch crossed device->host to get here: count the transfer
        # (wf/stats_record.hpp:78-80 bytes_copied_dh) + live-tuple ingress
        rec = self._stats[0]
        rec.bytes_copied_dh += sum(
            a.nbytes for a in jax.tree.leaves(host) if hasattr(a, "nbytes"))
        n_live = int(v.sum())
        rec.record_input(n_live)
        if not n_live:
            return
        self._deliver({
            "key": host.key[v], "id": host.id[v], "ts": host.ts[v],
            "payload": jax.tree.map(lambda a: a[v], host.payload),
        })

    def consume(self, batch: Optional[Batch]):
        """Host-side: deliver one batch (or None at EOS) to the user callback."""
        if self.async_depth:
            if self._shipper is None:
                from ..runtime.async_sink import AsyncResultShipper
                self._shipper = AsyncResultShipper(depth=self.async_depth)
            if batch is None:
                for rec in self._shipper.drain():
                    self._deliver_host(rec.value)
                self._deliver(None)
                return
            self._shipper.ship(batch)
            for rec in self._shipper.harvest():
                self._deliver_host(rec.value)
            return
        if batch is None:
            self._deliver(None)
            return
        self._deliver_host(jax.tree.map(np.asarray, batch))


class ReduceSink(Basic_Operator):
    """In-graph reduction sink: ``value_fn(t) -> pytree`` per tuple, associative
    ``combine`` across all tuples of the stream (device-resident accumulator)."""

    def __init__(self, value_fn: Callable, *, combine: Callable = None, identity=0,
                 name: str = "reduce_sink", parallelism: int = 1):
        super().__init__(name, parallelism)
        self.value_fn = value_fn
        self.combine = combine or jnp.add
        self.identity = identity

    def init_state(self, payload_spec: Any):
        from .accumulator import _ref_spec
        val = jax.eval_shape(self.value_fn, _ref_spec(payload_spec))
        return jax.tree.map(
            lambda s: jnp.broadcast_to(jnp.asarray(self.identity, s.dtype),
                                       s.shape).copy(), val)

    def apply(self, state, batch: Batch):
        vals = jax.vmap(self.value_fn)(tuple_refs(batch))
        def red(acc, v):
            m = batch.valid.reshape(batch.valid.shape + (1,) * (v.ndim - 1))
            v = jnp.where(m, v, jnp.asarray(self.identity, v.dtype))
            if self.combine is jnp.add:
                return acc + jnp.sum(v, axis=0)
            return self.combine(acc, jax.lax.reduce(
                v, jnp.asarray(self.identity, v.dtype), self.combine, (0,)))
        state = jax.tree.map(red, state, vals)
        return state, batch

    def result(self, state):
        return state
