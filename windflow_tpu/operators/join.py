"""Streaming joins: stream-table enrichment and interval (stream-stream) join.

The reference's operator taxonomy (PAPER.md survey §2.4) lists joins as the
first operator family this repro did not exercise: WindFlow itself joins
per tuple against in-memory hash maps (the YSB campaign join,
``src/yahoo_test_cpu``), and every production stream system beyond it needs
stream-table and interval joins. TPU formulation:

- **Two-input wiring** rides ``PipeGraph`` merge semantics: both inputs merge
  into one pipe (identical payload specs — the ``wf/pipegraph.hpp:1573-1578``
  typeid check) and the join operator separates the sides per tuple with a
  ``side_fn`` over the unified schema (``MultiPipe.join_with`` packages the
  merge + add). Under ``Mode.DETERMINISTIC`` the merge's Ordering_Node makes
  the interleave — and therefore the join — byte-identical across drivers.
- :class:`StreamTableJoin` probes the **versioned, watermark-consistent
  JoinTable** of ``ops/lookup.py`` (``join_table_*``): build-side tuples
  upsert (versioned by event time, last-writer-wins), probe-side tuples read
  the table as-of the build watermark through the kernel registry's
  ``join_probe`` kernel — the production call site the round-5 Pallas probe
  was waiting for. Probing a table above the Pallas ``K <= 2048`` envelope
  routes to the XLA reference inside the kernel call (never raises).
- :class:`IntervalJoin` holds both sides in bounded on-device archives and
  matches each arriving tuple against the opposite archive with one fused
  ``[C, A]`` compare + masked select-reduce — the same contraction shape as
  the probe kernel, so the whole match stage fuses into the chain's single
  device program (the amortization argument of arXiv:1305.1183). A pair is
  emitted exactly once, when its later tuple arrives.

Both operators' state is a plain pytree — checkpoints, supervised replay and
the exactly-once outbox carry it with zero new machinery.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..basic import routing_modes_t, DEFAULT_MAX_KEYS
from ..batch import Batch, CTRL_DTYPE, TupleRef, tuple_refs
from ..observability import event_time as _et
from ..ops.lookup import (JOIN_KEY_SENTINEL, count_drops, join_table_init,
                          join_table_probe, join_table_stats,
                          join_table_tier_evict, join_table_tier_init,
                          join_table_tier_resolve, join_table_tier_stats,
                          join_table_tier_touch, join_table_upsert)
from .base import Basic_Operator

_IMIN = -(1 << 31)


def _ref_spec(payload_spec):
    s = jax.ShapeDtypeStruct((), CTRL_DTYPE)
    return TupleRef(key=s, id=s, ts=s, data=payload_spec)


def _tier_counters(state, tier) -> dict:
    """Per-stage tier counters/gauges of one tiered keyed table (names
    registered in ``observability/names.py`` — the count_drops discipline):
    device movement counters + hot/cold occupancy."""
    import numpy as np
    used_key = "used" if "used" in state else "hused"
    return {
        "state_spills": int(np.asarray(state["spills"])),
        "state_readmits": int(np.asarray(state["readmits"])),
        "state_compactions":
            tier.controller.counters()["state_compactions"],
        "tier_hot_used": int(np.asarray(state[used_key]).sum()),
        "tier_cold_keys": tier.store.key_count(),
    }


def _default_pair_emit(l: TupleRef, r: TupleRef):
    """Union payload of a matched pair: dict payloads merge under ``l_``/
    ``r_`` prefixes; other pytrees nest under ``{"l": ..., "r": ...}``."""
    if isinstance(l.data, dict) and isinstance(r.data, dict):
        out = {f"l_{k}": v for k, v in l.data.items()}
        out.update({f"r_{k}": v for k, v in r.data.items()})
        return out
    return {"l": l.data, "r": r.data}


class StreamTableJoin(Basic_Operator):
    """Stream-table join over one merged (tagged) stream.

    ``side_fn(t) -> bool`` marks **build**-side tuples (table upserts);
    everything else probes. ``key_fn(t) -> i32`` extracts the join key on
    both sides (keys must be > INT32_MIN); ``val_fn(t) -> pytree of
    scalars`` extracts the build-side value columns. ``emit(t, v) ->
    payload`` shapes the probe-side output (default: merge the probe payload
    with the value dict). ``delay`` is the build-side lateness allowance: an
    upsert becomes probe-visible once the build watermark passes
    ``ts + delay``, so probes read the table **as-of the watermark** —
    deterministic under any arrival interleave the watermark contract
    admits. Duplicate-key upserts are last-writer-wins by ``(ts, id)``.

    Misses emit zero values with ``hit`` False; by default miss lanes are
    masked out (inner join) — ``emit_misses=True`` keeps them (left join)."""

    routing = routing_modes_t.KEYBY

    def __init__(self, side_fn: Callable, key_fn: Callable, val_fn: Callable,
                 *, num_slots: int = DEFAULT_MAX_KEYS,
                 pending: Optional[int] = None, delay: int = 0,
                 emit: Optional[Callable] = None, emit_misses: bool = False,
                 tiered=None,
                 name: str = "stream_table_join", parallelism: int = 1):
        super().__init__(name, parallelism)
        if delay < 0:
            raise ValueError(f"{name}: delay (lateness) must be >= 0")
        self.side_fn = side_fn
        self.key_fn = key_fn
        self.val_fn = val_fn
        self.num_slots = int(num_slots)
        self.pending = None if pending is None else int(pending)
        self.delay = int(delay)
        self.emit_misses = bool(emit_misses)
        self.emit = emit
        self._pending_resolved = pending
        self._version_synced = 0
        # tiered keyed state (ROADMAP 3): None consults WF_STATE_TIERED —
        # off by default, OFF path byte-for-byte today's state/programs
        from ..state import TierConfig
        self._tier_cfg = TierConfig.resolve(tiered)
        self._tier = None
        self._cap_resolved = None

    def bind_geometry(self, batch_capacity: int) -> None:
        self._cap_resolved = int(batch_capacity)
        if self.pending is None:
            # one batch of pure build tuples must always fit, with headroom
            # for upserts parked behind a nonzero delay
            self._pending_resolved = 2 * int(batch_capacity)
        else:
            self._pending_resolved = self.pending

    def _emit(self, t: TupleRef, v):
        if self.emit is not None:
            return self.emit(t, v)
        if isinstance(t.data, dict) and isinstance(v, dict):
            return {**t.data, **v}
        return {"probe": t.data, "join": v}

    def _val_spec(self, payload_spec):
        return jax.eval_shape(self.val_fn, _ref_spec(payload_spec))

    def init_state(self, payload_spec: Any):
        pending = self._pending_resolved or 2 * DEFAULT_MAX_KEYS
        vspec = self._val_spec(payload_spec)
        if self._tier_cfg is not None:
            from ..state.tiered import JoinTableTier
            hot = int(self._tier_cfg.hot_capacity or self.num_slots)
            # per-batch admission bound: the resolve pass may readmit every
            # distinct batch key plus every parked pending key, so the hot
            # table keeps that many slots free (WF114 checks hot > reserve)
            cap = self._cap_resolved or DEFAULT_MAX_KEYS
            self._reserve = cap + pending
            self._hot_target = max(1, hot - self._reserve)
            # actuator setpoint gauge (PR 17): the hot capacity this run was
            # BUILT with — a traced constant, so remediation can only
            # recommend a new one (last-write-wins across tables, the
            # join_table_version convention)
            from ..control import _state as _cstate
            _cstate.set_gauge("hot_capacity", float(hot))
            outbox = int(self._tier_cfg.outbox or 4 * self._reserve)
            state = join_table_init(hot, pending, vspec)
            state = join_table_tier_init(state, outbox, vspec)
            self._tier = JoinTableTier(self.name, vspec, self._tier_cfg)
        else:
            state = join_table_init(self.num_slots, pending, vspec)
        if self._event_time:
            # build-side lateness histogram (event-time observability only:
            # absent from the state pytree — and from the compiled program —
            # when the toggle is off)
            state["lat_hist"] = _et.lateness_init()
        return state

    def tier_controllers(self):
        return (self._tier.controller,) if self._tier is not None else ()

    def out_spec(self, payload_spec: Any) -> Any:
        vspec = self._val_spec(payload_spec)
        return jax.eval_shape(self._emit, _ref_spec(payload_spec), vspec)

    def apply(self, state, batch: Batch):
        refs = tuple_refs(batch)
        build = jax.vmap(self.side_fn)(refs).astype(jnp.bool_) & batch.valid
        probe_mask = batch.valid & ~build
        jkey = jax.vmap(self.key_fn)(refs).astype(jnp.int32)
        bval = jax.vmap(self.val_fn)(refs)
        fb_vals = fb_ok = None
        if self._tier is not None:
            # miss -> readmit -> (re)probe, BEFORE the upsert: resolve every
            # batch key AND every parked pending key (a parked upsert's key
            # stays hot until it applies, so the LWW never-roll-back check
            # always sees the applied version — placement-independent)
            rkeys = jnp.concatenate([jkey, state["pkey"]])
            rok = jnp.concatenate([batch.valid, state["pok"]])
            state, fb_vals, fb_ok = join_table_tier_resolve(
                state, rkeys, rok, self._tier.lookup_cb)
        # upsert BEFORE probe: a probe sees every build tuple up to and
        # including its own batch (the as-of-watermark read point); with
        # tiering on, a saturated table diverts winning upserts to the
        # spill outbox instead of dropping them
        state = join_table_upsert(state, jkey, bval, batch.ts, batch.id,
                                  build, delay=self.delay,
                                  divert=self._tier is not None)
        if self._event_time:
            # observed build-side lateness vs the post-upsert watermark: one
            # masked reduction, results untouched (the hist is state-only)
            state = dict(state, lat_hist=_et.lateness_update(
                state["lat_hist"], state["wm"], batch.ts, build))
        vals, hit = join_table_probe(state, jkey, probe_mask)
        if self._tier is not None:
            # saturation fallback chain: a probe lane that still misses the
            # hot table reads (1) the newest outbox entry of its key —
            # covering this batch's diverted upserts and unsettled spills —
            # then (2) the resolve pass's host-store value; results never
            # depend on tier placement
            from ..ops.lookup import join_table_tier_fallback
            C = batch.capacity
            ob_vals, ob_hit = join_table_tier_fallback(
                state, jkey, probe_mask & ~hit)
            fb = fb_ok[:C] & probe_mask & ~hit & ~ob_hit
            vals = jax.tree.map(
                lambda v, o, f: jnp.where(
                    ob_hit, o.astype(v.dtype),
                    jnp.where(fb, f[:C].astype(v.dtype), v)),
                vals, ob_vals, fb_vals)
            hit = hit | ob_hit | fb
            state = join_table_tier_touch(state, jkey, batch.valid)
            state = join_table_tier_evict(state, self._hot_target)
        payload = jax.vmap(self._emit)(refs, vals)
        valid = probe_mask & (hit | self.emit_misses)
        return state, batch.replace(payload=payload, valid=valid)

    def collect_stats(self, state: Any = None) -> None:
        if state is None:
            return
        import numpy as np
        from ..control import _state as _cstate
        v = int(np.asarray(state["version"]))
        if v != self._version_synced:
            self._version_synced = v
            _cstate.set_gauge("join_table_version", float(v))
        counters = {
            "join_table_version": v,
            "overflow_drops": int(np.asarray(state["dropped"]))}
        if self._tier is not None:
            counters.update(_tier_counters(state, self._tier))
        self._publish_stage_counters(counters)

    def drop_counters(self, state: Any = None) -> dict:
        if state is None:
            return {}
        import numpy as np
        return {"overflow_drops": int(np.asarray(state["dropped"]))}

    def event_time_stats(self, state: Any = None):
        """Watermark-map section: build watermark, applied version, table
        occupancy, pending-ring pressure, and the build-side lateness
        histogram with its ``recommend_delay`` advice."""
        if state is None:
            return None
        out = join_table_stats(state)
        out["delay"] = self.delay
        if self._tier is not None:
            out["tier"] = {**join_table_tier_stats(state),
                           **self._tier.controller.stats()}
        counts = _et.read_hist(state.get("lat_hist"))
        if counts is not None:
            out["lateness"] = {"build": _et.summarize(counts)}
        return out


class IntervalJoin(Basic_Operator):
    """Interval (stream-stream) join over one merged (tagged) stream.

    A pair ``(l, r)`` matches when ``l.key == r.key`` and
    ``r.ts - l.ts in [lower, upper]`` — the match window is expressed
    against the same event-time/watermark machinery ``WindowSpec.fired_hi_tb``
    uses: both archives evict exactly the tuples the watermark proves can no
    longer match (``l.ts < wm - delay - upper``, ``r.ts < wm - delay +
    lower``). Each arriving tuple probes the opposite archive (plus, for the
    left side, the batch's own right tuples), so every pair is emitted
    exactly once, when its later member arrives — the emitted multiset is
    batching-invariant. Up to ``max_matches`` matches per probing tuple are
    kept (candidate order: archive slot, then batch lane — deterministic);
    overflow is counted in ``state["match_drops"]``. ``ts_l``/``ts_r``
    optionally extract per-side event time from the payload (the two-input
    dtype contract ``validate()``'s WF111 checks pre-run).

    ``emit(l, r) -> payload`` shapes the output (default: ``l_``/``r_``
    prefixed union). Output capacity is ``2 * C * max_matches`` (one
    ``max_matches`` budget per probing lane, both directions)."""

    routing = routing_modes_t.KEYBY

    def __init__(self, side_fn: Callable, lower: int, upper: int, *,
                 archive: Optional[int] = None, max_matches: int = 4,
                 delay: int = 0, emit: Optional[Callable] = None,
                 ts_l: Optional[Callable] = None,
                 ts_r: Optional[Callable] = None, tiered=None,
                 name: str = "interval_join", parallelism: int = 1):
        super().__init__(name, parallelism)
        self.side_fn = side_fn
        self.lower = int(lower)
        self.upper = int(upper)
        self.archive = None if archive is None else int(archive)
        self.max_matches = int(max_matches)
        self.delay = int(delay)
        self.emit = emit or _default_pair_emit
        self.ts_l = ts_l
        self.ts_r = ts_r
        if self.max_matches < 1:
            raise ValueError(f"{name}: max_matches must be >= 1")
        if self.delay < 0:
            raise ValueError(f"{name}: delay (lateness) must be >= 0")
        self._archive_resolved = archive
        # tiered archives: ring-overwritten LIVE rows (today's arch_drops)
        # spill to per-side multimap host stores and come back as extra
        # match candidates; the watermark frontier retires them
        from ..state import TierConfig
        self._tier_cfg = TierConfig.resolve(tiered)
        self._tier_l = self._tier_r = None
        self._cap_resolved = None

    def bind_geometry(self, batch_capacity: int) -> None:
        self._cap_resolved = int(batch_capacity)
        a = self.archive if self.archive is not None \
            else 2 * int(batch_capacity)
        if a < batch_capacity:
            raise ValueError(
                f"{self.name}: archive={a} < batch capacity "
                f"{batch_capacity} — one batch's ring writes would collide "
                f"(size archive >= the batch capacity)")
        self._archive_resolved = int(a)

    def out_capacity(self, in_capacity: int) -> int:
        return 2 * in_capacity * self.max_matches

    def out_spec(self, payload_spec: Any) -> Any:
        r = _ref_spec(payload_spec)
        return jax.eval_shape(self.emit, r, r)

    def init_state(self, payload_spec: Any):
        A = self._archive_resolved or 2 * DEFAULT_MAX_KEYS

        def side():
            return {
                "key": jnp.full((A,), JOIN_KEY_SENTINEL, jnp.int32),
                "ts": jnp.zeros((A,), jnp.int32),
                "id": jnp.zeros((A,), jnp.int32),
                "ok": jnp.zeros((A,), jnp.bool_),
                "pay": jax.tree.map(
                    lambda s: jnp.zeros((A,) + tuple(s.shape), s.dtype),
                    payload_spec),
            }
        state = {"l": side(), "r": side(),
                 "lcur": jnp.asarray(0, jnp.int32),
                 "rcur": jnp.asarray(0, jnp.int32),
                 "wm": jnp.asarray(_IMIN, jnp.int32),
                 "match_drops": jnp.asarray(0, jnp.int32),
                 "arch_drops": jnp.asarray(0, jnp.int32)}
        if self._tier_cfg is not None:
            from ..state.tiered import ArchiveTier
            S = int(self._tier_cfg.outbox
                    or 4 * (self._cap_resolved or DEFAULT_MAX_KEYS))
            for p in ("l", "r"):
                state[f"{p}okey"] = jnp.full((S,), JOIN_KEY_SENTINEL,
                                             jnp.int32)
                state[f"{p}ots"] = jnp.zeros((S,), jnp.int32)
                state[f"{p}oid"] = jnp.zeros((S,), jnp.int32)
                state[f"{p}opay"] = jax.tree.map(
                    lambda s: jnp.zeros((S,) + tuple(s.shape), s.dtype),
                    payload_spec)
                state[f"{p}ocnt"] = jnp.asarray(0, jnp.int32)
            state["spills"] = jnp.asarray(0, jnp.int32)
            state["readmits"] = jnp.asarray(0, jnp.int32)
            # per-side retention bounds — the SAME arithmetic the in-graph
            # eviction applies to the rings (fired_hi_tb family)
            self._tier_l = ArchiveTier(
                self.name, payload_spec, self._tier_cfg, "l",
                lambda wm: wm - self.delay - self.upper)
            self._tier_r = ArchiveTier(
                self.name, payload_spec, self._tier_cfg, "r",
                lambda wm: wm - self.delay + self.lower)
        if self._event_time:
            # per-side observed-lateness histograms (event-time monitoring
            # only — absent otherwise, so the off program is unchanged)
            state["lat_l"] = _et.lateness_init()
            state["lat_r"] = _et.lateness_init()
        return state

    def tier_controllers(self):
        if self._tier_l is None:
            return ()
        return (self._tier_l.controller, self._tier_r.controller)

    def _event_ts(self, refs, is_l, batch):
        if self.ts_l is None and self.ts_r is None:
            return batch.ts
        tl = (jax.vmap(self.ts_l)(refs) if self.ts_l is not None
              else batch.ts)
        tr = (jax.vmap(self.ts_r)(refs) if self.ts_r is not None
              else batch.ts)
        return jnp.where(is_l, tl.astype(jnp.int32), tr.astype(jnp.int32))

    def _probe(self, pmask, pkey, pts, ck, cts, cid, cok, cpay):
        """Match probing lanes against a candidate set: returns
        (matched bool[C*M], picked (key, ts, id, payload)[C*M], overflow)."""
        M = self.max_matches
        diff = cts[None, :] - pts[:, None]
        m = (pmask[:, None] & cok[None, :]
             & (pkey[:, None] == ck[None, :])
             & (diff >= 0) & (diff <= self.upper - self.lower))
        # NOTE: callers pre-shift pts so the window is [0, upper-lower]
        rank = jnp.cumsum(m.astype(jnp.int32), axis=1) - 1
        cnt = jnp.sum(m.astype(jnp.int32), axis=1)
        overflow = jnp.sum(jnp.maximum(cnt - M, 0))
        matched, ks, xs, ids, pays = [], [], [], [], []
        for mm in range(M):
            sel = m & (rank == mm)                       # [C, Ncand] one-hot

            def pick(a):
                s = sel.reshape(sel.shape + (1,) * (a.ndim - 1))
                return jnp.sum(jnp.where(s, a[None, ...],
                                         jnp.zeros((), a.dtype)), axis=1)
            matched.append(jnp.any(sel, axis=1))
            ks.append(pick(ck))
            xs.append(pick(cts))
            ids.append(pick(cid))
            pays.append(jax.tree.map(pick, cpay))
        flat = lambda parts: jnp.stack(parts, axis=1).reshape(-1)
        pay = jax.tree.map(
            lambda *ls: jnp.stack(ls, axis=1).reshape(
                (-1,) + ls[0].shape[1:]), *pays)
        return (flat(matched), flat(ks), flat(xs), flat(ids), pay, overflow)

    def _rows(self, batch, pmask, ets, cand, swap):
        """Output rows of one probe direction: ``cand`` is the candidate
        side's (key, ts, id, ok, pay); ``swap`` True when the PROBING lane is
        the right side (candidates are archived left tuples)."""
        M = self.max_matches
        ck, cts, cid, cok, cpay = cand
        # shift so _probe's [0, upper-lower] window encodes r.ts - l.ts in
        # [lower, upper] for either probe direction: left probes ask for
        # cand.ts - (ets + lower) in [0, span]; right probes negate the axis
        pts = ets + self.lower if not swap else -ets + self.lower
        cts_in = cts if not swap else -cts
        matched, k2, x2, id2, pay2, overflow = self._probe(
            pmask, batch.key, pts, ck, cts_in, cid, cok, cpay)
        x2 = x2 if not swap else -x2
        rep = lambda a: jnp.repeat(a, M, axis=0)
        # the probing side's ref carries the EXTRACTED event time (the
        # archive stores ets too, so the same logical pair reaches emit()
        # with identical fields whichever member arrived later)
        probe_ref = TupleRef(key=rep(batch.key), id=rep(batch.id),
                             ts=rep(ets),
                             data=jax.tree.map(rep, batch.payload))
        cand_ref = TupleRef(key=k2, id=id2, ts=x2, data=pay2)
        l_ref, r_ref = ((probe_ref, cand_ref) if not swap
                        else (cand_ref, probe_ref))
        payload = jax.vmap(self.emit)(l_ref, r_ref)
        return (matched, rep(batch.key),
                jnp.maximum(l_ref.ts, r_ref.ts), rep(batch.id), payload,
                overflow)

    def _append(self, side, cur, mask, key, ets, batch):
        """Ring-append the batch's ``mask`` lanes into one side's archive;
        returns (side, cur, live slots overwritten)."""
        A = side["key"].shape[0]
        csum = jnp.cumsum(mask.astype(jnp.int32))
        pos = (cur + csum - 1) % A
        idx = jnp.where(mask, pos, A)
        overwrote = jnp.sum((mask & side["ok"][pos % A]
                             & (idx < A)).astype(jnp.int32))
        out = {
            "key": side["key"].at[idx].set(key, mode="drop"),
            "ts": side["ts"].at[idx].set(ets, mode="drop"),
            "id": side["id"].at[idx].set(batch.id, mode="drop"),
            "ok": side["ok"].at[idx].set(True, mode="drop"),
            "pay": jax.tree.map(lambda t, v: t.at[idx].set(v, mode="drop"),
                                side["pay"], batch.payload),
        }
        return out, (cur + csum[-1]) % A, overwrote

    def _append_spill(self, state, p, side, cur, mask, key, ets, batch):
        """Tiered ring-append: a LIVE row the ring is about to overwrite
        (still inside its match window — today's arch_drop) is packed into
        the side's spill outbox first; only outbox exhaustion still drops.
        Returns (side, cur, dropped, spilled, outbox updates)."""
        A = side["key"].shape[0]
        csum = jnp.cumsum(mask.astype(jnp.int32))
        pos = (cur + csum - 1) % A
        idx = jnp.where(mask, pos, A)
        ow = mask & jnp.take(side["ok"], pos)
        S = state[f"{p}okey"].shape[0]
        orank = jnp.cumsum(ow.astype(jnp.int32)) - 1
        fits = ow & (state[f"{p}ocnt"] + orank < S)
        opos = jnp.where(fits, state[f"{p}ocnt"] + orank, S)
        upd = {
            f"{p}okey": state[f"{p}okey"].at[opos].set(
                jnp.take(side["key"], pos), mode="drop"),
            f"{p}ots": state[f"{p}ots"].at[opos].set(
                jnp.take(side["ts"], pos), mode="drop"),
            f"{p}oid": state[f"{p}oid"].at[opos].set(
                jnp.take(side["id"], pos), mode="drop"),
            f"{p}opay": jax.tree.map(
                lambda t, a: t.at[opos].set(jnp.take(a, pos, axis=0),
                                            mode="drop"),
                state[f"{p}opay"], side["pay"]),
            f"{p}ocnt": state[f"{p}ocnt"]
            + jnp.sum(fits.astype(jnp.int32)),
        }
        out = {
            "key": side["key"].at[idx].set(key, mode="drop"),
            "ts": side["ts"].at[idx].set(ets, mode="drop"),
            "id": side["id"].at[idx].set(batch.id, mode="drop"),
            "ok": side["ok"].at[idx].set(True, mode="drop"),
            "pay": jax.tree.map(lambda t, v: t.at[idx].set(v, mode="drop"),
                                side["pay"], batch.payload),
        }
        dropped = jnp.sum((ow & ~fits).astype(jnp.int32))
        spilled = jnp.sum(fits.astype(jnp.int32))
        return out, (cur + csum[-1]) % A, dropped, spilled, upd

    def _cold_candidates(self, state, batch, lmask, rmask, horizon):
        """Extra match candidates from the cold tiers: each side's spill
        outbox (in state — unsettled spills stay probeable) + up to
        ``readmit_rows`` host-store rows per probing lane (ONE ordered
        ``io_callback`` per side), both masked by the same per-side
        eviction frontier the rings apply. Returns (right extras for left
        probes, left extras for right probes, rows fetched). NOTE: for the
        interval join ``state_readmits`` counts cold rows SERVED as
        candidates — the fetch is read-only (rows never change tiers), so
        a persistent in-window cold row counts once per probing batch."""
        from jax.experimental import io_callback
        C = batch.capacity
        M = int(self._tier_cfg.readmit_rows)
        leaves = jax.tree.leaves(batch.payload)
        treedef = jax.tree.structure(batch.payload)

        def fetch(tier, want, frontier):
            shapes = ([jax.ShapeDtypeStruct((C, M), jnp.bool_),
                       jax.ShapeDtypeStruct((C, M), jnp.int32),
                       jax.ShapeDtypeStruct((C, M), jnp.int32)]
                      + [jax.ShapeDtypeStruct((C, M) + leaf.shape[1:],
                                              leaf.dtype)
                         for leaf in leaves])
            res = io_callback(tier.fetch_cb, shapes, batch.key, want,
                              ordered=True)
            mask = res[0] & want[:, None] & (res[1] >= frontier)
            # candidates are GLOBAL (every probe lane sees the whole
            # axis): a row fetched by N lanes of the same key must appear
            # once, not N times — dedup by tuple id (unique per row)
            from ..ops.segment import segment_rank
            ids_flat = res[2].reshape(-1)
            uniq = mask.reshape(-1) & (segment_rank(
                ids_flat, mask.reshape(-1)) == 0)
            k2 = jnp.where(uniq, jnp.repeat(batch.key, M),
                           JOIN_KEY_SENTINEL)
            pay = jax.tree.unflatten(treedef, [
                r.reshape((-1,) + r.shape[2:]) for r in res[3:]])
            return (k2, res[1].reshape(-1), ids_flat, uniq, pay), \
                jnp.sum(uniq.astype(jnp.int32))

        def outbox(p, frontier):
            S = state[f"{p}okey"].shape[0]
            live = (jnp.arange(S, dtype=jnp.int32) < state[f"{p}ocnt"]) \
                & (state[f"{p}ots"] >= frontier)
            return (state[f"{p}okey"], state[f"{p}ots"], state[f"{p}oid"],
                    live, state[f"{p}opay"])

        r_front = horizon + self.lower
        l_front = horizon - self.upper
        r_fetch, n_r = fetch(self._tier_r, lmask, r_front)
        l_fetch, n_l = fetch(self._tier_l, rmask, l_front)
        r_extra = [outbox("r", r_front), r_fetch]
        l_extra = [outbox("l", l_front), l_fetch]
        return r_extra, l_extra, n_r + n_l

    def apply(self, state, batch: Batch):
        refs = tuple_refs(batch)
        is_l = jax.vmap(self.side_fn)(refs).astype(jnp.bool_)
        lmask = batch.valid & is_l
        rmask = batch.valid & ~is_l
        ets = self._event_ts(refs, is_l, batch)
        wm = jnp.maximum(state["wm"],
                         jnp.max(jnp.where(batch.valid, ets, _IMIN)))
        # evict against the watermark AS OF THE START of the batch: this
        # batch's own probes may carry timestamps below the post-batch
        # watermark, and the lateness contract only promises future arrivals
        # stay >= (previous wm) - delay
        horizon = state["wm"] - self.delay
        l, r = dict(state["l"]), dict(state["r"])
        # watermark eviction: exactly the tuples no future arrival can match
        l["ok"] = l["ok"] & (l["ts"] >= horizon - self.upper)
        r["ok"] = r["ok"] & (r["ts"] >= horizon + self.lower)
        # left probes see archived rights PLUS the batch's own rights (an
        # in-batch pair counts once, from the left side); with tiering on,
        # each side's spill outbox + host-store rows join the candidate set
        # (appended AFTER archive + batch lanes, so candidate rank — and
        # therefore the max_matches truncation order — is unchanged when
        # the cold tiers are empty)
        cat = lambda a, b: jnp.concatenate([a, b], axis=0)
        catn = lambda *xs: jnp.concatenate(xs, axis=0)
        r_cand = (cat(r["key"], jnp.where(rmask, batch.key,
                                          JOIN_KEY_SENTINEL)),
                  cat(r["ts"], ets), cat(r["id"], batch.id),
                  cat(r["ok"], rmask),
                  jax.tree.map(cat, r["pay"], batch.payload))
        l_cand = (l["key"], l["ts"], l["id"], l["ok"], l["pay"])
        tier_upd = {}
        if self._tier_l is not None:
            r_extra, l_extra, n_fetched = self._cold_candidates(
                state, batch, lmask, rmask, horizon)
            def join_c(base, extras):
                return (catn(base[0], *(e[0] for e in extras)),
                        catn(base[1], *(e[1] for e in extras)),
                        catn(base[2], *(e[2] for e in extras)),
                        catn(base[3], *(e[3] for e in extras)),
                        jax.tree.map(catn, base[4],
                                     *(e[4] for e in extras)))
            r_cand = join_c(r_cand, r_extra)
            l_cand = join_c(l_cand, l_extra)
            tier_upd["readmits"] = state["readmits"] + n_fetched
        lrows = self._rows(batch, lmask, ets, r_cand, swap=False)
        rrows = self._rows(batch, rmask, ets, l_cand, swap=True)
        valid = cat(lrows[0], rrows[0])
        out = Batch(key=cat(lrows[1], rrows[1]), id=cat(lrows[3], rrows[3]),
                    ts=cat(lrows[2], rrows[2]),
                    payload=jax.tree.map(cat, lrows[4], rrows[4]),
                    valid=valid)
        if self._tier_l is not None:
            l, lcur, odl, spl, upd_l = self._append_spill(
                state, "l", l, state["lcur"], lmask, batch.key, ets, batch)
            tier_upd.update(upd_l)
            r, rcur, odr, spr, upd_r = self._append_spill(
                state, "r", r, state["rcur"], rmask, batch.key, ets, batch)
            tier_upd.update(upd_r)
            tier_upd["spills"] = state["spills"] + spl + spr
        else:
            l, lcur, odl = self._append(l, state["lcur"], lmask, batch.key,
                                        ets, batch)
            r, rcur, odr = self._append(r, state["rcur"], rmask, batch.key,
                                        ets, batch)
        new_state = dict(
            state, l=l, r=r, lcur=lcur, rcur=rcur, wm=wm,
            match_drops=count_drops(state["match_drops"], "match_drops",
                                    lrows[5] + rrows[5]),
            arch_drops=count_drops(state["arch_drops"], "arch_drops",
                                   odl + odr))
        new_state.update(tier_upd)
        if self._event_time:
            # per-stream lateness vs the post-batch watermark: one masked
            # reduction per side, state-only (results untouched)
            new_state["lat_l"] = _et.lateness_update(
                state["lat_l"], wm, ets, lmask)
            new_state["lat_r"] = _et.lateness_update(
                state["lat_r"], wm, ets, rmask)
        return new_state, out

    def collect_stats(self, state: Any = None) -> None:
        if state is None:
            return
        counters = dict(self.drop_counters(state))
        if self._tier_l is not None:
            import numpy as np
            counters.update({
                "state_spills": int(np.asarray(state["spills"])),
                "state_readmits": int(np.asarray(state["readmits"])),
                "state_compactions":
                    self._tier_l.store.compacted_rows
                    + self._tier_r.store.compacted_rows,
                "tier_cold_keys": self._tier_l.store.key_count()
                + self._tier_r.store.key_count(),
            })
        self._publish_stage_counters(counters)

    def drop_counters(self, state: Any = None) -> dict:
        if state is None:
            return {}
        import numpy as np
        return {"match_drops": int(np.asarray(state["match_drops"])),
                "arch_drops": int(np.asarray(state["arch_drops"]))}

    def event_time_stats(self, state: Any = None):
        """Watermark-map section: per-side archive fill, the watermark
        eviction frontiers, overflow/match drops, and per-stream lateness
        histograms."""
        if state is None:
            return None
        import numpy as np
        A = int(state["l"]["key"].shape[0])
        lfill = int(np.asarray(state["l"]["ok"]).sum())
        rfill = int(np.asarray(state["r"]["ok"]).sum())
        wm = int(np.asarray(state["wm"]))
        horizon = wm - self.delay
        out = {
            "watermark_ts": wm,
            "delay": self.delay,
            "archive_slots": A,
            "l_fill": lfill, "r_fill": rfill,
            "l_fill_pct": round(100.0 * lfill / A, 2),
            "r_fill_pct": round(100.0 * rfill / A, 2),
            # a side's archived tuple below its frontier can no longer match
            # any future arrival and is evicted on the next batch
            "evict_frontier_l_ts": horizon - self.upper,
            "evict_frontier_r_ts": horizon + self.lower,
            "match_drops": int(np.asarray(state["match_drops"])),
            "arch_drops": int(np.asarray(state["arch_drops"])),
        }
        if self._tier_l is not None:
            out["tier"] = {
                "outbox_depth": int(np.asarray(state["locnt"]))
                + int(np.asarray(state["rocnt"])),
                "state_spills": int(np.asarray(state["spills"])),
                "state_readmits": int(np.asarray(state["readmits"])),
                "l_cold_rows": len(self._tier_l.store),
                "r_cold_rows": len(self._tier_r.store),
                **{k: self._tier_l.store.counters()[k]
                   + self._tier_r.store.counters()[k]
                   for k in ("state_compactions",)},
            }
        lat = {}
        for stream, key in (("l", "lat_l"), ("r", "lat_r")):
            counts = _et.read_hist(state.get(key))
            if counts is not None:
                lat[stream] = _et.summarize(counts)
        if lat:
            out["lateness"] = lat
        return out
