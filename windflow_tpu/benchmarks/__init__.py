"""Benchmark harness utilities shared by the sweep, bench.py, and probes."""

from typing import Callable, Optional

import jax


def device_cursor_step(chain, src, batch: int,
                       out_fn: Optional[Callable] = None):
    """Build the canonical jitted bench step with a DEVICE-RESIDENT cursor:
    ``step(states, cur) -> (states, cur + batch, out_fn(b))``.

    One host->device scalar upload at open, zero per step — the same
    discipline as ``operators/source.py::batches`` (a per-step host-int
    argument costs a 4 B H2D on every dispatch, RTT-class through the
    tunneled dev chip, and sits inside every latency sample). ``out_fn``
    picks the step output to hang timing/data-dependence on (default: the
    batch's valid mask)."""
    if out_fn is None:
        out_fn = lambda b: b.valid  # noqa: E731

    def step(states, cur):
        b = src.make_batch(cur, batch)
        states = list(states)
        for j, op in enumerate(chain.ops):
            states[j], b = op.apply(states[j], b)
        return tuple(states), cur + batch, out_fn(b)

    return jax.jit(step, donate_argnums=(0, 1))
