"""Benchmark sweep harness — counterpart of the reference's committed sweep
(``src/GPU_Tests/new_tests/run_tests.py:20-28``: {batch 1k/5k/10k} x {1..14
sources} x {1..10k keys}, results recorded as org-tables in
``results.org``). Sweeps {batch capacity} x {num_keys} x {workload} on the
current default device and renders a markdown table (``RESULTS.md``).

Workloads mirror the reference benchmark programs:

- ``map_stateless``    — MapGPU stateless analogue (results.org:22-31)
- ``map_stateful``     — keyed per-key running state (results.org:8-18)
- ``filter``           — FilterGPU analogue (results.org:55-66)
- ``win_kf``           — keyed sliding CB windows (Key_FFAT)

Run: ``python -m windflow_tpu.benchmarks.sweep [--steps N] [--out RESULTS.md]``
(defaults sized for the real chip; the test suite drives tiny shapes on CPU).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Tuple


def _throughput(step: Callable, states, n_steps: int, batch: int) -> float:
    import jax
    import jax.numpy as jnp
    # device-resident cursor, advanced in-program — no per-step host scalar
    # upload (same discipline as operators/source.py::batches)
    cur = jnp.asarray(0, jnp.int32)
    states, cur, out = step(states, cur)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        states, cur, out = step(states, cur)
    jax.block_until_ready(out)
    return n_steps * batch / (time.perf_counter() - t0)


def _chain_step(ops, src, batch):
    from . import device_cursor_step
    from ..runtime.pipeline import CompiledChain

    chain = CompiledChain(ops, src.payload_spec(), batch_capacity=batch,
                          event_time=False)
    return device_cursor_step(chain, src, batch), tuple(chain.states)


def workloads(batch: int, keys: int, total: int):
    import jax.numpy as jnp
    from ..operators.accumulator import Accumulator
    from ..operators.filter import Filter
    from ..operators.map import Map
    from ..operators.source import DeviceSource
    from ..operators.win_patterns import Key_FFAT
    from ..operators.window import WindowSpec

    src = DeviceSource(lambda i: {"v": (i % 997).astype(jnp.float32)},
                       total=total, num_keys=keys)
    return {
        "map_stateless": (src, [Map(lambda t: {"v": t.v * 2.0 + 1.0})]),
        "filter": (src, [Filter(lambda t: t.v > 100.0)]),
        "map_stateful": (src, [Accumulator(lambda t: t.data["v"],
                                           init_value=0.0,
                                           num_keys=max(keys, 8))]),
        "win_kf": (src, [Key_FFAT(lambda t: t.v, jnp.add,
                                  spec=WindowSpec(1024, 512),
                                  num_keys=max(keys, 8))]),
    }


def run_sweep(batches=(1 << 16, 1 << 18, 1 << 20), keyset=(1, 500, 10_000),
              names=("map_stateless", "map_stateful", "filter", "win_kf"),
              steps: int = 20) -> List[Tuple[str, int, int, float]]:
    rows = []
    for batch in batches:
        for keys in keyset:
            wl = workloads(batch, keys, total=(steps + 2) * batch)
            for name in names:
                src, ops = wl[name]
                step, states = _chain_step(ops, src, batch)
                tps = _throughput(step, states, steps, batch)
                rows.append((name, batch, keys, tps))
    return rows


def run_nexmark(batches=(1 << 12, 1 << 14), steps: int = 20
                ) -> List[Tuple[str, int, int, float]]:
    """Sweep rows for every Nexmark query (``names.py::NEXMARK_QUERIES``):
    the scenario-diversity table beside the classic four workloads. The key
    column reports each query's own key domain (auctions/bidders are fixed
    by the generators, not swept)."""
    from ..nexmark import QUERIES, make_query
    from ..nexmark import generators as g
    keys_of = {"q5_session": g.N_BIDDERS}
    rows = []
    for batch in batches:
        for name in QUERIES:
            src, ops = make_query(name, total=(steps + 2) * batch)
            step, states = _chain_step(ops, src, batch)
            tps = _throughput(step, states, steps, batch)
            rows.append((f"nexmark:{name}", batch,
                         keys_of.get(name, g.N_AUCTIONS), tps))
    return rows


def run_adaptive(batches=(1 << 16, 1 << 18, 1 << 20), keyset=(1, 500, 10_000),
                 names=("map_stateless", "map_stateful", "filter", "win_kf"),
                 steps: int = 20, cache_path=None,
                 ) -> List[Tuple[str, int, int, float]]:
    """The autotuned counterpart of :func:`run_sweep`: for each workload the
    control plane's :class:`~windflow_tpu.control.CapacityAutotuner` hill-
    climbs the SAME capacity ladder the fixed sweep enumerates, measuring each
    rung it visits with the same ``_throughput`` recipe — so the ``adaptive``
    table rows are directly comparable with the fixed-ladder rows (chosen
    capacity in the batch column, its measured rate in the rate column).
    ``cache_path`` persists/consumes the tuning cache: a second call
    warm-starts converged at the cached rung and measures only that rung."""
    from ..control.autotune import (CapacityAutotuner, TuningCache,
                                    chain_signature, device_kind,
                                    payload_signature, tuning_key)
    ladder = sorted(int(b) for b in batches)
    cache = TuningCache(cache_path) if cache_path else None
    rows = []
    for keys in keyset:
        for name in names:
            def measure(batch):
                wl = workloads(batch, keys, total=(steps + 2) * batch)
                src, ops = wl[name]
                step, states = _chain_step(ops, src, batch)
                return _throughput(step, states, steps, batch)
            key = None
            if cache is not None:
                # signature from freshly built (unbound) ops — the geometry
                # attrs the signature reads are set at construction
                src0, ops0 = workloads(ladder[0], keys, 4 * ladder[0])[name]
                key = tuning_key(chain_signature(ops0),
                                 payload_signature(src0.payload_spec()),
                                 device_kind())
            tuner = CapacityAutotuner(ladder, start_capacity=ladder[0],
                                      cache=cache, cache_key=key,
                                      name=f"sweep:{name}:k{keys}")
            tps = None
            while True:
                tps = measure(tuner.capacity)
                if tuner.converged:
                    break           # warm start: one confirming measurement
                nxt = tuner.observe(tps)
                if tuner.converged and nxt is None:
                    # converged on the rung just measured
                    break
                # converged with a switch back to the best rung: loop once
                # more to measure/report the winner; otherwise keep climbing
            rows.append((f"{name} (adaptive)", tuner.capacity, keys, tps))
    return rows


def render_markdown(rows, device: str) -> str:
    lines = [
        "# RESULTS — swept throughput (tuples/s)",
        "",
        f"Device: {device}. Counterpart of the reference's committed sweep "
        "tables (`src/GPU_Tests/new_tests/results/results.org`; CUDA bars: "
        "~16.6M stateless, 11.8M stateful @500 keys, 0.44-0.64M @1 key, "
        "~10M @10k keys). `(adaptive)` rows: the control plane's capacity "
        "autotuner hill-climbed the same ladder — batch column = chosen "
        "capacity.",
        "",
        "| workload | batch | keys | M tuples/s |",
        "|---|---|---|---|",
    ]
    for name, batch, keys, tps in rows:
        lines.append(f"| {name} | {batch} | {keys} | {tps / 1e6:.2f} |")
    return "\n".join(lines) + "\n"


def main(argv=None):
    import argparse
    import sys

    import jax

    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--out", default="RESULTS.md")
    ap.add_argument("--no-adaptive", action="store_true",
                    help="skip the autotuned rows (fixed-ladder sweep only)")
    ap.add_argument("--no-nexmark", action="store_true",
                    help="skip the Nexmark query rows")
    ap.add_argument("--tuning-cache", default=None,
                    help="tuning-cache path for the adaptive rows (a second "
                    "run warm-starts at the cached optimum)")
    args = ap.parse_args(argv)
    rows = run_sweep(steps=args.steps)
    if not args.no_adaptive:
        rows += run_adaptive(steps=args.steps, cache_path=args.tuning_cache)
    if not args.no_nexmark:
        rows += run_nexmark(steps=args.steps)
    md = render_markdown(rows, str(jax.devices()[0]))
    with open(args.out, "w") as f:
        f.write(md)
    print(md, file=sys.stderr)
    print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
