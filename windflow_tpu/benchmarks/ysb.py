"""Yahoo Streaming Benchmark (YSB) — the flagship macro-benchmark.

Counterpart of ``src/yahoo_test_cpu`` (``test_ysb_kf.cpp:18-26``: EventSource ->
Filter -> Project -> Join -> KeyFarm window count -> Sink; campaign fixture
``campaign_generator.hpp``; latency vector ``ysb_nodes.hpp:200-216``). The north-star
metric is tuples/sec/chip + p99 window-result latency (BASELINE.json).

Pipeline (TPU formulation):
1. EventSource: synthetic ad events ``(ad_id, event_type, ts)`` generated on device.
2. Filter: keep ``event_type == VIEW`` (1 of 3 types — 1/3 selectivity like the
   reference generator).
3. Project+Join: map ``ad_id -> campaign_id`` via a constant device-resident table
   (the reference joins against an in-memory campaign map).
4. Key_FFAT: per-campaign tumbling TB window (10-time-unit panes) counting views —
   associative lift/combine, the reference uses an incremental count window.
5. ReduceSink (device) or host Sink recording per-window results + latencies.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..basic import win_type_t
from ..batch import Batch, CTRL_DTYPE
from ..operators.filter import Filter
from ..operators.map import Map
from ..operators.sink import ReduceSink
from ..operators.source import DeviceSource
from ..operators.win_patterns import Key_FFAT
from ..operators.window import WindowSpec
from ..runtime.pipeline import CompiledChain, Pipeline

N_CAMPAIGNS = 100
ADS_PER_CAMPAIGN = 10
N_ADS = N_CAMPAIGNS * ADS_PER_CAMPAIGN
WIN_LEN = 100          # time units per tumbling window (reference: 10s of event time)
EVENTS_PER_TICK = 10   # synthetic event-time rate: ts = i // EVENTS_PER_TICK


def make_ops(num_keys: int = N_CAMPAIGNS, win_len: int = WIN_LEN,
             pane_capacity: int = None, max_wins: int = None):
    """The YSB operator chain after the source (filter -> join -> window count)."""
    # ad -> campaign: static fixture table (campaign_generator.hpp analogue)
    camp_of = jnp.asarray(np.arange(N_ADS) // ADS_PER_CAMPAIGN, CTRL_DTYPE)

    from ..operators.map import BatchMap
    from ..ops.lookup import table_lookup

    filt = Filter(lambda t: t.event_type == 0, name="ysb_filter")
    # per-tuple campaign join via the gather-free small-table lookup (the reference
    # joins a hash map per tuple; jnp.take would serialize at ~5.6 ns/tuple)
    join = BatchMap(lambda p: {"cmp": table_lookup(camp_of, p["ad_id"])},
                    name="ysb_join")

    # Key routing: the window op keys on campaign id (KEYBY re-route on a
    # payload field)
    from ..operators.map import KeyBy
    rekey = KeyBy(lambda t: t.cmp, num_keys, name="ysb_rekey")
    window = Key_FFAT(lambda t: jnp.ones((), jnp.int32), jnp.add,
                      spec=WindowSpec(win_len, win_len, win_type_t.TB),
                      num_keys=num_keys, name="ysb_window",
                      pane_capacity=pane_capacity, max_wins=max_wins)
    return [filt, join, rekey, window]


def make_ops_wmr(num_keys: int = N_CAMPAIGNS, win_len: int = WIN_LEN,
                 map_parallelism: int = 2, **engine_kw):
    """YSB with a Win_MapReduce window stage — the ``test_ysb_wmr.cpp`` variant of
    the reference (each window's content partitioned over MAP workers, partial
    counts combined by REDUCE). ``engine_kw`` (``max_wins``, ``tb_capacity``,
    ...) forwards to the underlying Win_Seq engine — large batches need
    explicit fired-window budgets (the engine's default budget guard raises)."""
    from ..operators.win_patterns import Win_MapReduce
    filt, join, rekey, _ = make_ops(num_keys=num_keys, win_len=win_len)
    window = Win_MapReduce(lambda wid, it: it.size(),
                           lambda wid, it: it.sum(),
                           WindowSpec(win_len, win_len, win_type_t.TB),
                           map_parallelism=map_parallelism, num_keys=num_keys,
                           name="ysb_window_wmr", **engine_kw)
    return [filt, join, rekey, window]


def make_source(total: int, name: str = "ysb_source") -> DeviceSource:
    def gen(i):
        return {"ad_id": (i * 7919) % N_ADS,     # pseudo-random ad
                "event_type": i % 3}
    return DeviceSource(gen, total=total, name=name,
                        key_fn=lambda i: (i * 7919) % N_ADS % N_CAMPAIGNS,
                        ts_fn=lambda i: i // EVENTS_PER_TICK)


def make_pipeline(total: int, batch_size: int = 8192,
                  count_sink: bool = True) -> Pipeline:
    ops = make_ops()
    if count_sink:
        ops.append(ReduceSink(lambda t: t.data, name="ysb_windows_total"))
    src = make_source(total)
    return Pipeline(src, ops, batch_size=batch_size)


def oracle_totals(total: int) -> int:
    """Total view events (the sum of all window counts must equal this)."""
    return len([i for i in range(total) if i % 3 == 0])
