"""Adaptive control plane — closed-loop micro-batch autotuning, backpressure,
and load-shedding admission control.

The reference WindFlow fixes batch size and queue capacities at
graph-construction time and hand-searches them offline (the committed
{batch} x {sources} x {keys} sweep in ``src/GPU_Tests/new_tests/
run_tests.py``); PR 1's observability layer exposed exactly the signals a
controller needs (service percentiles, queue-depth gauges, watermark lag) but
nothing consumed them. This package closes the loop:

- ``autotune.py`` — :class:`CapacityAutotuner`: a power-of-two capacity
  ladder hill-climbed on measured tuples/s, switching among *cached* compiled
  executables (capacity is a static trace shape — ``CompiledChain.warm``
  pre-compiles every rung; the hot path never retraces), with a JSON
  :class:`TuningCache` keyed by (chain signature, payload spec, device kind)
  for warm starts. Actuated by the ``Pipeline`` driver via a
  :class:`Rebatcher` at the ingest boundary.
- ``governor.py`` — :class:`BackpressureGovernor`: per-edge high/low
  watermarks over the SPSC ring depths; throttles the source loop and pauses
  ``prefetch_to_device`` when a downstream stage falls behind. Actuated by
  ``ThreadedPipeline`` and ``PipeGraph._run_threaded``.
- ``admission.py`` — :class:`AdmissionController`: token-bucket rate
  limiting (:class:`TokenBucket` wall-clock / :class:`PositionBucket`
  deterministic-for-replay) + pluggable shed policy (``drop_newest`` /
  ``drop_oldest_ts``) at every driver's ingest boundary.
- ``remediation.py`` — :class:`RemediationPolicy`/:class:`RemediationEngine`:
  self-driving remediation mapping SLO burn signatures to these actuators —
  live on the Reporter tick, or as the deterministic
  :class:`BarrierRemediation` at supervised commit barriers (checkpointed
  decision state, byte-identical replay). Behind ``remediation=`` /
  ``WF_REMEDIATION``.

Everything is **off by default** and enabled per driver via ``control=``
(True, a dict of :class:`ControlConfig` fields, a config object) or
process-wide via ``WF_CONTROL`` — the ``monitoring=``/``faults=`` convention.
Every decision is counted (``MetricsRegistry`` snapshot section ``control``,
Prometheus ``windflow_control_*`` series) and journaled (``shed`` /
``throttle`` / ``capacity_switch`` / ``tuning_converged`` events).
"""

from ._state import bump, counters, gauges, reset, set_gauge
from .admission import (AdmissionController, PositionBucket, TokenBucket,
                        admission_from_config, admission_group,
                        bucket_from_config)
from .autotune import (CapacityAutotuner, Rebatcher, TuningCache,
                       build_ladder, chain_signature, device_kind,
                       dispatch_tuning_key, payload_signature, tuning_key)
from .config import ControlConfig
from .governor import BackpressureGovernor, governor_from_config
from .remediation import (ACTUATORS, BarrierRemediation, RemediationAction,
                          RemediationEngine, RemediationPolicy,
                          barrier_policy_problems, default_barrier_policy,
                          default_policy, resolve_barrier_policy,
                          resolve_policy)

__all__ = [
    "ControlConfig", "AdmissionController", "TokenBucket", "PositionBucket",
    "BackpressureGovernor", "CapacityAutotuner", "Rebatcher", "TuningCache",
    "build_ladder", "chain_signature", "payload_signature", "device_kind",
    "tuning_key", "dispatch_tuning_key", "admission_from_config",
    "admission_group", "bucket_from_config", "governor_from_config",
    "RemediationAction", "RemediationPolicy", "RemediationEngine",
    "BarrierRemediation", "ACTUATORS", "default_policy", "resolve_policy",
    "default_barrier_policy", "resolve_barrier_policy",
    "barrier_policy_problems",
    "counters", "gauges", "reset", "bump", "set_gauge",
]
