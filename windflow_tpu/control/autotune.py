"""Capacity autotuner — closed-loop micro-batch sizing over a rung ladder.

The reference fixes ``batch_len`` at graph-construction time and hand-searches
it offline (``src/GPU_Tests/new_tests/run_tests.py`` sweeps {batch} x {sources}
x {keys} into committed org-tables); our port inherited a static
``batch_capacity``. This module closes the loop at runtime:

- :func:`build_ladder` — a power-of-two ladder of capacities around the base
  (``base * 2^k``; down-rungs stop when the base stops dividing evenly, so
  rebatching stays an exact concat/slice with no re-padding).
- :class:`Rebatcher` — converts the source's base-capacity batches to the
  current rung at the ingest boundary: up-rungs concatenate 2^k base batches
  (``concat_batches``), down-rungs slice one base batch into 2^k pieces
  (``split_batch`` — the ``create_sub_batch`` analogue). Lane content is
  unchanged, so results are invariant to the rung schedule (the mp-matrix
  geometry-invariance property, asserted by the controller regression test).
- :class:`CapacityAutotuner` — hill-climbs tuples/s over the ladder.
  Capacity is a static trace shape on TPU, so a rung switch *selects a cached
  executable* (jax.jit keeps one compiled program per input shape; ``prewarm``
  compiles every rung up front via ``CompiledChain.warm`` — a functional
  dry-run that never touches operator state) — the hot path never retraces.
- :class:`TuningCache` — persists the winning rung to JSON keyed by
  (chain signature, payload spec, device kind), so later runs warm-start at
  the optimum instead of re-exploring.

The measured signal is the same substrate the observability layer aggregates:
tuples pushed per wall second at the chain boundary (the ``Stats_Record`` /
``MetricsRegistry`` rate definition), sampled over ``decide_every``-batch
windows with a ``settle_batches`` blackout after each switch so compile and
pipeline-refill transients never pollute a measurement.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from functools import reduce
from typing import List, Optional

from ..batch import concat_batches, split_batch
from ..observability import journal as _journal
from . import _state


def build_ladder(base: int, up: int = 2, down: int = 2,
                 min_capacity: int = 8, max_capacity: Optional[int] = None,
                 ) -> List[int]:
    """Power-of-two capacity rungs around ``base``, ascending, ``base``
    included. Down-rungs require exact divisibility (a base batch must slice
    into whole pieces) and stop at ``min_capacity``."""
    base = int(base)
    if base < 1:
        raise ValueError(f"ladder base must be >= 1, got {base}")
    rungs = [base]
    c = base
    for _ in range(max(0, int(down))):
        if c % 2 or c // 2 < min_capacity:
            break
        c //= 2
        rungs.append(c)
    c = base
    for _ in range(max(0, int(up))):
        c *= 2
        if max_capacity is not None and c > max_capacity:
            break
        rungs.append(c)
    return sorted(rungs)


class Rebatcher:
    """Base-capacity batches in, current-rung-capacity batches out.

    Rungs are exact multiples/divisors of the base capacity, so rebatching is
    a pure concat/slice — no padding, no compaction, no device sync. Switches
    take effect at base-batch boundaries; batches buffered toward a larger
    rung when the target shrinks are released at their own (base) capacity,
    which is always a ladder rung and therefore already traced."""

    def __init__(self, base_capacity: int):
        self.base = int(base_capacity)
        self.target = self.base
        self._buf: List = []

    def set_target(self, capacity: int) -> None:
        if capacity >= self.base and capacity % self.base:
            raise ValueError(f"target {capacity} is not a multiple of the "
                             f"base capacity {self.base}")
        if capacity < self.base and self.base % capacity:
            raise ValueError(f"target {capacity} does not divide the base "
                             f"capacity {self.base}")
        self.target = int(capacity)

    def _release_buffer(self) -> List:
        out, self._buf = self._buf, []
        return out

    def feed(self, batch) -> List:
        """One base batch in; zero or more target-capacity batches out."""
        if batch.capacity != self.base:
            # sources emit a fixed capacity; anything else passes through
            # untouched (EOS flush cascades re-enter at odd capacities)
            return self._release_buffer() + [batch]
        if self.target == self.base:
            return self._release_buffer() + [batch]
        if self.target < self.base:
            return self._release_buffer() + split_batch(batch, self.target)
        self._buf.append(batch)
        if len(self._buf) * self.base >= self.target:
            merged = reduce(concat_batches, self._buf)
            self._buf = []
            return [merged]
        return []

    def drain(self) -> List:
        """EOS: release the partial accumulation at base capacity."""
        return self._release_buffer()


# --------------------------------------------------------------- tuning cache

def chain_signature(ops) -> str:
    """Structural signature of an operator chain — what the tuned capacity is
    conditioned on. Geometry-bearing attributes only (window spec, key space,
    fan-out, parallelism), not user lambdas: two runs of the same topology
    share a cache entry even though their closures hash differently."""
    sig = []
    for op in ops:
        row = {"type": type(op).__name__,
               "routing": op.getRoutingMode().name,
               "parallelism": op.getParallelism()}
        spec = getattr(op, "spec", None)
        if spec is not None and hasattr(spec, "win_len"):
            row["win"] = [int(spec.win_len), int(spec.slide),
                          getattr(getattr(spec, "wtype", None), "name", "")]
        for attr in ("num_keys", "max_fanout", "pane_len"):
            v = getattr(op, attr, None)
            if isinstance(v, int):
                row[attr] = v
        sig.append(row)
    return json.dumps(sig, sort_keys=True)


def payload_signature(spec) -> str:
    import jax
    leaves = jax.tree.leaves(spec)
    return json.dumps([[list(getattr(l, "shape", ())),
                        str(getattr(l, "dtype", "?"))] for l in leaves])


def device_kind() -> str:
    try:
        import jax
        d = jax.devices()[0]
        return f"{d.platform}:{getattr(d, 'device_kind', '?')}"
    except Exception:                         # noqa: BLE001 — no backend
        return "unknown"


def tuning_key(chain_sig: str, payload_sig: str, device: str) -> str:
    h = hashlib.sha1(f"{chain_sig}\n{payload_sig}\n{device}".encode())
    return h.hexdigest()[:16]


def kernel_tuning_key(kernel: str, spec_key: str, device: str) -> str:
    """Cache key for a per-backend KERNEL impl winner (``ops/registry.py``):
    the same keyed-by-(signature, spec, device) discipline as the capacity
    plans, with the kernel family name standing in for the chain signature —
    capacity entries and kernel entries share one cache file without
    colliding."""
    return tuning_key(f"kernel:{kernel}", spec_key, device)


def dispatch_tuning_key(chain_sig: str, payload_sig: str, device: str) -> str:
    """Cache key for the scan-dispatch K winner (``runtime/dispatch.py``):
    the capacity key's (chain, payload, device) coordinates under a
    ``dispatch:`` namespace, so K plans and capacity plans for the SAME chain
    live side by side in one cache file."""
    return tuning_key(f"dispatch:{chain_sig}", payload_sig, device)


class TuningCache:
    """JSON file of winning plans, read-merge-atomic-replace on ``put``; a
    corrupt/missing file reads empty. Two entry kinds share the store:

    - **capacity plans** (``tuning_key``): ``{"capacity": c, "tps": r,
      "ladder": [...], "name": ...}`` — the autotuner's converged rung.
    - **kernel impl winners** (``kernel_tuning_key``, written by
      ``ops/registry.py::persist_winner``): ``{"impl": "pallas", "kernel":
      "histogram", "spec": ..., "tps": ...}`` — the per-backend registry
      warm-starts kernel selection from these, so a chain's first trace
      already uses the best known implementation for this device.

    Consumers ignore entry kinds they don't understand (``get`` returns the
    raw dict), so the schema extension is forward- and backward-compatible.
    """

    def __init__(self, path: str):
        self.path = path

    def _load(self) -> dict:
        try:
            with open(self.path) as f:
                obj = json.load(f)
            return obj if isinstance(obj, dict) else {}
        except (OSError, ValueError):
            return {}

    def get(self, key: str) -> Optional[dict]:
        return self._load().get(key)

    def put(self, key: str, entry: dict) -> None:
        store = self._load()
        store[key] = dict(entry, wall=time.time())
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(store, f, indent=1, sort_keys=True)
        os.replace(tmp, self.path)


# ------------------------------------------------------------- the autotuner

class CapacityAutotuner:
    """Hill-climber over a capacity ladder.

    Protocol: the driver calls :meth:`on_batch` after every chain push; a
    non-None return is the new capacity to actuate (the driver points its
    :class:`Rebatcher` at it). Internally each rung is measured over
    ``decide_every`` batches (after a ``settle_batches`` blackout), then
    :meth:`observe` — the pure decision core, directly drivable by harnesses
    like ``benchmarks/sweep.py`` — records the rate and picks the next rung:
    climb up from the seed while each move beats the previous rung by
    ``improve_threshold``, then climb down from the seed the same way, then
    settle on the argmax, journal ``tuning_converged``, and persist the plan.

    A cache hit warm-starts *converged* at the cached rung — the second-run
    acceptance property: no re-exploration, first batch already optimal.
    """

    def __init__(self, ladder: List[int], *, start_capacity: Optional[int] = None,
                 decide_every: int = 8, settle_batches: int = 2,
                 improve_threshold: float = 0.05, clock=time.monotonic,
                 cache: Optional[TuningCache] = None,
                 cache_key: Optional[str] = None, name: str = "",
                 gauge: str = "chosen_capacity"):
        if not ladder:
            raise ValueError("empty capacity ladder")
        self.ladder = sorted(int(c) for c in ladder)
        self.decide_every = max(1, int(decide_every))
        self.settle_batches = max(0, int(settle_batches))
        self.improve_threshold = float(improve_threshold)
        self.clock = clock
        self.cache = cache
        self.cache_key = cache_key
        self.name = name
        #: control gauge this tuner publishes its chosen rung under — the
        #: capacity ladder's "chosen_capacity", or "dispatch_k" when the SAME
        #: hill-climber is pointed at a scan-dispatch K ladder
        self.gauge = gauge
        self.converged = False
        self.decisions = 0
        self._rates = {}                      # capacity -> tuples/s
        self._phase = "up"
        self._prev_rate: Optional[float] = None

        seed = start_capacity if start_capacity in self.ladder else self.ladder[0]
        if cache is not None and cache_key is not None:
            hit = cache.get(cache_key)
            if hit and int(hit.get("capacity", -1)) in self.ladder:
                seed = int(hit["capacity"])
                self.converged = True
                _state.bump("tuning_cache_hits")
                _journal.record("tuning_warm_start", tuner=name,
                                capacity=seed, key=cache_key)
        self.capacity = seed
        self._seed = seed
        _state.set_gauge(self.gauge, self.capacity)
        # measurement window
        self._settle = self.settle_batches
        self._win_batches = 0
        self._win_tuples = 0
        self._win_t0: Optional[float] = None
        # remediation re-climb request (control/remediation.py): SET from
        # the Reporter thread, CONSUMED by the driver loop at the next
        # on_batch boundary — the Event is the only cross-thread surface;
        # all tuner state stays single-writer[driver]
        self._reclimb = threading.Event()

    # -- decision core (pure w.r.t. time: rates come in from outside) -------

    def observe(self, rate: float) -> Optional[int]:
        """Record ``rate`` (tuples/s) for the current capacity and return the
        next capacity to try (None = stay / converged)."""
        if self.converged:
            return None
        self.decisions += 1
        _state.bump("tuning_decisions")
        self._rates[self.capacity] = float(rate)
        i = self.ladder.index(self.capacity)
        improved = (self._prev_rate is None
                    or rate > self._prev_rate * (1 + self.improve_threshold))
        if self._phase == "up":
            if (improved and i + 1 < len(self.ladder)
                    and self.ladder[i + 1] not in self._rates):
                self._prev_rate = rate
                return self._switch(self.ladder[i + 1])
            self._phase = "down"
            self._prev_rate = self._rates[self._seed]
            j = self.ladder.index(self._seed)
            if j - 1 >= 0 and self.ladder[j - 1] not in self._rates:
                return self._switch(self.ladder[j - 1])
            return self._finish()
        # phase == "down"
        if (improved and i - 1 >= 0
                and self.ladder[i - 1] not in self._rates):
            self._prev_rate = rate
            return self._switch(self.ladder[i - 1])
        return self._finish()

    def _switch(self, capacity: int) -> Optional[int]:
        if capacity == self.capacity:
            return None
        self.capacity = capacity
        _state.bump("capacity_switches")
        _state.set_gauge(self.gauge, capacity)
        _journal.record("capacity_switch", tuner=self.name, capacity=capacity)
        self._settle = self.settle_batches
        return capacity

    def _finish(self) -> Optional[int]:
        best = max(self._rates, key=self._rates.get)
        self.converged = True
        _journal.record("tuning_converged", tuner=self.name, capacity=best,
                        tps=round(self._rates[best], 1),
                        rates={str(k): round(v, 1)
                               for k, v in self._rates.items()})
        if self.cache is not None and self.cache_key is not None:
            self.cache.put(self.cache_key, {
                "capacity": int(best), "tps": self._rates[best],
                "ladder": self.ladder, "name": self.name})
        return self._switch(best)

    # -- remediation actuator surface ---------------------------------------

    def request_reclimb(self) -> None:
        """The ``autotune_reclimb`` remediation actuator: ask the driver loop
        to un-converge this tuner at its next batch boundary.  Thread-safe
        (an Event set); actuation itself happens on the driver thread via
        :meth:`reclimb`."""
        self._reclimb.set()

    def reclimb(self) -> bool:
        """Driver-thread: un-converge and re-explore the ladder from the
        current rung.  A tuner still exploring (including one inside a
        settle blackout after a switch) is a no-op — the climb in progress
        IS the re-climb; clobbering its window/blackout mid-measurement
        would poison the rate it is collecting."""
        if not self.converged:
            return False
        self.converged = False
        self._rates = {}
        self._phase = "up"
        self._prev_rate = None
        self._seed = self.capacity
        self._settle = self.settle_batches
        self._win_t0 = None
        _journal.record("tuning_reclimb", tuner=self.name,
                        capacity=self.capacity)
        return True

    # -- driver-loop surface ------------------------------------------------

    def on_batch(self, n_tuples: int) -> Optional[int]:
        """Account one pushed batch; returns a new capacity on a decision
        boundary that switched rungs, else None."""
        if self._reclimb.is_set():
            self._reclimb.clear()
            self.reclimb()
        if self.converged:
            return None
        if self._settle > 0:
            self._settle -= 1
            self._win_t0 = None               # blackout resets the window
            return None
        if self._win_t0 is None:
            # this batch opens the window (its push predates t0 — counting it
            # would inflate the first window's rate); measure the next N
            self._win_t0 = self.clock()
            self._win_batches = 0
            self._win_tuples = 0
            return None
        self._win_batches += 1
        self._win_tuples += int(n_tuples)
        if self._win_batches < self.decide_every:
            return None
        dt = max(self.clock() - self._win_t0, 1e-9)
        rate = self._win_tuples / dt
        self._win_t0 = None
        return self.observe(rate)

    def plan(self) -> dict:
        return {"capacity": self.capacity, "converged": self.converged,
                "rates": dict(self._rates), "ladder": self.ladder}
