"""Self-driving remediation — close the loop from SLO PAGE to actuator.

The PR 15/16 SLO stack judges (OK->WARN->PAGE, multi-window burn rates,
incident bundles, fleet correlation) but never acts: every actuator the
system owns — admission shed rates (``admission.py``), autotuner capacity/K
re-climb (``autotune.py``), ``reshard="auto"`` (``runtime/supervisor.py``),
tiered hot capacity, ``recommend_delay(q)`` (``observability/event_time.py``)
— waits for a human.  This module is the wiring between them: a declarative
:class:`RemediationPolicy` maps burn signatures to actuator invocations,
rate-limited and damped exactly like the subsystems it drives.

Two evaluation modes share one policy grammar:

- **Live mode** (:class:`RemediationEngine`): subscribes to the SLO engine's
  per-tick verdicts on the Reporter thread (``SLOEngine.verdict_hook``).  On
  a PAGE it fires the matching action through a driver-*bound* actuator
  callable — ``Pipeline``/``ThreadedPipeline`` bind what they own (admission
  rate, tuner re-climb) in ``run()``; an action whose actuator the run never
  bound skips loudly (``remediation_skip`` reason ``unbound``) instead of
  guessing.  Wall-clock cooldown + max-actions budget (the incident-bundle
  rate-limit pattern) and no-improvement damping (the auto-reshard 0.9
  pattern) bound the blast radius.
- **Barrier mode** (:class:`BarrierRemediation`): supervised drivers cannot
  act on wall-clock verdicts — replay must re-derive byte-identical results.
  The barrier evaluator consumes only *committed deterministic signals*
  (PositionBucket shed ratios, per-shard interval counts — pure functions of
  stream position) at each commit barrier, counts consecutive violations
  against the action's ``target``/``window``, and its entire decision state
  is a JSON dict checkpointed beside the admission bucket — replay from any
  checkpoint re-derives the exact same actions at the exact same barriers.

Geometry-baked setpoints (tiered ``hot_capacity``, ``WindowSpec.delay``) are
traced constants — mutating them mid-run would retrace every cached
executable and trip the WF109 unexpected-retrace detector.  Their actuators
are therefore **advisory**: the recommendation is journaled + gauged
(``remediation_recommended_*``) for the next restart to pick up, never
applied to a live trace.

Everything is off by default behind ``remediation=`` / ``WF_REMEDIATION``
(the ``monitoring=``/``control=`` convention); config that cannot work is a
loud ``ValueError`` at construction, mirrored pre-run by the WF118
validator.  Stdlib only — no JAX at module scope (the analyzers and the
poisoned-jax CLI smoke load the observability plane without a backend).
"""

from __future__ import annotations

import json
import math
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..observability import journal as _journal
from . import _state

#: engine-wide defaults (overridable via MonitoringConfig /
#: WF_REMEDIATION_COOLDOWN_S / WF_REMEDIATION_MAX_ACTIONS)
DEFAULT_COOLDOWN_S = 60.0
DEFAULT_MAX_ACTIONS = 8

#: no-improvement damping threshold — an action whose triggering metric has
#: not improved by >10% since it last fired is not helping and stops (the
#: ShardedSupervisor auto-reshard damping constant)
DAMP_RATIO = 0.9

#: every actuator a policy may name -> what firing it does.  THE registry the
#: WF118 validator and the policy constructor check actuator names against —
#: a typo'd actuator is a construction-time ValueError, not a silent no-op.
ACTUATORS = {
    "admission_rate":
        "scale the admission bucket refill rate by `factor` (clamped at "
        "`floor` tuples/interval) — shed harder at the ingest boundary",
    "autotune_reclimb":
        "un-converge the capacity/K autotuner so it re-explores its ladder "
        "(deferred past any settle blackout, actuated on the driver thread)",
    "reshard":
        "request a key-ownership reshard at the next commit barrier "
        "(sharded supervision; loses deterministically to a pending "
        "auto-reshard at the same barrier)",
    "hot_capacity":
        "recommend a larger tiered hot capacity (advisory: geometry is a "
        "traced constant — journaled + gauged for the next restart)",
    "widen_delay":
        "recommend a wider watermark delay from the lateness histogram "
        "(advisory: WindowSpec.delay is a traced constant)",
    "tenant_rate":
        "scale ONE tenant's admission bucket by `factor` (clamped at "
        "`floor`) — the serving plane resolves the firing SLO's tenant= "
        "label to its bucket, so a noisy tenant is shed without touching "
        "its neighbors' budgets (serving/runtime.py binds it)",
}

#: barrier-mode deterministic signal each actuator is evaluated on (None =
#: not barrier-actionable: the signal cannot be derived from committed state)
BARRIER_SIGNALS = {
    "admission_rate": "drop_ratio",   # interval shed/(shed+admitted)
    "reshard": "shard_skew",          # hot fraction: max/total of per-shard
    #                                   interval tuples (the governor's
    #                                   scale-free recommend_reshard signal)
}

#: gauges advisory actuators publish their recommendation under
ADVISORY_GAUGES = {
    "hot_capacity": "remediation_hot_capacity",
    "widen_delay": "remediation_recommended_delay",
}


# ------------------------------------------------------------ policy grammar

@dataclass(frozen=True)
class RemediationAction:
    """One burn-signature -> actuator mapping.

    ``slo`` names the :class:`~..observability.slo.SLOSpec` whose PAGE fires
    this action (live mode); ``target``/``window`` drive the barrier-mode
    evaluator instead (consecutive barriers the deterministic signal must
    exceed ``target``).  ``gate`` optionally conditions firing on a health
    gauge — ``"dispatch_ratio>=0.5"`` is how the default policy tells a
    dispatch-bound latency burn apart from a compute-bound one (PR 10's
    disambiguator) before re-climbing the tuner."""

    name: str                 # unique ledger/journal handle
    slo: str                  # SLO spec name whose PAGE triggers the action
    actuator: str             # ACTUATORS key
    factor: float = 0.7       # multiplicative setpoint scale (rate actions)
    floor: float = 1.0        # lower clamp for scaled setpoints
    gate: str = ""            # optional "gauge>=value" / "gauge<=value"
    target: float = 0.05      # barrier mode: violation threshold
    window: int = 5           # barrier mode: consecutive violating barriers
    max_applies: int = 4      # per-action cap within one run


@dataclass(frozen=True)
class RemediationPolicy:
    """An ordered tuple of actions (evaluation order = declaration order)."""

    actions: Tuple[RemediationAction, ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "actions", tuple(self.actions))
        probs = policy_problems(self)
        if probs:
            raise ValueError("invalid remediation policy: " + "; ".join(probs))


def default_policy() -> RemediationPolicy:
    """The ``remediation=True`` policy — actions over the default SLO specs
    (``observability/slo.py::default_specs``): shed harder on ``drop_ratio``
    burn, re-climb the tuner on dispatch-bound ``e2e_p99_ms`` burn."""
    return RemediationPolicy(actions=(
        RemediationAction(name="shed_harder", slo="drops",
                          actuator="admission_rate", factor=0.7, floor=1.0,
                          target=0.05, window=5),
        RemediationAction(name="reclimb_dispatch", slo="latency_e2e",
                          actuator="autotune_reclimb",
                          gate="dispatch_ratio>=0.5"),
    ))


def default_barrier_policy(*, admission: bool,
                           shards: int) -> RemediationPolicy:
    """The supervised ``remediation=True`` policy — only actions whose
    actuator the run actually OWNS (admission bucket on, shards > 1): shed
    harder on the interval shed ratio, split the hot shard on sustained
    skew.  A run owning neither is a loud ValueError (remediation armed
    with nothing to actuate would read as covered while nothing watches)."""
    actions = []
    if admission:
        actions.append(RemediationAction(
            name="shed_harder", slo="drops", actuator="admission_rate",
            factor=0.7, floor=1.0, target=0.05, window=5))
    if shards > 1:
        actions.append(RemediationAction(
            name="split_hot_shard", slo="shards", actuator="reshard",
            target=0.6, window=3, max_applies=2))
    if not actions:
        raise ValueError(
            "remediation=True under supervision, but the run owns no "
            "barrier actuator — enable deterministic admission control "
            "(ControlConfig(admission=True, refill_per_batch=...)) and/or "
            "sharding (shards > 1); the WF118 validator reports this "
            "pre-run")
    return RemediationPolicy(actions=tuple(actions))


def barrier_policy_problems(p: RemediationPolicy, *, admission: bool,
                            shards: int) -> List[str]:
    """Supervised-mode legality over and above :func:`policy_problems`:
    every action must be barrier-actionable (its actuator has a
    deterministic committed signal) AND owned by the run config — shared by
    the construction-time ValueError and the WF118 validator."""
    probs: List[str] = []
    for a in p.actions:
        if a.actuator not in BARRIER_SIGNALS:
            probs.append(
                f"action {a.name!r}: actuator {a.actuator!r} has no "
                f"deterministic barrier signal, so supervised replay could "
                f"not re-derive it (barrier-actionable: "
                f"{', '.join(sorted(BARRIER_SIGNALS))}; use the live "
                f"drivers' monitoring= remediation for the rest)")
        elif a.actuator == "admission_rate" and not admission:
            probs.append(
                f"action {a.name!r}: actuator 'admission_rate' but the run "
                f"has no admission controller — enable ControlConfig("
                f"admission=True, refill_per_batch=...)")
        elif a.actuator == "reshard" and shards <= 1:
            probs.append(
                f"action {a.name!r}: actuator 'reshard' but the run is not "
                f"sharded (shards= / WF_SHARDS)")
    return probs


def resolve_barrier_policy(arg, *, admission: bool,
                           shards: int) -> Optional[RemediationPolicy]:
    """The supervised drivers' ``remediation=`` / ``WF_REMEDIATION``
    resolution: ``True`` builds :func:`default_barrier_policy` from the
    actuators the run owns; an explicit policy must pass
    :func:`barrier_policy_problems` (loud ValueError, mirrored by WF118)."""
    if arg is None or arg is False or arg == "" or arg == "0":
        return None
    if arg is True or arg == "1" or arg == 1:
        return default_barrier_policy(admission=admission, shards=shards)
    policy = resolve_policy(arg)
    probs = barrier_policy_problems(policy, admission=admission,
                                    shards=shards)
    if probs:
        raise ValueError(
            "invalid supervised remediation policy (the WF118 validator "
            "reports this pre-run): " + "; ".join(probs))
    return policy


def _parse_gate(gate: str) -> Optional[Tuple[str, str, float]]:
    """``"dispatch_ratio>=0.5"`` -> ("dispatch_ratio", ">=", 0.5); None for
    the empty gate; ValueError for anything else."""
    if not gate:
        return None
    for op in (">=", "<="):
        if op in gate:
            lhs, _, rhs = gate.partition(op)
            try:
                return (lhs.strip(), op, float(rhs))
            except ValueError:
                break
    raise ValueError(f"unparseable remediation gate {gate!r} "
                     f"(expected '<gauge>>=<value>' or '<gauge><=<value>')")


def action_problems(a: RemediationAction,
                    spec_names: Optional[List[str]] = None) -> List[str]:
    """Legality problems with one action — shared verbatim by the
    construction-time ValueError and the WF118 pre-run validator (the
    ``slo.spec_problems`` discipline: one source of truth, two surfaces)."""
    probs: List[str] = []
    if not a.name or not str(a.name).strip():
        probs.append("action has an empty name")
        return probs
    if a.actuator not in ACTUATORS:
        probs.append(f"action {a.name!r}: unknown actuator {a.actuator!r} "
                     f"(known: {', '.join(sorted(ACTUATORS))})")
    if not a.slo or not str(a.slo).strip():
        probs.append(f"action {a.name!r}: empty slo name")
    elif spec_names is not None and a.slo not in spec_names:
        probs.append(f"action {a.name!r}: references SLO {a.slo!r} which is "
                     f"not among the configured specs "
                     f"({', '.join(spec_names) or 'none'})")
    if not (a.factor > 0):
        probs.append(f"action {a.name!r}: factor must be > 0, got {a.factor}")
    if a.window < 1:
        probs.append(f"action {a.name!r}: window must be >= 1, got {a.window}")
    if a.max_applies < 1:
        probs.append(f"action {a.name!r}: max_applies must be >= 1, "
                     f"got {a.max_applies}")
    try:
        _parse_gate(a.gate)
    except ValueError as e:
        probs.append(f"action {a.name!r}: {e}")
    return probs


def policy_problems(p: RemediationPolicy,
                    spec_names: Optional[List[str]] = None) -> List[str]:
    probs: List[str] = []
    if not p.actions:
        probs.append("policy has no actions")
    seen = set()
    for a in p.actions:
        if a.name in seen:
            probs.append(f"duplicate action name {a.name!r}")
        seen.add(a.name)
        probs.extend(action_problems(a, spec_names))
    return probs


def _action_from_dict(d: dict) -> RemediationAction:
    if not isinstance(d, dict):
        raise ValueError(f"remediation action must be a dict, got {type(d).__name__}")
    allowed = {f for f in RemediationAction.__dataclass_fields__}
    unknown = set(d) - allowed
    if unknown:
        raise ValueError(f"unknown remediation action fields "
                         f"{sorted(unknown)} (allowed: {sorted(allowed)})")
    return RemediationAction(**d)


def resolve_policy(arg) -> Optional[RemediationPolicy]:
    """``remediation=`` / ``WF_REMEDIATION`` -> policy (None = off).

    Accepts: falsy / ``"0"`` (off), True / ``"1"`` (the default policy), a
    :class:`RemediationPolicy`, a list of actions/dicts, a dict with an
    ``"actions"`` key, a JSON file path, or inline JSON.  Malformed config
    is a loud ValueError — a policy that silently resolves to nothing would
    read as "remediation armed" while nothing watches the pager."""
    if arg is None or arg is False or arg == "" or arg == "0":
        return None
    if arg is True or arg == "1" or arg == 1:
        return default_policy()
    if isinstance(arg, RemediationPolicy):
        return arg
    if isinstance(arg, RemediationAction):
        return RemediationPolicy(actions=(arg,))
    if isinstance(arg, (list, tuple)):
        acts = tuple(a if isinstance(a, RemediationAction)
                     else _action_from_dict(a) for a in arg)
        return RemediationPolicy(actions=acts)
    if isinstance(arg, dict):
        if "actions" not in arg:
            raise ValueError("remediation dict must carry an 'actions' list")
        return resolve_policy(arg["actions"])
    if isinstance(arg, str):
        text = arg
        if os.path.exists(arg):
            with open(arg) as f:
                text = f.read()
        try:
            obj = json.loads(text)
        except ValueError:
            raise ValueError(
                f"WF_REMEDIATION / remediation= string {arg!r} is neither "
                f"'0'/'1', an existing JSON file path, nor inline JSON")
        return resolve_policy(obj)
    raise ValueError(f"cannot resolve remediation config from "
                     f"{type(arg).__name__}: {arg!r}")


# ------------------------------------------------------- live (reporter) mode

def _lookup_gauge(section, name: str) -> Optional[float]:
    """Max numeric value under key ``name`` anywhere inside a snapshot
    section — health gauges nest per device/stage and the gate cares about
    the worst edge (a single dispatch-bound stage names the fusion
    candidate), so shape-agnostic max is the right fold."""
    best: Optional[float] = None
    stack = [section]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            for k, v in node.items():
                if k == name and isinstance(v, (int, float)):
                    best = v if best is None else max(best, v)
                else:
                    stack.append(v)
        elif isinstance(node, (list, tuple)):
            stack.extend(node)
    return best


class RemediationEngine:  # wf-lint: single-writer[reporter, driver]
    """Live-mode policy evaluator — runs inside the Reporter tick.

    Single-writer[reporter]: :meth:`on_verdicts` is called only from the SLO
    engine's ``verdict_hook`` (Reporter thread; the final ``stop()`` emit
    runs after join, the SLOEngine discipline).  Actuator *callables* bound
    via :meth:`bind` must themselves be safe to invoke from this thread —
    ``AdmissionController.scale_rate`` takes the bucket lock,
    ``CapacityAutotuner.request_reclimb`` sets an Event the driver loop
    consumes at a batch boundary."""

    def __init__(self, policy: RemediationPolicy, *,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 max_actions: int = DEFAULT_MAX_ACTIONS,
                 clock: Callable[[], float] = time.monotonic):
        if policy is None or not isinstance(policy, RemediationPolicy):
            raise ValueError("RemediationEngine requires a RemediationPolicy "
                             f"(got {type(policy).__name__}) — resolve with "
                             "remediation.resolve_policy first")
        if cooldown_s < 0:
            raise ValueError(f"remediation cooldown_s must be >= 0, "
                             f"got {cooldown_s}")
        if max_actions < 1:
            raise ValueError(f"remediation max_actions must be >= 1, "
                             f"got {max_actions}")
        self.policy = policy
        self.cooldown_s = float(cooldown_s)
        self.max_actions = int(max_actions)
        self.clock = clock
        self._bindings: Dict[str, Callable] = {}
        self.applied = 0
        self.skipped = 0
        self._last_apply_t: Optional[float] = None
        self._ledger = deque(maxlen=64)
        self._per = {a.name: {"applies": 0, "prev_burn": None,
                              "stopped": False, "last_skip": None}
                     for a in policy.actions}

    # -- driver surface ----------------------------------------------------

    def bind(self, actuator: str, fn: Callable) -> None:
        """Bind an actuator callable ``fn(action) -> dict`` (details for the
        journal/ledger).  Drivers bind only what the run actually owns."""
        if actuator not in ACTUATORS:
            raise ValueError(f"unknown actuator {actuator!r}")
        self._bindings[actuator] = fn

    def bound(self) -> List[str]:
        return sorted(self._bindings)

    # -- reporter-tick surface --------------------------------------------

    def on_verdicts(self, snap: dict) -> None:
        """One SLO tick's verdicts in; zero or more actuations out.  Folds
        the ``remediation`` snapshot section in place (after acting, so the
        section reflects this tick's ledger)."""
        slos = snap.get("slo") or {}
        for a in self.policy.actions:
            st = slos.get(a.slo)
            # the section rows carry both the state string and its numeric
            # code (slo.py::_SLOState.row) — PAGE is code 2
            if not isinstance(st, dict) or int(st.get("code", 0)) < 2:
                continue
            burn = float(st.get("burn_fast", 0.0))
            self._consider(a, burn, snap)
        snap["remediation"] = self.section()

    def _consider(self, a: RemediationAction, burn: float, snap: dict) -> None:
        per = self._per[a.name]
        reason = None
        if per["stopped"]:
            reason = "damped"
        elif per["applies"] >= a.max_applies:
            reason = "action_budget"
        elif self.applied >= self.max_actions:
            reason = "run_budget"
        elif (self._last_apply_t is not None
              and self.clock() - self._last_apply_t < self.cooldown_s):
            reason = "cooldown"
        elif (per["prev_burn"] is not None
              and burn >= DAMP_RATIO * per["prev_burn"]):
            # fired before and the burn has not improved by >10% — the
            # actuator is not helping this incident; stop re-firing it
            # (the auto-reshard damping pattern)
            per["stopped"] = True
            reason = "damped"
        else:
            gate = _parse_gate(a.gate)
            if gate is not None:
                g, op, v = gate
                cur = _lookup_gauge(snap.get("health") or {}, g)
                if cur is None:
                    reason = "gate_unobserved"
                elif not (cur >= v if op == ">=" else cur <= v):
                    reason = "gate"
        if (reason is None and a.actuator not in self._bindings
                and a.actuator not in ADVISORY_GAUGES):
            reason = "unbound"
        if reason is not None:
            self._skip(a, reason, burn)
            return
        try:
            if a.actuator in self._bindings:
                details = self._bindings[a.actuator](a) or {}
            else:
                details = self._advisory(a, snap)
                if details is None:
                    # nothing observable to scale a recommendation from
                    self._skip(a, "unobserved", burn)
                    return
        except Exception as e:  # noqa: BLE001 — an actuator that throws must
            # not kill the tick, but must not die silently either
            self._skip(a, f"actuator_error:{type(e).__name__}", burn)
            return
        self.applied += 1
        per["applies"] += 1
        per["prev_burn"] = burn
        per["last_skip"] = None
        self._last_apply_t = self.clock()
        _state.bump("remediation_actions")
        rec = dict(action=a.name, actuator=a.actuator, slo=a.slo,
                   burn=round(burn, 3), applied=True, **details)
        self._ledger.append(rec)
        _journal.record("remediation_apply", **rec)

    def _advisory(self, a: RemediationAction, snap: dict) -> Optional[dict]:
        """Advisory actuation — geometry-baked setpoints are traced
        constants (mutating them mid-run would retrace every cached
        executable: WF109), so the 'actuation' is the recommendation
        itself: published under the ``ADVISORY_GAUGES`` control gauge and
        journaled for the next restart to consume.  None when the snapshot
        carries nothing observable to recommend from."""
        if a.actuator == "hot_capacity":
            cur = _lookup_gauge(snap.get("control") or {}, "hot_capacity")
            if cur is None:
                return None
            # factor < 1 scales the setpoint UP for capacity-style knobs
            rec = max(float(a.floor), float(math.ceil(cur / a.factor)))
        else:                               # widen_delay
            # the lateness histogram's own advice (event_time.summarize):
            # the smallest delay covering q of observed lateness
            rec = _lookup_gauge(snap, "recommend_delay_p99")
            if rec is None:
                return None
        _state.set_gauge(ADVISORY_GAUGES[a.actuator], float(rec))
        return {"recommended": float(rec), "advisory": True}

    def _skip(self, a: RemediationAction, reason: str, burn: float) -> None:
        self.skipped += 1
        _state.bump("remediation_skips")
        per = self._per[a.name]
        if per["last_skip"] == reason:
            return  # journal only reason TRANSITIONS — a paging SLO in
            # cooldown would otherwise spam one skip per tick
        per["last_skip"] = reason
        rec = dict(action=a.name, actuator=a.actuator, slo=a.slo,
                   burn=round(burn, 3), applied=False, reason=reason)
        self._ledger.append(rec)
        _journal.record("remediation_skip", **rec)

    # -- observability surface --------------------------------------------

    def section(self) -> dict:
        """The ``remediation`` snapshot section (and the incident bundle's
        ``remediation.json`` payload)."""
        return {"enabled": True, "applied": self.applied,
                "skipped": self.skipped, "bound": self.bound(),
                "actions": [a.name for a in self.policy.actions],
                "ledger": list(self._ledger)}


# --------------------------------------------------- deterministic (barrier)

class BarrierRemediation:
    """Barrier-mode evaluator for supervised drivers.

    Pure function of (policy, committed signals, own checkpointed state) —
    no wall clock, no thread: the owning driver calls :meth:`on_barrier`
    at every commit barrier with signals derived from committed state
    (PositionBucket counters, per-shard interval tuples), applies the
    returned decisions itself in barrier order, and checkpoints
    :meth:`state` beside the admission bucket so replay re-derives the
    identical action sequence.  Cooldown is counted in *barriers*
    (``cooldown_barriers = max(1, round(cooldown_s))`` — the documented
    deterministic proxy for the wall-clock cooldown)."""

    def __init__(self, policy: RemediationPolicy, *,
                 cooldown_barriers: int = 60,
                 max_actions: int = DEFAULT_MAX_ACTIONS):
        if policy is None or not isinstance(policy, RemediationPolicy):
            raise ValueError("BarrierRemediation requires a RemediationPolicy")
        if cooldown_barriers < 1:
            raise ValueError(f"cooldown_barriers must be >= 1, "
                             f"got {cooldown_barriers}")
        if max_actions < 1:
            raise ValueError(f"max_actions must be >= 1, got {max_actions}")
        self.policy = policy
        self.cooldown_barriers = int(cooldown_barriers)
        self.max_actions = int(max_actions)
        #: actions this evaluator may fire — only actuators with a
        #: deterministic barrier signal; the rest are WF118's problem
        self.actions = tuple(a for a in policy.actions
                             if a.actuator in BARRIER_SIGNALS)
        # all below: wf-lint: single-writer[driver]
        self.applied = 0
        self._cool = 0
        self._per = {a.name: {"win": 0, "applies": 0, "prev": None,
                              "stopped": False} for a in self.actions}

    # -- checkpointed state ------------------------------------------------

    def state(self) -> dict:
        """JSON-able decision state — stored under the admission snapshot's
        ``"remediation"`` key, so a checkpoint taken mid-incident replays
        the remaining actions at the same barriers."""
        return {"applied": self.applied, "cool": self._cool,
                "per": {k: dict(v) for k, v in self._per.items()}}

    def set_state(self, st: dict) -> None:
        if not isinstance(st, dict):
            return
        self.applied = int(st.get("applied", 0))
        self._cool = int(st.get("cool", 0))
        per = st.get("per") or {}
        for name, mine in self._per.items():
            got = per.get(name)
            if isinstance(got, dict):
                mine.update({"win": int(got.get("win", 0)),
                             "applies": int(got.get("applies", 0)),
                             "prev": got.get("prev"),
                             "stopped": bool(got.get("stopped", False))})

    # -- barrier surface ---------------------------------------------------

    def on_barrier(self, pos: int, signals: dict) -> List[dict]:
        """Evaluate one committed barrier.  ``signals`` maps barrier-signal
        names (``BARRIER_SIGNALS`` values) to this interval's deterministic
        measurements; a missing signal leaves its actions' windows frozen.
        Returns the decisions to apply, in declaration order — the caller
        actuates and journals them (``remediation_apply`` with ``pos=``)."""
        if self._cool > 0:
            self._cool -= 1
        fired: List[dict] = []
        for a in self.actions:
            sig = BARRIER_SIGNALS[a.actuator]
            if sig not in signals:
                continue
            v = float(signals[sig])
            per = self._per[a.name]
            per["win"] = per["win"] + 1 if v > a.target else 0
            if per["win"] < a.window:
                continue
            if (per["stopped"] or per["applies"] >= a.max_applies
                    or self.applied >= self.max_actions or self._cool > 0):
                continue
            if per["prev"] is not None and v >= DAMP_RATIO * per["prev"]:
                per["stopped"] = True  # fired before, signal not improving
                fired.append(dict(action=a.name, actuator=a.actuator,
                                  slo=a.slo, pos=int(pos), value=round(v, 4),
                                  applied=False, reason="damped"))
                continue
            self.applied += 1
            per["applies"] += 1
            per["prev"] = v
            per["win"] = 0
            self._cool = self.cooldown_barriers
            fired.append(dict(action=a.name, actuator=a.actuator, slo=a.slo,
                              pos=int(pos), value=round(v, 4), applied=True,
                              factor=a.factor, floor=a.floor))
        return fired
