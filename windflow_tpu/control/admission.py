"""Admission control — token-bucket rate limiting + load shedding at ingest.

The reference has no overload story beyond its bounded FastFlow rings (a full
ring blocks the producer); the TB window engine's only shedding is the
OLD-straggler drop behind the fired frontier (``wf/win_seqffat.hpp``). This
module makes overload a first-class, *measured* input: every source loop can
offer its batches to an :class:`AdmissionController` that either admits them
or sheds them per policy, with every decision counted
(``windflow_control_shed_*`` series) and journaled (``shed`` events).

Two bucket flavours share one duck interface (``tick()`` / ``try_take(n)`` /
``state()`` / ``set_state()``):

- :class:`TokenBucket` — wall-clock refill (``rate_tps`` tuples/second,
  ``burst`` cap). The live-driver form.
- :class:`PositionBucket` — refills a fixed quantum per *offered batch*.
  Deterministic: shed decisions become a pure function of stream position,
  which is what the supervised drivers need — checkpoint replay re-offers the
  same batches and must re-shed the same ones, so the bucket state is included
  in the supervisor's snapshot and restored with it.

Shed policies (batch granularity — tuple-level masking would cost a device
pass per batch on the admit path):

- ``drop_newest`` — the incoming batch is shed when tokens are insufficient
  (classic tail drop).
- ``drop_oldest_ts`` — up to ``hold_max`` batches are held back while the
  bucket refills; overflow sheds the *oldest held* batch (lowest ts, since
  sources emit in ts order) — the OLD-straggler stance: prefer fresh data,
  drop stale.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

from ..observability import journal as _journal
from . import _state


class TokenBucket:
    """Wall-clock token bucket: ``rate`` tokens/second, capacity ``burst``.
    ``clock`` is injectable (fake clocks in tests)."""

    # the wall-clock flavour is EXPLICITLY non-replayable — the supervised
    # drivers reject it (_supervised_admission); live drivers only
    def __init__(self, rate: float, burst: float,
                 clock=time.monotonic):      # wf-lint: allow[wall-clock]
        self.rate = float(rate)
        self.burst = float(burst)
        self.clock = clock
        self.tokens = float(burst)
        self._last: Optional[float] = None

    def tick(self) -> None:
        now = self.clock()
        if self._last is not None:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._last) * self.rate)
        self._last = now

    def try_take(self, n: float) -> bool:
        # a cost above the whole bucket could never be afforded — charge the
        # bucket's capacity instead of wedging (documented: size burst >= one
        # batch)
        n = min(float(n), self.burst)
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def get_rate(self) -> float:
        return self.rate

    def set_rate(self, value: float) -> None:
        self.rate = float(value)

    def state(self) -> dict:
        return {"tokens": self.tokens}

    def set_state(self, st: dict) -> None:
        self.tokens = float(st["tokens"])
        self._last = None                     # restart the refill epoch


class PositionBucket:
    """Deterministic bucket: ``refill_per_batch`` tokens added per ``tick()``
    (one tick per offered batch). No clock — replay-stable by construction."""

    def __init__(self, refill_per_batch: float, burst: float):
        self.refill = float(refill_per_batch)
        self.burst = float(burst)
        self.tokens = float(burst)

    def tick(self) -> None:
        self.tokens = min(self.burst, self.tokens + self.refill)

    def try_take(self, n: float) -> bool:
        n = min(float(n), self.burst)
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def get_rate(self) -> float:
        return self.refill

    def set_rate(self, value: float) -> None:
        self.refill = float(value)

    def state(self) -> dict:
        # NOTE deliberately tokens-only: the refill rate is config, not
        # accumulated state — remediation-scaled rates ride the snapshot's
        # "remediation" key instead (runtime/supervisor.py), so checkpoints
        # taken with remediation OFF stay byte-for-byte unchanged
        return {"tokens": self.tokens}

    def set_state(self, st: dict) -> None:
        self.tokens = float(st["tokens"])


class AdmissionController:
    """Offer/shed gate in front of a source loop.

    ``offer(batch)`` returns the list of batches to process *now* (empty when
    the offer was shed or held); ``drain()`` releases any held batches at EOS
    (the overload is over — a bounded ``hold_max`` tail is admitted rather
    than lost). Thread-safe: the PipeGraph threaded driver offers from several
    source threads through one shared controller.

    Cost model: one batch costs its *capacity* in tokens — the static shape,
    not the live-lane count, which would need a device sync per batch on the
    admit path. Document the distinction when sizing ``rate_tps``.
    """

    def __init__(self, bucket, policy: str = "drop_newest", *,
                 hold_max: int = 2, driver: str = "", lock=None):
        if policy not in ("drop_newest", "drop_oldest_ts"):
            raise ValueError(f"unknown shed policy {policy!r}")
        self.bucket = bucket
        self.policy = policy
        self.hold_max = max(0, int(hold_max))
        self.driver = driver
        self.held: deque = deque()           # wf-lint: guarded-by[_lock]
        self.admitted = 0                     # batches (per-controller, tests)
        self.shed = 0                         # batches
        #: tuple capacity the shed batches carried — the authoritative
        #: per-controller shed accounting (an empty offer() return is NOT a
        #: shed signal: drop_oldest_ts holds batches for a later drain).
        #: In-memory only — deliberately NOT in state(): the supervised
        #: checkpoint shape is pinned (test_remediation); callers that need
        #: restore-spanning totals track per-offer deltas of this counter
        #: (serving.tenants.TenantRegistry)
        self.shed_tuples = 0
        #: pass one shared lock to controllers sharing one bucket (a graph
        #: with several sources rate-limits total ingest through one bucket
        #: but needs a *per-source* holding cell, so held batches always
        #: re-enter their own source's queue)
        self._lock = lock if lock is not None else threading.Lock()

    # -- internals ----------------------------------------------------------

    def _cost(self, batch) -> int:
        return int(batch.capacity)

    def _shed(self, batch, pos, stream=None) -> None:
        cost = self._cost(batch)
        self.shed += 1
        self.shed_tuples += cost
        _state.bump("shed_batches")
        _state.bump("shed_tuples", cost)
        extra = {} if stream is None else {"stream": stream}
        _journal.record("shed", policy=self.policy, driver=self.driver,
                        pos=pos, tuples=cost, **extra)

    def _admit(self, batch) -> None:
        self.admitted += 1
        _state.bump("admitted_batches")
        _state.bump("admitted_tuples", self._cost(batch))

    # -- surface ------------------------------------------------------------

    def offer(self, batch, pos=None, stream=None) -> List:
        """Offer one source batch; returns the batches admitted right now.
        ``pos``/``stream`` are journal coordinates only (never part of the
        shed decision): the graph drivers pass the per-root offered position
        and the root index — the SAME coordinates causal tracing mints ids
        from, so ``wf_trace.py --report`` joins shed events to traced
        batches exactly."""
        with self._lock:
            self.bucket.tick()
            if self.policy == "drop_newest":
                if self.bucket.try_take(self._cost(batch)):
                    self._admit(batch)
                    return [batch]
                self._shed(batch, pos, stream)
                return []
            # drop_oldest_ts: FIFO holding cell, shed from the stale end
            self.held.append((batch, pos, stream))
            out = []
            while self.held and self.bucket.try_take(
                    self._cost(self.held[0][0])):
                b, _, _ = self.held.popleft()
                self._admit(b)
                out.append(b)
            while len(self.held) > self.hold_max:
                b, p, s = self.held.popleft()    # oldest ts first
                self._shed(b, p, s)
            return out

    def drain(self) -> List:
        """EOS: admit the bounded held tail (delayed, not shed)."""
        with self._lock:
            out = []
            while self.held:
                b, _, _ = self.held.popleft()
                self._admit(b)
                out.append(b)
            return out

    # -- remediation actuator surface ---------------------------------------

    def current_rate(self) -> float:
        with self._lock:
            return self.bucket.get_rate()

    def set_rate(self, value: float) -> None:
        """Restore/replay path: pin the bucket's refill rate outright (the
        remediation-scaled rate rides the supervisor snapshot's
        ``"remediation"`` key, not the bucket state)."""
        with self._lock:
            self.bucket.set_rate(value)
            _state.set_gauge("bucket_rate", float(value))

    def scale_rate(self, factor: float, floor: float = 1.0) -> dict:
        """The ``admission_rate`` remediation actuator: multiply the bucket's
        refill rate by ``factor`` (tighten: factor < 1), clamped at ``floor``.
        Takes the bucket lock, so a rate change is atomic w.r.t. a racing
        ``offer`` — held batches (drop_oldest_ts) are untouched; the next
        ``tick()`` simply refills at the new rate.  Returns the setpoint
        delta for the journal/ledger."""
        with self._lock:
            cur = float(self.bucket.get_rate())
            new = max(float(floor), cur * float(factor))
            self.bucket.set_rate(new)
            _state.set_gauge("bucket_rate", new)
            return {"rate": round(new, 3), "prev_rate": round(cur, 3)}

    # -- supervised snapshot/restore ---------------------------------------

    def state(self) -> dict:
        """Replay snapshot. Only the bucket: the supervised drivers restrict
        to ``drop_newest`` (no held data), so held batches never need to be
        serialized into a checkpoint."""
        with self._lock:
            return {"bucket": self.bucket.state(),
                    "admitted": self.admitted, "shed": self.shed}

    def set_state(self, st: dict) -> None:
        with self._lock:
            self.bucket.set_state(st["bucket"])
            self.admitted = int(st["admitted"])
            self.shed = int(st["shed"])
            self.held.clear()


def resolve_burst(cfg, base_capacity: int) -> float:
    """THE burst-sizing policy (default 4 base batches, floored at one batch
    so a single batch can always be afforded) — one definition shared by the
    live drivers and the supervised drivers' deterministic bucket."""
    return max(float(cfg.burst_tuples or 4 * base_capacity),
               float(base_capacity))


def bucket_from_config(cfg, base_capacity: int,
                       clock=time.monotonic):  # wf-lint: allow[wall-clock]
    """The bucket a ``ControlConfig`` asks for (None when admission is off or
    rate-unlimited). The wall-clock default only ever reaches the live
    drivers — the supervised path requires ``refill_per_batch`` and builds a
    clock-free :class:`PositionBucket`."""
    if cfg is None or not cfg.admission:
        return None
    burst = resolve_burst(cfg, base_capacity)
    if cfg.refill_per_batch is not None:
        _state.set_gauge("bucket_rate", float(cfg.refill_per_batch))
        return PositionBucket(cfg.refill_per_batch, burst)
    if cfg.rate_tps is not None:
        _state.set_gauge("bucket_rate", float(cfg.rate_tps))
        return TokenBucket(cfg.rate_tps, burst, clock=clock)
    return None                               # admission on, rate unlimited


def admission_from_config(cfg, base_capacity: int, *, driver: str = "",
                          clock=time.monotonic,  # wf-lint: allow[wall-clock]
                          ) -> Optional[AdmissionController]:
    """One controller over its own bucket (single-source drivers)."""
    bucket = bucket_from_config(cfg, base_capacity, clock=clock)
    if bucket is None:
        return None
    return AdmissionController(bucket, cfg.shed_policy,
                               hold_max=cfg.hold_max, driver=driver)


def admission_group(cfg, base_capacity: int, n: int, *, driver: str = "",
                    clock=time.monotonic,    # wf-lint: allow[wall-clock]
                    ) -> List[Optional[AdmissionController]]:
    """``n`` controllers sharing ONE bucket (and one lock): a multi-source
    graph rate-limits *total* ingest while each source keeps its own holding
    cell, so held batches always re-enter their own source's stream."""
    bucket = bucket_from_config(cfg, base_capacity, clock=clock)
    if bucket is None:
        return [None] * n
    lock = threading.Lock()
    return [AdmissionController(bucket, cfg.shed_policy,
                                hold_max=cfg.hold_max,
                                driver=f"{driver}[{i}]", lock=lock)
            for i in range(n)]
