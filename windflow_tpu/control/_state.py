"""Process-wide control-plane counters and gauges.

Same stance as ``runtime/faults.py``'s recovery counters: a flat dict behind a
lock, bumped from the actuation points (shed decisions, throttle waits,
capacity switches) and surfaced by ``observability.MetricsRegistry.snapshot``
under the ``"control"`` section and by ``to_prometheus`` as
``windflow_control_<name>_total`` (counters) / ``windflow_control_<name>``
(gauges). Kept in its own module so ``config``/``admission``/``governor``/
``autotune`` can import it without touching the package ``__init__``.
"""

from __future__ import annotations

import threading
from typing import Dict

from ..observability.names import CONTROL_COUNTERS

#: canonical counter names live in the observability registry so the static
#: linter can check every ``bump("...")`` call site against one source of truth
_COUNTER_NAMES = CONTROL_COUNTERS

_counters: Dict[str, float] = {k: 0 for k in _COUNTER_NAMES}
_gauges: Dict[str, float] = {}
_lock = threading.Lock()


def bump(name: str, n: float = 1) -> None:
    """Increment a process-wide control counter (monotonic; rendered as
    ``windflow_control_<name>_total``)."""
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def set_gauge(name: str, value: float) -> None:
    """Publish a control gauge (last-write-wins; e.g. ``chosen_capacity``)."""
    with _lock:
        _gauges[name] = value


def counters() -> Dict[str, float]:
    with _lock:
        return dict(_counters)


def gauges() -> Dict[str, float]:
    with _lock:
        return dict(_gauges)


def reset() -> None:
    """Zero everything (test isolation)."""
    with _lock:
        for k in list(_counters):
            _counters[k] = 0
        _gauges.clear()
