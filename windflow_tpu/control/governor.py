"""Backpressure governor — per-edge high/low watermarks over SPSC ring depths.

The threaded drivers' SPSC rings already provide *implicit* backpressure (a
full ring blocks the producer inside ``push``), but blocking there is
invisible: the producer wedges mid-push with no signal, no counter, and the
ring sits pegged at capacity. The governor makes backpressure *explicit and
observable*: the source loop calls :meth:`throttle` before each push; when any
watched edge's depth reaches its high watermark the source pauses — setting
``pause_event`` so a prefetch worker (``operators/source.py::
prefetch_to_device``) stops starting new H2D transfers too — until every edge
drains to its low watermark. Every throttle episode is counted
(``windflow_control_throttle_*``) and journaled.

Watermarks are fractions of each edge's ring capacity (defaults 0.75 / 0.25),
so per-edge capacities (the ``queue_capacity`` dict/callable on the threaded
drivers) automatically scale their thresholds.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

from ..observability import journal as _journal
from ..observability import tracing as _tracing
from . import _state


class BackpressureGovernor:
    """Throttles a source loop on downstream ring depth."""

    def __init__(self, high_watermark: float = 0.75,
                 low_watermark: float = 0.25, poll_s: float = 0.001,
                 clock=time.monotonic):
        self.high_watermark = float(high_watermark)
        self.low_watermark = float(low_watermark)
        self.poll_s = float(poll_s)
        self.clock = clock
        # actuator setpoints (PR 17): the watermark fractions this governor
        # throttles on, observable beside the remediation ledger so a
        # before/after delta is visible in the snapshot + Prometheus
        _state.set_gauge("governor_high_watermark", self.high_watermark)
        _state.set_gauge("governor_low_watermark", self.low_watermark)
        #: set while the governor is actively throttling — the prefetch
        #: pause hook (pass it as ``pause_event=`` to ``batches_prefetched``)
        self.pause_event = threading.Event()
        self.throttles = 0                    # episodes (per-governor, tests)
        # watch() registers every edge on the driver BEFORE the source/stage
        # threads start throttling; throttle() only iterates
        self._edges: List[Tuple[str, Callable[[], int], int, int]] = []  # wf-lint: single-writer[driver]
        self._stop = threading.Event()

    def watch(self, edge: str, size_fn: Callable[[], int],
              capacity: int) -> None:
        """Register one ring: ``size_fn`` probes its live depth."""
        hi = max(1, int(capacity * self.high_watermark))
        lo = min(max(0, int(capacity * self.low_watermark)), hi - 1)
        self._edges.append((edge, size_fn, hi, lo))

    def _over_high(self) -> Optional[Tuple[str, int, int]]:
        for edge, size_fn, hi, _lo in self._edges:
            try:
                d = int(size_fn())
            except Exception:                 # noqa: BLE001 — ring freed at EOS
                continue
            if d >= hi:
                return edge, d, hi
        return None

    def _all_low(self) -> bool:
        for _edge, size_fn, _hi, lo in self._edges:
            try:
                if int(size_fn()) > lo:
                    return False
            except Exception:                 # noqa: BLE001
                continue
        return True

    def throttle(self, heartbeat=None) -> float:
        """Called by the source loop before each push. Returns seconds spent
        throttled (0.0 on the fast path: one depth probe per edge).
        ``heartbeat`` (optional zero-arg callable) is invoked every poll so a
        stage watchdog can tell an intentional throttle wait from a hang."""
        over = self._over_high()
        if over is None or self._stop.is_set():
            return 0.0
        edge, depth, hi = over
        self.throttles += 1
        _state.bump("throttle_events")
        _journal.record("throttle", edge=edge, depth=depth, high=hi)
        # throttle episodes also land in the flight recorder (a span on the
        # "governor" pseudo-stage) so the Perfetto view shows exactly which
        # batches sat behind a throttled source — one None check when off
        stall = _tracing.stall(f"governor:{edge}")
        self.pause_event.set()
        t0 = self.clock()
        try:
            while not self._stop.is_set() and not self._all_low():
                if heartbeat is not None:
                    heartbeat()
                time.sleep(self.poll_s)
        finally:
            self.pause_event.clear()
            if stall is not None:
                stall.done()
        dt = self.clock() - t0
        _state.bump("throttle_seconds", dt)
        _journal.record("throttle_end", edge=edge, waited_s=round(dt, 6))
        return dt

    def stop(self) -> None:
        """Release any in-flight throttle wait (failure/teardown path: a dead
        consumer must not leave the source wedged in the governor)."""
        self._stop.set()
        self.pause_event.clear()


def governor_from_config(cfg, clock=time.monotonic,
                         ) -> Optional[BackpressureGovernor]:
    if cfg is None or not cfg.backpressure:
        return None
    return BackpressureGovernor(cfg.high_watermark, cfg.low_watermark,
                                cfg.throttle_poll_s, clock=clock)


def recommend_reshard(loads, assignment, *, hot_fraction: float = 0.6,
                      max_shards: int = 64, min_load: float = 1.0):
    """The governor's re-sharding planner — a PURE function from per-shard
    load signals to a :class:`~windflow_tpu.parallel.sharding.ReshardPlan`
    (or None).

    ``loads``: per-shard load, e.g. the sharded supervisor's committed
    ``interval_tuples`` (a pure function of stream position, so
    supervised replay re-derives the identical plan), or live queue depths
    for an external operator. ``assignment``: the current
    ``ShardAssignment`` (or a bare shard count). Doubling is recommended
    when the hottest shard carries more than ``hot_fraction`` of the TOTAL
    load — a scale-free signal (a max/mean ratio would grow with the shard
    count even on a perfectly balanced layout whenever active keys are
    fewer than shards): ``key % 2N`` splits every shard (the hot one
    included) in two without shuffling keys between survivors.
    Deterministic; never wall-clock."""
    vals = [float(v) for v in
            (loads.values() if isinstance(loads, dict) else loads)]
    if not vals:
        return None
    total = sum(vals)
    if total / len(vals) < float(min_load):
        return None                       # nothing measured yet
    if max(vals) < float(hot_fraction) * total:
        return None
    n = getattr(assignment, "num_shards", None)
    n = int(assignment) if n is None else int(n)
    if n * 2 > int(max_shards):
        return None
    from ..parallel.sharding import ReshardPlan
    return ReshardPlan(new_shards=n * 2)
