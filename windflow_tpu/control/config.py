"""Control-plane configuration — the ``control=`` argument, resolved.

Mirrors the ``monitoring=`` / ``faults=`` conventions of the other opt-in
subsystems: ``None`` consults the ``WF_CONTROL`` environment variable
(``''``/``'0'`` = off, ``'1'`` = defaults, inline JSON object or a path to a
JSON file = field overrides), ``False`` forces off, ``True`` = defaults, a
dict = field overrides, a :class:`ControlConfig` passes through. Off by
default: with control off every driver runs today's exact code path and no
controller state is created.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional, Union


@dataclasses.dataclass
class ControlConfig:
    """Resolved control-plane settings for one driver run.

    Three independent sub-systems, each with its own enable flag:

    - **autotune** — the capacity ladder + hill-climbing batch autotuner
      (``control/autotune.py``); honored by the ``Pipeline`` driver (the
      compiled-chain execution core the ladder actuates).
    - **backpressure** — per-edge SPSC high/low watermark governor
      (``control/governor.py``); honored by ``ThreadedPipeline`` and
      ``PipeGraph._run_threaded``.
    - **admission** — token-bucket rate limiting + load shedding at the
      ingest boundary (``control/admission.py``); honored by every driver
      (the supervised drivers require the deterministic ``refill_per_batch``
      bucket — see ``runtime/supervisor.py``).
    """

    # -- capacity autotuner -------------------------------------------------
    autotune: bool = True
    #: rungs above/below the base capacity (each a x2 / /2 step; down-rungs
    #: stop early when the base capacity stops dividing evenly)
    ladder_up: int = 2
    ladder_down: int = 2
    #: measurement window: batches per hill-climb decision
    decide_every: int = 8
    #: batches ignored right after a rung switch (compile + pipeline refill)
    settle_batches: int = 2
    #: a move must beat the previous rung's rate by this fraction to continue
    improve_threshold: float = 0.05
    #: compile every rung's executable up front (functional dry-run — states
    #: untouched) so switches on the hot path never pay a trace
    prewarm: bool = True
    #: JSON tuning-cache path; None = no persistence (cold start every run)
    cache_path: Optional[str] = None

    # -- backpressure governor ----------------------------------------------
    backpressure: bool = True
    #: watermarks as fractions of each edge's ring capacity
    high_watermark: float = 0.75
    low_watermark: float = 0.25
    throttle_poll_s: float = 0.001

    # -- admission control ---------------------------------------------------
    admission: bool = False
    #: token refill rate in tuples/second (wall-clock bucket); None with
    #: admission on = unlimited rate (no shedding, counting only)
    rate_tps: Optional[float] = None
    #: bucket capacity in tuples; None = 4x one base batch (resolved by the
    #: driver, which knows its batch capacity)
    burst_tuples: Optional[float] = None
    #: deterministic positional bucket: tokens refilled per OFFERED batch
    #: instead of per wall-clock second — the replay-stable form the
    #: supervised drivers require (shed decisions become a pure function of
    #: stream position, so checkpoint replay reproduces them exactly)
    refill_per_batch: Optional[float] = None
    #: "drop_newest" sheds the incoming batch when the bucket is empty;
    #: "drop_oldest_ts" holds up to ``hold_max`` batches and sheds the oldest
    #: (lowest-ts) held batch first — the Win_SeqFFAT OLD-straggler stance:
    #: prefer fresh data, drop stale
    shed_policy: str = "drop_newest"
    hold_max: int = 2

    def __post_init__(self):
        if self.shed_policy not in ("drop_newest", "drop_oldest_ts"):
            raise ValueError(
                f"unknown shed_policy {self.shed_policy!r} "
                f"(policies: drop_newest, drop_oldest_ts)")
        if not (0.0 <= self.low_watermark < self.high_watermark <= 1.0):
            raise ValueError(
                f"watermarks must satisfy 0 <= low < high <= 1, got "
                f"low={self.low_watermark} high={self.high_watermark}")

    @classmethod
    def resolve(cls, control: Union[None, bool, str, dict, "ControlConfig"],
                ) -> Optional["ControlConfig"]:
        """Normalize the user-facing ``control=`` argument; None when off."""
        if control is False:
            return None
        if isinstance(control, ControlConfig):
            return control
        if isinstance(control, dict):
            return cls(**control)
        if control is True:
            return cls()
        if isinstance(control, str):
            return cls._from_text(control)
        env = os.environ.get("WF_CONTROL", "")
        if env in ("", "0"):
            return None
        return cls._from_text(env)

    @classmethod
    def _from_text(cls, text: str) -> "ControlConfig":
        text = text.strip()
        if text in ("1", "true"):
            return cls()
        if text and text[0] == "{":
            return cls(**json.loads(text))
        with open(text) as f:                 # a path to a JSON config file
            return cls(**json.load(f))
