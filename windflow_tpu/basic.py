"""Basic definitions: enums and constants governing the whole runtime.

TPU-native counterpart of the reference's ``wf/basic.hpp`` (enums at
``wf/basic.hpp:86-132``, clocks at ``:54-74``, ``WinOperatorConfig`` at ``:154-184``).
The names and taxonomy are kept so a WindFlow user finds the same vocabulary; the
*meanings* are re-grounded in the micro-batch execution model:

- ``Mode.DEFAULT`` / ``Mode.DETERMINISTIC``: in the reference, DETERMINISTIC inserts
  ``Ordering_Node``s before replicas (``wf/pipegraph.hpp:1197-1199``). Here a compiled
  pipeline is already bit-deterministic (one XLA program, stable batch order);
  DETERMINISTIC additionally forces a stable sort by ``(ts, id)`` at merge points and
  shuffle boundaries (see ``parallel/ordering.py``).
- ``win_type_t.CB`` / ``TB``: count-based windows index by per-key arrival position,
  time-based by the tuple timestamp with a configurable ``triggering_delay`` (lateness),
  mirroring ``Triggerer_CB``/``Triggerer_TB`` (``wf/window.hpp:48-121``).
- ``opt_level_t``: the reference's LEVEL1/LEVEL2 remove collectors and flatten farms
  (``wf/win_farm.hpp:188-230``). Under XLA the analogue — fusing adjacent stages into
  one compiled program — is *always on* for chained operators; the enum is kept for API
  parity and influences how many separate programs a MultiPipe compiles to.
"""

from __future__ import annotations

import enum
import time


class Mode(enum.Enum):
    """Processing mode of the PipeGraph (``wf/basic.hpp:86``)."""

    DEFAULT = 0
    DETERMINISTIC = 1


class win_type_t(enum.Enum):
    """Window type: count-based, time-based (``wf/basic.hpp:89``), or
    session (data-dependent gap — an extension beyond the reference's fixed
    CB/TB lattice; the survey's operator taxonomy, PAPER.md §2.4, lists
    session windows as the third firing family every production stream
    system carries)."""

    CB = 0
    TB = 1
    SESSION = 2


class opt_level_t(enum.Enum):
    """Optimization level of complex window operators (``wf/basic.hpp:92``)."""

    LEVEL0 = 0
    LEVEL1 = 1
    LEVEL2 = 2


class routing_modes_t(enum.Enum):
    """How an operator's input is distributed to its replicas (``wf/basic.hpp:95``)."""

    NONE = 0
    FORWARD = 1
    KEYBY = 2
    COMPLEX = 3


class pattern_t(enum.Enum):
    """Taxonomy of windowed-operator patterns (``wf/basic.hpp:98``)."""

    SEQ_CPU = 0
    SEQ_GPU = 1
    WF_CPU = 2
    WF_GPU = 3
    KF_CPU = 4
    KF_GPU = 5
    KFF_CPU = 6
    KFF_GPU = 7
    PF_CPU = 8
    PF_GPU = 9
    WMR_CPU = 10
    WMR_GPU = 11


class win_event_t(enum.Enum):
    """Events raised by a triggerer for a tuple vs. a window (``wf/basic.hpp:126``)."""

    OLD = 0        # tuple precedes the window (dropped / already purged)
    IN = 1         # tuple belongs to the (still open) window
    DELAYED = 2    # TB only: tuple within the lateness allowance
    FIRED = 3      # window is complete
    BATCHED = 4    # window queued in the current device micro-batch


class ordering_mode_t(enum.Enum):
    """Ordering criterion used at shuffle/merge boundaries (``wf/basic.hpp:129``)."""

    ID = 0
    TS = 1
    TS_RENUMBERING = 2


class role_t(enum.Enum):
    """Role of a sequential window engine inside a composed pattern (``wf/basic.hpp:132``)."""

    SEQ = 0
    PLQ = 1
    WLQ = 2
    MAP = 3
    REDUCE = 4


# --- defaults (counterparts of wf/basic.hpp:76-84) -----------------------------------

#: default micro-batch capacity (tuples per batch) for device operators; the reference's
#: GPU operators default their batch_len similarly (``wf/builders_gpu.hpp:67-71``).
DEFAULT_BATCH_SIZE = 4096

#: default capacity (in fired windows) of one windowed-operator device batch
#: (counterpart of ``DEFAULT_BATCH_SIZE_TB``, ``wf/basic.hpp:80``).
DEFAULT_WIN_BATCH = 256

#: default number of distinct key slots for keyed state tables.
DEFAULT_MAX_KEYS = 1024


def current_time_usecs() -> int:
    """Monotonic clock in microseconds (``wf/basic.hpp:54-63``)."""
    return time.monotonic_ns() // 1_000


def current_time_nsecs() -> int:
    """Monotonic clock in nanoseconds (``wf/basic.hpp:65-74``)."""
    return time.monotonic_ns()


class WinOperatorConfig:
    """Window-distribution coordinate system of a sequential engine inside a composed
    pattern (counterpart of ``wf/basic.hpp:154-184``).

    ``(id_outer, n_outer, slide_outer)`` locate the engine inside the outer pattern
    (e.g. which Win_Farm replica it is); ``(id_inner, n_inner, slide_inner)`` locate it
    inside a nested pattern. ``Win_Seq`` uses these to derive its first global window id
    and initial tuple id (``wf/win_seq.hpp:328-332``).
    """

    __slots__ = ("id_outer", "n_outer", "slide_outer", "id_inner", "n_inner", "slide_inner")

    def __init__(self, id_outer=0, n_outer=1, slide_outer=0, id_inner=0, n_inner=1, slide_inner=0):
        self.id_outer = id_outer
        self.n_outer = n_outer
        self.slide_outer = slide_outer
        self.id_inner = id_inner
        self.n_inner = n_inner
        self.slide_inner = slide_inner

    def __repr__(self):
        return (f"WinOperatorConfig(outer=({self.id_outer}/{self.n_outer},{self.slide_outer}),"
                f" inner=({self.id_inner}/{self.n_inner},{self.slide_inner}))")
