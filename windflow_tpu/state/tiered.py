"""Tiered keyed state — the host-side controller of the two-tier layer.

``TieredTable`` supervises ONE device-resident table's cold tier: the
operator's ``apply`` packs eviction candidates into a bounded **outbox**
inside the compiled program (a deterministic pure function of watermark,
per-key last-access position, and occupancy — never wall clock), and this
controller moves them to the :class:`~windflow_tpu.state.host_store.
HostStore` with the PR 7 ordering-readback discipline: ``copy_to_host_async``
started right after a push, consumed at the next maintenance point — no
synchronous D2H on the hot path.

The spill protocol (each :meth:`maintain` call = one push boundary, so the
cadence is a pure function of stream position and supervised replay re-walks
it exactly):

1. a *count probe* (one async-copied scalar) discovers whether the outbox
   holds anything;
2. when it does, a *full copy* of the outbox columns (+ the watermark
   scalar) is started asynchronously;
3. the next maintain **applies** the copied prefix to the host store and
   **clears** exactly that prefix from the device outbox (one tiny jitted
   shift program) — entries leave the outbox only *after* they are in the
   store, so the union (device table ∪ outbox ∪ host store) always covers
   every key and the in-graph miss-resolution (which probes the outbox
   before falling back to the host ``io_callback``) can never lose a row.

``settle()`` forces the pipeline synchronously (supervised snapshots settle
first; a checkpoint therefore captures a consistent (state, store) pair and
a restore just discards whatever async copies were in flight — replay
re-derives them). Watermark compaction runs on a maintain-count cadence with
the async-copied watermark as its frontier hint: a stale hint only retains
rows longer, never retires one early, so compaction is semantics-free.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, Dict, List, Optional, Union

import numpy as np

from ..observability import journal as _journal
from .host_store import HostStore


@dataclasses.dataclass
class TierConfig:
    """Resolved tiered-state settings for one stateful operator.

    The ``tiered=`` kwarg / ``WF_STATE_TIERED`` env of the stateful
    operators (``StreamTableJoin``/``Distinct``/``SessionWindow``/``TopN``/
    ``IntervalJoin``) — off by default; the OFF path is byte-for-byte
    today's state pytrees and compiled programs."""

    #: device-resident hot-table slots (None = the operator's own
    #: ``num_slots``/``num_keys`` — today's geometry). ``WF_STATE_HOT_CAPACITY``
    #: overrides for every tiered operator (the WF_DISPATCH_K convention).
    hot_capacity: Optional[int] = None
    #: spill-outbox slots (None = auto: 4x the operator's per-batch
    #: admission bound, absorbing the 3-phase async drain latency)
    outbox: Optional[int] = None
    #: interval-join re-admission: max cold rows matched per probing lane
    #: per batch (bounded candidate growth; truncation is deterministic)
    readmit_rows: int = 8
    #: maintains between host-store watermark compactions
    compact_every: int = 64
    #: optional cold-tier TTL in event-time ticks for the JoinTable-backed
    #: operators (None = dimension-table semantics, rows live forever);
    #: a row is retired once its version ts < watermark - ttl
    ttl: Optional[int] = None

    def __post_init__(self):
        if self.hot_capacity is not None and int(self.hot_capacity) < 2:
            raise ValueError("tiered hot_capacity must be >= 2")
        if self.outbox is not None and int(self.outbox) < 1:
            raise ValueError("tiered outbox must be >= 1")
        if int(self.readmit_rows) < 1:
            raise ValueError("tiered readmit_rows must be >= 1")
        if int(self.compact_every) < 1:
            raise ValueError("tiered compact_every must be >= 1")

    @classmethod
    def resolve(cls, tiered: Union[None, bool, str, dict, "TierConfig"]
                ) -> Optional["TierConfig"]:
        """Normalize the user-facing ``tiered=`` argument; None when off.
        ``None`` consults ``WF_STATE_TIERED`` (``''``/``'0'`` = off,
        ``'1'`` = defaults, inline JSON object / JSON file path = field
        overrides); ``WF_STATE_HOT_CAPACITY`` overrides the hot-table size
        whenever tiering is on. Read at operator construction —
        geometry-binding (the WF_MONITORING_EVENT_TIME convention): the
        tier fields live in the state pytree, so toggling after
        construction needs a fresh operator."""
        cfg = None
        if isinstance(tiered, TierConfig):
            cfg = tiered
        elif isinstance(tiered, dict):
            cfg = cls(**tiered)
        elif tiered is None:
            env = os.environ.get("WF_STATE_TIERED", "")
            if env not in ("", "0", "false", "False"):
                if env == "1" or env.lower() == "true":
                    cfg = cls()
                elif env.lstrip().startswith("{"):
                    cfg = cls(**json.loads(env))
                elif os.path.exists(env):
                    with open(env, encoding="utf-8") as f:
                        cfg = cls(**json.load(f))
                else:
                    raise ValueError(
                        f"WF_STATE_TIERED={env!r} is neither a toggle, "
                        f"inline JSON, nor a readable JSON file")
        elif tiered:
            cfg = cls()
        if cfg is not None:
            hot = os.environ.get("WF_STATE_HOT_CAPACITY", "")
            if hot:
                cfg = dataclasses.replace(cfg, hot_capacity=int(hot))
        return cfg


def _np_tree(tree):
    import jax
    return jax.tree.map(lambda a: np.asarray(a), tree)


def _slice_tree(tree, n):
    import jax
    return jax.tree.map(lambda a: a[:n], tree)


# the controller's async-pipeline fields (_cnt/_full/_wm_hint/...) are
# confined to the ONE thread driving the owning chain (the pipeline driver,
# or a segment thread of the threaded driver); the JAX callback threads
# only ever touch the lock-guarded HostStore, never this controller —
# checked by the thread-role annotations on maintain/settle below
class TieredTable:  # wf-lint: single-writer[driver, stage]
    """Host-side supervisor of one device table's spill outbox + cold tier.

    ``col_keys`` name the outbox fields inside the operator's state dict
    (each may itself be a pytree); ``count_key`` the live-entry count
    scalar; ``apply_fn(store, n, cols)`` turns ``n`` copied outbox rows
    into host-store writes; ``compact_fn(store, wm)`` (optional) applies
    the operator's watermark retention bound."""

    def __init__(self, name: str, store: HostStore, count_key: str,
                 col_keys: List[str],
                 apply_fn: Callable[[HostStore, int, dict], int], *,
                 wm_key: Optional[str] = None,
                 compact_fn: Optional[Callable[[HostStore, int], int]] = None,
                 compact_every: int = 64):
        self.name = name
        self.store = store
        self.count_key = count_key
        self.col_keys = list(col_keys)
        self.apply_fn = apply_fn
        self.wm_key = wm_key
        self.compact_fn = compact_fn
        self.compact_every = max(1, int(compact_every))
        self._maintains = 0
        self._cnt = None       # async count probe (phase 1)
        self._full = None      # (count, cols, wm) async full copy (phase 2)
        self._wm_hint = None   # last copied watermark (compaction frontier)
        self._clear_fn = None  # jitted prefix-shift, built lazily
        self._journal_synced = {"state_spills": 0, "state_readmits": 0,
                                "state_compactions": 0}

    # -- jitted outbox clear ----------------------------------------------

    def _clear(self, state, c0: int):
        """Shift the first ``c0`` outbox entries out of ``state`` (they are
        in the host store now) — ONE cached executable, ``c0`` traced."""
        import jax
        import jax.numpy as jnp
        if self._clear_fn is None:
            count_key, col_keys = self.count_key, tuple(self.col_keys)

            def clear(st, c):
                out = dict(st)
                for k in col_keys:
                    out[k] = jax.tree.map(
                        lambda a: jnp.take(
                            a, jnp.arange(a.shape[0]) + c, axis=0,
                            mode="fill", fill_value=0), st[k])
                out[count_key] = jnp.maximum(st[count_key] - c, 0)
                return out
            self._clear_fn = jax.jit(clear)
        return self._clear_fn(state, np.int32(c0))

    # -- the per-push maintenance point -----------------------------------

    def maintain(self, state):  # wf-lint: thread-role[driver, stage]
        """One push boundary: advance the 3-phase async spill pipeline +
        the compaction cadence. Pure host work; the only device interaction
        is starting async copies and (when a prefix settled) one cached
        clear executable.

        OWNING-THREAD ONLY — statically checked: the ``thread-role``
        annotation restricts maintenance to the chain's driving thread
        (driver, or the owning segment thread); WF261 fails the gate if a
        reporter/watchdog/pool/JAX-callback thread ever reaches it."""
        self._maintains += 1
        if self._full is not None:
            cnt, cols, wm = self._full
            self._full = None
            c0 = int(np.asarray(cnt))
            if wm is not None:
                self._wm_hint = int(np.asarray(wm))
            if c0 > 0:
                # barrier BEFORE touching the store: the just-dispatched
                # push may still be executing, and its re-admission
                # callbacks read the store — applying rows their in-graph
                # state still holds in the outbox would let one probe see a
                # row in BOTH tiers (a duplicate match). Blocking on the
                # producing push's state settles it (ordered io_callbacks
                # complete with the program), exactly the PR 7 settling
                # discipline; the copies themselves stayed async.
                import jax
                jax.block_until_ready(state[self.count_key])
                host = {k: _slice_tree(_np_tree(v), c0)
                        for k, v in cols.items()}
                self.apply_fn(self.store, c0, host)
                state = self._clear(state, c0)
        elif self._cnt is not None:
            cnt, wm = self._cnt
            self._cnt = None
            if wm is not None:
                self._wm_hint = int(np.asarray(wm))
            if int(np.asarray(cnt)) > 0:
                self._full = self._start_copy(state, full=True)
        if self._full is None and self._cnt is None:
            self._cnt = self._start_copy(state, full=False)
        if (self.compact_fn is not None and self._wm_hint is not None
                and self._maintains % self.compact_every == 0):
            self.compact_fn(self.store, self._wm_hint)
        self._journal_deltas()
        return state

    def _start_copy(self, state, full: bool):
        cnt = state[self.count_key]
        wm = state[self.wm_key] if self.wm_key is not None else None
        for a in ([cnt] + ([wm] if wm is not None else [])):
            if hasattr(a, "copy_to_host_async"):
                a.copy_to_host_async()
        if not full:
            return (cnt, wm)
        import jax
        cols = {k: state[k] for k in self.col_keys}
        for leaf in jax.tree.leaves(cols):
            if hasattr(leaf, "copy_to_host_async"):
                leaf.copy_to_host_async()
        return (cnt, cols, wm)

    def settle(self, state):  # wf-lint: thread-role[driver, stage]
        """Synchronously drain the outbox into the host store (one blocking
        readback) and drop the async pipeline — the pre-snapshot barrier:
        after settle, (state, store) is a consistent pair and nothing is in
        flight.  Owning-thread only (the maintain contract, statically
        checked via the thread-role annotation)."""
        self._cnt = None
        self._full = None
        c0 = int(np.asarray(state[self.count_key]))
        if self.wm_key is not None:
            self._wm_hint = int(np.asarray(state[self.wm_key]))
        if c0 > 0:
            host = {k: _slice_tree(_np_tree(state[k]), c0)
                    for k in self.col_keys}
            self.apply_fn(self.store, c0, host)
            state = self._clear(state, c0)
        self._journal_deltas()
        return state

    def discard_inflight(self) -> None:
        """Restore path: drop async copies from the failed attempt — the
        restored state still holds the entries in its outbox, so replay
        re-derives the spill."""
        self._cnt = None
        self._full = None

    # -- durability / telemetry -------------------------------------------

    def manifest(self) -> Dict[str, np.ndarray]:
        return self.store.manifest()

    def restore(self, manifest: Dict[str, np.ndarray]) -> None:
        self.discard_inflight()
        self.store.restore(manifest)
        self._journal_synced = dict(self.store.counters())

    def counters(self) -> dict:
        return self.store.counters()

    def stats(self) -> dict:
        """The ``tier`` section of the operator's event-time snapshot row:
        cold-tier size + movement counters (host side; the device-side
        outbox depth/occupancy ride the operator's own section)."""
        out = {"cold_keys": self.store.key_count(),
               "cold_rows": len(self.store)}
        out.update(self.store.counters())
        return out

    def _journal_deltas(self) -> None:
        """Emit ``spill``/``readmit`` journal events for counter movement
        since the last maintenance point.  Runs only under maintain/settle
        (whose thread-role annotations keep the callback threads out — so
        the JAX callback threads never touch the journal)."""
        if _journal.get_active() is None:
            return
        cur = self.store.counters()
        for kind, event in (("state_spills", "spill"),
                            ("state_readmits", "readmit")):
            delta = cur[kind] - self._journal_synced[kind]
            if delta > 0:
                _journal.record(event, table=self.name, n=delta,
                                total=cur[kind])
        self._journal_synced.update(
            {k: cur[k] for k in ("state_spills", "state_readmits")})
        # compactions are quieter: counted, not journaled per event
        self._journal_synced["state_compactions"] = cur["state_compactions"]


# ------------------------------------------------- per-table-shape runtimes


class JoinTableTier:
    """Cold tier + controller + host callback for one versioned JoinTable
    (``ops/lookup.py`` ``join_table_*`` — StreamTableJoin and Distinct).
    Row schema: the table's value columns + the ``(ver, vid, vseq)`` LWW
    version triplet (so cross-tier last-writer-wins is exactly the device
    table's never-roll-back rule)."""

    def __init__(self, name: str, val_spec, cfg: TierConfig):
        import jax
        self.cfg = cfg
        self._leaves = jax.tree.leaves(val_spec)
        cols = {f"v{i}": np.dtype(getattr(leaf, "dtype", np.int32))
                for i, leaf in enumerate(self._leaves)}
        self.store = HostStore(name, cols, unique=True)

        def apply_fn(store, n, host):
            import jax as _jax
            leaves = _jax.tree.leaves(host["oval"])
            return store.upsert(
                host["okey"], host["over"], host["ovid"], host["ovseq"],
                {f"v{i}": leaf for i, leaf in enumerate(leaves)})

        compact_fn = None
        if cfg.ttl is not None:
            ttl = int(cfg.ttl)

            def compact_fn(store, wm):     # noqa: F811 — the optional hook
                return store.compact_below("m0", wm - ttl)

        self.controller = TieredTable(
            name, self.store, "ocnt",
            ["okey", "oval", "over", "ovid", "ovseq"],
            apply_fn, wm_key="wm", compact_fn=compact_fn,
            compact_every=cfg.compact_every)

    def lookup_cb(self, keys, want):
        """The ordered-``io_callback`` target: probe the cold tier for the
        wanted keys. Zero-mask calls are host no-ops (the ``warm()``
        contract)."""
        found, meta, cols = self.store.lookup(keys, want)
        out = [found, meta[:, 0].astype(np.int32),
               meta[:, 1].astype(np.int32), meta[:, 2].astype(np.int32)]
        for i, leaf in enumerate(self._leaves):
            out.append(cols[f"v{i}"].astype(
                np.dtype(getattr(leaf, "dtype", np.int32))))
        return tuple(out)


class ArchiveTier:
    """Cold tier + controller for ONE side of an interval-join archive — a
    MULTIMAP: every spilled row (an archived tuple the ring overwrote while
    still inside its match window) is retained until the watermark frontier
    retires it. Re-admission is read-only (``fetch_multi``): cold rows are
    matched as extra candidates and stay probeable by later arrivals —
    removal would lose pairs, duplication is impossible because a row lives
    in exactly one tier (archive XOR outbox XOR here)."""

    def __init__(self, name: str, payload_spec, cfg: TierConfig, side: str,
                 compact_bound):
        import jax
        self.cfg = cfg
        self.side = side
        self._leaves = jax.tree.leaves(payload_spec)
        cols = {"ts": np.int32, "id": np.int32}
        shapes = {"ts": (), "id": ()}
        for i, leaf in enumerate(self._leaves):
            cols[f"p{i}"] = np.dtype(getattr(leaf, "dtype", np.int32))
            shapes[f"p{i}"] = tuple(getattr(leaf, "shape", ()))
        self.store = HostStore(f"{name}.{side}", cols, shapes, unique=False)

        def apply_fn(store, n, host):
            import jax as _jax
            leaves = _jax.tree.leaves(host[f"{side}opay"])
            rows = {"ts": host[f"{side}ots"], "id": host[f"{side}oid"]}
            rows.update({f"p{i}": leaf for i, leaf in enumerate(leaves)})
            z = np.zeros(n, np.int64)
            return store.append(host[f"{side}okey"], z, z, z, rows)

        def compact_fn(store, wm):
            return store.compact_below("ts", compact_bound(wm))

        self.controller = TieredTable(
            f"{name}.{side}", self.store, f"{side}ocnt",
            [f"{side}okey", f"{side}ots", f"{side}oid", f"{side}opay"],
            apply_fn, wm_key="wm", compact_fn=compact_fn,
            compact_every=cfg.compact_every)

    def fetch_cb(self, keys, want):
        """Ordered-``io_callback`` target: up to ``readmit_rows`` cold rows
        per probing lane's key — ``(mask [C, M], ts, id, *pay leaves)``."""
        mask, _meta, cols = self.store.fetch_multi(keys, want,
                                                   self.cfg.readmit_rows)
        out = [mask, cols["ts"].astype(np.int32),
               cols["id"].astype(np.int32)]
        for i, leaf in enumerate(self._leaves):
            out.append(cols[f"p{i}"].astype(
                np.dtype(getattr(leaf, "dtype", np.int32))))
        return tuple(out)


# --------------------------------------- in-graph slot-directory primitives
#
# The session/top-N tables are DIRECT-indexed (the tuple key IS the slot);
# tiering them needs a key -> hot-slot directory in front of the existing
# table math. These primitives are the directory: pure jnp, fixed shapes,
# the same deterministic cumsum fresh-slot discipline as the JoinTable.

_KEY_SENTINEL = -(1 << 31)


def slot_lookup(hkey, hused, keys, ok):
    """``(hit [R], slot [R])`` of each wanted key in the hot directory."""
    import jax.numpy as jnp
    tk = jnp.where(hused, hkey, _KEY_SENTINEL)
    eq = keys[:, None] == tk[None, :]
    hit = jnp.any(eq, axis=1) & ok & (keys != _KEY_SENTINEL)
    return hit, jnp.argmax(eq, axis=1)


def slot_alloc(hused, adm):
    """Deterministic fresh slots: the r-th admitted lane claims the r-th
    free slot (ascending). ``(got [R], slot [R])``."""
    import jax.numpy as jnp
    rank = jnp.cumsum(adm.astype(jnp.int32)) - 1
    free = ~hused
    free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
    oh = free[None, :] & (free_rank[None, :] == rank[:, None])
    got = jnp.any(oh, axis=1) & adm
    return got, jnp.argmax(oh, axis=1)


def outbox_find_last(okey, ocnt, keys, need):
    """Newest outbox entry per wanted key: ``(found [R], idx [R])``."""
    import jax.numpy as jnp
    S = okey.shape[0]
    olive = jnp.arange(S, dtype=jnp.int32) < ocnt
    eq = (keys[:, None] == okey[None, :]) & olive[None, :]
    idx = jnp.max(jnp.where(eq, jnp.arange(S, dtype=jnp.int32)[None, :], -1),
                  axis=1)
    return need & (idx >= 0), jnp.maximum(idx, 0)


def slot_directory_resolve(state, keys, ok, lookup_cb, host_shapes,
                           admit_write):
    """Generic key -> hot-slot resolution for a direct-indexed table:
    touch hot hits, then for missing keys search the spill outbox (newest
    entry), then the host store (ONE ordered ``io_callback``), and admit
    EVERY missing first-occurrence key — readmitted with its cold row,
    or fresh — through the deterministic cumsum fresh-slot discipline.
    ``admit_write(out, widx, got, in_ob, oidx, host_res)`` writes the
    operator's own columns for the admitted slots. Returns ``(state,
    slot [R], live [R])`` — ``live`` excludes lanes whose key could not
    get a slot (hot directory saturated; the caller counts those as
    overflow drops through ``count_drops``)."""
    import jax.numpy as jnp
    from jax.experimental import io_callback
    from ..ops.segment import segment_rank
    H = state["hkey"].shape[0]
    keys = keys.astype(jnp.int32)
    ok = ok.astype(jnp.bool_) & (keys != _KEY_SENTINEL)
    tick = state["tick"]
    hit, slot = slot_lookup(state["hkey"], state["hused"], keys, ok)
    lap = state["lap"].at[jnp.where(hit, slot, H)].set(tick, mode="drop")
    need = ok & ~hit
    in_ob, oidx = outbox_find_last(state["okey"], state["ocnt"], keys, need)
    need_host = need & ~in_ob
    shapes = (host_shapes(keys.shape[0]) if callable(host_shapes)
              else host_shapes)
    host_res = io_callback(lookup_cb, shapes, keys, need_host,
                           ordered=True)
    host_found = host_res[0] & need_host
    adm = need & (segment_rank(keys, need) == 0)
    got, snew = slot_alloc(state["hused"], adm)
    widx = jnp.where(got, snew, H)
    out = dict(state)
    out["hkey"] = state["hkey"].at[widx].set(keys, mode="drop")
    out["hused"] = state["hused"].at[widx].set(True, mode="drop")
    out["lap"] = lap.at[widx].set(tick, mode="drop")
    out = admit_write(out, widx, got, in_ob, oidx, host_res)
    out["readmits"] = state["readmits"] + jnp.sum(
        (got & (in_ob | host_found)).astype(jnp.int32))
    hit2, slot2 = slot_lookup(out["hkey"], out["hused"], keys, ok)
    return out, slot2, ok & hit2


def slot_directory_evict(state, hot_target, evictable, discardable,
                         pack_write):
    """Generic pressure eviction over a hot directory: free the coldest
    ``used - hot_target`` evictable slots. Rows with nothing worth
    remembering (``discardable``) are freed outright; the rest pack into
    the spill outbox (``okey``/``otick`` here, the operator's columns via
    ``pack_write(out, opos, perm, spill)``), bounded by outbox space —
    a full outbox simply defers those evictions. Pure function of
    (occupancy, last-access) — the deterministic-policy contract — and
    closes the batch by advancing ``tick``."""
    import jax.numpy as jnp
    imax = jnp.iinfo(jnp.int32).max
    H = state["hkey"].shape[0]
    S = state["okey"].shape[0]
    used = state["hused"]
    used_n = jnp.sum(used.astype(jnp.int32))
    need = jnp.maximum(used_n - jnp.asarray(int(hot_target), jnp.int32), 0)
    cand = used & evictable
    sortkey = jnp.where(cand, state["lap"], imax)
    perm = jnp.lexsort((jnp.arange(H, dtype=jnp.int32), sortkey))
    r = jnp.arange(H, dtype=jnp.int32)
    sel = (r < need) & jnp.take(cand, perm)
    disc = jnp.take(discardable, perm)
    spill = sel & ~disc
    srank = jnp.cumsum(spill.astype(jnp.int32)) - 1
    fits = spill & (state["ocnt"] + srank < S)
    evict = sel & (disc | fits)
    opos = jnp.where(fits, state["ocnt"] + srank, S)
    out = dict(state)
    out["okey"] = state["okey"].at[opos].set(jnp.take(state["hkey"], perm),
                                             mode="drop")
    out["otick"] = state["otick"].at[opos].set(state["tick"], mode="drop")
    out = pack_write(out, opos, perm, fits)
    cleared = jnp.where(evict, perm, H)
    out["hused"] = used.at[cleared].set(False, mode="drop")
    out["hkey"] = out["hkey"].at[cleared].set(_KEY_SENTINEL, mode="drop")
    n = jnp.sum(fits.astype(jnp.int32))
    out["ocnt"] = state["ocnt"] + n
    out["spills"] = state["spills"] + n
    out["tick"] = state["tick"] + 1
    return out


def slot_directory_init(hot: int, outbox: int, extra_outbox_cols):
    """The directory + outbox state fields shared by every slot-directory
    tier (``extra_outbox_cols``: name -> zero array factory over [S])."""
    import jax.numpy as jnp
    H, S = int(hot), int(outbox)
    out = {
        "hkey": jnp.full((H,), _KEY_SENTINEL, jnp.int32),
        "hused": jnp.zeros((H,), jnp.bool_),
        "lap": jnp.zeros((H,), jnp.int32),
        "tick": jnp.asarray(0, jnp.int32),
        "okey": jnp.full((S,), _KEY_SENTINEL, jnp.int32),
        "otick": jnp.zeros((S,), jnp.int32),
        "ocnt": jnp.asarray(0, jnp.int32),
        "spills": jnp.asarray(0, jnp.int32),
        "readmits": jnp.asarray(0, jnp.int32),
    }
    for name, factory in extra_outbox_cols.items():
        out[name] = factory(S)
    return out


def slot_directory_stats(state) -> dict:
    """Device-side tier numbers of a slot directory (snapshot time only)."""
    H = int(state["hkey"].shape[0])
    S = int(state["okey"].shape[0])
    used = int(np.asarray(state["hused"]).sum())
    return {
        "hot_slots": H,
        "hot_used": used,
        "hot_pct": round(100.0 * used / H, 2),
        "outbox_slots": S,
        "outbox_depth": int(np.asarray(state["ocnt"])),
        "state_spills": int(np.asarray(state["spills"])),
        "state_readmits": int(np.asarray(state["readmits"])),
    }


class SlotTableTier:
    """Cold tier + controller for a direct-indexed keyed table behind a
    slot directory (SessionWindow floors, TopN leaderboards). Row schema =
    ``cols`` (name -> (dtype, trailing shape)); LWW meta is the spill tick
    (chronological — a later spill of the same key always wins)."""

    def __init__(self, name: str, cols, cfg: TierConfig, *,
                 count_key: str, col_keys, state_to_store,
                 compact_col: Optional[str] = None,
                 compact_bound=None, wm_key: Optional[str] = "wm"):
        self.cfg = cfg
        self._cols = {k: np.dtype(d) for k, (d, _s) in cols.items()}
        self._shapes = {k: tuple(s) for k, (_d, s) in cols.items()}
        self.store = HostStore(name, self._cols, self._shapes, unique=True)
        self._state_to_store = state_to_store
        compact_fn = None
        if compact_col is not None and compact_bound is not None:
            def compact_fn(store, wm):  # noqa: F811 — optional hook:
                # retire rows the operator's retention arithmetic proves
                # unreachable (the fired_hi_tb family; a stale wm hint
                # only RETAINS longer, never retires early)
                return store.compact_below(compact_col, compact_bound(wm))
        self.controller = TieredTable(
            name, self.store, count_key, list(col_keys),
            self._apply, wm_key=wm_key, compact_fn=compact_fn,
            compact_every=cfg.compact_every)

    def _apply(self, store, n, host):
        keys, tick, cols = self._state_to_store(n, host)
        return store.upsert(keys, tick, np.zeros(n, np.int64),
                            np.zeros(n, np.int64), cols)

    def lookup_cb(self, keys, want):
        found, _meta, cols = self.store.lookup(keys, want)
        return (found,) + tuple(
            cols[k].astype(self._cols[k]) for k in sorted(self._cols))

