"""Tiered keyed state — million-key tables over fixed-capacity HBM tables.

The two-tier state layer of ROADMAP item 3: every stateful operator keeps
its hot set device-resident at today's geometry while cold keys live in a
host-side :class:`HostStore`, moved by the :class:`TieredTable` controller
with async spills (``copy_to_host_async``), probe-miss re-admission
(ordered ``io_callback``), and watermark compaction. Off by default behind
the ``tiered=`` kwarg / ``WF_STATE_TIERED`` env (the ``kwarg=``/``WF_*``
convention); the OFF path is byte-for-byte today's programs.

See ``docs/ARCHITECTURE.md`` §18 for the protocol and determinism contract.
"""

from .host_store import HostStore
from .tiered import TierConfig, TieredTable

__all__ = ["HostStore", "TierConfig", "TieredTable"]
