"""Cold-tier keyed store — the host side of the two-tier state layer.

The HBM tables of the stateful operators (``ops/lookup.py`` JoinTable,
session open-table, top-N leaderboards, interval-join archives) are
fixed-capacity: production key cardinalities are millions, the tables are
thousands. The :class:`HostStore` is where cold keys live between touches —
plain numpy dict-of-arrays (the portable-primitive stance of
arXiv:2603.18695: the store is generic over *columns*, never over operator
types), touched only at the edges of the device program:

- **spill in** (``upsert``/``append``): applied by the
  :class:`~windflow_tpu.state.tiered.TieredTable` settle point from the
  async-copied device outbox — never on the hot path;
- **re-admission out** (``lookup``/``fetch_multi``): called from the
  operators' ordered ``io_callback`` when a device probe misses all
  device-resident tiers;
- **watermark compaction** (``compact_below``): rows whose entire eligible
  probe window is behind the frontier are retired (the ``fired_hi_tb``
  arithmetic family — each operator supplies its own retention bound).

Two shapes:

- ``unique=True`` (keyed tables): one row per key, last-writer-wins by the
  lexicographic 3-tuple meta ``(m0, m1, m2)`` — the JoinTable's
  ``(ver, vid, vseq)`` version triplet, so a stale spill can never roll a
  newer cold row back (the same never-roll-back rule the device table
  enforces).
- ``unique=False`` (interval-join archives): a multimap — every appended row
  is retained until compaction retires it; ``fetch_multi`` returns up to R
  rows per key *without* removing them (a row lives in exactly one tier:
  device archive XOR device outbox XOR here — matched rows must stay
  probeable by later arrivals).

Everything is guarded by one lock (re-admission callbacks run on JAX's
callback threads while the driver thread settles spills — the static
concurrency lint infers exactly this split: the ``*_cb`` targets carry the
``jax-callback`` role, maintain/settle the ``driver``/``stage`` roles, and
WF260 demands this lock around every field both sides touch), and the whole
store round-trips through :meth:`manifest`/:meth:`restore` as a dict of
numpy arrays — it rides the existing checkpoint/exactly-once machinery as
just more arrays, with per-array checksums for free.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

#: initial row capacity; grows geometrically
_INIT_CAP = 256
#: manifest schema version (bumped if the layout ever changes)
_MANIFEST_VERSION = 1


class HostStore:
    """Growable host-memory column store keyed by int32 join keys."""

    def __init__(self, name: str, cols: Dict[str, np.dtype],
                 col_shapes: Optional[Dict[str, tuple]] = None,
                 unique: bool = True):
        self.name = name
        self.unique = bool(unique)
        self._dtypes = {k: np.dtype(v) for k, v in cols.items()}
        self._shapes = {k: tuple(col_shapes.get(k, ()))
                        if col_shapes else () for k in cols}
        self._lock = threading.RLock()
        # monotonically appended rows; holes left by compaction/overwrite
        # are reclaimed by _rebuild when they dominate
        self._cap = _INIT_CAP
        self._n = 0
        self._key = np.zeros(self._cap, np.int64)
        self._live = np.zeros(self._cap, np.bool_)
        self._meta = np.zeros((self._cap, 3), np.int64)   # (m0, m1, m2) LWW
        self._cols = {k: np.zeros((self._cap,) + self._shapes[k], dt)
                      for k, dt in self._dtypes.items()}
        self._index: Dict[int, object] = {}   # key -> row | list[row]
        # counters (host side of the tier telemetry)
        self.spilled_rows = 0        # rows applied from device outboxes
        self.readmitted_rows = 0     # rows handed back to the device tier
        self.compacted_rows = 0      # rows retired by watermark compaction

    # -- internals --------------------------------------------------------

    def _grow(self, need: int) -> None:
        while self._cap < need:
            self._cap *= 2
        self._key = np.resize(self._key, self._cap)
        self._live = np.resize(self._live, self._cap)
        self._meta = np.resize(self._meta, (self._cap, 3))
        for k in self._cols:
            self._cols[k] = np.resize(self._cols[k],
                                      (self._cap,) + self._shapes[k])

    def _append_row(self, key: int, meta, row: dict) -> int:
        if self._n >= self._cap:
            self._grow(self._n + 1)
        i = self._n
        self._n += 1
        self._key[i] = key
        self._live[i] = True
        self._meta[i] = meta
        for k, v in row.items():
            self._cols[k][i] = v
        return i

    def _rebuild(self) -> None:
        """Compact away dead rows (holes) when they dominate the storage."""
        live_idx = np.flatnonzero(self._live[:self._n])
        n = len(live_idx)
        self._key[:n] = self._key[live_idx]
        self._meta[:n] = self._meta[live_idx]
        for k in self._cols:
            self._cols[k][:n] = self._cols[k][live_idx]
        self._live[:self._n] = False
        self._live[:n] = True
        self._n = n
        self._reindex()

    def _reindex(self) -> None:
        self._index.clear()
        for i in np.flatnonzero(self._live[:self._n]):
            i = int(i)
            k = int(self._key[i])
            if self.unique:
                self._index[k] = i
            else:
                self._index.setdefault(k, []).append(i)

    # -- write side (settle point / spill application) --------------------

    def upsert(self, keys, m0, m1, m2, cols: dict, ok=None) -> int:
        """Apply spilled rows, LWW per key by ``(m0, m1, m2)`` (unique mode).
        Returns the number of rows applied (newer-or-new)."""
        assert self.unique, "upsert is the unique-mode write; use append"
        keys = np.asarray(keys)
        ok = np.ones(len(keys), bool) if ok is None else np.asarray(ok)
        m0, m1, m2 = np.asarray(m0), np.asarray(m1), np.asarray(m2)
        cols = {c: np.asarray(v) for c, v in cols.items()}
        applied = 0
        with self._lock:
            for i in np.flatnonzero(ok):
                i = int(i)
                k = int(keys[i])
                meta = (int(m0[i]), int(m1[i]), int(m2[i]))
                row = {c: v[i] for c, v in cols.items()}
                j = self._index.get(k)
                if j is None:
                    self._index[k] = self._append_row(k, meta, row)
                    applied += 1
                elif tuple(self._meta[j]) <= meta:
                    self._meta[j] = meta
                    for c, v in row.items():
                        self._cols[c][j] = v
                    applied += 1
            self.spilled_rows += applied
        return applied

    def append(self, keys, m0, m1, m2, cols: dict, ok=None) -> int:
        """Append rows unconditionally (multimap mode)."""
        assert not self.unique, "append is the multimap write; use upsert"
        keys = np.asarray(keys)
        ok = np.ones(len(keys), bool) if ok is None else np.asarray(ok)
        m0, m1, m2 = np.asarray(m0), np.asarray(m1), np.asarray(m2)
        cols = {c: np.asarray(v) for c, v in cols.items()}
        n = 0
        with self._lock:
            for i in np.flatnonzero(ok):
                i = int(i)
                k = int(keys[i])
                meta = (int(m0[i]), int(m1[i]), int(m2[i]))
                row = {c: v[i] for c, v in cols.items()}
                self._index.setdefault(k, []).append(
                    self._append_row(k, meta, row))
                n += 1
            self.spilled_rows += n
        return n

    # -- read side (re-admission callbacks) -------------------------------

    def lookup(self, keys, want) -> Tuple[np.ndarray, np.ndarray, dict]:
        """Unique-mode probe: ``(found [R] bool, meta [R, 3] int64,
        cols {name: [R, ...]})`` — zeros where not found. Rows stay in the
        store (supersession is by LWW spill, never by removal: a re-admitted
        row that fails to win a device slot must remain probeable)."""
        keys = np.asarray(keys)
        want = np.asarray(want)
        r = len(keys)
        found = np.zeros(r, np.bool_)
        meta = np.zeros((r, 3), np.int64)
        out = {k: np.zeros((r,) + self._shapes[k], dt)
               for k, dt in self._dtypes.items()}
        with self._lock:
            for i in np.flatnonzero(want):
                i = int(i)
                j = self._index.get(int(keys[i]))
                if j is None:
                    continue
                found[i] = True
                meta[i] = self._meta[j]
                for k in out:
                    out[k][i] = self._cols[k][j]
            self.readmitted_rows += int(found.sum())
        return found, meta, out

    def fetch_multi(self, keys, want, rows_per_key: int
                    ) -> Tuple[np.ndarray, np.ndarray, dict]:
        """Multimap probe: up to ``rows_per_key`` rows per wanted key —
        ``(mask [R, M] bool, meta [R, M, 3], cols {name: [R, M, ...]})``.
        Rows are NOT removed (see the module docstring's one-tier rule);
        truncation beyond M is deterministic (oldest rows first)."""
        keys = np.asarray(keys)
        want = np.asarray(want)
        r, m = len(keys), int(rows_per_key)
        mask = np.zeros((r, m), np.bool_)
        meta = np.zeros((r, m, 3), np.int64)
        out = {k: np.zeros((r, m) + self._shapes[k], dt)
               for k, dt in self._dtypes.items()}
        with self._lock:
            for i in np.flatnonzero(want):
                i = int(i)
                rows = self._index.get(int(keys[i]))
                if not rows:
                    continue
                for s, j in enumerate(rows[:m]):
                    mask[i, s] = True
                    meta[i, s] = self._meta[j]
                    for k in out:
                        out[k][i, s] = self._cols[k][j]
            # NOT counted as re-admission: fetch is read-only (rows never
            # change tiers — a persistent cold row served as a candidate
            # every batch is stable residency, not movement)
        return mask, meta, out

    def pop_keys(self, max_keys: int) -> Tuple[np.ndarray, dict]:
        """Remove and return up to ``max_keys`` keys' rows in ascending key
        order (unique mode) — the deterministic EOS drain the tiered TopN
        flush waves ride. Returns ``(keys [n], cols {name: [n, ...]})``."""
        with self._lock:
            ks = sorted(self._index)[:int(max_keys)]
            n = len(ks)
            keys = np.asarray(ks, np.int64)
            out = {k: np.zeros((n,) + self._shapes[k], dt)
                   for k, dt in self._dtypes.items()}
            for i, k in enumerate(ks):
                j = self._index.pop(k)
                self._live[j] = False
                for c in out:
                    out[c][i] = self._cols[c][j]
        return keys, out

    # -- watermark compaction ---------------------------------------------

    def compact_below(self, col: str, threshold: int) -> int:
        """Retire every row whose ``col`` value is strictly below
        ``threshold`` — the per-operator retention bound applied to the cold
        tier (a retired row could never be probed/matched again). Returns
        the number of rows retired."""
        removed = 0
        with self._lock:
            if col in ("m0", "m1", "m2"):      # the LWW meta triplet (e.g.
                #                                the JoinTable's version ts)
                vals = self._meta[:self._n, ("m0", "m1", "m2").index(col)]
            else:
                vals = self._cols[col][:self._n]
            dead = self._live[:self._n] & (
                vals.reshape(self._n, -1).max(axis=1) < threshold
                if vals.ndim > 1 else vals < threshold)
            idx = np.flatnonzero(dead)
            if len(idx):
                self._live[idx] = False
                removed = len(idx)
                self._reindex()
                if self._live[:self._n].sum() * 2 < self._n:
                    self._rebuild()
            self.compacted_rows += removed
        return removed

    # -- introspection / durability ---------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._index) if self.unique else \
                int(self._live[:self._n].sum())

    def key_count(self) -> int:
        with self._lock:
            return len(self._index)

    def counters(self) -> dict:
        with self._lock:
            return {"state_spills": self.spilled_rows,
                    "state_readmits": self.readmitted_rows,
                    "state_compactions": self.compacted_rows}

    def manifest(self) -> Dict[str, np.ndarray]:
        """Checkpointable snapshot: dense copies of the live rows + the
        counters — plain numpy arrays, so the checkpoint layer's per-array
        sha256 and atomic-write machinery cover the cold tier unchanged."""
        with self._lock:
            live_idx = np.flatnonzero(self._live[:self._n])
            out = {"key": self._key[live_idx].copy(),
                   "meta": self._meta[live_idx].copy(),
                   "counters": np.asarray(
                       [_MANIFEST_VERSION, self.spilled_rows,
                        self.readmitted_rows, self.compacted_rows],
                       np.int64)}
            for k in self._cols:
                out[f"col_{k}"] = self._cols[k][live_idx].copy()
            return out

    def restore(self, manifest: Dict[str, np.ndarray]) -> None:
        """Replace the store content with a :meth:`manifest` snapshot (the
        supervised-restore path: in-flight spills were discarded by the
        controller; replay re-derives them)."""
        with self._lock:
            keys = np.asarray(manifest["key"])
            n = len(keys)
            self._cap = max(_INIT_CAP, 1 << max(1, (n - 1).bit_length()))
            self._n = n
            self._key = np.zeros(self._cap, np.int64)
            self._key[:n] = keys
            self._live = np.zeros(self._cap, np.bool_)
            self._live[:n] = True
            self._meta = np.zeros((self._cap, 3), np.int64)
            self._meta[:n] = np.asarray(manifest["meta"]).reshape(n, 3)
            self._cols = {k: np.zeros((self._cap,) + self._shapes[k], dt)
                          for k, dt in self._dtypes.items()}
            for k in self._cols:
                self._cols[k][:n] = np.asarray(manifest[f"col_{k}"])
            ctr = np.asarray(manifest.get("counters",
                                          np.zeros(4, np.int64)))
            self.spilled_rows = int(ctr[1]) if len(ctr) > 1 else 0
            self.readmitted_rows = int(ctr[2]) if len(ctr) > 2 else 0
            self.compacted_rows = int(ctr[3]) if len(ctr) > 3 else 0
            self._reindex()
