"""Shipper — push-style output handle for Source and FlatMap user code.

Counterpart of ``wf/shipper.hpp:50-104`` (``push`` at ``:85-103``). The reference
Shipper heap-allocates and sends one tuple per push; here pushes are *recorded during
tracing* (under ``vmap``) and stacked into fixed fan-out slots, which makes FlatMap's
1:N expansion XLA-static: an input batch of capacity C with max fan-out F yields an
output batch of capacity C*F with a validity mask.

``push(payload, when=..., key=..., ts=...)`` supports data-dependent emission via the
``when`` mask (the traced analogue of conditionally calling ``shipper.push`` in C++).
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax.numpy as jnp


class Shipper:
    def __init__(self, max_fanout: int):
        self.max_fanout = int(max_fanout)
        self._payloads: List[Any] = []
        self._whens: List[Any] = []
        self._keys: List[Optional[Any]] = []
        self._ts: List[Optional[Any]] = []
        self.delivered = 0  # trace-time push count (reference counts delivered tuples)

    def push(self, payload: Any, *, when=True, key=None, ts=None):
        if len(self._payloads) >= self.max_fanout:
            raise ValueError(
                f"Shipper: more than max_fanout={self.max_fanout} pushes; raise "
                f"max_fanout on the FlatMap/Source builder")
        self._payloads.append(payload)
        self._whens.append(jnp.asarray(when, jnp.bool_))
        self._keys.append(key)
        self._ts.append(ts)
        self.delivered += 1

    # accessors used by the FlatMap implementation
    def _recorded(self):
        return self._payloads, self._whens, self._keys, self._ts
