"""Central registries of journal event names and metric/counter names.

THE single source of truth for every name the runtime emits into the
observability plane: journal events/spans (``journal.record``/``journal.span``),
process-wide recovery counters (``runtime.faults.bump``), control-plane
counters/gauges (``control._state.bump``/``set_gauge``).  The emitting modules
import their name tables from here, and the static-analysis linter
(``windflow_tpu/analysis/lint.py``) checks every emission call site against
these registries — a typo'd event name (``"chekpoint"``) or an undeclared
counter fails tier-1 instead of silently forking the metric namespace.

Pure data, stdlib only, imported by ``runtime``/``control``/``analysis`` —
this module must never import anything from the package (the linter parses it
with ``ast`` so it can run without JAX installed).

Adding a name: add it here AND emit it — the linter flags emissions missing
from the registry; an unused registry entry is harmless (names outlive call
sites across refactors).
"""

from __future__ import annotations

#: every journal event name emitted via ``journal.record``/``EventJournal.
#: event`` and every span name opened via ``journal.span`` (spans appear as
#: ``phase=begin/end`` pairs under the same name)
JOURNAL_EVENTS = (
    # observability lifecycle (observability/__init__.py Monitor)
    "monitoring_start", "monitoring_end",
    # compiled-chain hot path (runtime/pipeline.py, sampled): per-batch
    # "launch", and "dispatch_fused" for a sampled K-batch scan dispatch
    # (runtime/dispatch.py scan dispatcher; k= says how many batches rode
    # the one compiled program)
    "launch", "dispatch_fused",
    # EOS protocol (runtime/pipeline.py, runtime/pipegraph.py)
    "eos", "eos_flush", "eos_propagate",
    # ordering buffer (parallel/ordering.py, via its _journal_release wrapper)
    "ordering_flush", "ordering_close_channel",
    # supervision / recovery (runtime/supervisor.py, runtime/faults.py,
    # runtime/checkpoint.py, runtime/threaded.py)
    "checkpoint", "restore",                       # spans
    "checkpoint_invalid", "checkpoint_fallback",
    "restart_exhausted", "dead_letter", "backoff",
    "watchdog_timeout", "watchdog_stale",
    "fault_injected",
    # control plane (control/admission.py, control/governor.py,
    # control/autotune.py, runtime/supervisor.py warm start)
    "shed", "throttle", "throttle_end",
    "capacity_switch", "tuning_converged", "tuning_warm_start",
    # per-batch causal tracing lifecycle (observability/tracing.py Tracer)
    "trace_start", "trace_end",
    # event-time forensics (runtime/pipeline.py CompiledChain, event_time
    # monitoring only): a stateful operator's drop counter advanced — the
    # record carries (op, kind, n) plus the PR 5 trace coordinates
    # (tid/pos) of the sampled batch the readback rode, so wf_trace.py /
    # wf_state.py join drops to traced batches
    "lateness_drop",
    # runtime health ledger (observability/device_health.py, health
    # monitoring only): "compile" = one jit trace of a CompiledChain
    # step/scan program (cause, cache key, compile duration, AOT cost
    # flops/bytes); "retrace_unexpected" = the live retrace detector — a
    # warm executable re-traced under an ALREADY-TRACED signature (jit
    # cache eviction/clear, the WF102/WF109 hazard caught at runtime);
    # "kernel_resolve" = a per-backend kernel registry resolution
    # (ops/registry.py) observed while the ledger was active
    "compile", "retrace_unexpected", "kernel_resolve",
    # tiered keyed state (state/tiered.py TieredTable, maintain cadence):
    # "spill" = outbox rows settled into the host store, "readmit" = cold
    # rows handed back to the device tier on probe miss — both carry
    # (table, n, total); emitted on the driver thread only
    "spill", "readmit",
    # shard-local supervision (runtime/supervisor.py ShardedSupervisor):
    # "shard_restore" = ONE shard restored + replayed its own key range
    # while peers kept serving (shard id + replay extent: replay_from/
    # at_batch); "reshard" = a live re-sharding span (from_shards/
    # to_shards/moves/at_pos; discarded=True marks an in-flight handoff
    # manifest dropped on restore — replay re-derives the move)
    "shard_restore", "reshard",
    # SLO engine (observability/slo.py, Reporter-tick evaluation):
    # "slo_page" = an SLO's multi-window burn crossed page_burn on BOTH
    # windows (slo/signal/value/target/burn_fast/burn_slow/tick — incident
    # capture follows, rate-limited); "slo_recover" = a warned/paged SLO
    # returned to OK (from_state says which)
    "slo_page", "slo_recover",
    # fleet telemetry plane (observability/fleet.py):
    # "telemetry_connect"/"telemetry_lost" = the host agent's sender thread
    # (re)gained / dropped its aggregator connection (host/endpoint) — a
    # flapping link shows as a connect/lost train in the HOST journal;
    # "fleet_host_join"/"fleet_host_leave" = the AGGREGATOR saw a new host
    # tag's first frame / a host stream close (host, mon_dir on join)
    "telemetry_connect", "telemetry_lost",
    "fleet_host_join", "fleet_host_leave",
    # self-driving remediation (control/remediation.py, evaluated on the
    # Reporter tick in live mode / at commit barriers in supervised mode):
    # "remediation_apply" = a policy action fired an actuator (action/
    # actuator/slo + burn or barrier pos + setpoint details);
    # "remediation_skip" = an action wanted to fire but was held back —
    # reason says why (cooldown, run/action budget, damped, unbound
    # actuator, gate, arbitration loss to auto-reshard); "tuning_reclimb" =
    # a converged autotuner was un-converged to re-explore its ladder
    "remediation_apply", "remediation_skip", "tuning_reclimb",
    # serving front-end (serving/runtime.py ServingRuntime):
    # "serving_start"/"serving_end" frame one service run (endpoint +
    # tenant ids / batch + swap totals); "graph_swap" is BOTH the
    # quiesce->warm->cutover span around a zero-downtime chain swap AND
    # the point records inside it (applied=True with carried_state/
    # warmed/quiesce_ms, or rejected=True for an unregistered wire swap)
    "serving_start", "serving_end", "graph_swap",
)

#: flight-recorder record kinds (``observability/tracing.py``; the
#: ``flight.jsonl`` schema consumed by ``scripts/wf_trace.py``) — listed here
#: so tooling has one source of truth beside the journal/counter names
TRACE_RECORD_KINDS = ("ingest", "enq", "deq", "begin", "end")

#: flight-recorder stage labels minted OUTSIDE driver loops (driver stages
#: and ring edges are named by the drivers themselves: "chain", "seg<i>",
#: "pipe<i>", "sink", and the edge labels of ``PipeGraph._iter_edges`` /
#: ``ThreadedPipeline.edge_names``)
TRACE_STAGES = ("ingest",)

#: stage-label *families* (prefix + variable suffix): governor throttle
#: episodes record on ``governor:<edge>`` pseudo-stages
#: (``control/governor.py``) — match by prefix, not equality
TRACE_STAGE_PREFIXES = ("governor:",)

#: process-wide recovery counters (``runtime/faults.py``; surfaced in the
#: metrics snapshot under ``"recovery"`` and in Prometheus as
#: ``windflow_recovery_<name>_total``)
RECOVERY_COUNTERS = (
    "restarts", "backoff_sleeps", "backoff_seconds",
    "dead_letters", "watchdog_timeouts", "faults_injected",
    "checkpoint_saves", "checkpoint_corrupt_skipped",
    "checkpoint_fallbacks",
    # cumulative seconds spent inside supervisor restore spans (whole-domain
    # AND shard-local) — the per-tick delta is the SLO engine's
    # "recovery_s" signal (observability/slo.py)
    "recovery_seconds",
)

#: process-wide control-plane counters (``control/_state.py``; snapshot
#: ``"control"`` section, Prometheus ``windflow_control_<name>_total``)
CONTROL_COUNTERS = (
    "admitted_batches", "admitted_tuples", "shed_batches", "shed_tuples",
    "throttle_events", "throttle_seconds", "capacity_switches",
    "tuning_decisions", "tuning_cache_hits",
    # nexmark-class operator family (operators/session.py, operators/
    # rank.py): sessions closed by the data-dependent triggerer, and
    # leaderboard candidates evicted by the top-N rank merge
    "sessions_closed", "topn_evictions",
    # self-driving remediation (control/remediation.py): policy actions
    # that fired an actuator, and actions held back (cooldown / budget /
    # damping / unbound / gate / arbitration)
    "remediation_actions", "remediation_skips",
)

#: control-plane gauges (``control/_state.py::set_gauge``; Prometheus
#: ``windflow_control_<name>``)
CONTROL_GAUGES = (
    "chosen_capacity",
    # scan dispatch (runtime/dispatch.py MicrobatchAccumulator + the
    # autotuner's K ladder): batches buffered awaiting a fused launch, and
    # the K rung the dispatch tuner currently runs
    "dispatch_linger_depth", "dispatch_k",
    # versioned join-state table (ops/lookup.py join_table_*): applied
    # upsert count of the most recently synced table (last-write-wins
    # across tables, the chosen_capacity convention)
    "join_table_version",
    # actuator setpoints (PR 17 remediation observability): current
    # admission bucket refill rate (control/admission.py, updated by
    # scale_rate), governor high/low queue-depth watermarks
    # (control/governor.py), and the tiered hot-capacity target the run
    # was built with (operators/join.py / operators/rank.py tier wiring,
    # last-write-wins across tables) — so remediation deltas are
    # observable before/after each action
    "bucket_rate", "governor_high_watermark", "governor_low_watermark",
    "hot_capacity",
    # advisory remediation recommendations (control/remediation.py):
    # geometry-baked setpoints (tiered hot capacity, watermark delay) are
    # traced constants, so their actuators gauge a recommendation for the
    # next restart instead of mutating a live trace
    "remediation_hot_capacity", "remediation_recommended_delay",
)

#: per-STAGE counters exported in the metrics snapshot's operator rows
#: (``row["counters"]``) and in Prometheus as
#: ``windflow_stage_<name>_total`` with HELP/TYPE lines — the PR 8 operator
#: counters promoted from process-wide totals to a uniform per-operator
#: surface.  Operators publish them via ``Basic_Operator.
#: _publish_stage_counters`` (which validates against this tuple, the
#: WF240/241 one-source-of-truth discipline); ``metrics.py`` renders ONLY
#: registered names.
STAGE_COUNTERS = (
    "sessions_closed",     # operators/session.py: sessions the triggerer closed
    "topn_evictions",      # operators/rank.py: leaderboard candidates evicted
    "match_drops",         # operators/join.py IntervalJoin: per-probe overflow
    "arch_drops",          # operators/join.py IntervalJoin: archive overwrites
    "overflow_drops",      # ops/lookup.py JoinTable: pending-ring/table drops
    "old_drops",           # session/win_seqffat OLD straggler drops (also in
    #                        tuples_dropped_old — here beside the other drops)
    # tiered keyed state (state/ + the per-operator tier wiring): device
    # rows spilled to the outbox, cold rows re-admitted on probe miss, and
    # host-store rows retired by watermark compaction
    "state_spills", "state_readmits", "state_compactions",
)

#: per-stage gauges (same surface, ``windflow_stage_<name>`` gauge form)
STAGE_GAUGES = (
    "join_table_version",  # applied upsert count of the op's own JoinTable
    # tiered keyed state: hot-table occupancy (slots in use) and cold-tier
    # key count — the per-operator tier_occupancy pair wf_state.py trends
    # and wf_health.py cross-references against the HBM headroom gauge
    "tier_hot_used", "tier_cold_keys",
)

#: per-operator event-time gauges of the watermark propagation map
#: (``metrics.py``: snapshot ``event_time`` sections -> Prometheus
#: ``windflow_event_time_<name>``; only registered names are rendered).
#: ``min_watermark`` and ``skew`` are graph-level (the frontier + per-edge
#: watermark skew of the topology export).
EVENT_TIME_GAUGES = (
    "watermark",           # operator event-time frontier (max ts applied)
    "lag", "occupancy_pct", "pending_depth", "open_sessions",
    "oldest_open_age", "archive_fill_pct",
    "lateness_p50", "lateness_p99",        # lateness histogram quantiles
    "min_watermark", "skew",               # graph frontier + per-edge skew
)

#: per-SHARD gauges of the ``shards`` snapshot section (the shard-local
#: supervision layer's health surface: ``SupervisedPipeline.shard_report``
#: -> ``MetricsRegistry.attach_shards`` -> snapshot ``shards`` rows,
#: rendered per shard by ``scripts/wf_health.py``/``wf_state.py`` and
#: folded HOST-TAGGED (never summed — the fleet view must name WHICH
#: shard is hot) by ``device_health.merge_snapshots``)
SHARD_GAUGES = (
    "occupancy_tuples",     # live tuples this shard processed since commit
    "restarts",             # shard-local recoveries (global restarts excluded)
    "last_recovery_s",      # duration of the most recent shard restore+replay
    "dead_letters",         # sub-batches this shard quarantined
    "reshard_moves",        # times this shard's key range changed in a reshard
    "committed_pos",        # stream position of the shard's last commit
)

#: runtime-health gauges of the ``health`` snapshot section
#: (``MonitoringConfig.health`` / ``WF_MONITORING_HEALTH``;
#: ``metrics.py::_prometheus_health`` renders ONLY registered names — its
#: local HELP map is checked against this tuple at import, the
#: EVENT_TIME_GAUGES lockstep discipline).  The ``hbm_*`` family renders as
#: ``windflow_hbm_<name>`` (per device), the rest as
#: ``windflow_health_<name>`` (graph-/operator-/stage-labelled).
HEALTH_GAUGES = (
    "hbm_headroom_bytes",      # per device: bytes_limit - bytes_in_use —
    #                            THE eviction signal for tiered state
    "hbm_bytes_in_use", "hbm_bytes_limit",
    "live_buffer_bytes", "live_buffer_count",
    "state_bytes",             # per operator: state-pytree footprint
    "compiles", "retraces", "retraces_unexpected",  # compile ledger totals
    "compile_seconds",
    "device_ms", "dispatch_ms",                     # per stage label
    "dispatch_ratio",          # host dispatch / device time — >= 0.5 names
    #                            a fusion candidate (dispatch-bound edge)
)

#: per-SLO gauges of the ``slo`` snapshot section (``observability/slo.py``
#: SLOEngine, evaluated inside the Reporter tick; ``metrics.py::
#: _prometheus_slo`` renders ONLY registered names as
#: ``windflow_slo_<name>{graph,slo=...}`` — its local HELP map is checked
#: against this tuple at import, the HEALTH_GAUGES lockstep discipline).
#: Folded by ``device_health.merge_snapshots`` as worst-state-wins (code
#: MAX), burn rates MAX, pages summed + host-tagged.
SLO_GAUGES = (
    "state",            # health state code: 0 ok, 1 warn, 2 page
    "burn_fast",        # error-budget burn over the fast window
    "burn_slow",        # error-budget burn over the slow window
    "signal",           # latest observed signal value
    "target",           # the spec's target threshold
    "pages",            # PAGE transitions this run
)

#: gauges of the host-side ``telemetry`` snapshot section
#: (``observability/fleet.py`` TelemetryAgent.stats(), present only when
#: ``MonitoringConfig.telemetry`` is on; ``metrics.py::
#: _prometheus_telemetry`` renders ONLY registered names as
#: ``windflow_telemetry_<name>{graph=...}`` — its local HELP map is checked
#: against this tuple at import, the SLO_GAUGES lockstep discipline)
TELEMETRY_GAUGES = (
    "frames_sent",      # frames delivered to the aggregator socket
    "frames_dropped",   # frames evicted by the bounded drop-oldest outbox
    "reconnects",       # successful reconnects after a lost aggregator
    "outbox_depth",     # frames queued right now (bounded by the outbox)
    "connected",        # 1 = live aggregator connection, 0 = not
)

#: gauges of the aggregator-side ``fleet`` snapshot section
#: (``observability/fleet.py`` FleetAggregator, stamped into every merged
#: fleet snapshot and rendered as ``windflow_fleet_<name>{graph=...}`` by
#: ``fleet.render_prometheus`` — ``fleet._FLEET_HELP`` is pinned against
#: this tuple by ``tests/test_fleet.py``, the path-loadable analogue of the
#: import-time lockstep check)
FLEET_GAUGES = (
    "hosts_connected",  # hosts with a live telemetry stream right now
    "hosts_seen",       # distinct host tags seen since the serve started
    "frames_received",  # telemetry frames decoded across all hosts
    "frames_torn",      # frames lost to torn/corrupt wire data (resync'd)
    "ticks",            # fleet merge ticks emitted
)

#: run-level gauges of the ``serving`` snapshot section
#: (``serving/runtime.py`` ServingRuntime.serving_section ->
#: ``MetricsRegistry.attach_serving``; ``metrics.py::_prometheus_serving``
#: renders ONLY registered names as ``windflow_serving_<name>{graph=...}``
#: — its local HELP map is checked against this tuple at import, the
#: SLO_GAUGES lockstep discipline).  Counters summed, never host-tagged,
#: by ``device_health.merge_snapshots`` (``swaps_applied`` across hosts is
#: a fleet total like ``frames_torn``).
SERVING_GAUGES = (
    "swaps_applied",     # zero-downtime graph_swap cutovers completed
    "swaps_rejected",    # wire swap frames naming an unregistered graph
    "frames_decoded",    # intact WFS1 record frames ingested
    "frames_torn",       # bytes resync'd past (torn client / garbage)
    "frames_dup",        # reconnect-overlap frames deduped by tenant seq
    "clients_seen",      # ingest connections accepted since start
    "unknown_offered",   # batches from tenant ids nobody declared
)

#: per-TENANT gauges of the ``serving.tenants`` snapshot rows
#: (``serving/tenants.py`` TenantRegistry.counters; rendered as
#: ``windflow_tenant_<name>{graph,tenant=...}`` — the SHARD_GAUGES
#: per-label discipline; folded SUMMED per tenant id across hosts by
#: ``device_health.merge_snapshots``, so one tenant's fleet-wide shed
#: pressure is one series)
TENANT_GAUGES = (
    "offered",           # batches this tenant offered to its bucket
    "admitted",          # batches its controller admitted
    "shed",              # batches its controller shed
    "shed_tuples",       # tuple capacity those shed batches carried
    "rate",              # the bucket's live refill rate (remediation moves it)
    # per-tenant e2e latency (MetricsRegistry.record_tenant_e2e LogHistograms,
    # sampled on the serving drive loop beside the run-level e2e sample; rows
    # only carry these keys once the tenant has samples, so the off path stays
    # byte-identical).  Percentile folds are MAX across hosts (the PR 10 e2e
    # convention), samples summed, exemplar from the worst host.
    "e2e_p50_ms", "e2e_p95_ms", "e2e_p99_ms",
    "e2e_p99_tick_ms",   # windowed p99 over the last reporter tick — THE
    #                      tenant_e2e_p99_ms SLO signal's read (cumulative
    #                      p99 can never recover after a stall)
    "e2e_samples", "e2e_samples_tick",
    "e2e_p99_exemplar",  # trace id of a batch observed in the p99 bucket
)

#: kernel families selectable through the per-backend kernel registry
#: (``ops/registry.py``).  The linter (WF250) checks every literal kernel
#: name passed to ``register_kernel``/``resolve_impl`` against this tuple —
#: a typo'd kernel name would silently fork the selection/autotune namespace
#: (its env overrides, tuning-cache entries, and WF109 trace records would
#: never match the real kernel's).  The perf gate's proxy microbenchmarks
#: also enumerate this tuple, so a registered-but-unbenchmarked kernel fails
#: ``tests/test_perfgate.py``.
KERNELS = (
    "histogram",        # ops/histogram.py keyed_pane_histogram
    "lookup",           # ops/lookup.py table_lookup (factored path)
    "ordering_merge",   # parallel/ordering.py bitonic merge/sort network
    "segment_fold",     # ops/segment.py segment_fold (window fold path)
    "join_probe",       # ops/lookup.py join_probe (stream-table join)
)

#: non-kernel proxy-microbench families the hermetic perf gate must ALSO
#: cover (``analysis/perfgate.py::compare``: a family without a proxy row is
#: a coverage finding, the KERNELS convention). "dispatch" times the scan
#: dispatcher's fused ``push_many`` launch and carries its jit-boundary
#: launch counts — the 1-executable-call-per-K-batches amortization claim
#: ``tests/test_perfgate.py`` asserts.
PERF_PROXY_FAMILIES = (
    "dispatch",
    # "join" times the full versioned JoinTable step (upsert + registry
    # probe, ops/lookup.py join_table_*) — the probe kernels keep their
    # microbench or tests/test_perfgate.py fails coverage
    "join",
    # "spill" times the tiered-state eviction/pack path (ops/lookup.py
    # join_table_tier_evict: coldness sort + outbox pack + slot clear) —
    # the device-side half of the HBM->host spill protocol
    "spill",
    # "shard" times the sharded supervisor's key-ownership splitter
    # (parallel/sharding.py ShardAssignment.split_fn — the per-batch
    # program the reshard_pack AOT pin also covers): one masked split
    # into N sub-batches, the only per-batch cost sharding adds
    "shard",
)

#: Nexmark-style benchmark queries (``windflow_tpu/nexmark/queries.py``).
#: THE name registry for the workload suite: ``bench.py::bench_nexmark``,
#: ``benchmarks/sweep.py``, the perf-gate nexmark workload pins, and
#: ``tests/test_nexmark.py``'s dense oracles all enumerate this tuple, so a
#: query added to the package without bench/test coverage fails loudly.
NEXMARK_QUERIES = (
    "q1_currency",       # currency-map: per-bid dollar -> euro projection
    "q2_selection",      # selection-filter: auctions of interest
    "q3_enrich_join",    # stream-table join: bid -> auction category
    "q4_interval_join",  # interval join: bid within an auction's open window
    "q5_session",        # session-aggregate: per-bidder activity sessions
    "q6_topn",           # top-N-by-key: highest bids per auction
    "q7_distinct",       # distinct: first bid per selected auction
)

#: implementation names a kernel may register under (WF250 checks literal
#: impl names at ``register_kernel`` call sites too)
KERNEL_IMPLS = (
    "xla",              # reference formulation — always registered
    "pallas",           # fused Pallas kernel (TPU; interpret mode on CPU)
    "pallas_mm",        # histogram only: static-store matmul placement
)
